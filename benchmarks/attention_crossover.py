"""Measure the dense/flash attention crossover on the current device.

The ``impl="auto"`` dispatch in ``accelerate_tpu/ops/attention.py`` switches
from the dense einsum to the Pallas flash kernel at a per-device-kind sequence
length (``_FLASH_CROSSOVER``). This script reproduces that measurement so the
table can be re-derived on new TPU generations:

    python benchmarks/attention_crossover.py

Timing notes: each config runs ``ITERS`` attention calls chained inside one
``jit`` (a data dependency through q), so per-call host/tunnel latency is
amortized away; the host round-trip is measured separately and subtracted.
"""

import argparse
import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp


def measure(fn, q, k, v, iters):
    @jax.jit
    def loop(q, k, v):
        def body(i, qq):
            return fn(qq, k, v, causal=True).astype(qq.dtype)

        return jax.lax.fori_loop(0, iters, body, q).sum()

    float(loop(q, k, v))  # compile + warm
    # Host round-trip floor: median of several tiny pre-compiled fetches.
    probe = jax.jit(lambda x: x.sum())
    float(probe(jnp.zeros(8)))
    rtts = []
    for _ in range(5):
        t0 = time.perf_counter()
        float(probe(jnp.zeros(8)))
        rtts.append(time.perf_counter() - t0)
    rtt = sorted(rtts)[2]
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(loop(q, k, v))
        times.append(time.perf_counter() - t0)
    return (sorted(times)[1] - rtt) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head_dim", type=int, default=128)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--seqs", type=int, nargs="+", default=[512, 1024, 2048, 4096])
    args = ap.parse_args()

    from accelerate_tpu.ops.attention import (
        _flash_available,
        dense_attention,
        flash_attention,
    )

    kind = jax.devices()[0].device_kind
    print(f"device_kind: {kind}  flash_available: {_flash_available()}")
    crossover = None
    for S in args.seqs:
        ks = jax.random.split(jax.random.key(0), 3)
        q = jax.random.normal(ks[0], (args.batch, S, args.heads, args.head_dim), jnp.bfloat16)
        k = jax.random.normal(ks[1], q.shape, jnp.bfloat16)
        v = jax.random.normal(ks[2], q.shape, jnp.bfloat16)
        t_dense = measure(dense_attention, q, k, v, args.iters)
        row = f"S={S:6d}  dense {t_dense * 1e3:8.3f} ms"
        if _flash_available():
            t_flash = measure(flash_attention, q, k, v, args.iters)
            row += f"  flash {t_flash * 1e3:8.3f} ms  winner: {'flash' if t_flash < t_dense else 'dense'}"
            if crossover is None and t_flash < t_dense:
                crossover = S
        print(row)
    if crossover is not None:
        print(f"suggested _FLASH_CROSSOVER[{kind!r}] = {crossover}")


if __name__ == "__main__":
    main()
