"""Attribute speculative decoding on the paged serving engine.

Speculative decoding's pitch is k-for-1: a tiny draft proposes k tokens per
slot and the target verifies the whole window in ONE paged forward, so the
target's per-token cost drops by the acceptance rate. This profile measures
that pitch the way ``serving_decode_profile.py`` measures the paged-capacity
pitch — probe by probe, against the non-speculative wave at IDENTICAL
outputs (greedy spec decode is bit-identical by construction; a mismatch
here is a correctness regression, not noise):

- ``wave_baseline``: the non-speculative paged wave — tokens/s and target
  decode dispatches.
- ``wave_spec_k{K}``: the same wave under speculation — tokens/s, verify
  dispatches (one per window instead of ``sync_every`` decode steps),
  proposed/accepted draft tokens, acceptance rate, accepted-tokens/s.
- ``headline``: outputs_identical verdict + the speedup and
  dispatch-reduction ratios.

The draft is the target itself in SMALL smoke runs (acceptance ~1 — probes
the machinery, not a real draft) and the zoo "tiny" preset otherwise.

Prints one JSON line per probe; ``summarize()`` returns the dict bench.py
embeds as ``detail.serving.spec`` under ``BENCH_SPEC=1``.
``BENCH_PROFILE_SMALL=1`` shrinks everything for CPU smoke runs.

Usage: python benchmarks/spec_decode_profile.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


SMALL = os.environ.get("BENCH_PROFILE_SMALL", "0") == "1"


def _shapes():
    if SMALL:
        return dict(layers=2, heads=4, kv=2, hidden=64, inter=128, vocab=256,
                    slots=2, max_new=8, sync=2, block=4, ks=(2,),
                    prompt_lens=(5, 14, 3, 12, 7, 4), buckets=(8, 16))
    return dict(layers=8, heads=16, kv=8, hidden=1024, inter=4096, vocab=32000,
                slots=8, max_new=64, sync=8, block=16, ks=(2, 4),
                prompt_lens=(33, 180, 12, 250, 96, 40, 140, 64),
                buckets=(64, 128, 256))


def _build_model(s):
    import jax

    from accelerate_tpu.models import Llama, LlamaConfig

    cfg = LlamaConfig.tiny(
        vocab_size=s["vocab"], hidden_size=s["hidden"],
        intermediate_size=s["inter"], num_hidden_layers=s["layers"],
        num_attention_heads=s["heads"], num_key_value_heads=s["kv"],
    )
    model = Llama(cfg)
    model.init_params(jax.random.key(0))
    return model


def _build_draft(s, target):
    # SMALL: draft == target — deterministic full acceptance exercises the
    # whole verify/commit path without paying a second model's compiles on
    # the smoke rig. Full runs draft with the zoo "tiny" preset at the
    # target's vocab (the deployment shape).
    if SMALL:
        return target
    import jax

    from accelerate_tpu.models import Llama, LlamaConfig

    draft = Llama(LlamaConfig.tiny(
        vocab_size=s["vocab"],
        max_position_embeddings=target.config.max_position_embeddings,
    ))
    draft.init_params(jax.random.key(1))
    return draft


def probe_wave(model, s, k: int = 0, draft=None):
    """One paged wave; ``k > 0`` speculates with ``draft``. Returns the
    probe dict plus outputs for the bit-identity join."""
    import jax.numpy as jnp

    from accelerate_tpu.serving import ContinuousBatcher

    kw = dict(batch_slots=s["slots"], max_new_tokens=s["max_new"],
              max_cache_len=4096 if not SMALL else 1024,
              cache_dtype=jnp.float32, bucket_sizes=s["buckets"],
              sync_every=s["sync"], paged=True, block_size=s["block"])
    if k:
        kw.update(speculative_k=k, draft_model=draft)
    engine = ContinuousBatcher(model, **kw)
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, s["vocab"], (n,)).astype(np.int32)
               for n in s["prompt_lens"]]
    rids = [engine.submit(p) for p in prompts]
    t0 = time.perf_counter()
    outs = engine.run()
    dt = time.perf_counter() - t0
    gen = sum(len(outs[r]) for r in rids)
    windows = sum(1 for e in engine._dispatch_log
                  if e.startswith(("decode", "verify")))
    probe = {
        "mode": f"spec_k{k}" if k else "baseline",
        "wall_s": round(dt, 4),
        "tokens_per_sec": round(gen / dt, 1),
        "generated_tokens": gen,
        "target_windows": windows,
    }
    if k:
        rep = engine.spec_report()
        probe.update({
            "proposed_tokens": rep["proposed_tokens"],
            "accepted_tokens": rep["accepted_tokens"],
            "acceptance_rate": rep["acceptance_rate"],
            "accepted_tokens_per_sec": round(rep["accepted_tokens"] / dt, 1),
        })
    return probe, [outs[r] for r in rids]


def summarize(model=None):
    """Run every probe; returns the ``detail.serving.spec`` dict."""
    s = _shapes()
    if model is None:
        model = _build_model(s)
    draft = _build_draft(s, model)
    out = {"small": SMALL, "sync_every": s["sync"],
           "draft": "target" if draft is model else "tiny-preset"}
    base, base_outs = probe_wave(model, s)
    out["wave_baseline"] = base
    for k in s["ks"]:
        wave, outs = probe_wave(model, s, k=k, draft=draft)
        wave["outputs_identical"] = bool(
            all(np.array_equal(a, b) for a, b in zip(base_outs, outs)))
        wave["speedup_x"] = round(
            wave["tokens_per_sec"] / max(base["tokens_per_sec"], 1e-9), 3)
        wave["window_reduction_x"] = round(
            base["target_windows"] / max(wave["target_windows"], 1), 3)
        out[f"wave_spec_k{k}"] = wave
    out["outputs_identical"] = bool(
        all(out[f"wave_spec_k{k}"]["outputs_identical"] for k in s["ks"]))
    return out


def main():
    summary = summarize()
    s = _shapes()
    print(json.dumps({"probe": "wave_baseline", **summary["wave_baseline"]}))
    for k in s["ks"]:
        print(json.dumps({"probe": f"wave_spec_k{k}",
                          **summary[f"wave_spec_k{k}"]}))
    print(json.dumps({"probe": "headline",
                      "outputs_identical": summary["outputs_identical"],
                      "draft": summary["draft"]}))


if __name__ == "__main__":
    main()
