"""Attribute the MoE layer's time at the op level on the real chip.

Times fwd+bwd of each piece at the bench shape (E8 k2 h1024 i2816, T=B*S
tokens) so the gap between the einsum path's measured active-MFU and the
routing-free ceiling can be assigned to (a) expert matmuls themselves,
(b) dispatch/combine matmuls, (c) routing front-end, (d) the sorted path's
gather/permute glue vs lax.ragged_dot proper. Prints one JSON line per probe.

Usage: python benchmarks/moe_op_attribution.py  (runs on the default backend)
"""

import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

from accelerate_tpu.ops import moe as M

E, K, H, I = 8, 2, 1024, 2816
B, S = 16, 1024
T = B * S
DTYPE = jnp.bfloat16
STEPS, WARMUP = 30, 5


def bench(name, fn, *args, flops=None):
    f = jax.jit(jax.grad(lambda *a: fn(*a).astype(jnp.float32).sum(), argnums=0))
    for _ in range(WARMUP):
        out = f(*args)
    jax.tree_util.tree_leaves(out)[0].block_until_ready()
    np.asarray(jax.tree_util.tree_leaves(out)[0][..., 0:1])  # tunnel-safe sync
    t0 = time.perf_counter()
    for _ in range(STEPS):
        out = f(*args)
    np.asarray(jax.tree_util.tree_leaves(out)[0][..., 0:1])
    dt = (time.perf_counter() - t0) / STEPS
    rec = {"probe": name, "ms": round(dt * 1e3, 3)}
    if flops:
        rec["tflops_s"] = round(flops / dt / 1e12, 1)
    print(json.dumps(rec))
    return dt


def main():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((B, S, H)), DTYPE)
    router_w = jnp.asarray(rng.standard_normal((H, E)) * 0.02, jnp.float32)
    w_gate = jnp.asarray(rng.standard_normal((E, H, I)) * 0.02, DTYPE)
    w_up = jnp.asarray(rng.standard_normal((E, H, I)) * 0.02, DTYPE)
    w_down = jnp.asarray(rng.standard_normal((E, I, H)) * 0.02, DTYPE)

    # fwd+bwd matmul FLOPs for T*K claim rows through the 3 expert matmuls
    expert_flops = 3 * 2 * (T * K) * H * I * 3  # x2 bwd => x3 total

    # (a) the three ragged_dot matmuls on PRE-SORTED contiguous rows, balanced
    # groups — lax.ragged_dot with zero routing/glue.
    sorted_rows = jnp.asarray(rng.standard_normal((T * K, H)), DTYPE)
    group_sizes = jnp.full((E,), T * K // E, jnp.int32)

    def ragged_only(rows):
        rd = lambda lhs, rhs: jax.lax.ragged_dot(lhs, rhs, group_sizes)
        return rd(jax.nn.silu(rd(rows, w_gate)) * rd(rows, w_up), w_down)

    bench("ragged_dot_3mm_presorted", ragged_only, sorted_rows, flops=expert_flops)

    # (b) the SAME three matmuls as dense per-expert einsums on capacity slots
    # shaped (E, B, C, H) with C = T*K/(B*E) (cf=1.0 equivalent, no padding).
    C = T * K // (B * E)
    slots = jnp.asarray(rng.standard_normal((E, B, C, H)), DTYPE)

    def dense_expert(slots):
        g = jax.nn.silu(jnp.einsum("ebch,ehi->ebci", slots, w_gate))
        u = jnp.einsum("ebch,ehi->ebci", slots, w_up)
        return jnp.einsum("ebci,eih->ebch", g * u, w_down)

    bench("dense_expert_3mm_slots", dense_expert, slots, flops=expert_flops)

    # (c) full layers, each back-end (fwd+bwd), cf=1.0.
    for name, fn in (("einsum", M.moe_ffn_einsum), ("sorted", M.moe_ffn_sorted),
                     ("indexed", M.moe_ffn_indexed)):
        bench(
            f"layer_{name}_cf1.0",
            lambda x, f=fn: f(x, router_w, w_gate, w_up, w_down,
                              k=K, capacity_factor=1.0)[0],
            x, flops=expert_flops,
        )

    # (d) routing front-end alone (softmax/top-k/cumsum/one-hot, no experts).
    def routing_only(x):
        logits = (x @ router_w.astype(x.dtype)).astype(jnp.float32)
        d, c, aux = M.top_k_routing(logits, K, M.router_capacity(S, E, K, 1.0),
                                    dtype=x.dtype)
        return d.sum() + c.sum() + aux

    bench("routing_frontend_only", routing_only, x)

    # (e) dense FFN with k*i width — the routing-free active-FLOPs equivalent.
    wg2 = jnp.asarray(rng.standard_normal((H, K * I)) * 0.02, DTYPE)
    wu2 = jnp.asarray(rng.standard_normal((H, K * I)) * 0.02, DTYPE)
    wd2 = jnp.asarray(rng.standard_normal((K * I, H)) * 0.02, DTYPE)

    def dense_ffn(x):
        return (jax.nn.silu(x @ wg2) * (x @ wu2)) @ wd2

    bench("dense_ffn_k_times_i", dense_ffn, x, flops=expert_flops)


if __name__ == "__main__":
    main()
