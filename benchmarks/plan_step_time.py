"""Step-time comparison across sharding plans on the virtual 8-device CPU mesh.

HLO collective counts (tests/test_hlo_collectives.py) catch communication-
*pattern* regressions; this catches communication-*cost* regressions: a plan
whose HLO still looks right but whose step got slower (VERDICT r2 weak #8).
CPU timings are not TPU timings, but plan-over-plan ratios are stable enough
to flag e.g. the round-2 pp design (all-gather of stage weights) being
strictly slower than fsdp over the same axis — the new GPipe schedule must
not be.

Usage: python benchmarks/plan_step_time.py [--steps N] [--layers L]
Prints one JSON line per plan: {"plan": ..., "step_ms": ..., "ratio_vs_dp": ...}.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from accelerate_tpu.utils.environment import pin_cpu_platform

pin_cpu_platform(8)

import numpy as np

import jax
import optax

from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.models import Llama, LlamaConfig
from accelerate_tpu.state import AcceleratorState, GradientState
from accelerate_tpu.utils.dataclasses import PipelineParallelPlugin

PLANS = {
    "dp8": (ParallelismConfig(), None),
    "fsdp8": (ParallelismConfig(fsdp_size=8), None),
    "fsdp2_dp4": (ParallelismConfig(fsdp_size=2, dp_size=4), None),
    "tp2_dp4": (ParallelismConfig(tp_size=2), None),
    "pp2_dp4": (ParallelismConfig(pp_size=2), None),
    "pp2_dp4_1f1b": (
        ParallelismConfig(pp_size=2),
        PipelineParallelPlugin(pp_size=2, schedule="1f1b"),
    ),
    "pp2_fsdp2_tp2": (ParallelismConfig(pp_size=2, fsdp_size=2, tp_size=2), None),
    "dcn2_dp4": (ParallelismConfig(dcn_size=2), None),
}


def time_plan(plan, steps: int, layers: int, hidden: int = 128, batch: int = 32,
              seq: int = 64):
    parallelism, pp_plugin = plan
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc = Accelerator(parallelism_config=parallelism, pp_plugin=pp_plugin)
    cfg = LlamaConfig.tiny(
        vocab_size=256, hidden_size=hidden, intermediate_size=2 * hidden,
        num_attention_heads=4, num_key_value_heads=4, num_hidden_layers=layers,
        max_position_embeddings=seq,
    )
    model = Llama(cfg)
    model.init_params(jax.random.key(0))
    pmodel, popt = acc.prepare(model, optax.adamw(1e-3))
    step = acc.build_train_step(pmodel, popt)
    ids = np.random.default_rng(0).integers(0, 256, (batch, seq)).astype(np.int32)
    batch_d = {"input_ids": ids, "labels": ids}
    float(step(batch_d))  # compile + warm
    times = []
    for _ in range(steps):
        t0 = time.perf_counter()
        float(step(batch_d))  # per-step host sync so each sample is complete
        times.append(time.perf_counter() - t0)
    # Median rejects scheduler hiccups on shared CI machines (means don't).
    return float(np.median(times)) * 1000.0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--plans", type=str, default=",".join(PLANS))
    args = ap.parse_args()

    results = {}
    for name in args.plans.split(","):
        ms = time_plan(PLANS[name], args.steps, args.layers)
        results[name] = ms
        # Meaningful only when the dp8 baseline actually ran in this invocation.
        ratio = round(ms / results["dp8"], 2) if "dp8" in results else None
        print(json.dumps({"plan": name, "step_ms": round(ms, 2),
                          "ratio_vs_dp": ratio}), flush=True)
    return results


if __name__ == "__main__":
    main()
