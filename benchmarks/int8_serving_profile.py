"""Attribute int8 weight-quantized serving end-to-end.

The kernel-backed int8 matmul (``ops/int8.py``, routed through every Llama
projection when ``matmul_precision="int8"``) already carries op-level
microbenches; this profile prices the precision policy where it ships — the
serving forward — against the default-precision wave:

- ``matmul_{default,int8}``: op-level decode-shaped matmul at each
  precision (activation row-quant + int8 MXU dot vs the default dot).
- ``wave_{default,int8}``: the mixed-length serving wave under each
  precision policy — tokens/s plus token-level divergence (weight
  quantization shifts logits; greedy outputs may diverge — the fraction is
  the signal, bit-identity is NOT the contract here, unlike spec decode).

Prints one JSON line per probe; ``summarize()`` returns the dict bench.py
embeds as ``detail.serving.int8_serving`` under ``BENCH_INT8_SERVING=1``.
``BENCH_PROFILE_SMALL=1`` shrinks everything for CPU smoke runs.

Usage: python benchmarks/int8_serving_profile.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


SMALL = os.environ.get("BENCH_PROFILE_SMALL", "0") == "1"


def _shapes():
    if SMALL:
        return dict(layers=2, heads=4, kv=2, hidden=64, inter=128, vocab=256,
                    slots=2, max_new=8, sync=2, block=4,
                    prompt_lens=(5, 14, 3, 12, 7, 4), buckets=(8, 16))
    return dict(layers=8, heads=16, kv=8, hidden=1024, inter=4096, vocab=32000,
                slots=8, max_new=64, sync=8, block=16,
                prompt_lens=(33, 180, 12, 250, 96, 40, 140, 64),
                buckets=(64, 128, 256))


def _build_model(s):
    import jax

    from accelerate_tpu.models import Llama, LlamaConfig

    cfg = LlamaConfig.tiny(
        vocab_size=s["vocab"], hidden_size=s["hidden"],
        intermediate_size=s["inter"], num_hidden_layers=s["layers"],
        num_attention_heads=s["heads"], num_key_value_heads=s["kv"],
    )
    model = Llama(cfg)
    model.init_params(jax.random.key(0))
    return model


def probe_matmul(s):
    """Op-level: a decode-shaped projection at each precision."""
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.ops.int8 import matmul

    rng = np.random.default_rng(0)
    b, h, inter = s["slots"], s["hidden"], s["inter"]
    x = jnp.asarray(rng.standard_normal((b, h)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((h, inter)), jnp.float32)

    f_def = jax.jit(lambda x, w: matmul(x, w, precision="default"))
    f_q = jax.jit(lambda x, w: matmul(x, w, precision="int8"))

    def timeit(f):
        out = f(x, w)
        np.asarray(out[..., 0:1])
        steps = 5 if SMALL else 100
        t0 = time.perf_counter()
        for _ in range(steps):
            out = f(x, w)
        np.asarray(out[..., 0:1])
        return (time.perf_counter() - t0) / steps

    t_def = timeit(f_def)
    t_q = timeit(f_q)
    return {
        "matmul_default_ms": round(t_def * 1e3, 4),
        "matmul_int8_ms": round(t_q * 1e3, 4),
        "int8_speedup_x": round(t_def / max(t_q, 1e-9), 2),
    }


def probe_wave(model, s, precision: str | None):
    import jax.numpy as jnp

    from accelerate_tpu.serving import ContinuousBatcher

    engine = ContinuousBatcher(
        model, batch_slots=s["slots"], max_new_tokens=s["max_new"],
        max_cache_len=4096 if not SMALL else 1024, cache_dtype=jnp.float32,
        bucket_sizes=s["buckets"], sync_every=s["sync"], paged=True,
        block_size=s["block"], matmul_precision=precision,
    )
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, s["vocab"], (n,)).astype(np.int32)
               for n in s["prompt_lens"]]
    rids = [engine.submit(p) for p in prompts]
    t0 = time.perf_counter()
    outs = engine.run()
    dt = time.perf_counter() - t0
    gen = sum(len(outs[r]) for r in rids)
    return {
        "mode": precision or "default",
        "wall_s": round(dt, 4),
        "tokens_per_sec": round(gen / dt, 1),
    }, [outs[r] for r in rids]


def summarize(model=None):
    """Run every probe; returns the ``detail.serving.int8_serving`` dict."""
    s = _shapes()
    if model is None:
        model = _build_model(s)
    out = {"small": SMALL}
    out.update(probe_matmul(s))
    wave_d, outs_d = probe_wave(model, s, None)
    wave_q, outs_q = probe_wave(model, s, "int8")
    out["wave_default"] = wave_d
    out["wave_int8"] = wave_q
    total = sum(len(a) for a in outs_d)
    diverged = sum(
        int(np.sum(np.asarray(a)[: min(len(a), len(b))]
                   != np.asarray(b)[: min(len(a), len(b))]))
        + abs(len(a) - len(b))
        for a, b in zip(outs_d, outs_q)
    )
    out["tokens_total"] = total
    out["tokens_diverged"] = int(diverged)
    out["divergence_fraction"] = round(diverged / max(total, 1), 4)
    out["serving_speedup_x"] = round(
        wave_q["tokens_per_sec"] / max(wave_d["tokens_per_sec"], 1e-9), 3)
    return out


def main():
    summary = summarize()
    for key in ("matmul_default_ms", "matmul_int8_ms", "int8_speedup_x"):
        print(json.dumps({"probe": key, "value": summary[key]}))
    for key in ("wave_default", "wave_int8"):
        print(json.dumps({"probe": key, **summary[key]}))
    print(json.dumps({
        "probe": "headline",
        "serving_speedup_x": summary["serving_speedup_x"],
        "divergence_fraction": summary["divergence_fraction"],
    }))


if __name__ == "__main__":
    main()
