"""Attribute a serving decode wave at the op and engine level.

The serving acceptance criteria are ratios, not absolutes — this script
measures them the way ``vocab128k_profile.py`` measures the fused-loss sweep:
probe-by-probe, so a regression (or the future Pallas paged kernel's win,
ROADMAP item 3) is attributed instead of guessed:

- ``decode_attention_{contiguous,paged}``: the op-level seam — one decode
  step's attention against a contiguous cache (``cached_attention``) vs
  block tables (``paged_attention``'s reference gather lowering) at the same
  logical shape. The gap between these two IS the gather tax the Pallas
  kernel exists to kill.
- ``wave_{contiguous,paged}``: a mixed-length wave through
  ``ContinuousBatcher`` in each cache mode at identical outputs —
  tokens/s, observed TTFT/TPOT, and **effective batch capacity** (admitted
  tokens per consumed KV slot; slot bytes are identical across modes), whose
  ratio is the >= 1.3x acceptance gate.
- ``prefill_{monolithic,chunked}``: a long prompt admitted mid-wave, with
  the max gap between consecutive decode windows recorded — chunked prefill
  must bound per-step decode stall by one chunk's compute (the <= 2x
  criterion), where monolithic prefill stalls by the whole prompt.

Prints one JSON line per probe; ``summarize()`` returns the same dict that
``bench.py`` embeds as ``detail.serving`` under ``BENCH_SERVING=1``.
``BENCH_PROFILE_SMALL=1`` shrinks everything for CPU smoke runs (the test
suite's path).

Usage: python benchmarks/serving_decode_profile.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


SMALL = os.environ.get("BENCH_PROFILE_SMALL", "0") == "1"


def _shapes():
    if SMALL:
        return dict(layers=2, heads=4, kv=2, hidden=64, inter=128, vocab=256,
                    slots=2, max_new=8, sync=2, block=4,
                    prompt_lens=(5, 14, 3, 12, 7, 4), long_len=21,
                    chunk=8, buckets=(8, 16), mono_bucket=32)
    return dict(layers=8, heads=16, kv=8, hidden=1024, inter=4096, vocab=32000,
                slots=8, max_new=64, sync=8, block=16,
                prompt_lens=(33, 180, 12, 250, 96, 40, 140, 64), long_len=480,
                chunk=128, buckets=(64, 128, 256), mono_bucket=512)


class _TimedBatcher:
    """Wrap a ContinuousBatcher subclass-style: record the wall gap between
    consecutive decode-window completions (the report fetch blocks until the
    window's compute lands, so on a real chip the gap IS window latency plus
    whatever prefill interleaved ahead of it)."""

    def __init__(self, engine):
        self.engine = engine
        self.window_gaps = []
        self._last_t = None
        orig = engine._process_report

        def timed(report, force_stop):
            orig(report, force_stop)
            t = time.perf_counter()
            if self._last_t is not None:
                self.window_gaps.append(t - self._last_t)
            self._last_t = t

        engine._process_report = timed


def _build_model(s):
    import jax

    from accelerate_tpu.models import Llama, LlamaConfig

    cfg = LlamaConfig.tiny(
        vocab_size=s["vocab"], hidden_size=s["hidden"],
        intermediate_size=s["inter"], num_hidden_layers=s["layers"],
        num_attention_heads=s["heads"], num_key_value_heads=s["kv"],
    )
    model = Llama(cfg)
    model.init_params(jax.random.key(0))
    return model


def probe_decode_attention(s):
    """Op-level: one decode step's attention, contiguous vs paged gather."""
    import jax.numpy as jnp

    from accelerate_tpu.ops.attention import cached_attention
    from accelerate_tpu.ops.paged_attention import paged_attention

    rng = np.random.default_rng(0)
    b, bs = s["slots"], s["block"]
    m = max(2, (max(s["prompt_lens"]) + s["max_new"]) // bs + 1)
    k_len = m * bs
    hkv, d, h = s["kv"], s["hidden"] // s["heads"], s["heads"]
    q = jnp.asarray(rng.standard_normal((b, 1, h, d)), jnp.float32)
    k_cache = jnp.asarray(rng.standard_normal((b, k_len, hkv, d)), jnp.float32)
    v_cache = jnp.asarray(rng.standard_normal((b, k_len, hkv, d)), jnp.float32)
    n = b * m + 1
    k_pool = jnp.asarray(rng.standard_normal((n, bs, hkv, d)), jnp.float32)
    v_pool = jnp.asarray(rng.standard_normal((n, bs, hkv, d)), jnp.float32)
    tables = jnp.asarray(1 + np.arange(b * m, dtype=np.int32).reshape(b, m))
    pool_mask = jnp.ones((n, bs), jnp.int32)
    kv_mask = jnp.ones((b, k_len), jnp.int32)
    q_pos = jnp.full((b, 1), k_len - 1, jnp.int32)

    import jax

    f_cont = jax.jit(lambda q, k, v: cached_attention(
        q, k, v, q_positions=q_pos, kv_mask=kv_mask))
    f_paged = jax.jit(lambda q, kp, vp: paged_attention(
        q, kp, vp, tables, q_positions=q_pos, pool_mask=pool_mask))

    def timeit(f, *args):
        out = f(*args)
        np.asarray(out[..., 0:1])
        steps = 5 if SMALL else 50
        t0 = time.perf_counter()
        for _ in range(steps):
            out = f(*args)
        np.asarray(out[..., 0:1])
        return (time.perf_counter() - t0) / steps

    t_cont = timeit(f_cont, q, k_cache, v_cache)
    t_paged = timeit(f_paged, q, k_pool, v_pool)
    return {
        "decode_attention_contiguous_ms": round(t_cont * 1e3, 4),
        "decode_attention_paged_ms": round(t_paged * 1e3, 4),
        "gather_overhead_x": round(t_paged / max(t_cont, 1e-9), 2),
    }


def probe_wave(model, s, paged: bool):
    """A mixed-length wave through one cache mode: throughput, latency
    accounting, and consumed-capacity; returns outputs for the parity join."""
    import jax.numpy as jnp

    from accelerate_tpu.serving import ContinuousBatcher

    kw = dict(batch_slots=s["slots"], max_new_tokens=s["max_new"],
              max_cache_len=4096 if not SMALL else 1024,
              cache_dtype=jnp.float32, bucket_sizes=s["buckets"],
              sync_every=s["sync"])
    if paged:
        kw.update(paged=True, block_size=s["block"])
    engine = ContinuousBatcher(model, **kw)
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, s["vocab"], (n,)).astype(np.int32)
               for n in s["prompt_lens"]]
    rids = [engine.submit(p) for p in prompts]
    t0 = time.perf_counter()
    outs = engine.run()
    dt = time.perf_counter() - t0
    gen = sum(len(outs[r]) for r in rids)
    admitted = gen + sum(p.size for p in prompts)
    report = engine.slo_report()
    return {
        "mode": "paged" if paged else "contiguous",
        "wall_s": round(dt, 4),
        "tokens_per_sec": round(gen / dt, 1),
        "admitted_tokens": admitted,
        "consumed_kv_slots_peak": engine.kv_consumed_slots_peak,
        "tokens_per_kv_slot": round(admitted / engine.kv_consumed_slots_peak, 4),
        "kv_cache_bytes": engine.kv_cache_bytes,
        "ttft_s": [round(x, 5) for x in report["ttft_s"]],
        "tpot_s": [round(x, 6) for x in report["tpot_s"]],
        # Per-request lifecycle summary (telemetry/requests.py): TTFT/TPOT
        # quantiles + the slowest-request table — summarize() hoists the
        # paged wave's copy to detail.serving.requests (schema v11).
        "requests": engine.tracer.summary() if engine.tracer is not None else None,
    }, [outs[r] for r in rids]


def probe_prefill_stall(model, s, mode: str):
    """Decode-window pacing with a long prompt admitted mid-wave ("chunked" /
    "monolithic" — both through the paged engine, so the ONLY variable is the
    chunking policy) or with no admission at all ("none" — the no-admit
    baseline the <= 2x stall criterion is measured against)."""
    import jax.numpy as jnp

    from accelerate_tpu.serving import ContinuousBatcher

    chunked = mode == "chunked"
    buckets = s["buckets"] if chunked else tuple(
        sorted(set(s["buckets"]) | {s["mono_bucket"]})
    )
    engine = ContinuousBatcher(
        model, batch_slots=s["slots"], max_new_tokens=s["max_new"],
        max_cache_len=4096 if not SMALL else 1024, cache_dtype=jnp.float32,
        bucket_sizes=buckets, sync_every=s["sync"], paged=True,
        block_size=s["block"],
        prefill_chunk=s["chunk"] if chunked else s["mono_bucket"],
        max_tokens_per_request=s["mono_bucket"] + s["max_new"] + s["chunk"],
    )
    timer = _TimedBatcher(engine)
    rng = np.random.default_rng(9)
    short = rng.integers(1, s["vocab"], (s["prompt_lens"][0],)).astype(np.int32)
    long_p = rng.integers(1, s["vocab"], (s["long_len"],)).astype(np.int32)
    engine.submit(short)       # establishes the decode wave
    if mode != "none":
        engine.submit(long_p)  # admitted mid-wave: the stall source
    outs = engine.run()
    # Drop the first gap: it carries the one-time chunk/decode program
    # compiles, which on tiny smoke shapes dwarf the steady-state window.
    gaps = timer.window_gaps[1:] if len(timer.window_gaps) > 1 \
        else timer.window_gaps or [0.0]
    chunks = sum(1 for e in engine._dispatch_log if e.startswith("chunk"))
    return {
        "mode": mode,
        "prefill_dispatches": chunks,
        "max_window_gap_s": round(max(gaps), 5),
        "mean_window_gap_s": round(sum(gaps) / len(gaps), 5),
        "max_decode_step_stall_s": round(max(gaps) / s["sync"], 6),
    }, outs


def summarize(model=None):
    """Run every probe; returns the ``detail.serving`` dict for bench.py."""
    s = _shapes()
    if model is None:
        model = _build_model(s)
    out = {"small": SMALL, "sync_every": s["sync"], "block_size": s["block"]}
    out.update(probe_decode_attention(s))
    wave_c, outs_c = probe_wave(model, s, paged=False)
    wave_p, outs_p = probe_wave(model, s, paged=True)
    identical = all(np.array_equal(a, b) for a, b in zip(outs_c, outs_p))
    # The request-trace summary rides once at the top level (schema v11
    # detail.serving.requests) — the paged wave is the production shape.
    wave_c.pop("requests", None)
    out["requests"] = wave_p.pop("requests", None)
    out["wave_contiguous"] = wave_c
    out["wave_paged"] = wave_p
    out["outputs_identical"] = bool(identical)
    out["effective_capacity_x"] = round(
        wave_p["tokens_per_kv_slot"] / wave_c["tokens_per_kv_slot"], 2
    )
    none, _ = probe_prefill_stall(model, s, mode="none")
    mono, _ = probe_prefill_stall(model, s, mode="monolithic")
    chk, _ = probe_prefill_stall(model, s, mode="chunked")
    out["prefill_no_admit"] = none
    out["prefill_monolithic"] = mono
    out["prefill_chunked"] = chk
    out["stall_ratio_chunked_vs_monolithic"] = round(
        chk["max_window_gap_s"] / max(mono["max_window_gap_s"], 1e-9), 3
    )
    # The acceptance criterion's shape: chunked admission vs the no-admit
    # baseline (<= 2x on a compute-dominated rig; dispatch/compile-dominated
    # smoke shapes inflate it — read it from a real-chip BENCH_SERVING row).
    out["stall_ratio_chunked_vs_no_admit"] = round(
        chk["max_window_gap_s"] / max(none["max_window_gap_s"], 1e-9), 3
    )
    return out


def main():
    summary = summarize()
    for key in ("decode_attention_contiguous_ms", "decode_attention_paged_ms",
                "gather_overhead_x"):
        print(json.dumps({"probe": key, "value": summary[key]}))
    for key in ("wave_contiguous", "wave_paged", "prefill_no_admit",
                "prefill_monolithic", "prefill_chunked"):
        print(json.dumps({"probe": key, **summary[key]}))
    print(json.dumps({
        "probe": "headline",
        "outputs_identical": summary["outputs_identical"],
        "effective_capacity_x": summary["effective_capacity_x"],
        "stall_ratio_chunked_vs_monolithic":
            summary["stall_ratio_chunked_vs_monolithic"],
        "stall_ratio_chunked_vs_no_admit":
            summary["stall_ratio_chunked_vs_no_admit"],
    }))


if __name__ == "__main__":
    main()
