"""Attribute the int8 KV-cache pool on the paged serving engine.

int8 KV blocks store quantized k/v with one f32 scale per token row, so the
pool holds ~2x the tokens per HBM byte (the exact ratio is
``4HD / (2HD + 8)`` per token — per-token scales amortize away as the head
dim grows). The cost is a dequant on every gather, which the Pallas paged
kernels fold into the DMA-to-VMEM step. This profile prices both sides:

- ``pool_capacity``: ``kv_cache_bytes`` for the fp32 vs int8 pool at the
  same block count — the capacity_x ratio IS the >= 1.8x acceptance gate.
- ``gather_{fp,int8}``: op-level view assembly (``gather_view``) against
  each pool layout — the dequant tax at the seam the kernel optimizes.
- ``wave_{fp,int8}``: the mixed-length wave in each pool dtype —
  tokens/s plus the token-level divergence count (quantization noise is
  allowed; the pinned tolerance lives in tests/test_speculative.py).

Prints one JSON line per probe; ``summarize()`` returns the dict bench.py
embeds as ``detail.serving.kv_quant`` under ``BENCH_KV_QUANT=1``.
``BENCH_PROFILE_SMALL=1`` shrinks everything for CPU smoke runs.

Usage: python benchmarks/kv_quant_profile.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


SMALL = os.environ.get("BENCH_PROFILE_SMALL", "0") == "1"


def _shapes():
    if SMALL:
        return dict(layers=2, heads=4, kv=2, hidden=128, inter=256, vocab=256,
                    slots=2, max_new=8, sync=2, block=4,
                    prompt_lens=(5, 14, 3, 12, 7, 4), buckets=(8, 16))
    return dict(layers=8, heads=16, kv=8, hidden=1024, inter=4096, vocab=32000,
                slots=8, max_new=64, sync=8, block=16,
                prompt_lens=(33, 180, 12, 250, 96, 40, 140, 64),
                buckets=(64, 128, 256))


def _build_model(s):
    import jax

    from accelerate_tpu.models import Llama, LlamaConfig

    cfg = LlamaConfig.tiny(
        vocab_size=s["vocab"], hidden_size=s["hidden"],
        intermediate_size=s["inter"], num_hidden_layers=s["layers"],
        num_attention_heads=s["heads"], num_key_value_heads=s["kv"],
    )
    model = Llama(cfg)
    model.init_params(jax.random.key(0))
    return model


def probe_gather(s):
    """Op-level: paged view assembly from an fp32 vs int8 pool at the same
    logical shape — the dequant tax at the DMA seam."""
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.ops.paged_attention import gather_view

    rng = np.random.default_rng(0)
    b, bs = s["slots"], s["block"]
    m = max(2, (max(s["prompt_lens"]) + s["max_new"]) // bs + 1)
    hkv, d = s["kv"], s["hidden"] // s["heads"]
    n = b * m + 1
    pool_f = jnp.asarray(rng.standard_normal((n, bs, hkv, d)), jnp.float32)
    scale = jnp.abs(pool_f).max(axis=(-2, -1)) / 127.0
    pool_q = jnp.round(pool_f / scale[..., None, None]).astype(jnp.int8)
    tables = jnp.asarray(1 + np.arange(b * m, dtype=np.int32).reshape(b, m))

    f_fp = jax.jit(lambda p: gather_view(p, tables))
    f_q = jax.jit(lambda p, sc: gather_view(p, tables, scales=sc,
                                            out_dtype=jnp.float32))

    def timeit(f, *args):
        out = f(*args)
        np.asarray(out[..., 0:1])
        steps = 5 if SMALL else 50
        t0 = time.perf_counter()
        for _ in range(steps):
            out = f(*args)
        np.asarray(out[..., 0:1])
        return (time.perf_counter() - t0) / steps

    t_fp = timeit(f_fp, pool_f)
    t_q = timeit(f_q, pool_q, scale)
    return {
        "gather_fp_ms": round(t_fp * 1e3, 4),
        "gather_int8_ms": round(t_q * 1e3, 4),
        "dequant_overhead_x": round(t_q / max(t_fp, 1e-9), 2),
    }


def probe_wave(model, s, quant: bool):
    import jax.numpy as jnp

    from accelerate_tpu.serving import ContinuousBatcher

    engine = ContinuousBatcher(
        model, batch_slots=s["slots"], max_new_tokens=s["max_new"],
        max_cache_len=4096 if not SMALL else 1024, cache_dtype=jnp.float32,
        bucket_sizes=s["buckets"], sync_every=s["sync"], paged=True,
        block_size=s["block"], kv_quant="int8" if quant else None,
    )
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, s["vocab"], (n,)).astype(np.int32)
               for n in s["prompt_lens"]]
    rids = [engine.submit(p) for p in prompts]
    t0 = time.perf_counter()
    outs = engine.run()
    dt = time.perf_counter() - t0
    gen = sum(len(outs[r]) for r in rids)
    return {
        "mode": "int8" if quant else "fp",
        "wall_s": round(dt, 4),
        "tokens_per_sec": round(gen / dt, 1),
        "kv_cache_bytes": engine.kv_cache_bytes,
    }, [outs[r] for r in rids]


def summarize(model=None):
    """Run every probe; returns the ``detail.serving.kv_quant`` dict."""
    s = _shapes()
    if model is None:
        model = _build_model(s)
    out = {"small": SMALL, "block_size": s["block"]}
    out.update(probe_gather(s))
    wave_f, outs_f = probe_wave(model, s, quant=False)
    wave_q, outs_q = probe_wave(model, s, quant=True)
    out["wave_fp"] = wave_f
    out["wave_int8"] = wave_q
    out["pool_capacity_x"] = round(
        wave_f["kv_cache_bytes"] / max(wave_q["kv_cache_bytes"], 1), 3)
    total = sum(len(a) for a in outs_f)
    diverged = sum(
        int(np.sum(np.asarray(a)[: min(len(a), len(b))]
                   != np.asarray(b)[: min(len(a), len(b))]))
        + abs(len(a) - len(b))
        for a, b in zip(outs_f, outs_q)
    )
    out["tokens_total"] = total
    out["tokens_diverged"] = int(diverged)
    out["divergence_fraction"] = round(diverged / max(total, 1), 4)
    return out


def main():
    summary = summarize()
    for key in ("gather_fp_ms", "gather_int8_ms", "dequant_overhead_x"):
        print(json.dumps({"probe": key, "value": summary[key]}))
    for key in ("wave_fp", "wave_int8"):
        print(json.dumps({"probe": key, **summary[key]}))
    print(json.dumps({
        "probe": "headline",
        "pool_capacity_x": summary["pool_capacity_x"],
        "divergence_fraction": summary["divergence_fraction"],
    }))


if __name__ == "__main__":
    main()
