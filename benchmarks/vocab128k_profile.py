"""Attribute the vocab128k train step's time at the op level on the real chip.

The `BENCH_CONFIG=vocab128k` row (Llama-3.2-proportioned h2048/i8192, V=128256
tied) trails the swept-shape headline because of non-matmul overhead that an
end-to-end MFU number cannot localize. This script times fwd+bwd of each piece
at the bench shape so the tax is measured, not guessed:

- ``embed``: the (V, h) table lookup (+ scatter-add backward);
- ``block`` / ``mlp``: one decoder layer and its SwiGLU FFN in isolation
  (attention ≈ block − mlp);
- ``layers_<policy>``: the full L-layer remat'd scan per BENCH_REMAT_POLICY;
- ``head_dense``: final norm + full-logit matmul + CE (the path that cannot
  compile at b8 on a 16G chip — expect OOM there, that is the finding);
- ``head_fused_*``: the vocab-chunked streaming CE across the sweep surface —
  chunk sizes (BENCH_VOCAB_CHUNK, comma list), chunk dtype (BENCH_FUSED_DTYPE),
  backward strategy (BENCH_FUSED_BWD: custom|ad|both) and scan unroll
  (BENCH_FUSED_UNROLL).

The same envs drive bench.py's vocab128k config, so a winning knob found here
is re-checked end-to-end by exporting the identical variables. Model code runs
under ``jax.named_scope`` tags (embed/attn/mlp/lm_head), so a captured profile
(``jax.profiler.trace``) attributes to the same names these probes use.

Prints one JSON line per probe. BENCH_PROFILE_SMALL=1 shrinks every dimension
for CPU smoke runs (used by the test suite).

Usage: python benchmarks/vocab128k_profile.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

SMALL = os.environ.get("BENCH_PROFILE_SMALL", "0") == "1"
if SMALL:
    H, I, V, L, HEADS, KV, B, S = 64, 128, 1000, 2, 4, 2, 2, 32
    STEPS, WARMUP = 3, 1
    CHUNKS = [int(c) for c in os.environ.get("BENCH_VOCAB_CHUNK", "256,512").split(",")]
else:
    H, I, V, L, HEADS, KV, B, S = 2048, 8192, 128256, 8, 32, 8, 8, 1024
    STEPS, WARMUP = 20, 3
    CHUNKS = [int(c) for c in os.environ.get("BENCH_VOCAB_CHUNK", "4096,8192,16384,32768").split(",")]
T = B * S
DTYPE = jnp.bfloat16


def bench(name, fn, *args, flops=None, grad_argnums=0):
    f = jax.jit(jax.grad(lambda *a: fn(*a).astype(jnp.float32).sum(), argnums=grad_argnums))
    try:
        for _ in range(WARMUP):
            out = f(*args)
    except Exception as exc:  # OOM / compile rejection IS a datapoint
        print(json.dumps({"probe": name, "error": f"{type(exc).__name__}: {exc}"[:200]}))
        return None
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(leaf[..., 0:1])  # tunnel-safe sync
    t0 = time.perf_counter()
    for _ in range(STEPS):
        out = f(*args)
    np.asarray(jax.tree_util.tree_leaves(out)[0][..., 0:1])
    dt = (time.perf_counter() - t0) / STEPS
    rec = {"probe": name, "ms": round(dt * 1e3, 3)}
    if flops:
        rec["tflops_s"] = round(flops / dt / 1e12, 1)
    print(json.dumps(rec))
    return dt


def main():
    from accelerate_tpu.models import Llama, LlamaConfig
    from accelerate_tpu.ops.losses import cross_entropy_loss, fused_cross_entropy_loss

    rng = np.random.default_rng(0)
    cfg = LlamaConfig(
        vocab_size=V, hidden_size=H, intermediate_size=I,
        num_hidden_layers=L, num_attention_heads=HEADS, num_key_value_heads=KV,
        max_position_embeddings=S, tie_word_embeddings=True,
    )
    model = Llama(cfg)
    params = jax.tree_util.tree_map(
        lambda t: t.astype(DTYPE), model.init_params(jax.random.key(0))
    )
    ids = jnp.asarray(rng.integers(0, V, (B, S)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, V, (B, S)), jnp.int32)
    x = jnp.asarray(rng.standard_normal((B, S, H)), DTYPE)
    table = params["embed"]["weight"]  # (V, H) — the tied head, native layout

    # (a) embedding lookup + scatter-add backward.
    def embed_fn(table):
        h, _ = model.embed({"embed": {"weight": table}}, ids)
        return h

    bench("embed", embed_fn, table)

    # (b) one decoder block and its FFN alone (attention ≈ block − mlp).
    layer = jax.tree_util.tree_map(lambda t: t[0], params["layers"])
    _, ctx = model.embed(params, ids)
    block_flops = 3 * 2 * T * (H * (HEADS + 2 * KV) * cfg.head_dim + HEADS * cfg.head_dim * H + 3 * H * I)
    mlp_flops = 3 * 2 * T * 3 * H * I
    bench("block", lambda x: model.block(layer, x, ctx), x, flops=block_flops)
    bench("mlp", lambda x: model.mlp(layer, x), x, flops=mlp_flops)

    # (c) the full remat'd layer stack per policy (BENCH_REMAT_POLICY, comma
    # list; names_saveable exercises the checkpoint_name tags).
    policies = os.environ.get(
        "BENCH_REMAT_POLICY", "dots_with_no_batch_dims_saveable,names_saveable"
    ).split(",")
    for policy in [p.strip() for p in policies if p.strip()]:
        import dataclasses

        m2 = Llama(dataclasses.replace(cfg, remat=True, remat_policy=policy))

        def layers_fn(x, _m=m2):
            out, _ = _m._run_layers(params["layers"], x, ctx)
            return out

        bench(f"layers_{policy}", layers_fn, x, flops=L * block_flops)

    # (d) the head: dense full-logit CE vs the fused sweep. 3 matmul passes
    # (fwd + dx + dw) for dense; the fused custom backward pays 4 (fwd +
    # recompute + dx + dw), its structural overhead.
    head_flops_dense = 3 * 2 * T * H * V
    head_flops_fused = 4 * 2 * T * H * V
    shifted = jnp.asarray(labels)

    def head_dense(x, table):
        logits = jax.lax.dot_general(x, table.astype(x.dtype), (((2,), (1,)), ((), ())))
        return cross_entropy_loss(logits, shifted)

    bench("head_dense", head_dense, x, table, flops=head_flops_dense, grad_argnums=(0, 1))

    dtypes = [d for d in os.environ.get("BENCH_FUSED_DTYPE", "fp32,bf16").split(",") if d]
    bwds = os.environ.get("BENCH_FUSED_BWD", "both")
    bwds = ["custom", "ad"] if bwds == "both" else [bwds]
    unroll = int(os.environ.get("BENCH_FUSED_UNROLL", "1"))
    for chunk in CHUNKS:
        for cd in dtypes:
            for bwd in bwds:

                def head_fused(x, table, _c=chunk, _cd=cd, _b=bwd):
                    return fused_cross_entropy_loss(
                        x, table.astype(x.dtype), shifted,
                        vocab_chunk=_c, chunk_dtype=_cd, unroll=unroll,
                        head_transposed=True, custom_backward=_b == "custom",
                    )

                bench(
                    f"head_fused_c{chunk}_{cd}_{bwd}", head_fused, x, table,
                    flops=head_flops_fused, grad_argnums=(0, 1),
                )


if __name__ == "__main__":
    main()
