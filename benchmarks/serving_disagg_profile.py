"""Profile the disaggregated serving path end-to-end over real HTTP.

``serving_decode_profile.py`` attributes the single-host engine; this script
stands up the full ``serving_net`` rig IN one process — a prefill worker, a
decode worker, and an affinity router, each behind its own loopback
``MetricsServer`` — and drives it through the actual wire format (POST
/v1/generate against the router, SSE frames back), so every number is
measured through the same code path a multi-host fleet runs:

- **routing split**: which tier each request entered (the SLO sentinel's
  arbitration — single-chunk prompts decode where they land, multi-chunk
  prompts enter the prefill tier) plus the router's affinity hit rate.
  NOTE: in a pure prefill/decode rig the hit rate measures 0 by design —
  ``export_chain`` frees the prefill host's chain and ``import_chain`` keeps
  imported blocks private, so only prefixes left resident on a decode
  worker by its OWN single-chunk requests can match.
- **handoff volume**: chains/blocks/bytes shipped prefill → decode, read
  from the prefill engine's tracer records (per-request attribution, not
  process-global counters).
- **per-tier latency**: each tier's TTFT/TPOT quantiles from its own
  tracer, so the handoff RTT shows up as the prefill-entry TTFT tax the
  arbitration policy trades against decode-tier TPOT protection.
- **parity**: the same prompts through one unified engine with identical
  kwargs — disaggregated greedy output must be bit-identical
  (``outputs_identical``), and every relayed stream's ``done`` trace must
  span router → prefill → decode (``trace_spans_tiers``).

Prints one JSON line per probe; ``summarize()`` returns the dict bench.py
embeds as ``detail.serving.routing`` under ``BENCH_SERVING_DISAGG=1``
(schema v12). ``BENCH_PROFILE_SMALL=1`` shrinks everything for CPU smoke
runs (the test suite's path).

Usage: python benchmarks/serving_disagg_profile.py
"""

import json
import os
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


SMALL = os.environ.get("BENCH_PROFILE_SMALL", "0") == "1"


def _shapes():
    if SMALL:
        # 5/3-token prompts fit one 8-token chunk (decode entry); 14/21 are
        # multi-chunk (prefill entry, chain handoff). The trailing repeat of
        # the first prompt probes affinity against whatever its first pass
        # left resident on the decode worker.
        return dict(layers=2, heads=4, kv=2, hidden=64, inter=128, vocab=256,
                    slots=2, max_new=8, sync=2, block=4, chunk=8,
                    buckets=(8, 16), cache=1024,
                    prompt_lens=(5, 14, 3, 21), repeat_first=True)
    return dict(layers=8, heads=16, kv=8, hidden=1024, inter=4096, vocab=32000,
                slots=8, max_new=64, sync=8, block=16, chunk=128,
                buckets=(64, 128, 256), cache=4096,
                prompt_lens=(33, 180, 12, 250, 96, 480), repeat_first=True)


def _build_model(s):
    import jax

    from accelerate_tpu.models import Llama, LlamaConfig

    cfg = LlamaConfig.tiny(
        vocab_size=s["vocab"], hidden_size=s["hidden"],
        intermediate_size=s["inter"], num_hidden_layers=s["layers"],
        num_attention_heads=s["heads"], num_key_value_heads=s["kv"],
    )
    model = Llama(cfg)
    model.init_params(jax.random.key(0))
    return model


def _engine(model, s):
    """One paged engine; the prefill tier, the decode tier, and the unified
    parity baseline all build from THESE kwargs — identical programs, so the
    only variable between rigs is where the chain lives."""
    import jax.numpy as jnp

    from accelerate_tpu.serving import ContinuousBatcher

    return ContinuousBatcher(
        model, batch_slots=s["slots"], max_new_tokens=s["max_new"],
        max_cache_len=s["cache"], cache_dtype=jnp.float32,
        bucket_sizes=s["buckets"], sync_every=s["sync"], paged=True,
        block_size=s["block"], prefill_chunk=s["chunk"],
        max_tokens_per_request=max(s["prompt_lens"]) + s["max_new"] + s["chunk"],
    )


def _start_worker(engine, role):
    """One serving worker on a loopback port: its own MetricsServer with the
    frontend attached per-server (the multi-role single-process rig)."""
    from accelerate_tpu.serving_net import ServingFrontend
    from accelerate_tpu.telemetry.metrics import MetricsServer

    server = MetricsServer(0, host="127.0.0.1")
    port = server.start()
    endpoint = f"127.0.0.1:{port}"
    frontend = ServingFrontend(engine, role=role)
    frontend.install(server=server, endpoint=endpoint)
    return server, frontend, endpoint


def _generate(endpoint, prompt, max_new):
    """One client request through the real wire format."""
    from accelerate_tpu.serving_net.frontend import read_sse_response

    req = urllib.request.Request(
        f"http://{endpoint}/v1/generate",
        data=json.dumps({"prompt": [int(t) for t in prompt],
                         "max_new_tokens": int(max_new)}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=300.0) as response:
        return read_sse_response(response)


def _tier_summary(tracer):
    """The per-tier latency slice of a tracer summary (the slowest-N table
    stays out of the bench row — it is debugging payload, not a metric)."""
    if tracer is None:
        return None
    summary = tracer.summary()
    return {key: summary.get(key)
            for key in ("total", "states", "ttft_s", "tpot_s")}


def probe_disagg(model, s):
    """Drive the 3-tier rig through the router; returns the routing payload
    plus each request's streamed tokens for the parity join."""
    from accelerate_tpu.serving_net import Router
    from accelerate_tpu.serving_net.router import reset_serving_registry
    from accelerate_tpu.telemetry.metrics import MetricsServer

    prefill_engine = _engine(model, s)
    decode_engine = _engine(model, s)
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, s["vocab"], (n,)).astype(np.int32)
               for n in s["prompt_lens"]]
    if s["repeat_first"]:
        prompts.append(prompts[0].copy())

    servers, frontends = [], []
    try:
        server, frontend, prefill_ep = _start_worker(prefill_engine, "prefill")
        servers.append(server)
        frontends.append(frontend)
        server, frontend, decode_ep = _start_worker(decode_engine, "decode")
        servers.append(server)
        frontends.append(frontend)
        router_server = MetricsServer(0, host="127.0.0.1")
        router_port = router_server.start()
        servers.append(router_server)
        router = Router(workers=[
            {"rank": 0, "role": "prefill", "endpoint": prefill_ep},
            {"rank": 1, "role": "decode", "endpoint": decode_ep},
        ])
        router.install(server=router_server,
                       endpoint=f"127.0.0.1:{router_port}")
        router_ep = f"127.0.0.1:{router_port}"

        results = [None] * len(prompts)
        errors = []

        def client(i, prompt):
            try:
                results[i] = _generate(router_ep, prompt, s["max_new"])
            except Exception as exc:  # surfaced after join — not swallowed
                errors.append(f"request {i}: {exc!r}")

        # The original mix rides concurrently (continuous batching on both
        # tiers); the repeat goes AFTER the joined wave so its affinity
        # probe sees whatever pass one left resident.
        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(i, p))
                   for i, p in enumerate(prompts[: len(s["prompt_lens"])])]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join()
        if s["repeat_first"]:
            client(len(prompts) - 1, prompts[-1])
        wall_s = time.perf_counter() - t0
        if errors:
            raise RuntimeError("; ".join(errors))

        handoff = {"chains": 0, "blocks": 0, "bytes": 0}
        if prefill_engine.tracer is not None:
            for record in prefill_engine.tracer.records():
                leg = record.get("handoff")
                if leg and leg.get("direction") == "out":
                    handoff["chains"] += 1
                    handoff["blocks"] += int(leg.get("blocks", 0))
                    handoff["bytes"] += int(leg.get("bytes", 0))

        spans = []
        for result in results:
            tiers = [r.get("tier") for r in result["done"].get("trace", [])]
            spans.append(tiers)
        trace_spans_tiers = all(
            t[0] == "router" and t[-1] == "decode"
            and ("prefill" in t) == (len(t) == 3)
            for t in spans
        )
        stats = router.stats()
        payload = {
            "requests": len(prompts),
            "wall_s": round(wall_s, 4),
            "routed": stats["routed"],
            "affinity_hits": stats["affinity_hits"],
            "affinity_hit_rate": stats["affinity_hit_rate"],
            "handoff": handoff,
            "trace_spans_tiers": bool(trace_spans_tiers),
            "tiers": {
                "router": _tier_summary(router.tracer),
                "prefill": _tier_summary(prefill_engine.tracer),
                "decode": _tier_summary(decode_engine.tracer),
            },
        }
        return payload, [r["tokens"] for r in results], prompts
    finally:
        for frontend in frontends:
            frontend.uninstall()
        for server in servers:
            server.stop()
        reset_serving_registry()


def probe_unified(model, s, prompts):
    """The parity baseline: the SAME prompts through one unified engine with
    identical kwargs — greedy output must be bit-identical to the routed
    path (handoff is state surgery, never a recompute)."""
    engine = _engine(model, s)
    rids = [engine.submit(p) for p in prompts]
    outs = engine.run()
    return [[int(t) for t in outs[r]] for r in rids]


def summarize(model=None):
    """Run the rig; returns the ``detail.serving.routing`` dict for bench.py
    (schema v12, BENCH_SERVING_DISAGG=1)."""
    s = _shapes()
    if model is None:
        model = _build_model(s)
    payload, disagg_tokens, prompts = probe_disagg(model, s)
    unified_tokens = probe_unified(model, s, prompts)
    payload["small"] = SMALL
    payload["prefill_chunk"] = s["chunk"]
    payload["outputs_identical"] = bool(
        len(disagg_tokens) == len(unified_tokens)
        and all(a == b for a, b in zip(disagg_tokens, unified_tokens))
    )
    return payload


def main():
    summary = summarize()
    print(json.dumps({"probe": "routing", "routed": summary["routed"],
                      "affinity_hits": summary["affinity_hits"],
                      "affinity_hit_rate": summary["affinity_hit_rate"]}))
    print(json.dumps({"probe": "handoff", **summary["handoff"]}))
    for tier, stats in summary["tiers"].items():
        print(json.dumps({"probe": f"tier_{tier}", **(stats or {})}))
    print(json.dumps({
        "probe": "headline",
        "requests": summary["requests"],
        "wall_s": summary["wall_s"],
        "outputs_identical": summary["outputs_identical"],
        "trace_spans_tiers": summary["trace_spans_tiers"],
    }))


if __name__ == "__main__":
    main()
