"""Op-level kernel-vs-reference grid for the Pallas kernel layer.

ROADMAP item 3's acceptance is attributed, not guessed: every registered
kernel op (ops/registry.py) is measured against its committed reference
lowering probe-by-probe, the way ``vocab128k_profile.py`` attributes the
fused-loss sweep and ``serving_decode_profile.py`` the serving wave:

- ``paged_decode``: the fused ragged decode-attention kernel (in-kernel
  block-chain walk) vs the reference gather + ``cached_attention``
  composition, across chain lengths and padded-slot fractions (the kernel
  skips dead slots; the reference pays full price for garbage).
- ``paged_gather``: the chain-walk view assembly vs the XLA block-table
  gather — the serving engine's per-window cost.
- ``fused_update``: the one-pass clip+moments+apply+cast kernel vs the optax
  reference chain on an adamw leaf set (parity is float-equivalent across
  the two modules — see docs/kernels.md; the value probe reports max ulp-
  scale deviation alongside the timing).
- ``int8_matmul``: the fused quantize+contract+rescale kernel vs the
  reference three-pass lowering (bit-exact).

One JSON line per (op, backend) cell: ``{op, backend, shape, mean_ms,
speedup_vs_reference, match}``. On CPU the kernel backend is the Pallas
interpreter — correctness evidence, not a perf claim (interpret mode trades
speed for exactness); the perf columns become meaningful on a TPU rig where
``pallas`` resolves to compiled Mosaic (BENCH_KERNELS=pallas in a bench
round embeds the train-step side as ``detail.kernels``).

``BENCH_PROFILE_SMALL=1`` shrinks shapes for CPU smoke runs (the test
suite's path). ``summarize()`` returns {op: {backend: cell}}.

Usage: python benchmarks/kernel_profile.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

SMALL = os.environ.get("BENCH_PROFILE_SMALL", "0") == "1"
REPS = 3 if SMALL else 10


def _shapes():
    if SMALL:
        return dict(slots=4, blocks=24, block=4, chain=4, kv=2, heads=4,
                    head_dim=8, layers=2, leaf=(64, 64), mm=(32, 64, 48))
    return dict(slots=16, blocks=512, block=16, chain=24, kv=8, heads=16,
                head_dim=128, layers=8, leaf=(2048, 2048), mm=(512, 2048, 2048))


def _backends():
    """reference always; the kernel cell is pallas on TPU, interpret off-TPU
    (the registry's own degradation — recorded per cell)."""
    from accelerate_tpu.ops.registry import pallas_supported

    return ["reference", "pallas" if pallas_supported() else "interpret"]


def _timeit(fn, *args):
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    times = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    return out, float(np.mean(times)) * 1e3


def _cell(op, backend, shape, mean_ms, ref_ms, match):
    cell = {
        "op": op,
        "backend": backend,
        "shape": shape,
        "mean_ms": round(mean_ms, 3),
        "speedup_vs_reference": round(ref_ms / mean_ms, 3) if mean_ms else None,
        "match": match,
    }
    print(json.dumps(cell))
    return cell


def probe_paged_decode(s):
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.ops.paged_attention import paged_attention

    rng = np.random.default_rng(0)
    N, bs, Hkv, D = s["blocks"], s["block"], s["kv"], s["head_dim"]
    B, M, H = s["slots"], s["chain"], s["heads"]
    kp = jnp.asarray(rng.normal(size=(N + 1, bs, Hkv, D)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(N + 1, bs, Hkv, D)), jnp.float32)
    mask = jnp.asarray(rng.integers(0, 2, (N + 1, bs)), jnp.int32).at[0].set(0)
    tables = jnp.asarray(rng.integers(1, N + 1, (B, M)), jnp.int32)
    q = jnp.asarray(rng.normal(size=(B, 1, H, D)), jnp.float32)
    pos = jnp.asarray(rng.integers(0, M * bs, (B, 1)), jnp.int32)
    active = jnp.asarray([1] * (B // 2) + [0] * (B - B // 2), jnp.int32)
    shape = f"B{B}xM{M}xbs{bs}xH{H}xD{D}"

    cells = {}
    ref = None
    ref_ms = None
    for backend in _backends():
        fn = jax.jit(lambda *a, _b=backend: paged_attention(
            *a, q_positions=pos, pool_mask=mask, active=active, backend=_b))
        out, ms = _timeit(fn, q, kp, vp, tables)
        if backend == "reference":
            ref, ref_ms = out, ms
            match = True
        else:
            # Active slots must agree bit-for-bit; the kernel skips the rest.
            na = int(np.sum(np.asarray(active)))
            match = bool(
                (np.asarray(out)[:na] == np.asarray(ref)[:na]).all()
            )
        cells[backend] = _cell("paged_decode", backend, shape, ms, ref_ms, match)
    return cells


def probe_paged_gather(s):
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.ops.paged_attention import gather_view

    rng = np.random.default_rng(1)
    N, bs, Hkv, D, L = s["blocks"], s["block"], s["kv"], s["head_dim"], s["layers"]
    B, M = s["slots"], s["chain"]
    pool = jnp.asarray(rng.normal(size=(L, N + 1, bs, Hkv, D)), jnp.float32)
    tables = jnp.asarray(rng.integers(1, N + 1, (B, M)), jnp.int32)
    shape = f"L{L}xB{B}xM{M}xbs{bs}"

    cells = {}
    ref = None
    ref_ms = None
    for backend in _backends():
        fn = jax.jit(lambda p, t, _b=backend: gather_view(p, t, backend=_b))
        out, ms = _timeit(fn, pool, tables)
        if backend == "reference":
            ref, ref_ms = out, ms
            match = True
        else:
            match = bool((np.asarray(out) == np.asarray(ref)).all())
        cells[backend] = _cell("paged_gather", backend, shape, ms, ref_ms, match)
    return cells


def probe_fused_update(s):
    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu.ops.pallas.fused_update import (
        fused_update_apply,
        plan_fused_update,
        reference_update_apply,
    )

    rng = np.random.default_rng(2)
    tx = optax.adamw(3e-4)
    plan = plan_fused_update(tx)
    params = {f"w{i}": jnp.asarray(rng.normal(size=s["leaf"]), jnp.float32)
              for i in range(2 if SMALL else 4)}
    grads = {k: jnp.asarray(rng.normal(size=v.shape), jnp.float32)
             for k, v in params.items()}
    state = tx.init(params)
    factor = jnp.float32(1.0)
    shape = f"{len(params)}x{s['leaf'][0]}x{s['leaf'][1]}"

    cells = {}
    ref = None
    ref_ms = None
    for backend in _backends():
        if backend == "reference":
            fn = jax.jit(lambda p, st, g: reference_update_apply(
                p, st, g, tx=tx, clip_factor=factor))
        else:
            fn = jax.jit(lambda p, st, g, _i=(backend == "interpret"):
                         fused_update_apply(p, st, g, plan=plan,
                                            clip_factor=factor, interpret=_i))
        out, ms = _timeit(fn, params, state, grads)
        if backend == "reference":
            ref, ref_ms = out, ms
            match = True
        else:
            # Two different XLA modules: float-equivalent, not bitwise
            # (docs/kernels.md); record the max deviation on params.
            dev = max(
                float(np.abs(np.asarray(a, np.float64) - np.asarray(b, np.float64)).max())
                for a, b in zip(ref[0].values(), out[0].values())
            )
            match = {"max_param_dev": dev, "close": bool(dev < 1e-5)}
        cells[backend] = _cell("fused_update", backend, shape, ms, ref_ms, match)
    return cells


def probe_int8_matmul(s):
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.ops.int8 import _int8_matmul_fwd_value
    from accelerate_tpu.ops.pallas.int8_mm import int8_matmul_kernel

    rng = np.random.default_rng(3)
    M, K, N = s["mm"]
    x = jnp.asarray(rng.normal(size=(M, K)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(K, N)), jnp.float32)
    shape = f"{M}x{K}x{N}"

    cells = {}
    ref = None
    ref_ms = None
    for backend in _backends():
        if backend == "reference":
            fn = jax.jit(_int8_matmul_fwd_value)
        else:
            fn = jax.jit(lambda x, w, _i=(backend == "interpret"):
                         int8_matmul_kernel(x, w, interpret=_i))
        out, ms = _timeit(fn, x, w)
        if backend == "reference":
            ref, ref_ms = out, ms
            match = True
        else:
            match = bool((np.asarray(out) == np.asarray(ref)).all())
        cells[backend] = _cell("int8_matmul", backend, shape, ms, ref_ms, match)
    return cells


def summarize() -> dict:
    s = _shapes()
    return {
        "paged_decode": probe_paged_decode(s),
        "paged_gather": probe_paged_gather(s),
        "fused_update": probe_fused_update(s),
        "int8_matmul": probe_int8_matmul(s),
    }


if __name__ == "__main__":
    summarize()
