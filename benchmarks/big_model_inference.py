"""Big-model-inference benchmark: load-time + s/token for dispatched models.

Counterpart of the reference's ``benchmarks/big_model_inference/
big_model_inference.py`` (load a checkpoint with a device_map — possibly
CPU/disk-offloaded — and measure model load time and generation latency;
published numbers in BASELINE.md's big-model table).

Scenarios measured, each printed as one JSON line:
  1. ``on_chip``      — checkpoint → load_checkpoint_and_dispatch(device_map
     'auto') with everything HBM-resident; fused scan-decode generation.
  2. ``cpu_offload``  — layers forced to host RAM, streamed per token
     (StreamedScanModel double-buffered DMA) — the OPT-30B-style config.
  3. ``disk_offload`` — layers memmapped from disk (GPT-NeoX-fp32-style).

Usage: python benchmarks/big_model_inference.py [tiny|medium|1b|3b] [--tokens N]
Default size: 1b on TPU, tiny elsewhere.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

SIZES = {
    # name -> (hidden, inter, layers, heads, kv_heads, vocab)
    "tiny": (64, 128, 2, 4, 2, 256),
    "medium": (512, 1408, 8, 8, 4, 8192),
    "1b": (2048, 5632, 22, 16, 4, 32000),
    "3b": (3072, 8192, 26, 24, 8, 32000),
}


def build(size: str):
    from accelerate_tpu.models import Llama, LlamaConfig

    h, inter, L, nh, nkv, vocab = SIZES[size]
    cfg = LlamaConfig(
        vocab_size=vocab, hidden_size=h, intermediate_size=inter,
        num_hidden_layers=L, num_attention_heads=nh, num_key_value_heads=nkv,
        max_position_embeddings=2048,
    )
    return Llama(cfg)


def run_scenario(name, size, checkpoint, device_map, offload_dir, prompt_len, n_tokens):
    import jax
    import jax.numpy as jnp

    from accelerate_tpu import load_checkpoint_and_dispatch
    from accelerate_tpu.big_modeling import init_empty_weights
    from accelerate_tpu.generation import generate

    with init_empty_weights():
        model = build(size)
        model.init_params(jax.random.key(0))

    t0 = time.perf_counter()
    model = load_checkpoint_and_dispatch(
        model, checkpoint, device_map=device_map, offload_folder=offload_dir
    )
    load_time = time.perf_counter() - t0

    ids = np.random.default_rng(0).integers(
        0, build(size).config.vocab_size, (1, prompt_len)
    ).astype(np.int32)

    # Warmup (compile) with a 2-token generation, then timed run.
    generate(model, ids, max_new_tokens=2, cache_dtype=jnp.bfloat16).block_until_ready()
    t0 = time.perf_counter()
    out = generate(model, ids, max_new_tokens=n_tokens, cache_dtype=jnp.bfloat16)
    out.block_until_ready()
    gen_time = time.perf_counter() - t0

    n_params = build(size).num_params()
    print(json.dumps({
        "scenario": name,
        "model": f"llama-{size}",
        "params": n_params,
        "load_time_s": round(load_time, 3),
        "s_per_token": round(gen_time / n_tokens, 4),
        "tokens_per_s": round(n_tokens / gen_time, 2),
        "backend": jax.default_backend(),
    }))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("size", nargs="?", default=None, choices=list(SIZES))
    parser.add_argument("--tokens", type=int, default=32)
    parser.add_argument("--prompt-len", type=int, default=64)
    parser.add_argument("--scenarios", default="on_chip,cpu_offload,disk_offload")
    args = parser.parse_args()

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bench import resolve_backend

    backend = resolve_backend()
    size = args.size or ("1b" if backend == "tpu" else "tiny")

    import jax

    from accelerate_tpu.checkpointing import export_full_weights

    # Materialize a real checkpoint once so load time is measured honestly.
    model = build(size)
    model.init_params(jax.random.key(0))
    tmp = tempfile.mkdtemp(prefix="bmi_ckpt_")
    export_full_weights(model.params, tmp, max_shard_size="1GB")
    del model

    scenarios = {
        "on_chip": ("auto", None),
        "cpu_offload": ({"layers": "cpu", "embed": "tpu:0", "final_norm": "tpu:0",
                         "lm_head": "tpu:0"}, None),
        "disk_offload": ({"layers": "disk", "embed": "tpu:0", "final_norm": "tpu:0",
                          "lm_head": "tpu:0"}, tempfile.mkdtemp(prefix="bmi_disk_")),
    }
    for name in args.scenarios.split(","):
        device_map, offload_dir = scenarios[name]
        run_scenario(name, size, tmp, device_map, offload_dir,
                     args.prompt_len, args.tokens)


if __name__ == "__main__":
    main()
