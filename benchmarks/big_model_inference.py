"""Big-model-inference benchmark: load-time + s/token for dispatched models.

Counterpart of the reference's ``benchmarks/big_model_inference/
big_model_inference.py`` (load a checkpoint with a device_map — possibly
CPU/disk-offloaded — and measure model load time and generation latency;
published numbers in BASELINE.md's big-model table).

Scenarios measured, each printed as one JSON line:
  1. ``on_chip``      — checkpoint → load_checkpoint_and_dispatch(device_map
     'auto') with everything HBM-resident; fused scan-decode generation.
  2. ``cpu_offload``  — layers forced to host RAM, streamed per token
     (StreamedScanModel double-buffered DMA) — the OPT-30B-style config.
  3. ``disk_offload`` — layers memmapped from disk (GPT-NeoX-fp32-style).

Usage: python benchmarks/big_model_inference.py [tiny|medium|1b|3b] [--tokens N]
Default size: 1b on TPU, tiny elsewhere.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

SIZES = {
    # name -> (hidden, inter, layers, heads, kv_heads, vocab)
    "tiny": (64, 128, 2, 4, 2, 256),
    "medium": (512, 1408, 8, 8, 4, 8192),
    "1b": (2048, 5632, 22, 16, 4, 32000),
    "3b": (3072, 8192, 26, 24, 8, 32000),
}


def build(size: str, family: str = "llama"):
    h, inter, L, nh, nkv, vocab = SIZES[size]
    if family == "llama":
        from accelerate_tpu.models import Llama, LlamaConfig

        return Llama(LlamaConfig(
            vocab_size=vocab, hidden_size=h, intermediate_size=inter,
            num_hidden_layers=L, num_attention_heads=nh, num_key_value_heads=nkv,
            max_position_embeddings=2048,
        ))
    # The baseline's own architectures (BASELINE.md tables: GPT-J / GPT-NeoX /
    # OPT) at the scaled-down SIZES shapes — same three placement regimes.
    from accelerate_tpu.models import GPTX, GPTXConfig

    rotary_dim = max(2, (h // nh) // 4 // 2 * 2)
    recipes = {
        "neox": dict(position_style="rotary_neox", rotary_dim=rotary_dim),
        "gptj": dict(position_style="rotary_gptj", rotary_dim=rotary_dim,
                     shared_layernorm=True, attention_bias=False, lm_head_bias=True),
        "opt": dict(position_style="learned", position_offset=2,
                    parallel_residual=False, hidden_act="relu",
                    tie_word_embeddings=True),
    }
    return GPTX(GPTXConfig(
        vocab_size=vocab, hidden_size=h, intermediate_size=inter,
        num_hidden_layers=L, num_attention_heads=nh,
        max_position_embeddings=2048, **recipes[family],
    ))


def run_scenario(name, size, family, checkpoint, device_map, offload_dir,
                 prompt_len, n_tokens):
    import jax
    import jax.numpy as jnp

    from accelerate_tpu import load_checkpoint_and_dispatch
    from accelerate_tpu.big_modeling import init_empty_weights
    from accelerate_tpu.generation import generate

    with init_empty_weights():
        model = build(size, family)
        model.init_params(jax.random.key(0))
    # The dispatched model may come back wrapped (StreamedScanModel for the
    # offload regimes) — read static facts off the bare zoo model now.
    n_params, vocab = model.num_params(), model.config.vocab_size

    t0 = time.perf_counter()
    model = load_checkpoint_and_dispatch(
        model, checkpoint, device_map=device_map, offload_folder=offload_dir
    )
    load_time = time.perf_counter() - t0

    ids = np.random.default_rng(0).integers(0, vocab, (1, prompt_len)).astype(np.int32)

    # Warmup (compile) with a 2-token generation, then timed run.
    generate(model, ids, max_new_tokens=2, cache_dtype=jnp.bfloat16).block_until_ready()
    t0 = time.perf_counter()
    out = generate(model, ids, max_new_tokens=n_tokens, cache_dtype=jnp.bfloat16)
    out.block_until_ready()
    gen_time = time.perf_counter() - t0

    print(json.dumps({
        "scenario": name,
        "model": f"{family}-{size}",
        "params": n_params,
        "load_time_s": round(load_time, 3),
        "s_per_token": round(gen_time / n_tokens, 4),
        "tokens_per_s": round(n_tokens / gen_time, 2),
        "backend": jax.default_backend(),
    }))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("size", nargs="?", default=None, choices=list(SIZES))
    parser.add_argument("--family", default="llama",
                        choices=["llama", "neox", "gptj", "opt"],
                        help="architecture recipe; neox/gptj/opt mirror the "
                             "reference baseline's own model families")
    parser.add_argument("--tokens", type=int, default=32)
    parser.add_argument("--prompt-len", type=int, default=64)
    parser.add_argument("--scenarios", default="on_chip,cpu_offload,disk_offload")
    args = parser.parse_args()

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bench import resolve_backend

    backend = resolve_backend()
    size = args.size or ("1b" if backend == "tpu" else "tiny")

    import jax

    from accelerate_tpu.checkpointing import export_full_weights

    # Materialize a real checkpoint once so load time is measured honestly.
    model = build(size, args.family)
    model.init_params(jax.random.key(0))
    tmp = tempfile.mkdtemp(prefix="bmi_ckpt_")
    export_full_weights(model.params, tmp, max_shard_size="1GB")
    top_keys = list(model.params)
    del model

    def offload_map(where):
        # Layer stack offloaded; every other top-level group stays HBM-resident
        # (key names differ per family: final_norm/ln_f, optional lm_head/wpe).
        return {k: ("tpu:0" if k != "layers" else where) for k in top_keys}

    scenarios = {
        "on_chip": ("auto", None),
        "cpu_offload": (offload_map("cpu"), None),
        "disk_offload": (offload_map("disk"), tempfile.mkdtemp(prefix="bmi_disk_")),
    }
    for name in args.scenarios.split(","):
        device_map, offload_dir = scenarios[name]
        run_scenario(name, size, args.family, tmp, device_map, offload_dir,
                     args.prompt_len, args.tokens)


if __name__ == "__main__":
    main()
