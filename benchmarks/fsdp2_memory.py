"""GSPMD full-shard memory-parity benchmark — the TPU answer to the reference's
``benchmarks/fsdp2`` suite (README.md:21-33 there publishes allocated/reserved
memory plots for torch ``fully_shard``; BASELINE.json configs[3]).

What the torch benchmark proves with CUDA allocator plots, GSPMD lets us prove
exactly: under full-shard (ZeRO-3 analog) the per-device bytes for parameters
and optimizer state must scale as 1/fsdp_size, while training numerics stay
identical to the unsharded run. This script measures both:

- per-device param / optimizer-state / gradient-buffer bytes from the actual
  array shards XLA placed (not an estimate);
- loss trajectory parity across fsdp sizes at ATOL 1e-4;
- the collectives XLA emitted (all-gather for reshard-on-use, reduce traffic).

Run on the virtual 8-device CPU mesh (default) or any real mesh::

    python benchmarks/fsdp2_memory.py           # table + one JSON line
    BENCH_FSDP_SIZES=1,2,4 python benchmarks/fsdp2_memory.py
"""

from __future__ import annotations

import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from accelerate_tpu.utils.environment import pin_cpu_platform  # noqa: E402


def _device_bytes(tree, device) -> int:
    """Bytes this device holds for a pytree of jax.Arrays (actual shard sizes)."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if not hasattr(leaf, "addressable_shards"):
            continue
        for shard in leaf.addressable_shards:
            if shard.device == device:
                total += shard.data.nbytes
    return total


def measure(fsdp_size: int, steps: int = 6):
    import numpy as np
    import optax

    import jax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models import Llama, LlamaConfig
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.utils.dataclasses import FullyShardedDataParallelPlugin

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    # min_shard_size=0: shard every tensor so the 1/N law is exact even for the
    # tiny benchmark model (the default threshold keeps small tensors replicated).
    acc = Accelerator(
        fsdp_plugin=FullyShardedDataParallelPlugin(fsdp_size=fsdp_size, min_shard_size=0)
    )
    cfg = LlamaConfig.tiny(
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_attention_heads=4,
        num_key_value_heads=4,
        num_hidden_layers=4,
        max_position_embeddings=64,
    )
    model = Llama(cfg)
    model.init_params(jax.random.key(0))
    pmodel, popt = acc.prepare(model, optax.adam(1e-2))
    step = acc.build_train_step(pmodel, popt)

    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (8, 32)).astype(np.int32)
    batch = {"input_ids": ids, "labels": ids}
    losses = [float(step(batch)) for _ in range(steps)]

    dev0 = jax.devices()[0]
    popt._ensure_initialized()
    param_b = _device_bytes(pmodel.params, dev0)
    opt_b = _device_bytes(popt.opt_state, dev0)

    hlo = step.lower(batch).compile().as_text()
    counts = {
        op: len(re.findall(rf"\b{op}", hlo))
        for op in ("all-reduce", "all-gather", "reduce-scatter")
    }
    return {
        "fsdp_size": fsdp_size,
        "param_bytes_dev0": param_b,
        "opt_bytes_dev0": opt_b,
        "final_loss": losses[-1],
        "losses": losses,
        "collectives": counts,
    }


def main():
    pin_cpu_platform(int(os.environ.get("BENCH_FSDP_DEVICES", "8")))
    import jax

    n_dev = len(jax.devices())
    sizes_env = os.environ.get("BENCH_FSDP_SIZES")
    if sizes_env:
        sizes = [int(s) for s in sizes_env.split(",")]
    else:
        sizes = [s for s in (1, 2, 4, 8) if s <= n_dev]

    rows = [measure(s) for s in sizes]
    base = rows[0]

    print(f"{'fsdp':>5} {'params/dev':>12} {'opt/dev':>12} {'vs 1/N':>8} "
          f"{'all-gather':>10} {'final loss':>11}")
    ok_memory, ok_numerics = True, True
    for row in rows:
        n = row["fsdp_size"]
        # Scale from whatever the first measured size was (it need not be 1):
        # total bytes are invariant, so dev0 bytes scale as base_n/n.
        expected = base["param_bytes_dev0"] * base["fsdp_size"] / n
        ratio = row["param_bytes_dev0"] / expected
        # Actual shard bytes may exceed the ideal 1/N by padding on
        # non-divisible dims; 15% covers the benchmark shapes.
        if ratio > 1.15:
            ok_memory = False
        if abs(row["final_loss"] - base["final_loss"]) > 1e-4:
            ok_numerics = False
        print(f"{n:>5} {row['param_bytes_dev0']:>12,} {row['opt_bytes_dev0']:>12,} "
              f"{ratio:>8.3f} {row['collectives']['all-gather']:>10} "
              f"{row['final_loss']:>11.5f}")

    shard_frac = rows[-1]["param_bytes_dev0"] / (base["param_bytes_dev0"] * base["fsdp_size"])
    print(json.dumps({
        "metric": "fsdp_full_shard_dev0_param_fraction",
        "value": round(shard_frac, 4),
        "unit": f"fraction_of_unsharded_at_fsdp{rows[-1]['fsdp_size']}",
        "vs_baseline": round((1.0 / rows[-1]["fsdp_size"]) / shard_frac, 4),
        "detail": {
            "memory_scales_as_1_over_n": ok_memory,
            "loss_parity_across_shardings": ok_numerics,
            "rows": [
                {k: row[k] for k in ("fsdp_size", "param_bytes_dev0", "opt_bytes_dev0",
                                     "final_loss", "collectives")}
                for row in rows
            ],
        },
    }))
    if not (ok_memory and ok_numerics):
        sys.exit(1)


if __name__ == "__main__":
    main()
