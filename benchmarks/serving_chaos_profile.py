"""Profile the serving tier's fault-tolerance tax end-to-end over real HTTP.

``serving_disagg_profile.py`` measures the no-fault routing rig; this script
measures what a mid-stream worker death COSTS. It stands up two decode
workers behind an affinity router (all in one process, each on its own
loopback ``MetricsServer``), drives the same prompt mix through twice, and
diffs the passes:

- **clean pass**: every request completes first-try; the per-request
  client-side TTFT (wall time from POST to the first streamed frame) is the
  baseline the fault tax is measured against.
- **faulted pass**: a fresh rig with ``ACCELERATE_FAULT_PLAN``-style chaos
  armed on worker A (``req:K=worker_kill`` with ``kill_mode="stream"`` — the
  stream breaks mid-delivery without a terminal frame, exactly the wire
  signature of a crashed host). The router must recover the request on
  worker B under the same rid with the already-delivered prefix trimmed.

Reported (the ``detail.serving.chaos`` dict bench.py embeds under
``BENCH_SERVING_CHAOS=1``, schema v13):

- **recovered_requests / lost_requests**: how many requests needed a retry
  leg (from each stream's ``done`` trace) and how many failed outright —
  the drill contract is recovered ≥ 1 and lost == 0.
- **added_ttft_under_fault_s / added_latency_under_fault_s**: the client-
  side TTFT and completion-time deltas the recovered request paid versus
  its own clean-pass run — the retry backoff + re-dispatch + re-prefill
  tax a fault adds to exactly the requests it touches. The TTFT delta is
  ~0 by contract (the victim streams the first frame before dying and the
  retry resumes the SAME client stream); the tax lands in completion time.
- **outputs_identical**: the faulted pass's streams are bit-identical to
  the clean pass's (greedy decode; retry is re-dispatch, never a re-roll).
- the router's ``retries``/``evictions`` rollups for the faulted pass.

Prints one JSON line per probe; ``summarize()`` returns the payload.
``BENCH_PROFILE_SMALL=1`` shrinks shapes for CPU smoke runs (the test
suite's path).

Usage: python benchmarks/serving_chaos_profile.py
"""

import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


SMALL = os.environ.get("BENCH_PROFILE_SMALL", "0") == "1"

# Which request (0-based, sequential) dies mid-stream on worker A. Sequential
# idle-rig requests all land on A (least-loaded ties break toward the lowest
# rank), so A's admission seq tracks the request index until the kill —
# offset by one because each pass spends A's seq 0 on an untimed JIT-warmup
# request (first-dispatch compile time would otherwise swamp the fault tax).
FAULT_AT = 2


def _shapes():
    if SMALL:
        return dict(layers=2, heads=4, kv=2, hidden=64, inter=128, vocab=256,
                    slots=2, max_new=8, sync=2, block=4, chunk=8,
                    buckets=(8, 16), cache=1024, prompt_lens=(5, 7, 3, 6))
    return dict(layers=8, heads=16, kv=8, hidden=1024, inter=4096, vocab=32000,
                slots=8, max_new=64, sync=8, block=16, chunk=128,
                buckets=(64, 128, 256), cache=4096,
                prompt_lens=(33, 96, 12, 57, 80, 21))


def _build_model(s):
    import jax

    from accelerate_tpu.models import Llama, LlamaConfig

    cfg = LlamaConfig.tiny(
        vocab_size=s["vocab"], hidden_size=s["hidden"],
        intermediate_size=s["inter"], num_hidden_layers=s["layers"],
        num_attention_heads=s["heads"], num_key_value_heads=s["kv"],
    )
    model = Llama(cfg)
    model.init_params(jax.random.key(0))
    return model


def _engine(model, s):
    import jax.numpy as jnp

    from accelerate_tpu.serving import ContinuousBatcher

    return ContinuousBatcher(
        model, batch_slots=s["slots"], max_new_tokens=s["max_new"],
        max_cache_len=s["cache"], cache_dtype=jnp.float32,
        bucket_sizes=s["buckets"], sync_every=s["sync"], paged=True,
        block_size=s["block"], prefill_chunk=s["chunk"],
        max_tokens_per_request=max(s["prompt_lens"]) + s["max_new"] + s["chunk"],
    )


def _start_worker(engine, role):
    from accelerate_tpu.serving_net import ServingFrontend
    from accelerate_tpu.telemetry.metrics import MetricsServer

    server = MetricsServer(0, host="127.0.0.1")
    port = server.start()
    endpoint = f"127.0.0.1:{port}"
    frontend = ServingFrontend(engine, role=role)
    frontend.install(server=server, endpoint=endpoint)
    return server, frontend, endpoint


def _generate_timed(endpoint, prompt, max_new):
    """One request through the real wire format, with the client-side TTFT
    clock: wall seconds from POST to the first streamed frame. Client-side
    on purpose — under a fault the survivor's tracer only sees the retry
    leg, so its ``ttft_s`` would hide exactly the tax being measured."""
    from accelerate_tpu.serving_net.frontend import (
        ServingStreamError,
        iter_sse,
    )

    req = urllib.request.Request(
        f"http://{endpoint}/v1/generate",
        data=json.dumps({"prompt": [int(t) for t in prompt],
                         "max_new_tokens": int(max_new)}).encode(),
        headers={"Content-Type": "application/json"},
    )
    t0 = time.perf_counter()
    ttft_s, deltas, done = None, [], None
    with urllib.request.urlopen(req, timeout=300.0) as response:
        for kind, data in iter_sse(response):
            if ttft_s is None:
                ttft_s = time.perf_counter() - t0
            payload = json.loads(data)
            if kind == "error":
                raise ServingStreamError(
                    f"serving stream error: {payload.get('error')}",
                    retryable=payload.get("retryable", True),
                )
            if kind == "tokens":
                deltas.append(payload["tokens"])
            elif kind == "done":
                done = payload
    if done is None:
        raise ServingStreamError("stream closed without a done event",
                                 retryable=True)
    return {"tokens": done["tokens"], "deltas": deltas, "done": done,
            "ttft_s": ttft_s, "wall_s": time.perf_counter() - t0}


def _rig(model, s, fault_plan=None):
    """Two decode workers + a router. ``fault_plan`` (a ``req:`` spec) arms
    worker A — the one sequential requests land on — with soft-death chaos."""
    from accelerate_tpu.resilience.faults import FaultPlan, set_active_plan
    from accelerate_tpu.serving_net import Router
    from accelerate_tpu.telemetry.metrics import MetricsServer

    servers, frontends = [], []
    server, frontend_a, ep_a = _start_worker(_engine(model, s), "decode")
    servers.append(server)
    frontends.append(frontend_a)
    server, frontend_b, ep_b = _start_worker(_engine(model, s), "decode")
    servers.append(server)
    frontends.append(frontend_b)
    if fault_plan:
        frontend_a.kill_mode = "stream"
        set_active_plan(FaultPlan.parse(fault_plan))
    router_server = MetricsServer(0, host="127.0.0.1")
    router_port = router_server.start()
    servers.append(router_server)
    router = Router(
        workers=[{"rank": 0, "role": "decode", "endpoint": ep_a},
                 {"rank": 1, "role": "decode", "endpoint": ep_b}],
        backoff_base_s=0.02, backoff_cap_s=0.1,
    )
    router.install(server=router_server, endpoint=f"127.0.0.1:{router_port}")
    return servers, frontends, router, f"127.0.0.1:{router_port}"


def _teardown(servers, frontends):
    from accelerate_tpu.resilience.faults import reset_active_plan
    from accelerate_tpu.serving_net.router import reset_serving_registry

    for frontend in frontends:
        frontend.uninstall()
    for server in servers:
        server.stop()
    reset_active_plan()
    reset_serving_registry()


def _pass(model, s, prompts, fault_plan=None):
    """One sequential pass of the prompt mix; returns per-request results
    plus the router's stats snapshot."""
    servers, frontends, router, router_ep = _rig(model, s, fault_plan)
    try:
        # Untimed warmup (spends worker A's admission seq 0): pays the
        # first-dispatch XLA compile outside the clock in BOTH passes, so
        # the clean baseline measures steady-state latency.
        _generate_timed(router_ep, prompts[0], s["max_new"])
        results = [_generate_timed(router_ep, p, s["max_new"])
                   for p in prompts]
        return results, router.stats()
    finally:
        _teardown(servers, frontends)


def summarize(model=None):
    """Run both passes; returns the ``detail.serving.chaos`` dict for
    bench.py (schema v13, BENCH_SERVING_CHAOS=1)."""
    s = _shapes()
    if model is None:
        model = _build_model(s)
    rng = np.random.default_rng(13)
    prompts = [rng.integers(1, s["vocab"], (n,)).astype(np.int32)
               for n in s["prompt_lens"]]

    clean, _ = _pass(model, s, prompts)
    faulted, stats = _pass(model, s, prompts,
                           fault_plan=f"req:{FAULT_AT + 1}=worker_kill")

    retried = [i for i, r in enumerate(faulted)
               if (r["done"].get("trace") or [{}])[0].get("retries")]
    clean_ttfts = [r["ttft_s"] for r in clean]
    mean_clean_ttft = sum(clean_ttfts) / len(clean_ttfts)
    # Per-index deltas over the recovered requests. The TTFT delta is
    # typically ~0 BY CONTRACT — the victim delivers the first frame before
    # dying and retry resumes the same client stream — so the fault tax
    # shows up in completion latency (re-dispatch + backoff + re-prefill).
    added_ttft = (max(faulted[i]["ttft_s"] - clean[i]["ttft_s"]
                      for i in retried) if retried else None)
    added_wall = (max(faulted[i]["wall_s"] - clean[i]["wall_s"]
                      for i in retried) if retried else None)
    payload = {
        "small": SMALL,
        "requests": len(prompts),
        "fault_at": FAULT_AT,
        "recovered_requests": len(retried),
        "lost_requests": 0,  # _pass raises on any failed stream
        "outputs_identical": bool(
            all(a["tokens"] == b["tokens"] for a, b in zip(clean, faulted))
        ),
        "clean_ttft_mean_s": round(mean_clean_ttft, 4),
        "added_ttft_under_fault_s": (round(added_ttft, 4)
                                     if added_ttft is not None else None),
        "added_latency_under_fault_s": (round(added_wall, 4)
                                        if added_wall is not None else None),
        "retries": stats["retries"],
        "evictions": stats["evictions"],
    }
    return payload


def main():
    summary = summarize()
    print(json.dumps({"probe": "recovery",
                      "recovered_requests": summary["recovered_requests"],
                      "lost_requests": summary["lost_requests"],
                      "retries": summary["retries"],
                      "evictions": summary["evictions"]}))
    print(json.dumps({"probe": "fault_tax",
                      "clean_ttft_mean_s": summary["clean_ttft_mean_s"],
                      "added_ttft_under_fault_s":
                          summary["added_ttft_under_fault_s"],
                      "added_latency_under_fault_s":
                          summary["added_latency_under_fault_s"]}))
    print(json.dumps({"probe": "headline",
                      "requests": summary["requests"],
                      "outputs_identical": summary["outputs_identical"]}))


if __name__ == "__main__":
    main()
