"""accelerate_tpu — a TPU-native (JAX/XLA/pjit) training & inference framework with
the capabilities of HuggingFace Accelerate.

Public surface mirrors the reference facade (``src/accelerate/__init__.py:16-46``):
``Accelerator``, ``PartialState``, big-modeling helpers, utils — re-architected
around one ``jax.sharding.Mesh`` and compiled train steps instead of wrapped
torch modules.
"""

__version__ = "0.1.0"

from .state import AcceleratorState, DistributedType, GradientState, PartialState
from .parallel.mesh import ParallelismConfig
from .utils.dataclasses import (
    AutocastKwargs,
    Fp8RecipeKwargs,
    DataLoaderConfiguration,
    FullyShardedDataParallelPlugin,
    GradientAccumulationPlugin,
    JaxShardingKwargs,
    MegatronStylePlugin,
    PipelineParallelPlugin,
    ProfileKwargs,
    SequenceParallelPlugin,
    TensorParallelPlugin,
)


def __getattr__(name):
    # Lazy imports keep `import accelerate_tpu` light and avoid circulars.
    if name == "Accelerator":
        from .accelerator import Accelerator

        return Accelerator
    if name in ("notebook_launcher", "debug_launcher"):
        from . import launchers

        return getattr(launchers, name)
    if name in (
        "init_empty_weights",
        "init_on_device",
        "dispatch_model",
        "load_checkpoint_and_dispatch",
        "cpu_offload",
        "disk_offload",
    ):
        from . import big_modeling

        return getattr(big_modeling, name)
    if name == "infer_auto_device_map":
        from .utils.modeling import infer_auto_device_map

        return infer_auto_device_map
    if name in ("load_and_quantize_model", "QuantizationConfig"):
        from .utils import quantization

        return getattr(quantization, name)
    if name == "find_executable_batch_size":
        from .utils.memory import find_executable_batch_size

        return find_executable_batch_size
    if name == "skip_first_batches":
        from .data_loader import skip_first_batches

        return skip_first_batches
    if name == "DeviceBatchPrefetcher":
        from .data_loader import DeviceBatchPrefetcher

        return DeviceBatchPrefetcher
    if name == "prepare_pippy":
        from .inference import prepare_pippy

        return prepare_pippy
    if name in ("LocalSGD", "LocalSGDTrainer"):
        from . import local_sgd

        return getattr(local_sgd, name)
    if name in ("generate", "sample_logits", "beam_search", "assisted_generate"):
        from . import generation

        return getattr(generation, name)
    if name == "ContinuousBatcher":
        from .serving import ContinuousBatcher

        return ContinuousBatcher
    if name in ("from_hf", "from_hf_checkpoint"):
        from .models import convert

        return getattr(convert, name)
    if name in ("GPTTrainStep", "BertTrainStep", "T5TrainStep", "get_train_step"):
        from . import train_steps

        return getattr(train_steps, name)
    if name in (
        "run_resilient",
        "PreemptionWatcher",
        "FaultPlan",
        "SimulatedFault",
        "GoodputLedger",
    ):
        from . import resilience

        return getattr(resilience, name)
    if name in (
        "Telemetry",
        "StepTimeline",
        "StragglerMonitor",
        "MetricsRegistry",
        "get_registry",
        "get_telemetry",
        "span",
        "ProfileManager",
        "FlightRecorder",
        "get_profile_manager",
        "get_flight_recorder",
    ):
        from . import telemetry

        return getattr(telemetry, name)
    raise AttributeError(f"module 'accelerate_tpu' has no attribute {name!r}")
