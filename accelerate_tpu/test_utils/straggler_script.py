"""Multi-host straggler drill, run under the real 2-process launcher::

    accelerate-tpu launch --cpu --num_processes 2 -m \
        accelerate_tpu.test_utils.straggler_script

Proves the property ``tests/test_telemetry.py`` pins: when one rank is slow,
EVERY rank's straggler exchange identifies the same slow rank by index, with
the same per-host vector and skew ratio. Per-host step times are synthetic
(rank 1 is deterministically 5x slower) so the assertion is exact; the
exchange itself is real — on CPU backends the XLA runtime refuses
multiprocess computations, so this drill exercises exactly the
coordination-service KV fallback the monitor must degrade to (the
device-collective path stays covered by the single-process tests).
"""

from __future__ import annotations

from accelerate_tpu import PartialState
from accelerate_tpu.telemetry import StragglerMonitor

FAST_S, SLOW_S, SLOW_RANK = 0.010, 0.050, 1


def main():
    state = PartialState()
    assert state.num_processes >= 2, "run under `launch --num_processes 2`"

    monitor = StragglerMonitor(every_steps=4, slow_ratio=1.3)
    local_mean = SLOW_S if state.process_index == SLOW_RANK else FAST_S
    assert not monitor.due(3) and monitor.due(4)

    report = monitor.report(state, local_mean, step=4)
    assert report is not None
    assert report.slowest_host == SLOW_RANK, report
    assert report.tripped, report
    assert abs(report.max_s - SLOW_S) < 1e-9 and abs(report.min_s - FAST_S) < 1e-9, report
    assert report.ratio > 1.3, report

    # A second exchange must agree too (fresh KV namespace per epoch).
    report2 = monitor.report(state, local_mean, step=8)
    assert report2.slowest_host == SLOW_RANK and report2.per_host_s == report.per_host_s

    print(
        f"STRAGGLER_OK rank={state.process_index} slowest={report.slowest_host} "
        f"ratio={report.ratio:.3f}"
    )


if __name__ == "__main__":
    main()
