"""Tiny training fixtures.

Reference parity: ``src/accelerate/test_utils/training.py:162`` —
``RegressionDataset``/``RegressionModel`` fit ``y = a*x + b`` so correctness is
checkable as exact parameter values with no accelerator-hours.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..modules import ModelOutput, Module


class RegressionDataset:
    """Map-style dataset of (x, y) pairs with y = a*x + b + noise."""

    def __init__(self, a: float = 2.0, b: float = 3.0, length: int = 64, seed: int = 42):
        rng = np.random.default_rng(seed)
        self.length = length
        self.x = rng.normal(size=(length,)).astype(np.float32)
        self.y = (a * self.x + b + 0.01 * rng.normal(size=(length,))).astype(np.float32)

    def __len__(self):
        return self.length

    def __getitem__(self, i):
        return {"x": self.x[i], "y": self.y[i]}


class RegressionModel(Module):
    """y_hat = a*x + b; returns MSE loss when labels present (HF convention)."""

    def __init__(self, a: float = 0.0, b: float = 0.0):
        self.a0 = a
        self.b0 = b
        self.params = None

    def init(self, rng, *example_inputs, **kwargs):
        return {"a": jnp.asarray(self.a0, jnp.float32), "b": jnp.asarray(self.b0, jnp.float32)}

    def apply(self, params, x=None, y=None, train: bool = False, rngs=None, **kwargs):
        pred = params["a"] * x + params["b"]
        out = ModelOutput(prediction=pred)
        if y is not None:
            out["loss"] = jnp.mean((pred - y) ** 2)
        return out


class MatrixRegressionModel(Module):
    """``y_hat = x @ W + b`` with matrix params large enough for the ZeRO
    planner (``plan_zero_shardings`` skips leaves below its minimum shard
    size) — the fixture for cross-replica optimizer-sharding tests, where
    ``RegressionModel``'s scalar params give the dp partitioner nothing to
    split. Deterministic init: no RNG, so drills stay reproducible."""

    def __init__(self, dim: int = 64):
        self.dim = dim
        self.params = None

    def init(self, rng, *example_inputs, **kwargs):
        d = self.dim
        w = ((np.arange(d * d, dtype=np.float32).reshape(d, d) % 7) - 3.0) / d
        return {"w": jnp.asarray(w), "b": jnp.zeros((d,), jnp.float32)}

    def apply(self, params, x=None, y=None, train: bool = False, rngs=None, **kwargs):
        pred = x @ params["w"] + params["b"]
        out = ModelOutput(prediction=pred)
        if y is not None:
            out["loss"] = jnp.mean((pred - y) ** 2)
        return out


def regression_batches(dataset: RegressionDataset, batch_size: int, drop_last: bool = True):
    """Plain-python iterable of numpy batches (a non-torch dataloader)."""
    batches = []
    n = len(dataset) - (len(dataset) % batch_size if drop_last else 0)
    for start in range(0, n, batch_size):
        idx = slice(start, min(start + batch_size, len(dataset)))
        batches.append({"x": dataset.x[idx], "y": dataset.y[idx]})
    return batches
