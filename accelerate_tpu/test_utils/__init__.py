from .drills import run_nonblocking_drill
from .training import (
    MatrixRegressionModel,
    RegressionDataset,
    RegressionModel,
    regression_batches,
)
