from .training import RegressionDataset, RegressionModel, regression_batches
