from .drills import run_nonblocking_drill
from .training import RegressionDataset, RegressionModel, regression_batches
