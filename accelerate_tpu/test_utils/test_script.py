"""Bundled smoke script run by `accelerate-tpu test` (and usable standalone).

Reference parity: ``src/accelerate/test_utils/scripts/test_script.py`` (952 LoC) —
asserts the install works end-to-end: state init, collectives, dataloader
sharding determinism vs a single-process baseline, and a short training run that
must converge. Kept to the same assertions, one mesh instead of process groups.
"""

from __future__ import annotations

import numpy as np


def check_state(accelerator):
    state = accelerator.state
    assert state.num_processes >= 1
    assert accelerator.device is not None
    print(f"state ok: {state!r}")


def check_collectives(accelerator):
    import jax.numpy as jnp

    from accelerate_tpu.utils.operations import broadcast, gather, reduce

    x = jnp.arange(4.0) + accelerator.process_index
    g = gather(x)
    assert g.shape[0] == 4 * accelerator.num_processes, g.shape
    r = reduce(x, reduction="sum")
    np.testing.assert_allclose(np.asarray(r)[0], sum(range(accelerator.num_processes)))
    b = broadcast(x, from_process=0)
    np.testing.assert_allclose(np.asarray(b), np.arange(4.0))
    print("collectives ok")


def check_dataloader(accelerator):
    from accelerate_tpu.data_loader import prepare_data_loader
    from accelerate_tpu.test_utils.training import RegressionDataset, regression_batches

    ds = RegressionDataset(length=96, seed=42)
    batches = list(regression_batches(ds, batch_size=8))
    loader = prepare_data_loader(batches, num_processes=1, process_index=0, put_on_device=False)
    flat = [np.asarray(b["x"]) for b in loader]
    baseline = [np.asarray(b["x"]) for b in batches]
    for got, want in zip(flat, baseline):
        np.testing.assert_allclose(got, want)
    print("dataloader ok")


def check_training(accelerator):
    import optax

    from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel, regression_batches

    model = RegressionModel()
    import jax

    model.init_params(jax.random.key(42))
    ds = RegressionDataset(length=64, seed=0)
    pmodel, popt = accelerator.prepare(model, optax.sgd(0.02))
    step = accelerator.build_train_step(pmodel, popt)
    losses = []
    for _ in range(4):
        for batch in regression_batches(ds, batch_size=16):
            losses.append(float(step({"x": batch["x"], "y": batch["y"]})))
    assert losses[-1] < losses[0], (losses[0], losses[-1])
    print(f"training ok: loss {losses[0]:.4f} -> {losses[-1]:.4f}")


def main():
    from accelerate_tpu import Accelerator

    accelerator = Accelerator()
    check_state(accelerator)
    check_collectives(accelerator)
    check_dataloader(accelerator)
    check_training(accelerator)
    accelerator.wait_for_everyone()
    if accelerator.is_main_process:
        print("All smoke checks passed.")


if __name__ == "__main__":
    main()
