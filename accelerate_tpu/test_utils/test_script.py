"""Bundled distributed-assert script run by ``accelerate-tpu test`` (and standalone).

Reference parity: ``src/accelerate/test_utils/scripts/test_script.py`` (952 LoC).
Covers the same ground, one mesh instead of process groups:

- state init + process-execution controls (on_*_process, main_process_first)
- cross-process RNG synchronization
- dataloader sharding determinism vs a single-process baseline (shard + central
  dispatch, both ``split_batches`` modes, seedable sampler)
- collectives: gather / gather_object / broadcast / pad_across_processes on the
  real process topology (whatever ``--num_processes`` the launcher provided)
- ``split_between_processes`` for list / tensor / nested dict, with padding
- training parity: imperative loop vs fused ``build_train_step`` at ATOL 1e-6,
  and distributed data-parallel gradients vs a pure-JAX full-batch baseline
- ``set_trigger``/``check_trigger`` early-stop flag propagation

Run directly, or under the launcher::

    accelerate-tpu test
    accelerate-tpu launch --cpu --num_processes 2 -m accelerate_tpu.test_utils.test_script
"""

from __future__ import annotations

import io
import os
from contextlib import redirect_stdout

import numpy as np


ATOL = 1e-6


def init_state_check(accelerator):
    state = accelerator.state
    assert state.num_processes >= 1
    assert 0 <= state.process_index < state.num_processes
    assert accelerator.device is not None
    if accelerator.is_main_process:
        print(f"state ok: {state!r}")


def process_execution_check(accelerator):
    """on_main_process / on_process / main_process_first execute on the right
    ranks (reference ``process_execution_check`` :94-164)."""
    state = accelerator.state

    buf = io.StringIO()
    with redirect_stdout(buf):
        state.on_main_process(lambda: print("main"))()
        state.on_local_main_process(lambda: print("local_main"))()
        state.on_last_process(lambda: print("last"))()
    out = buf.getvalue()
    if state.is_main_process:
        assert "main" in out
    else:
        assert "main" not in out
    if state.is_last_process:
        assert "last" in out
    # main_process_first: rank 0 enters before others leave their wait.
    order = []
    with state.main_process_first():
        order.append(state.process_index)
    assert len(order) == 1
    if accelerator.is_main_process:
        print("process execution ok")


def rng_sync_check(accelerator):
    """After synchronize_rng_states every rank draws identical numbers
    (reference ``rng_sync_check`` :175-191)."""
    from accelerate_tpu.utils.operations import gather_object
    from accelerate_tpu.utils.random import set_seed, synchronize_rng_states

    set_seed(1234 + accelerator.process_index)  # deliberately desynced
    synchronize_rng_states(["numpy", "torch"])
    val = float(np.random.random())
    vals = gather_object([val])
    assert all(abs(v - vals[0]) < 1e-12 for v in vals), vals
    try:
        import torch
    except ImportError:
        torch = None  # torch is optional everywhere else; keep `test` runnable
    if torch is not None:
        tval = float(torch.rand(1))
        tvals = gather_object([tval])
        assert all(abs(v - tvals[0]) < 1e-12 for v in tvals), tvals
    if accelerator.is_main_process:
        print("rng sync ok")


def _roundtrip_shards(accelerator, length, batch_size, split_batches):
    """Every rank shards the same index stream; gathering shards must rebuild
    the baseline stream exactly (reference ``dl_preparation_check`` :193-251)."""
    from accelerate_tpu.data_loader import BatchSamplerShard
    from accelerate_tpu.utils.operations import gather_object

    class _Sampler:
        def __iter__(self):
            yield from (
                list(range(i, min(i + batch_size, length)))
                for i in range(0, length, batch_size)
            )

        def __len__(self):
            return (length + batch_size - 1) // batch_size

        batch_size = None
        drop_last = False

    n, rank = accelerator.num_processes, accelerator.process_index
    shard = BatchSamplerShard(
        _Sampler(), num_processes=n, process_index=rank, split_batches=split_batches
    )
    mine = [idx for batch in shard for idx in batch]
    everyone = gather_object(mine)
    seen = sorted(set(everyone))
    assert seen == list(range(length)), f"lost indices: {set(range(length)) - set(seen)}"


def dl_preparation_check(accelerator):
    for split_batches in (False, True):
        bs = 8 if not split_batches else 8 * max(accelerator.num_processes, 1)
        _roundtrip_shards(accelerator, length=96, batch_size=bs, split_batches=split_batches)
        _roundtrip_shards(accelerator, length=90, batch_size=bs, split_batches=split_batches)
    if accelerator.is_main_process:
        print("dataloader sharding ok")


def central_dl_preparation_check(accelerator):
    """DataLoaderDispatcher: rank0 reads, everyone receives its slice; the
    reassembled stream equals the baseline (reference :253-316)."""
    from accelerate_tpu.data_loader import DataLoaderDispatcher

    n = accelerator.num_processes
    batches = [{"x": np.arange(i * 8, (i + 1) * 8, dtype=np.float32)} for i in range(6)]
    dispatcher = DataLoaderDispatcher(batches, put_on_device=False)
    got = [np.asarray(b["x"]) for b in dispatcher]
    assert len(got) == 6, len(got)
    for want, have in zip(batches, got):
        np.testing.assert_allclose(want["x"], have)
    if accelerator.is_main_process:
        print("central dataloader ok")


def check_seedable_sampler(accelerator):
    """SeedableRandomSampler: identical permutation across ranks, different per
    epoch (reference ``check_seedable_sampler`` :364-435)."""
    from accelerate_tpu.data_loader import SeedableRandomSampler
    from accelerate_tpu.utils.operations import gather_object

    class _DS:
        def __len__(self):
            return 24

    sampler = SeedableRandomSampler(_DS(), seed=99)
    sampler.set_epoch(0)
    perm0 = list(iter(sampler))
    sampler.set_epoch(1)
    perm1 = list(iter(sampler))
    assert sorted(perm0) == list(range(24))
    assert perm0 != perm1, "epochs must reshuffle"
    all_perms = gather_object([tuple(perm0)])
    assert all(p == all_perms[0] for p in all_perms), "ranks disagree on permutation"
    if accelerator.is_main_process:
        print("seedable sampler ok")


def collectives_check(accelerator):
    import jax.numpy as jnp

    from accelerate_tpu.utils.operations import (
        broadcast,
        gather,
        gather_object,
        pad_across_processes,
        reduce,
    )

    n, rank = accelerator.num_processes, accelerator.process_index
    x = jnp.arange(4.0) + rank
    g = gather(x)
    assert np.asarray(g).shape[0] == 4 * n, g.shape
    want = np.concatenate([np.arange(4.0) + r for r in range(n)])
    np.testing.assert_allclose(np.sort(np.asarray(g)), np.sort(want))

    r = reduce(x, reduction="sum")
    np.testing.assert_allclose(np.asarray(r)[0], sum(range(n)))

    b = broadcast(x, from_process=0)
    np.testing.assert_allclose(np.asarray(b), np.arange(4.0))

    objs = gather_object([f"rank{rank}"])
    assert objs == [f"rank{i}" for i in range(n)], objs

    # Uneven shapes: each rank contributes rank+1 rows; pad then gather.
    uneven = jnp.ones((rank + 1, 2)) * rank
    padded = pad_across_processes(uneven, dim=0)
    assert np.asarray(padded).shape[0] == n, padded.shape
    if accelerator.is_main_process:
        print("collectives ok")


def split_between_processes_check(accelerator):
    state = accelerator.state
    n, rank = state.num_processes, state.process_index

    # list
    items = list(range(n * 3 + 1))
    with state.split_between_processes(items) as shard:
        assert len(shard) >= 1
    from accelerate_tpu.utils.operations import gather_object

    with state.split_between_processes(items) as shard:
        recombined = gather_object(list(shard))
    assert sorted(recombined) == items, recombined

    # tensor
    t = np.arange(n * 4, dtype=np.float32).reshape(n * 4, 1)
    with state.split_between_processes(t) as shard:
        assert np.asarray(shard).shape[0] == 4

    # nested dict
    nested = {"a": list(range(n * 2)), "b": np.arange(n * 2)}
    with state.split_between_processes(nested) as shard:
        assert len(shard["a"]) == 2
        assert np.asarray(shard["b"]).shape[0] == 2

    # padding
    odd = list(range(n + 1))
    with state.split_between_processes(odd, apply_padding=True) as shard:
        lengths = gather_object([len(shard)])
    assert all(l == lengths[0] for l in lengths), lengths
    if accelerator.is_main_process:
        print("split_between_processes ok")


def training_check(accelerator):
    """Imperative vs fused parity at ATOL 1e-6, and distributed grads vs a
    pure-JAX full-batch baseline (reference ``training_check`` :455-663)."""
    import jax
    import optax

    from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel, regression_batches

    ds = RegressionDataset(length=64, seed=0)
    batches = regression_batches(ds, batch_size=16)

    def run_imperative():
        model = RegressionModel()
        model.init_params(jax.random.key(42))
        pmodel, popt = accelerator.prepare(model, optax.sgd(0.05))
        pmodel.train()
        for _ in range(3):
            for batch in batches:
                out = pmodel(**batch)
                accelerator.backward(out["loss"])
                popt.step()
                popt.zero_grad()
        sd = accelerator.get_state_dict(pmodel)
        return float(sd["a"]), float(sd["b"])

    def run_fused():
        model = RegressionModel()
        model.init_params(jax.random.key(42))
        pmodel, popt = accelerator.prepare(model, optax.sgd(0.05))
        step = accelerator.build_train_step(pmodel, popt)
        for _ in range(3):
            for batch in batches:
                step({"x": batch["x"], "y": batch["y"]})
        sd = accelerator.get_state_dict(pmodel)
        return float(sd["a"]), float(sd["b"])

    def run_pure_jax():
        params = {"a": np.float32(0.0), "b": np.float32(0.0)}
        params = {k: jax.numpy.asarray(v) for k, v in params.items()}
        tx = optax.sgd(0.05)
        opt_state = tx.init(params)

        @jax.jit
        def step(params, opt_state, x, y):
            def loss_fn(p):
                return jax.numpy.mean((p["a"] * x + p["b"] - y) ** 2)

            grads = jax.grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        for _ in range(3):
            for batch in batches:
                params, opt_state = step(params, opt_state, batch["x"], batch["y"])
        return float(params["a"]), float(params["b"])

    ia, ib = run_imperative()
    fa, fb = run_fused()
    ja, jb = run_pure_jax()
    assert abs(ia - fa) < ATOL and abs(ib - fb) < ATOL, (
        f"imperative vs fused diverged: ({ia},{ib}) vs ({fa},{fb})"
    )
    # The prepared paths shard the batch over the data axes; grads are averaged
    # across shards by GSPMD — numerically the full-batch gradient.
    assert abs(ia - ja) < 1e-4 and abs(ib - jb) < 1e-4, (
        f"distributed vs pure-jax baseline diverged: ({ia},{ib}) vs ({ja},{jb})"
    )
    if accelerator.is_main_process:
        print(f"training parity ok: a={ia:.5f} b={ib:.5f} (fused/pure-jax match)")


def grad_sync_check(accelerator):
    """Gradient-accumulation semantics on the real process topology (reference
    ``test_sync.py`` 410 LoC): the sync flag toggles on exact boundaries, banked
    grads agree across ranks (GSPMD reduces every microbatch), and k
    accumulated microbatches equal one k-times-larger batch at tight ATOL."""
    import jax
    import numpy as np
    import optax

    from accelerate_tpu import Accelerator, GradientAccumulationPlugin
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel, regression_batches
    from accelerate_tpu.utils.operations import gather_object

    AcceleratorState._reset_state()
    GradientState._reset_state()
    acc = Accelerator(
        gradient_accumulation_plugin=GradientAccumulationPlugin(
            num_steps=2, sync_with_dataloader=False
        )
    )
    # Batches must split over the mesh's combined data axes (multi-process
    # runs multiply the degree by the per-process device count).
    n_data = acc.mesh.shape["dp"] * acc.mesh.shape["fsdp"]
    bs = max(8, n_data)
    ds = RegressionDataset(length=4 * bs, seed=3)
    model = RegressionModel()
    model.init_params(jax.random.key(7))
    pmodel, popt = acc.prepare(model, optax.sgd(0.1))

    flags = []
    for batch in regression_batches(ds, batch_size=bs):
        with acc.accumulate(pmodel):
            flags.append(acc.sync_gradients)
            out = pmodel(**batch)
            acc.backward(out["loss"])
            if acc.sync_gradients:
                # Banked grads must be bitwise-identical across ranks: GSPMD
                # already reduced them inside the compiled backward.
                ga = float(np.asarray(popt.grads["a"]))
                everyone = gather_object([round(ga, 10)])
                assert all(v == everyone[0] for v in everyone), everyone
            popt.step()
            popt.zero_grad()
    assert flags == [False, True, False, True], flags
    accumulated = {k: float(v) for k, v in acc.get_state_dict(pmodel).items()}

    AcceleratorState._reset_state()
    GradientState._reset_state()
    acc2 = Accelerator()
    model2 = RegressionModel()
    model2.init_params(jax.random.key(7))
    pmodel2, popt2 = acc2.prepare(model2, optax.sgd(0.1))
    for batch in regression_batches(ds, batch_size=2 * bs):
        out = pmodel2(**batch)
        acc2.backward(out["loss"])
        popt2.step()
        popt2.zero_grad()
    onebatch = {k: float(v) for k, v in acc2.get_state_dict(pmodel2).items()}
    for k in accumulated:
        assert abs(accumulated[k] - onebatch[k]) < 1e-5, (k, accumulated[k], onebatch[k])

    # Restore a fresh default state for subsequent checks.
    AcceleratorState._reset_state()
    GradientState._reset_state()
    Accelerator()
    if accelerator.is_main_process:
        print("grad sync ok")


def trigger_check(accelerator):
    """A flag set on the last rank must be seen by every rank (reference
    ``test_trigger`` :837-852)."""
    if accelerator.process_index == accelerator.num_processes - 1:
        accelerator.set_trigger()
    assert accelerator.check_trigger() is True
    assert accelerator.check_trigger() is False  # cleared after firing
    if accelerator.is_main_process:
        print("trigger ok")


def main():
    from accelerate_tpu import Accelerator

    accelerator = Accelerator()
    init_state_check(accelerator)
    process_execution_check(accelerator)
    rng_sync_check(accelerator)
    dl_preparation_check(accelerator)
    central_dl_preparation_check(accelerator)
    check_seedable_sampler(accelerator)
    collectives_check(accelerator)
    split_between_processes_check(accelerator)
    training_check(accelerator)
    grad_sync_check(accelerator)
    trigger_check(accelerator)
    accelerator.wait_for_everyone()
    if accelerator.is_main_process:
        print("All distributed asserts passed.")


if __name__ == "__main__":
    main()
