"""Multi-host elastic world-size drill, run under the real 2-process launcher::

    accelerate-tpu launch --cpu --num_processes 2 --elastic \
        --min_data_parallel 1 -m accelerate_tpu.test_utils.elastic_script

Proves the multi-host half of the elastic contract (tests/test_elastic.py
covers the single-process reshard mechanics on the 8-device mesh):

- the launcher's ``--elastic``/``--min_data_parallel`` flags reach every
  worker as ACCELERATE_ELASTIC / ACCELERATE_MIN_DATA_PARALLEL, and
  ``run_resilient`` picks them up as its defaults;
- before re-forming a gang at a new size, every host agrees on the total
  surviving device count through :func:`~accelerate_tpu.resilience.elastic.
  agree_world_size`. On CPU backends the XLA runtime refuses multiprocess
  computations, which is exactly the environment where the exchange must
  ride the coordination-service KV fallback — each rank posts its local
  count (rank 0 simulates losing half its devices) and every rank reads the
  same total back;
- the agreed count resolves through the same mesh arithmetic the reshard
  uses (``elastic_parallelism_for``), including the min_data_parallel floor.
"""

from __future__ import annotations

import os

from accelerate_tpu import PartialState
from accelerate_tpu.parallel.mesh import elastic_parallelism_for
from accelerate_tpu.resilience.elastic import (
    agree_world_size,
    elastic_from_env,
    min_data_parallel_from_env,
)


def main():
    state = PartialState()
    assert state.num_processes >= 2, "run under `launch --num_processes 2`"

    # 1. The env contract reached this worker.
    assert os.environ.get("ACCELERATE_ELASTIC") == "1", os.environ.get("ACCELERATE_ELASTIC")
    assert elastic_from_env() is True
    assert min_data_parallel_from_env() == int(
        os.environ.get("ACCELERATE_MIN_DATA_PARALLEL", "1")
    )

    # 2. World-size agreement over the KV fallback: rank 0 "lost" half of a
    # simulated 4-device host, every other rank still holds 4 — all ranks
    # must compute the identical survivor total.
    local = 2 if state.process_index == 0 else 4
    total = agree_world_size(state, local_device_count=local)
    expected = 2 + 4 * (state.num_processes - 1)
    assert total == expected, f"rank {state.process_index}: {total} != {expected}"

    # A second exchange must not collide with the first (single-use KV
    # namespaces) and must agree again.
    assert agree_world_size(state, local_device_count=local) == expected

    # 3. The agreed total resolves through the elastic mesh arithmetic —
    # every non-dp axis fixed, dp absorbing the survivors — and the
    # min_data_parallel floor refuses pointedly below it.
    config = elastic_parallelism_for(state.mesh, expected, min_data_parallel=1)
    assert config.dp_size * config.fsdp_size >= 1
    try:
        elastic_parallelism_for(state.mesh, expected, min_data_parallel=expected + 1)
    except ValueError as exc:
        assert "min_data_parallel" in str(exc)
    else:
        raise AssertionError("min_data_parallel floor did not refuse")

    # No device barrier here: multiprocess CPU refuses collective
    # computations (the whole reason this drill rides the KV transport).
    # Every rank reports success; the test counts both.
    print(f"ELASTIC_AGREEMENT_OK rank={state.process_index}", flush=True)


if __name__ == "__main__":
    main()
