"""Disaggregated-serving drill, run under the real 2-process launcher::

    accelerate-tpu launch --cpu --num_processes 2 -m \
        accelerate_tpu.test_utils.disagg_script

Proves the tentpole property ``tests/test_serving_net.py`` pins: prefill and
decode run on disjoint "hosts" (rank 0 = decode, rank 1 = prefill — separate
processes, separate pools, separate metrics endpoints registered in the
coordination-service KV namespace), a router on the decode host discovers
BOTH workers through that registry, and a client driving the router over
real HTTP/SSE gets:

- token output **bit-identical** to one unified single-host paged engine
  running the same prompts (handoff is state surgery, never a recompute);
- one ``done``-event trace per request spanning router admission → prefill
  chunks → chain handoff → first decode token, with TTFT/TPOT and
  queue-wait attribution on the records;
- ``accelerate-tpu top`` (JSON and human frames, real subprocesses against
  the lead host's endpoint) showing BOTH tiers' fleet rollups.

The model is tiny and seeded identically on both ranks, so every parity
assertion is exact; the registration, discovery, routing, chunked prefill,
chain transfer, import surgery, and streaming are all real.
"""

from __future__ import annotations

import json
import subprocess
import sys
import threading
import urllib.request

import numpy as np

from accelerate_tpu import PartialState
from accelerate_tpu.telemetry import start_default_server
from accelerate_tpu.telemetry.fleet import (
    FleetAggregator,
    install_fleet_provider,
    publish_metrics_endpoint,
)
from accelerate_tpu.utils.agreement import kv_all_gather

# chunk=8 with these prompt lengths pins the routing split: 3/5 fit one
# chunk (decode entry), 14/21 are multi-chunk (prefill entry + handoff).
PROMPT_LENS = (5, 14, 3, 21)
CHUNK = 8
MAX_NEW = 8


def _model():
    import jax

    from accelerate_tpu.models import Llama, LlamaConfig

    cfg = LlamaConfig.tiny(vocab_size=256, hidden_size=64,
                           intermediate_size=128, num_hidden_layers=2,
                           num_attention_heads=4, num_key_value_heads=2)
    model = Llama(cfg)
    model.init_params(jax.random.key(0))  # same key both ranks: exact parity
    return model


def _engine(model):
    import jax.numpy as jnp

    from accelerate_tpu.serving import ContinuousBatcher

    return ContinuousBatcher(
        model, batch_slots=2, max_new_tokens=MAX_NEW, max_cache_len=1024,
        cache_dtype=jnp.float32, bucket_sizes=(8, 16), sync_every=2,
        paged=True, block_size=4, prefill_chunk=CHUNK,
        max_tokens_per_request=48,
    )


def _prompts():
    rng = np.random.default_rng(7)
    return [rng.integers(1, 256, (n,)).astype(np.int32) for n in PROMPT_LENS]


def _generate(endpoint: str, prompt) -> dict:
    from accelerate_tpu.serving_net.frontend import read_sse_response

    req = urllib.request.Request(
        f"http://{endpoint}/v1/generate",
        data=json.dumps({"prompt": [int(t) for t in prompt],
                         "max_new_tokens": MAX_NEW}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=300.0) as response:
        return read_sse_response(response)


def main():
    state = PartialState()
    assert state.num_processes >= 2, "run under `launch --num_processes 2`"
    rank = state.process_index
    role = "decode" if rank == 0 else "prefill"

    from accelerate_tpu.serving_net import Router, ServingFrontend

    model = _model()
    server = start_default_server(0)  # ephemeral: nobody knows the port
    endpoint = publish_metrics_endpoint(process_index=rank, server=server)
    assert endpoint is not None, "metrics endpoint registration failed"

    engine = _engine(model)
    frontend = ServingFrontend(engine, role=role)
    # Global-provider install: this rank's ONE metrics server now serves
    # /v1/* for its tier, and the role+endpoint lands in the serving KV
    # namespace (what the router discovers — no address list anywhere).
    frontend.install(process_index=rank, endpoint=endpoint)

    kv_all_gather("ready", state.num_processes, rank,
                  namespace="at_disagg_drill/ready")

    if rank == 0:
        # The single-host truth: one unified engine, same model, same
        # kwargs, same prompts — greedy output the routed path must match
        # bit for bit.
        prompts = _prompts()
        baseline_engine = _engine(model)
        rids = [baseline_engine.submit(p) for p in prompts]
        baseline = baseline_engine.run()
        expected = [[int(t) for t in baseline[r]] for r in rids]

        # The router rides its own loopback server (multi-role host): its
        # /v1 provider is attached per-server, so the default server keeps
        # serving the decode tier.
        from accelerate_tpu.telemetry.metrics import MetricsServer

        router_server = MetricsServer(0, host="127.0.0.1")
        router_port = router_server.start()
        router = Router(num_processes=state.num_processes)
        router_server.set_serving(router)
        router_ep = f"127.0.0.1:{router_port}"
        workers = {w["role"]: w for w in router.workers()}
        assert set(workers) == {"decode", "prefill"}, workers
        assert workers["decode"]["endpoint"] == endpoint, workers

        results = [None] * len(prompts)
        errors = []

        def client(i, prompt):
            try:
                results[i] = _generate(router_ep, prompt)
            except Exception as exc:
                errors.append(f"request {i}: {exc!r}")

        threads = [threading.Thread(target=client, args=(i, p))
                   for i, p in enumerate(prompts)]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join()
        assert not errors, errors

        # Bit-identical parity, every request.
        for i, result in enumerate(results):
            assert result["tokens"] == expected[i], (
                f"request {i}: disagg {result['tokens']} != unified {expected[i]}"
            )

        # One trace per request spanning every tier it crossed, TTFT/TPOT +
        # queue-wait attribution on the records.
        for i, result in enumerate(results):
            done = result["done"]
            trace = done["trace"]
            tiers = [r.get("tier") for r in trace]
            multi_chunk = PROMPT_LENS[i] > CHUNK
            want = (["router", "prefill", "decode"] if multi_chunk
                    else ["router", "decode"])
            assert tiers == want, (i, tiers)
            assert done["ttft_s"] is not None and done["tpot_s"] is not None, done
            router_rec, decode_rec = trace[0], trace[-1]
            assert router_rec["decision"] == (
                "route_prefill" if multi_chunk else "route_decode"
            ), router_rec
            # Queue wait is attributed on the tier the request ENTERED —
            # the prefill record for handed-off requests, the decode record
            # for requests that decoded where they landed.
            entered = trace[1] if multi_chunk else decode_rec
            assert entered["queue_wait_s"] is not None, entered
            assert decode_rec["state"] == "finished", decode_rec
            if multi_chunk:
                prefill_rec = trace[1]
                assert prefill_rec["state"] == "handed_off", prefill_rec
                leg = prefill_rec["handoff"]
                assert leg["direction"] == "out" and leg["bytes"] > 0, leg
                assert len(prefill_rec["chunks"]) >= 2, prefill_rec
                assert decode_rec["handoff"]["direction"] == "in", decode_rec
            # One rid spans every tier it crossed.
            assert len({r["rid"] for r in trace}) == 1, trace

        # The operator console: both tiers' rollups through the real
        # aggregate-and-render path.
        install_fleet_provider(FleetAggregator(state=state))
        snap = subprocess.run(
            [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
             "top", "--once", "--json", "--endpoint", endpoint],
            capture_output=True, text=True, timeout=120,
        )
        assert snap.returncode == 0, snap.stdout[-800:] + snap.stderr[-800:]
        got = json.loads(snap.stdout)
        assert got["hosts"]["0"]["serving_role"] == "decode", got["hosts"]
        assert got["hosts"]["1"]["serving_role"] == "prefill", got["hosts"]
        tiers = got["fleet"]["serving_tiers"]
        assert set(tiers) >= {"decode", "prefill"}, tiers
        assert tiers["decode"]["hosts"] == 1 and tiers["prefill"]["hosts"] == 1
        assert tiers["decode"]["requests"] >= len(prompts), tiers["decode"]
        assert tiers["prefill"]["handoff"]["out"]["chains"] == 2, tiers["prefill"]
        assert tiers["decode"]["handoff"]["in"]["chains"] == 2, tiers["decode"]
        assert tiers["decode"]["ttft_s_mean"] is not None, tiers["decode"]

        frame = subprocess.run(
            [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
             "top", "--once", "--endpoint", endpoint],
            capture_output=True, text=True, timeout=120,
        )
        assert frame.returncode == 0, frame.stderr[-800:]
        assert "serving[decode]" in frame.stdout, frame.stdout
        assert "serving[prefill]" in frame.stdout, frame.stdout

        router_server.stop()

    kv_all_gather("done", state.num_processes, rank,
                  namespace="at_disagg_drill/done")
    frontend.uninstall()
    print(f"DISAGG_OK rank={rank} role={role} endpoint={endpoint}")


if __name__ == "__main__":
    main()
