"""Disaggregated-serving drill, run under the real 2-process launcher::

    accelerate-tpu launch --cpu --num_processes 2 -m \
        accelerate_tpu.test_utils.disagg_script

Proves the tentpole property ``tests/test_serving_net.py`` pins: prefill and
decode run on disjoint "hosts" (rank 0 = decode, rank 1 = prefill — separate
processes, separate pools, separate metrics endpoints registered in the
coordination-service KV namespace), a router on the decode host discovers
BOTH workers through that registry, and a client driving the router over
real HTTP/SSE gets:

- token output **bit-identical** to one unified single-host paged engine
  running the same prompts (handoff is state surgery, never a recompute);
- one ``done``-event trace per request spanning router admission → prefill
  chunks → chain handoff → first decode token, with TTFT/TPOT and
  queue-wait attribution on the records;
- ``accelerate-tpu top`` (JSON and human frames, real subprocesses against
  the lead host's endpoint) showing BOTH tiers' fleet rollups.

The model is tiny and seeded identically on both ranks, so every parity
assertion is exact; the registration, discovery, routing, chunked prefill,
chain transfer, import surgery, and streaming are all real.

Chaos mode (``AT_DISAGG_CHAOS=1``, 3 processes) turns the same script into
the serving fault-tolerance drill ``tests/test_serving_faults.py`` pins::

    AT_DISAGG_CHAOS=1 accelerate-tpu launch --cpu --num_processes 3 \
        --serving_lease_ttl 2 --serving_retry_budget 3 --drain_grace_s 20 \
        -m accelerate_tpu.test_utils.disagg_script

Rank 0 runs the router, the prefill tier, and the client; ranks 1 and 2 are
decode workers. Three phases, each against the single-host baseline:

- **A (worker_kill)**: rank 1's fault plan kills its first stream after the
  first delta. The router retries on rank 2 under the same rid; the client
  sees ONE contiguous bit-identical stream, and the corpse is lease-evicted
  from discovery within its TTL.
- **B (handoff_drop)**: rank 0's first chain export is dropped on the wire.
  Free-on-ack returns every block to the prefill free list (no leaks) and
  the request still completes bit-identically through re-entry.
- **C (graceful drain)**: rank 2 gets SIGTERM mid-request. The in-flight
  stream finishes, the lease is revoked, and the next request is shed with
  a fast 503 + ``retry_after_s`` (every decode worker gone).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np

from accelerate_tpu import PartialState
from accelerate_tpu.telemetry import start_default_server
from accelerate_tpu.telemetry.fleet import (
    FleetAggregator,
    install_fleet_provider,
    publish_metrics_endpoint,
)
from accelerate_tpu.utils.agreement import kv_all_gather

# chunk=8 with these prompt lengths pins the routing split: 3/5 fit one
# chunk (decode entry), 14/21 are multi-chunk (prefill entry + handoff).
PROMPT_LENS = (5, 14, 3, 21)
CHUNK = 8
MAX_NEW = 8


def _model():
    import jax

    from accelerate_tpu.models import Llama, LlamaConfig

    cfg = LlamaConfig.tiny(vocab_size=256, hidden_size=64,
                           intermediate_size=128, num_hidden_layers=2,
                           num_attention_heads=4, num_key_value_heads=2)
    model = Llama(cfg)
    model.init_params(jax.random.key(0))  # same key both ranks: exact parity
    return model


def _engine(model):
    import jax.numpy as jnp

    from accelerate_tpu.serving import ContinuousBatcher

    return ContinuousBatcher(
        model, batch_slots=2, max_new_tokens=MAX_NEW, max_cache_len=1024,
        cache_dtype=jnp.float32, bucket_sizes=(8, 16), sync_every=2,
        paged=True, block_size=4, prefill_chunk=CHUNK,
        max_tokens_per_request=48,
    )


def _prompts():
    rng = np.random.default_rng(7)
    return [rng.integers(1, 256, (n,)).astype(np.int32) for n in PROMPT_LENS]


def _generate(endpoint: str, prompt) -> dict:
    from accelerate_tpu.serving_net.frontend import read_sse_response

    req = urllib.request.Request(
        f"http://{endpoint}/v1/generate",
        data=json.dumps({"prompt": [int(t) for t in prompt],
                         "max_new_tokens": MAX_NEW}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=300.0) as response:
        return read_sse_response(response)


def main():
    state = PartialState()
    assert state.num_processes >= 2, "run under `launch --num_processes 2`"
    rank = state.process_index
    role = "decode" if rank == 0 else "prefill"

    from accelerate_tpu.serving_net import Router, ServingFrontend

    model = _model()
    server = start_default_server(0)  # ephemeral: nobody knows the port
    endpoint = publish_metrics_endpoint(process_index=rank, server=server)
    assert endpoint is not None, "metrics endpoint registration failed"

    engine = _engine(model)
    frontend = ServingFrontend(engine, role=role)
    # Global-provider install: this rank's ONE metrics server now serves
    # /v1/* for its tier, and the role+endpoint lands in the serving KV
    # namespace (what the router discovers — no address list anywhere).
    frontend.install(process_index=rank, endpoint=endpoint)

    kv_all_gather("ready", state.num_processes, rank,
                  namespace="at_disagg_drill/ready")

    if rank == 0:
        # The single-host truth: one unified engine, same model, same
        # kwargs, same prompts — greedy output the routed path must match
        # bit for bit.
        prompts = _prompts()
        baseline_engine = _engine(model)
        rids = [baseline_engine.submit(p) for p in prompts]
        baseline = baseline_engine.run()
        expected = [[int(t) for t in baseline[r]] for r in rids]

        # The router rides its own loopback server (multi-role host): its
        # /v1 provider is attached per-server, so the default server keeps
        # serving the decode tier.
        from accelerate_tpu.telemetry.metrics import MetricsServer

        router_server = MetricsServer(0, host="127.0.0.1")
        router_port = router_server.start()
        router = Router(num_processes=state.num_processes)
        router_server.set_serving(router)
        router_ep = f"127.0.0.1:{router_port}"
        workers = {w["role"]: w for w in router.workers()}
        assert set(workers) == {"decode", "prefill"}, workers
        assert workers["decode"]["endpoint"] == endpoint, workers

        results = [None] * len(prompts)
        errors = []

        def client(i, prompt):
            try:
                results[i] = _generate(router_ep, prompt)
            except Exception as exc:
                errors.append(f"request {i}: {exc!r}")

        threads = [threading.Thread(target=client, args=(i, p))
                   for i, p in enumerate(prompts)]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join()
        assert not errors, errors

        # Bit-identical parity, every request.
        for i, result in enumerate(results):
            assert result["tokens"] == expected[i], (
                f"request {i}: disagg {result['tokens']} != unified {expected[i]}"
            )

        # One trace per request spanning every tier it crossed, TTFT/TPOT +
        # queue-wait attribution on the records.
        for i, result in enumerate(results):
            done = result["done"]
            trace = done["trace"]
            tiers = [r.get("tier") for r in trace]
            multi_chunk = PROMPT_LENS[i] > CHUNK
            want = (["router", "prefill", "decode"] if multi_chunk
                    else ["router", "decode"])
            assert tiers == want, (i, tiers)
            assert done["ttft_s"] is not None and done["tpot_s"] is not None, done
            router_rec, decode_rec = trace[0], trace[-1]
            assert router_rec["decision"] == (
                "route_prefill" if multi_chunk else "route_decode"
            ), router_rec
            # Queue wait is attributed on the tier the request ENTERED —
            # the prefill record for handed-off requests, the decode record
            # for requests that decoded where they landed.
            entered = trace[1] if multi_chunk else decode_rec
            assert entered["queue_wait_s"] is not None, entered
            assert decode_rec["state"] == "finished", decode_rec
            if multi_chunk:
                prefill_rec = trace[1]
                assert prefill_rec["state"] == "handed_off", prefill_rec
                leg = prefill_rec["handoff"]
                assert leg["direction"] == "out" and leg["bytes"] > 0, leg
                assert len(prefill_rec["chunks"]) >= 2, prefill_rec
                assert decode_rec["handoff"]["direction"] == "in", decode_rec
            # One rid spans every tier it crossed.
            assert len({r["rid"] for r in trace}) == 1, trace

        # The operator console: both tiers' rollups through the real
        # aggregate-and-render path.
        install_fleet_provider(FleetAggregator(state=state))
        snap = subprocess.run(
            [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
             "top", "--once", "--json", "--endpoint", endpoint],
            capture_output=True, text=True, timeout=120,
        )
        assert snap.returncode == 0, snap.stdout[-800:] + snap.stderr[-800:]
        got = json.loads(snap.stdout)
        assert got["hosts"]["0"]["serving_role"] == "decode", got["hosts"]
        assert got["hosts"]["1"]["serving_role"] == "prefill", got["hosts"]
        tiers = got["fleet"]["serving_tiers"]
        assert set(tiers) >= {"decode", "prefill"}, tiers
        assert tiers["decode"]["hosts"] == 1 and tiers["prefill"]["hosts"] == 1
        assert tiers["decode"]["requests"] >= len(prompts), tiers["decode"]
        assert tiers["prefill"]["handoff"]["out"]["chains"] == 2, tiers["prefill"]
        assert tiers["decode"]["handoff"]["in"]["chains"] == 2, tiers["decode"]
        assert tiers["decode"]["ttft_s_mean"] is not None, tiers["decode"]

        frame = subprocess.run(
            [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
             "top", "--once", "--endpoint", endpoint],
            capture_output=True, text=True, timeout=120,
        )
        assert frame.returncode == 0, frame.stderr[-800:]
        assert "serving[decode]" in frame.stdout, frame.stdout
        assert "serving[prefill]" in frame.stdout, frame.stdout

        router_server.stop()

    kv_all_gather("done", state.num_processes, rank,
                  namespace="at_disagg_drill/done")
    frontend.uninstall()
    print(f"DISAGG_OK rank={rank} role={role} endpoint={endpoint}")


def _drive_chaos(state, model, engine, endpoint: str, ttl: float):
    """Rank 0's client script for the chaos drill: baseline, router, the
    three phases (worker_kill / handoff_drop / drain), and the fleet-rollup
    asserts. ``engine`` is this rank's own prefill engine (phase B asserts
    directly on its free list)."""
    from accelerate_tpu.resilience.faults import FaultPlan, set_active_plan
    from accelerate_tpu.serving_net import Router
    from accelerate_tpu.telemetry.fleet import _kv_client
    from accelerate_tpu.telemetry.metrics import MetricsServer

    rng = np.random.default_rng(11)
    prompt_a = rng.integers(1, 256, (5,)).astype(np.int32)   # decode entry
    prompt_b = rng.integers(1, 256, (21,)).astype(np.int32)  # prefill entry
    prompt_c = rng.integers(1, 256, (6,)).astype(np.int32)   # decode entry

    baseline = _engine(model)
    rids = [baseline.submit(p) for p in (prompt_a, prompt_b, prompt_c)]
    outs = baseline.run()
    want_a, want_b, want_c = ([int(t) for t in outs[r]] for r in rids)

    router_server = MetricsServer(0, host="127.0.0.1")
    router_port = router_server.start()
    # cache_s is short so eviction polls observe fresh discovery; the retry
    # budget must be the --serving_retry_budget 3 the launcher exported.
    router = Router(num_processes=state.num_processes, cache_s=0.5)
    assert router.retry_budget == 3, router.retry_budget
    router_server.set_serving(router)
    router_ep = f"127.0.0.1:{router_port}"

    workers = router.workers()
    by_rank = {w["rank"]: w for w in workers}
    assert set(by_rank) == {0, 1, 2}, workers
    assert by_rank[0]["role"] == "prefill", workers
    assert {by_rank[1]["role"], by_rank[2]["role"]} == {"decode"}, workers
    for worker in workers:
        assert worker.get("expires"), f"lease without expiry: {worker}"
    victim_ep = by_rank[1]["endpoint"]

    # ------------------------------------------------- phase A: worker_kill
    # Least-loaded tie-break picks the lowest rank, so the first
    # decode-entry request deterministically lands on rank 1 — whose plan
    # kills the stream right after the first delta.
    res_a = _generate(router_ep, prompt_a)
    assert res_a["tokens"] == want_a, (res_a["tokens"], want_a)
    # ONE contiguous stream: the deltas across both legs concatenate to a
    # clean prefix of the final token list (the engine holds the last token
    # for the done frame) — replayed prefix trimmed, nothing lost.
    streamed = [t for d in res_a["deltas"] for t in d]
    assert streamed and streamed == want_a[:len(streamed)], res_a["deltas"]
    stats = router.stats()
    assert stats["retries"].get("stream_broken", 0) >= 1, stats["retries"]
    legs = res_a["done"]["trace"][0].get("retries")
    assert legs and legs[0]["reason"] == "stream_broken", legs

    # Lease eviction within one TTL of the corpse's last heartbeat: poll
    # discovery (bounded by TTL + one refresh slice + slack) until the
    # victim vanishes, then check the breaker opened and the reason stuck.
    deadline = time.monotonic() + ttl + 5.0
    while time.monotonic() < deadline:
        if victim_ep not in {w["endpoint"] for w in router.workers()}:
            break
        time.sleep(0.25)
    else:
        raise AssertionError(f"victim {victim_ep} never lease-evicted")
    stats = router.stats()
    assert stats["evictions"].get(victim_ep) == "lease_expired", stats
    assert stats["breakers"].get(victim_ep) == "open", stats["breakers"]

    # ------------------------------------------------ phase B: handoff_drop
    # This rank's FIRST chain export is dropped on the wire. The chain must
    # come back to the free list (free-on-ack — a dropped handoff never
    # leaks blocks) and the request must still finish bit-identically
    # through re-entry on a surviving path.
    set_active_plan(FaultPlan.parse("req:0=handoff_drop"))
    free0 = len(engine._free_blocks)
    res_b = _generate(router_ep, prompt_b)
    set_active_plan(None)
    assert res_b["tokens"] == want_b, (res_b["tokens"], want_b)
    streamed = [t for d in res_b["deltas"] for t in d]
    assert streamed == want_b[:len(streamed)], res_b["deltas"]
    deadline = time.monotonic() + 10.0
    while (len(engine._free_blocks) != free0
           and time.monotonic() < deadline):
        time.sleep(0.05)
    assert len(engine._free_blocks) == free0, (
        f"handoff_drop leaked blocks: {len(engine._free_blocks)} != {free0}"
    )
    stats = router.stats()
    assert stats["retries"].get("handoff_failed", 0) >= 1, stats["retries"]

    # ------------------------------------------------------ phase C: drain
    # SIGTERM the last decode worker while a request is in flight on it:
    # the stream must finish (drain waits), the lease must be revoked, and
    # the next request must be shed with a fast 503 + retry_after_s.
    client = _kv_client()
    result_c: dict = {}

    def run_c():
        try:
            result_c["res"] = _generate(router_ep, prompt_c)
        except Exception as exc:
            result_c["err"] = repr(exc)

    survivor_ep = next(w["endpoint"] for w in router.workers()
                       if w["role"] == "decode")
    thread = threading.Thread(target=run_c)
    thread.start()
    deadline = time.monotonic() + 60.0
    stats_c: dict = {}
    while time.monotonic() < deadline:
        with urllib.request.urlopen(
                f"http://{survivor_ep}/v1/stats", timeout=5.0) as response:
            stats_c = json.loads(response.read())
        if stats_c.get("in_flight", 0) >= 1:
            break
        if not thread.is_alive():
            raise AssertionError(
                f"phase-C request finished before the drain order — the "
                f"slow_worker fault never fired: client={result_c} "
                f"survivor_stats={stats_c}"
            )
        time.sleep(0.02)
    else:
        raise AssertionError(
            f"phase-C request never reached the survivor: client={result_c} "
            f"survivor_stats={stats_c} router={router.stats()}"
        )
    client.key_value_set("at_chaos_drill/drain", "1")
    thread.join(180.0)
    assert not thread.is_alive(), "phase-C stream never finished under drain"
    res_c = result_c.get("res")
    assert res_c is not None and res_c["tokens"] == want_c, result_c

    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        roles = {w["role"] for w in router.workers()}
        if not roles & {"decode", "unified"}:
            break
        time.sleep(0.25)
    else:
        raise AssertionError("survivor lease never revoked after drain")

    body = json.dumps({"prompt": [int(t) for t in prompt_c],
                       "max_new_tokens": MAX_NEW}).encode()
    request = urllib.request.Request(
        f"http://{router_ep}/v1/generate", data=body,
        headers={"Content-Type": "application/json"},
    )
    started = time.monotonic()
    try:
        urllib.request.urlopen(request, timeout=30.0)
        raise AssertionError("expected a 503 once every decode worker died")
    except urllib.error.HTTPError as exc:
        shed = json.loads(exc.read())
        assert exc.code == 503, exc.code
        assert shed.get("retryable") is True, shed
        assert shed.get("retry_after_s"), shed
    assert time.monotonic() - started < 15.0, "shed was not fast"

    # Fleet rollups: the retry/eviction counters live on this host (router
    # rides the prefill rank's registry), the drained-in-flight counter on
    # the decode tier (rank 2 booked its drain before revoking).
    agg = FleetAggregator(state=state)
    tiers = agg.snapshot()["fleet"]["serving_tiers"]
    assert tiers["prefill"]["evictions"].get("lease_expired", 0) >= 1, tiers
    retried = sum(tiers["prefill"].get("retries", {}).values())
    assert retried >= 2, tiers["prefill"]
    assert tiers["decode"].get("drained_in_flight", 0) >= 1, tiers["decode"]

    router_server.stop()
    print("CHAOS_PHASES_OK worker_kill handoff_drop drain")


def main_chaos():
    """Entry point for the 3-process chaos drill (module docstring)."""
    state = PartialState()
    assert state.num_processes >= 3, "run under `launch --num_processes 3`"
    rank = state.process_index
    role = "prefill" if rank == 0 else "decode"

    from accelerate_tpu.resilience.faults import FaultPlan, set_active_plan
    from accelerate_tpu.serving_net import ServingFrontend
    from accelerate_tpu.serving_net.lease import (
        drain_grace_from_env,
        lease_ttl_from_env,
        retry_budget_from_env,
    )
    from accelerate_tpu.telemetry.fleet import _kv_client

    # The launch flags must have reached every worker's env.
    ttl = lease_ttl_from_env()
    assert ttl == 2.0, f"drill expects --serving_lease_ttl 2, got {ttl}"
    assert retry_budget_from_env() == 3, retry_budget_from_env()
    assert drain_grace_from_env() == 20.0, drain_grace_from_env()

    model = _model()
    server = start_default_server(0)
    endpoint = publish_metrics_endpoint(process_index=rank, server=server)
    assert endpoint is not None, "metrics endpoint registration failed"

    engine = _engine(model)
    frontend = ServingFrontend(engine, role=role)
    if rank == 1:
        # The victim. Soft death ("stream") keeps the PROCESS alive so the
        # gang's coordination-service barriers stay sound, while the worker
        # behaves exactly like a corpse on the wire: its stream breaks with
        # no terminal frame, its heartbeat stops so the lease expires, and
        # every later handler answers 503 (probes fail). The hard
        # ``os._exit`` flavor stays the production default.
        frontend.kill_mode = "stream"
        set_active_plan(FaultPlan.parse("req:0=worker_kill"))
    elif rank == 2:
        # The survivor: stretch its third admission (phase C) so the drain
        # order always lands while that request is in flight — and exercise
        # the slow_worker grammar while at it. Admissions here: phase A's
        # retry leg (0), phase B's re-entry (1), phase C (2); seq 3 is armed
        # too in case phase B re-enters twice.
        set_active_plan(
            FaultPlan.parse("req:2=slow_worker:6x;req:3=slow_worker:6x"))
    frontend.install(process_index=rank, endpoint=endpoint)

    kv_all_gather("ready", state.num_processes, rank,
                  namespace="at_chaos_drill/ready")
    client = _kv_client()

    if rank == 0:
        _drive_chaos(state, model, engine, endpoint, ttl)
        client.key_value_set("at_chaos_drill/done", "1")
        frontend.uninstall()
    elif rank == 1:
        # Serve until rank 0 is done (the kill arrives over HTTP); no
        # all-rank barrier after the fault — the corpse must not be waited
        # on by anyone.
        client.blocking_key_value_get("at_chaos_drill/done", 480_000)
    else:
        # Serve until ordered to drain, then deliver SIGTERM to ourselves —
        # the preemption watcher (installed by frontend.install) flips the
        # flag, and the frontend's watch thread runs the drain: admission
        # stops, the in-flight stream finishes, the lease is revoked.
        client.blocking_key_value_get("at_chaos_drill/drain", 480_000)
        os.kill(os.getpid(), signal.SIGTERM)
        client.blocking_key_value_get("at_chaos_drill/done", 480_000)

    print(f"DISAGG_OK rank={rank} role={role} endpoint={endpoint}")


if __name__ == "__main__":
    if os.environ.get("AT_DISAGG_CHAOS") == "1":
        main_chaos()
    else:
        main()
