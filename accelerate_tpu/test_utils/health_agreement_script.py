"""Multi-host health-agreement drill, run under the real 2-process launcher::

    accelerate-tpu launch --cpu --num_processes 2 -m \
        accelerate_tpu.test_utils.health_agreement_script

Proves the property ``tests/test_health.py`` pins: when ONE host's guard trips
(a loss spike injected on rank 0 only), EVERY host learns of it through the
agreement exchange at the same step and rolls back identically — the resumed
state is bit-exact against a clean run that pre-quarantined the same batch,
on every rank, and the ranks agree with each other.

The training here is deliberately host-side (a scalar updated with
deterministic per-step increments): on CPU backends the XLA runtime refuses
multiprocess computations, which is exactly the environment where the guard's
coordination-service (KV-store) agreement fallback must carry the decision —
the device-collective path stays covered by the single-process drills. The
spike statistics are still real device state (single-device jit), snapshotted
and restored through :class:`~accelerate_tpu.health.LastKnownGood` like the
full integration does.
"""

from __future__ import annotations

from accelerate_tpu import PartialState
from accelerate_tpu.health import HealthGuard, LastKnownGood

TOTAL, TRIP, SNAPSHOT_EVERY = 12, 8, 3


def _loss(step: int) -> float:
    return 10.0 / step  # deterministic, smoothly decreasing


def _grad(step: int) -> float:
    return 0.25 * step  # deterministic toy "update"


def run(state, inject_rank: int | None):
    guard = HealthGuard(spike_warmup=3, spike_zscore=6.0, snapshot_every=SNAPSHOT_EVERY)
    lkg = LastKnownGood(every_steps=SNAPSHOT_EVERY)
    if inject_rank is None:
        guard.quarantine(TRIP)  # the clean comparator never sees the batch
    w, step, trips = 0.0, 0, 0
    while step < TOTAL:
        nxt = step + 1
        if guard.should_skip(nxt):
            step = nxt
            continue
        w += _grad(nxt)
        loss = _loss(nxt)
        if inject_rank == state.process_index and nxt == TRIP:
            loss *= 500.0  # one host's bad batch
        step = nxt
        flags, trip_step, _z = guard.check(loss, step=nxt, state=state)
        if flags:
            trips += 1
            guard.quarantine(trip_step)
            guard._pending.clear()
            step, spike_state, host = lkg.restore()
            guard._spike_state = spike_state
            w = host["w"]
        elif lkg.due(nxt):
            lkg.capture(nxt, device_state=guard._spike_state, host_state={"w": w})
    return w, trips, guard


def main():
    state = PartialState()
    assert state.num_processes >= 2, "run under `launch --num_processes 2`"

    clean_w, clean_trips, _ = run(state, inject_rank=None)
    assert clean_trips == 0, f"clean run tripped {clean_trips}x on rank {state.process_index}"

    faulted_w, faulted_trips, guard = run(state, inject_rank=0)
    # Rank 0 tripped locally; every OTHER rank must have tripped via agreement.
    assert faulted_trips == 1, f"rank {state.process_index} saw {faulted_trips} trips"
    assert guard.should_skip(TRIP)
    assert faulted_w == clean_w, (
        f"rank {state.process_index}: rolled-back run diverged "
        f"({faulted_w!r} != {clean_w!r})"
    )

    # The preemption watcher's sync rides the same fallback: a flag raised on
    # rank 0 only must come back agreed-True on every rank.
    from accelerate_tpu.resilience.preemption import PreemptionWatcher

    watcher = PreemptionWatcher(signals=())
    if state.process_index == 0:
        watcher._flag = True
    assert watcher.sync(state) is True, f"rank {state.process_index} missed the preemption"
    assert watcher.preemption_requested  # agreement is sticky everywhere

    # Cross-rank check: exchange finals through the coordination KV store.
    from jax._src.distributed import global_state as dist_state

    client = dist_state.client
    if client is not None:
        client.key_value_set(f"at_health_drill/final/{state.process_index}", repr(faulted_w))
        client.wait_at_barrier("at_health_drill/final_barrier", 60_000)
        finals = {
            rank: client.blocking_key_value_get(f"at_health_drill/final/{rank}", 60_000)
            for rank in range(state.num_processes)
        }
        assert len(set(finals.values())) == 1, f"ranks disagree: {finals}"

    print(f"HEALTH_AGREE_OK rank={state.process_index} final={faulted_w}")


if __name__ == "__main__":
    main()
