"""Multi-host fleet-aggregation drill, run under the real 2-process launcher::

    accelerate-tpu launch --cpu --num_processes 2 -m \
        accelerate_tpu.test_utils.fleet_script

Proves the tentpole property ``tests/test_fleet.py`` pins: each rank starts
its own metrics endpoint (EPHEMERAL port — nobody knows the address up
front), registers the actually-bound ``host:port`` in the coordination-
service KV registry, and the lead host's :class:`FleetAggregator` discovers
BOTH endpoints with no operator-supplied address list, scrapes them, and
joins the series under distinct ``host`` labels with fleet rollups (MFU
mean, step-time skew). ``accelerate-tpu top --once --json`` is then run as a
real subprocess against the lead host's endpoint and must return the same
two-host snapshot — the CI-consumable console contract.

Per-host series are synthetic (rank 1 publishes a deterministically 3x
slower step time) so every assertion is exact; the registration, discovery,
scrape, and join are all real.
"""

from __future__ import annotations

import json
import subprocess
import sys

from accelerate_tpu import PartialState
from accelerate_tpu.telemetry import get_registry, start_default_server
from accelerate_tpu.telemetry.fleet import (
    FleetAggregator,
    install_fleet_provider,
    publish_metrics_endpoint,
)
from accelerate_tpu.utils.agreement import kv_all_gather

STEP_S = {0: 0.010, 1: 0.030}
MFU = {0: 0.40, 1: 0.30}


def main():
    state = PartialState()
    assert state.num_processes >= 2, "run under `launch --num_processes 2`"
    rank = state.process_index

    registry = get_registry()
    hist = registry.histogram("accelerate_step_seconds", "Wall-clock per training step")
    for _ in range(4):
        hist.observe(STEP_S[rank])
    registry.gauge("accelerate_mfu_estimate", "MFU estimate").set(MFU[rank])
    registry.gauge("accelerate_goodput_fraction", "Goodput").set(0.9)

    server = start_default_server(0)  # ephemeral: the address CANNOT be guessed
    endpoint = publish_metrics_endpoint(process_index=rank, server=server)
    assert endpoint is not None and endpoint.endswith(f":{server.port}"), endpoint

    # Everyone registered — and ranks != 0 must keep serving until the lead
    # host has scraped them, so the drill brackets the aggregation between
    # two KV barriers.
    kv_all_gather("ready", state.num_processes, rank, namespace="at_fleet_drill/ready")

    if rank == 0:
        aggregator = install_fleet_provider(FleetAggregator(state=state))
        snap = aggregator.snapshot()
        hosts = snap["hosts"]
        assert hosts["0"]["up"] and hosts["1"]["up"], hosts
        assert abs(hosts["0"]["step_s_mean"] - STEP_S[0]) < 1e-9, hosts
        assert abs(hosts["1"]["step_s_mean"] - STEP_S[1]) < 1e-9, hosts
        fleet = snap["fleet"]
        assert fleet["hosts_up"] == 2 and fleet["hosts_total"] == 2, fleet
        assert abs(fleet["mfu"] - 0.35) < 1e-9, fleet
        assert abs(fleet["step_s"]["skew"] - STEP_S[1] / (0.5 * (STEP_S[0] + STEP_S[1]))) < 1e-6, fleet
        # Joined per-host-labeled series: BOTH hosts' step-time series exist
        # under distinct host labels.
        for host in ("0", "1"):
            assert f'accelerate_step_seconds_sum{{host="{host}"}}' in snap["series"], (
                sorted(snap["series"])[:20]
            )
        text = aggregator.prometheus_text()
        assert 'accelerate_mfu_estimate{host="0"} 0.4' in text, text[:800]
        assert 'accelerate_mfu_estimate{host="1"} 0.3' in text, text[:800]

        # The operator console, end to end: a real `accelerate-tpu top
        # --once --json` subprocess against the lead host's endpoint.
        result = subprocess.run(
            [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
             "top", "--once", "--json", "--endpoint", endpoint],
            capture_output=True, text=True, timeout=120,
        )
        assert result.returncode == 0, result.stdout[-800:] + result.stderr[-800:]
        got = json.loads(result.stdout)
        assert got["fleet"]["hosts_up"] == 2, got["fleet"]
        assert set(got["hosts"]) == {"0", "1"}, got["hosts"]
        assert got["hosts"]["1"]["step_s_mean"] == hosts["1"]["step_s_mean"]
        assert f'accelerate_step_seconds_sum{{host="1"}}' in got["series"]

        # And the human frame renders both hosts.
        frame = subprocess.run(
            [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
             "top", "--once", "--endpoint", endpoint],
            capture_output=True, text=True, timeout=120,
        )
        assert frame.returncode == 0, frame.stderr[-800:]
        assert "hosts 2/2 up" in frame.stdout and "skew" in frame.stdout, frame.stdout

    kv_all_gather("done", state.num_processes, rank, namespace="at_fleet_drill/done")
    print(f"FLEET_OK rank={rank} endpoint={endpoint}")


if __name__ == "__main__":
    main()
