"""Shared test drills — the load-tolerant spelling of wall-clock-sensitive
acceptance asserts.

The hot-loop acceptance bars pin ``transfer_stats()["blocking"] == 0``: the
dispatching thread never stalled on a device→host fetch. Whether a counted
fetch *blocks* depends on whether the device had finished by the time the
host asked — which is wall-clock, not logic: on a loaded CI machine a drill
that is perfectly async in its design can still catch one in-flight array
(the PR 5/6 ``test_guarded_telemetry_loop`` / ``test_window_retains_losses``
flakes). Retrying distinguishes the two failure modes: load-induced stalls
are transient and vanish on a re-run, while a genuinely regressed hot path
(an added ``float(loss)``, a dropped retained-loss drain) blocks
*deterministically* and fails every attempt.
"""

from __future__ import annotations

DEFAULT_ATTEMPTS = 3


def run_nonblocking_drill(drill, attempts: int = DEFAULT_ATTEMPTS,
                          keys: tuple = ("blocking", "h2d_blocking")):
    """Run ``drill()`` until its transfer-stats snapshot shows zero blocking
    transfers, retrying up to ``attempts`` times.

    ``drill`` must be self-contained — build its own training state, reset
    the transfer counters, run its loop, and return the
    ``transfer_stats()`` snapshot to judge (it may stash other objects for
    the caller's follow-up asserts). ``keys`` are the snapshot entries that
    must be zero. Returns the passing snapshot; raises ``AssertionError``
    after ``attempts`` consecutive blocking runs — that is a real
    regression, not scheduler jitter.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    last = None
    for _ in range(attempts):
        last = drill()
        if all(last.get(k, 0) == 0 for k in keys):
            return last
    raise AssertionError(
        f"hot loop blocked on a device transfer in {attempts}/{attempts} "
        f"attempts ({ {k: last.get(k, 0) for k in keys} }): deterministic — "
        "a retained value is being fetched before it materializes"
    )
