"""Fleet telemetry-journal drill, run under the real 3-process launcher::

    AT_JOURNAL_SKEW=0,120,-45 accelerate-tpu launch --cpu --num_processes 3 \
        --journal_dir <shared tmp> --trace_ring 512 --flight_ring 4096 \
        -m accelerate_tpu.test_utils.journal_script

Proves the tentpole property ``tests/test_journal.py`` pins: every rank
journals its streams durably to the shared ``--journal_dir`` (the launch
flag reaches every worker as ACCELERATE_JOURNAL_DIR — asserted in-script,
like the ring sizes), the coordination-KV clock exchange recovers each
rank's injected artificial wall skew, and ``accelerate-tpu timeline`` then
merges the fleet into ONE valid Chrome-trace file where a retried request's
router → prefill → handoff → decode legs are causally linked under its rid
with the cross-host skew corrected (the whole request spans seconds in the
corrected trace, not the ±minutes the injected skews would smear it across).

Topology mirrors the chaos drill's phase B: rank 0 runs the prefill tier,
the router, and the client; ranks 1 and 2 decode. Rank 0's first chain
export is dropped on the wire (``req:0=handoff_drop``), so the drilled
request carries a real ``handoff_failed`` retry leg plus a second, clean
handoff. Rank 0 finishes by driving ``accelerate-tpu report``: clean
self-compare exits 0, an injected regression exits 1.

Each rank injects ``AT_JOURNAL_SKEW[rank]`` seconds into its journal's wall
clock (the injectable-``wall_clock`` seam), so both the journal records AND
the clock-exchange stamps are consistently skewed — exactly what a rig of
hosts with drifted clocks produces.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import urllib.request

import numpy as np

from accelerate_tpu import PartialState
from accelerate_tpu.telemetry import start_default_server
from accelerate_tpu.telemetry.fleet import publish_metrics_endpoint
from accelerate_tpu.telemetry.journal import (
    TelemetryJournal,
    exchange_clock_sync,
    set_journal,
)
from accelerate_tpu.utils.agreement import kv_all_gather
from accelerate_tpu.utils.constants import (
    ENV_FLIGHT_RING,
    ENV_JOURNAL_DIR,
    ENV_TRACE_RING,
)

from .disagg_script import MAX_NEW, _engine, _generate, _model

PROMPT_LEN = 21  # > chunk: prefill entry + handoff to a decode tier


def _injected_skews(num_processes: int) -> list[float]:
    raw = os.environ.get("AT_JOURNAL_SKEW", "")
    if not raw:
        return [0.0] * num_processes
    skews = [float(part) for part in raw.split(",")]
    assert len(skews) == num_processes, (skews, num_processes)
    return skews


def _assert_env_contract(journal_dir: str):
    """The launch flags must have reached this worker's env (tri-state
    export leg) and the ring constructors must resolve them."""
    from accelerate_tpu.telemetry.flight import (
        get_flight_recorder,
        ring_capacity_from_env,
    )
    from accelerate_tpu.telemetry.requests import RequestTracer

    assert os.environ.get(ENV_JOURNAL_DIR) == journal_dir, (
        os.environ.get(ENV_JOURNAL_DIR), journal_dir)
    assert os.environ.get(ENV_TRACE_RING) == "512", os.environ.get(ENV_TRACE_RING)
    assert os.environ.get(ENV_FLIGHT_RING) == "4096", os.environ.get(ENV_FLIGHT_RING)
    assert ring_capacity_from_env(ENV_TRACE_RING, 1024) == 512
    assert RequestTracer().capacity == 512
    assert get_flight_recorder().capacity == 4096


def _assert_timeline(journal_dir: str, rid: int, skews: list[float]):
    """Rank 0: drive the real CLI over the shared journals and assert the
    merged trace is valid, causally linked, and skew-corrected."""
    out = os.path.join(journal_dir, "trace.json")
    proc = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
         "timeline", "--journal-dir", journal_dir, "--out", out],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout[-800:] + proc.stderr[-800:]
    with open(out, encoding="utf-8") as fh:
        trace = json.load(fh)
    events = trace["traceEvents"]
    assert events, "empty merged trace"

    # The recovered skew map matches the injected per-rank deltas (barrier
    # release jitter is the tolerance).
    recovered = {int(h): float(s) for h, s in trace["otherData"]["skew"].items()}
    for rank, injected in enumerate(skews):
        assert rank in recovered, recovered
        assert abs(recovered[rank] - injected) < 2.0, (recovered, skews)

    # One rid, every tier, causally linked: request legs from all three
    # tiers (incl. the handoff_failed retry and the handoff itself) under
    # the drilled rid, with flow arrows spanning more than one host pid.
    legs = [e for e in events if e.get("ph") == "X"
            and e.get("cat") == "request" and e.get("args", {}).get("rid") == rid]
    tiers = {e["name"].split(":")[0] for e in legs}
    assert {"router", "prefill", "decode"} <= tiers, tiers
    leg_names = {e["name"].split(":")[1] for e in legs}
    assert "retry" in leg_names and "handoff" in leg_names, leg_names
    retry = next(e for e in legs if e["name"].endswith(":retry"))
    assert retry["args"].get("reason") == "handoff_failed", retry
    flows = [e for e in events if e.get("ph") in ("s", "t", "f")
             and e.get("id") == rid]
    assert {e["ph"] for e in flows} >= {"s", "f"}, flows
    assert len({e["pid"] for e in flows}) >= 2, (
        f"rid {rid} flow never crossed hosts: {flows}")

    # Skew actually corrected: the request's corrected legs span seconds;
    # uncorrected, the injected skews would smear them across minutes.
    span_s = (max(e["ts"] for e in legs) - min(e["ts"] for e in legs)) / 1e6
    smear = max(skews) - min(skews)
    assert span_s < min(60.0, smear / 2), (
        f"rid legs span {span_s:.1f}s — skew not corrected (injected "
        f"smear {smear:.0f}s)")

    # --rid filtering keeps exactly that request's lanes.
    out_rid = os.path.join(journal_dir, "trace_rid.json")
    proc = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
         "timeline", "--journal-dir", journal_dir, "--out", out_rid,
         "--rid", str(rid)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout[-800:] + proc.stderr[-800:]
    with open(out_rid, encoding="utf-8") as fh:
        filtered = json.load(fh)["traceEvents"]
    kept = [e for e in filtered if e.get("ph") == "X"]
    assert kept and all(e.get("args", {}).get("rid") == rid for e in kept), kept
    print("JOURNAL_TIMELINE_OK")


def _assert_report(journal_dir: str):
    """Rank 0: `report` round trip — clean self-compare exits 0, an
    injected regression exits 1."""
    summary_path = os.path.join(journal_dir, "summary.json")
    proc = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
         "report", "--journal", journal_dir, "--out", summary_path],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout[-800:] + proc.stderr[-800:]
    clean = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
         "report", "--journal", journal_dir, "--compare", summary_path],
        capture_output=True, text=True, timeout=120,
    )
    assert clean.returncode == 0, clean.stdout[-800:] + clean.stderr[-800:]
    assert "no regressions" in clean.stdout, clean.stdout

    with open(summary_path, encoding="utf-8") as fh:
        summary = json.load(fh)
    assert summary.get("retries", 0) >= 1, summary  # the dropped handoff
    assert summary.get("ttft_mean") is not None, summary
    doctored = dict(summary)
    doctored["ttft_mean"] = summary["ttft_mean"] / 4  # "previous run was 4x faster"
    doctored["retries"] = 0
    prev_path = os.path.join(journal_dir, "prev.json")
    with open(prev_path, "w", encoding="utf-8") as fh:
        json.dump(doctored, fh)
    regressed = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
         "report", "--journal", journal_dir, "--compare", prev_path],
        capture_output=True, text=True, timeout=120,
    )
    assert regressed.returncode == 1, (
        regressed.returncode, regressed.stdout[-800:])
    assert "REGRESSION" in regressed.stderr, regressed.stderr
    print("JOURNAL_REPORT_OK")


def main():
    state = PartialState()
    assert state.num_processes >= 3, "run under `launch --num_processes 3`"
    rank = state.process_index
    role = "prefill" if rank == 0 else "decode"
    journal_dir = os.environ.get(ENV_JOURNAL_DIR, "")
    assert journal_dir, f"{ENV_JOURNAL_DIR} must reach the workers"
    skews = _injected_skews(state.num_processes)
    _assert_env_contract(journal_dir)

    # This rank's journal on a deliberately skewed wall clock — records and
    # clock-exchange stamps both read it, like a host with a drifted clock.
    my_skew = skews[rank]
    journal = TelemetryJournal(journal_dir, process_index=rank,
                               wall_clock=lambda: time.time() + my_skew)
    set_journal(journal)
    skew_map = exchange_clock_sync(state.num_processes, rank)
    assert abs(skew_map[rank] - (my_skew - skews[0])) < 2.0, (skew_map, skews)

    from accelerate_tpu.resilience.faults import FaultPlan, set_active_plan
    from accelerate_tpu.serving_net import Router, ServingFrontend
    from accelerate_tpu.telemetry.fleet import _kv_client

    model = _model()
    server = start_default_server(0)
    endpoint = publish_metrics_endpoint(process_index=rank, server=server)
    assert endpoint is not None, "metrics endpoint registration failed"
    engine = _engine(model)
    frontend = ServingFrontend(engine, role=role)
    if rank == 0:
        # Drop this rank's first chain export on the wire: the drilled
        # request must re-enter and carry a real handoff_failed retry leg.
        set_active_plan(FaultPlan.parse("req:0=handoff_drop"))
    frontend.install(process_index=rank, endpoint=endpoint)

    kv_all_gather("ready", state.num_processes, rank,
                  namespace="at_journal_drill/ready")
    client = _kv_client()

    if rank == 0:
        from accelerate_tpu.telemetry.collect import read_journal_dir
        from accelerate_tpu.telemetry.metrics import MetricsServer

        router_server = MetricsServer(0, host="127.0.0.1")
        router_port = router_server.start()
        router = Router(num_processes=state.num_processes)
        router_server.set_serving(router)

        rng = np.random.default_rng(23)
        prompt = rng.integers(1, 256, (PROMPT_LEN,)).astype(np.int32)
        result = _generate(f"127.0.0.1:{router_port}", prompt)
        set_active_plan(None)
        assert len(result["tokens"]) == MAX_NEW, result["tokens"]
        rid = result["done"]["trace"][0]["rid"]

        # This rank's own journal over the metrics server's tail route.
        with urllib.request.urlopen(
                f"http://{endpoint}/journal?since=0", timeout=10.0) as resp:
            tail = json.loads(resp.read())
        assert tail["records"] and tail["host"] == 0, tail
        with urllib.request.urlopen(
                f"http://{endpoint}/journal?since={tail['next']}",
                timeout=10.0) as resp:
            empty = json.loads(resp.read())
        assert empty["records"] == [], empty

        # Every tier journals its legs as they happen (flushed per record);
        # wait for the decode tier's finish leg to land on the shared dir.
        deadline = time.monotonic() + 90.0
        while time.monotonic() < deadline:
            merged = [r for records in read_journal_dir(journal_dir).values()
                      for r in records
                      if r.get("kind") == "request_leg" and r.get("rid") == rid]
            if any(r.get("leg") == "finish" for r in merged):
                break
            time.sleep(0.25)
        else:
            raise AssertionError(f"rid {rid} finish leg never journaled")

        journal.finalize_run(extra={"fingerprint": "journal-drill"})
        _assert_timeline(journal_dir, rid, skews)
        _assert_report(journal_dir)

        client.key_value_set("at_journal_drill/done", "1")
        router_server.stop()
    else:
        client.blocking_key_value_get("at_journal_drill/done", 480_000)

    frontend.uninstall()
    print(f"JOURNAL_OK rank={rank} role={role} skew={my_skew}")


if __name__ == "__main__":
    main()
