"""Notebook / debug launchers.

Reference parity: ``src/accelerate/launchers.py:40-302`` — ``notebook_launcher``
(xmp.spawn on TPU, torch start_processes on GPU) and ``debug_launcher``
(CPU-only multiprocess with a fake MASTER_ADDR :295).

JAX topology changes the picture: a notebook process already owns every local
TPU chip, so ``notebook_launcher`` does not need to fork per-core the way
``xmp.spawn`` does — parallelism is expressed through the mesh inside one
process. Forking is only needed to *simulate multi-host*, which is what
``debug_launcher`` does: N OS processes, each a JAX "host", rendezvousing on
localhost with virtual CPU devices.
"""

from __future__ import annotations

import os
import sys
import tempfile
import traceback

from .utils.constants import (
    ENV_COORDINATOR,
    ENV_CPU,
    ENV_MESH_SHAPE,
    ENV_NUM_PROCESSES,
    ENV_PROCESS_ID,
)


def notebook_launcher(
    function,
    args=(),
    num_processes: int | None = None,
    mixed_precision: str = "no",
    use_port: str = "29500",
    master_addr: str = "127.0.0.1",
    node_rank: int = 0,
    num_nodes: int = 1,
):
    """Run ``function(*args)`` for interactive/Colab use (reference ``launchers.py:40``).

    On TPU/single-host the function simply runs in-process — the mesh gives it all
    chips, so `num_processes` is advisory there (the reference forks 8 XLA
    processes; JAX needs one). When ``num_processes > 1`` on a CPU-only host we
    delegate to :func:`debug_launcher` semantics to simulate hosts.
    """
    import jax

    in_colab = "google.colab" in sys.modules
    in_kaggle = "KAGGLE_KERNEL_RUN_TYPE" in os.environ
    if (in_colab or in_kaggle) and os.environ.get("JAX_PLATFORMS", "") == "":
        # Interactive TPU runtimes are already initialized; nothing to patch.
        pass
    if mixed_precision not in ("no", "bf16", "fp16"):
        raise ValueError(f"Unknown mixed_precision mode: {mixed_precision}")
    os.environ.setdefault("ACCELERATE_MIXED_PRECISION", mixed_precision)

    platform = jax.default_backend()
    if platform in ("tpu", "gpu") or num_processes in (None, 0, 1):
        # One process drives all local devices — the JAX-native notebook path.
        return function(*args)
    return debug_launcher(function, args=args, num_processes=num_processes)


def _debug_worker(rank: int, num_processes: int, port: int, fn_path: str):
    import pickle

    os.environ[ENV_COORDINATOR] = f"127.0.0.1:{port}"
    os.environ[ENV_NUM_PROCESSES] = str(num_processes)
    os.environ[ENV_PROCESS_ID] = str(rank)
    os.environ[ENV_CPU] = "1"
    os.environ["JAX_PLATFORMS"] = "cpu"
    with open(fn_path, "rb") as f:
        function, args = pickle.load(f)
    function(*args)


def debug_launcher(function, args=(), num_processes: int = 2):
    """Fork ``num_processes`` CPU "hosts" on localhost and run ``function`` in each
    (reference ``debug_launcher`` :269-302, fake MASTER_ADDR=127.0.0.1 :295).

    Uses fork-based multiprocessing so closures defined in tests/notebooks work
    without being importable; each child becomes one JAX process in a
    ``jax.distributed`` job rendezvousing on a random localhost port.
    """
    import multiprocessing
    import pickle
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    ctx = multiprocessing.get_context("spawn")
    with tempfile.NamedTemporaryFile(suffix=".pkl", delete=False) as f:
        fn_path = f.name
        pickle.dump((function, args), f)
    procs = []
    try:
        for rank in range(num_processes):
            p = ctx.Process(target=_debug_worker, args=(rank, num_processes, port, fn_path))
            p.start()
            procs.append(p)
        failed = []
        for rank, p in enumerate(procs):
            p.join()
            if p.exitcode != 0:
                failed.append((rank, p.exitcode))
        if failed:
            raise RuntimeError(f"debug_launcher workers failed: {failed}")
    finally:
        for p in procs:
            if p.is_alive():
                p.terminate()
        try:
            os.unlink(fn_path)
        except OSError:
            traceback.print_exc()
