"""Notebook / debug launchers.

Reference parity: ``src/accelerate/launchers.py:40-302`` — ``notebook_launcher``
(xmp.spawn on TPU, torch start_processes on GPU) and ``debug_launcher``
(CPU-only multiprocess with a fake MASTER_ADDR :295).

JAX topology changes the picture: a notebook process already owns every local
TPU chip, so ``notebook_launcher`` does not need to fork per-core the way
``xmp.spawn`` does — parallelism is expressed through the mesh inside one
process. Forking is only needed to *simulate multi-host*, which is what
``debug_launcher`` does: N OS processes, each a JAX "host", rendezvousing on
localhost with virtual CPU devices.
"""

from __future__ import annotations

import os
import sys
import tempfile
import traceback

from .utils.constants import (
    ENV_COORDINATOR,
    ENV_CPU,
    ENV_NUM_PROCESSES,
    ENV_PROCESS_ID,
)


def notebook_launcher(
    function,
    args=(),
    num_processes: int | None = None,
    mixed_precision: str = "no",
    use_port: str = "29500",
    master_addr: str = "127.0.0.1",
    node_rank: int = 0,
    num_nodes: int = 1,
):
    """Run ``function(*args)`` for interactive/Colab use (reference ``launchers.py:40``).

    On TPU/single-host the function simply runs in-process — the mesh gives it all
    chips, so `num_processes` is advisory there (the reference forks 8 XLA
    processes; JAX needs one). When ``num_processes > 1`` on a CPU-only host we
    delegate to :func:`debug_launcher` semantics to simulate hosts.
    """
    if mixed_precision not in ("no", "bf16", "fp16"):
        raise ValueError(f"Unknown mixed_precision mode: {mixed_precision}")
    os.environ.setdefault("ACCELERATE_MIXED_PRECISION", mixed_precision)

    if num_processes in (None, 0, 1):
        # One process drives all local devices — the JAX-native notebook path.
        return function(*args)

    # num_processes > 1: only a CPU host simulates multiple processes. Decide
    # the platform WITHOUT initializing the XLA backend where we can — once a
    # backend exists, debug_launcher loses its fork path (closures stop
    # working, see _jax_backend_initialized).
    env_platforms = os.environ.get("JAX_PLATFORMS", os.environ.get("JAX_PLATFORM_NAME", ""))
    if env_platforms.split(",")[0].strip().lower() == "cpu" or os.environ.get(ENV_CPU):
        return debug_launcher(function, args=args, num_processes=num_processes)

    import jax

    platform = jax.default_backend()
    if platform in ("tpu", "gpu"):
        return function(*args)
    return debug_launcher(function, args=args, num_processes=num_processes)


def _set_debug_env(rank: int, num_processes: int, port: int):
    os.environ[ENV_COORDINATOR] = f"127.0.0.1:{port}"
    os.environ[ENV_NUM_PROCESSES] = str(num_processes)
    os.environ[ENV_PROCESS_ID] = str(rank)
    os.environ[ENV_CPU] = "1"
    os.environ["JAX_PLATFORMS"] = "cpu"


def _debug_worker_inline(rank: int, num_processes: int, port: int, function, args):
    # fork start method: function/args are inherited by memory, never pickled,
    # so lambdas and closures defined in notebooks/tests work. The parent may
    # have constructed state singletons before forking — drop that inherited
    # identity so this child reads its own env contract.
    _set_debug_env(rank, num_processes, port)
    from .state import AcceleratorState, GradientState
    from .utils.environment import maybe_enable_compilation_cache

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    # Forked children share the parent's compile-cache env contract but not
    # its jax.config mutations — re-apply before the child's first compile.
    maybe_enable_compilation_cache()
    function(*args)


def _jax_backend_initialized() -> bool:
    """True once any XLA backend exists in this process — after which forked
    children inherit live XLA threads and ``jax.distributed.initialize`` refuses
    to run, so fork is no longer safe."""
    try:
        from jax._src import xla_bridge

        if hasattr(xla_bridge, "backends_are_initialized"):
            return xla_bridge.backends_are_initialized()
        return bool(getattr(xla_bridge, "_backends", None))
    except Exception:
        return "jax" in sys.modules


def _debug_worker_pickled(rank: int, num_processes: int, port: int, fn_path: str):
    import pickle

    _set_debug_env(rank, num_processes, port)
    from .utils.environment import maybe_enable_compilation_cache

    maybe_enable_compilation_cache()
    with open(fn_path, "rb") as f:
        function, args = pickle.load(f)
    function(*args)


def debug_launcher(function, args=(), num_processes: int = 2):
    """Fork ``num_processes`` CPU "hosts" on localhost and run ``function`` in each
    (reference ``debug_launcher`` :269-302, fake MASTER_ADDR=127.0.0.1 :295).

    Uses fork-based multiprocessing where it is safe so closures defined in
    tests/notebooks work without being importable (the reference uses
    start_method='fork' for the same reason). Fork stops being safe the moment
    this process initializes an XLA backend — forked children would inherit live
    XLA threads and ``jax.distributed.initialize`` raises — so after any JAX
    compute in the parent, and on fork-less platforms, we fall back to
    spawn + pickle, which requires a picklable top-level function. Each child
    becomes one JAX process in a ``jax.distributed`` job rendezvousing on a
    random localhost port.
    """
    import multiprocessing
    import pickle
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    use_fork = (
        "fork" in multiprocessing.get_all_start_methods() and not _jax_backend_initialized()
    )
    fn_path = None
    if use_fork:
        ctx = multiprocessing.get_context("fork")
    else:
        ctx = multiprocessing.get_context("spawn")
        with tempfile.NamedTemporaryFile(suffix=".pkl", delete=False) as f:
            fn_path = f.name
            try:
                pickle.dump((function, args), f)
            except (pickle.PicklingError, AttributeError, TypeError) as e:
                raise RuntimeError(
                    "debug_launcher must spawn fresh interpreters here (the JAX "
                    "backend is already initialized in this process, so fork is "
                    "unsafe), which requires a picklable top-level function. "
                    "Either pass a module-level function, or call debug_launcher "
                    "before any JAX computation so the fork path can run your "
                    "closure."
                ) from e
    procs = []
    try:
        for rank in range(num_processes):
            if use_fork:
                p = ctx.Process(
                    target=_debug_worker_inline,
                    args=(rank, num_processes, port, function, args),
                )
            else:
                p = ctx.Process(
                    target=_debug_worker_pickled, args=(rank, num_processes, port, fn_path)
                )
            p.start()
            procs.append(p)
        failed = []
        for rank, p in enumerate(procs):
            p.join()
            if p.exitcode != 0:
                failed.append((rank, p.exitcode))
        if failed:
            raise RuntimeError(f"debug_launcher workers failed: {failed}")
    finally:
        for p in procs:
            if p.is_alive():
                p.terminate()
        if fn_path is not None:
            try:
                os.unlink(fn_path)
            except OSError:
                traceback.print_exc()
