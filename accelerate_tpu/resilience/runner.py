"""Auto-resume runner — restart is the recovery primitive on TPU.

XLA collectives cannot survive a lost participant, so "elastic" on a slice
means: the whole gang dies, a new incarnation starts, and training resumes
from the newest *complete* checkpoint. :func:`run_resilient` is that loop in
process form — the in-process twin of ``accelerate-tpu launch --max_restarts``
(which relaunches whole processes). It wraps a user ``train_fn`` with

- **auto-resume**: before every attempt, restore from the newest complete
  checkpoint (``load_accelerator_state`` already skips partially-written
  folders and falls back), so ``train_fn`` only needs to start its loop at
  ``accelerator.step``;
- **bounded retries**: exponential backoff with jitter between attempts
  (restarting a whole slice-worth of hosts at the same instant is how
  coordinators get hammered), giving up after ``max_restarts``;
- **crash-loop detection**: a restart *budget per time window* — a job that
  dies instantly N times in a row is broken, not preempted, and burning the
  restart budget on it hides the real failure;
- **goodput accounting**: restore time and backoff downtime land in the
  :mod:`.goodput` ledger, and the final breakdown is pushed through
  ``accelerator.log_goodput()``;
- **hang conversion** (``hang_timeout_s``): a :class:`~..health.hang.
  HangWatchdog` in ``raise`` mode runs for the duration — when no step
  boundary beats it within the deadline it async-raises
  :class:`~..health.hang.HangDetected` in the training thread, turning a
  silent Python-level stall into an ordinary restartable failure. (A hang
  inside a C++ collective can't be preempted in-process: the default
  env-installed watchdog handles that by exiting with the distinct
  ``HANG_EXIT_CODE`` for a process-level supervisor to restart.)
"""

from __future__ import annotations

import collections
import os
import random
import time
from typing import Any, Callable

from ..logging import get_logger
from .goodput import get_ledger

logger = get_logger(__name__)


def run_resilient(
    train_fn: Callable,
    accelerator,
    *,
    max_restarts: int = 3,
    backoff_base_s: float = 1.0,
    backoff_max_s: float = 60.0,
    backoff_jitter: float = 0.25,
    restart_budget: int | None = None,
    restart_window_s: float = 600.0,
    resume: bool = True,
    checkpoint_dir: str | None = None,
    hang_timeout_s: float | None = None,
    elastic: bool | None = None,
    min_data_parallel: int | None = None,
) -> Any:
    """Run ``train_fn(accelerator, attempt)`` to completion through failures.

    ``train_fn`` must be written resumable: loop from ``accelerator.step``
    (restored by ``load_state``) and call ``accelerator.save_state()``
    periodically plus ``accelerator.checkpoint_on_preemption()`` each step.
    ``train_fn`` taking a single argument is also accepted.

    ``checkpoint_dir`` resumes from an explicit folder; the default resumes
    via the project configuration's ``automatic_checkpoint_naming`` layout.
    ``restart_budget`` restarts within ``restart_window_s`` seconds trip the
    crash-loop detector (a ``RuntimeError`` that preserves the original
    failure as its cause); ``None`` disables the window check.

    ``elastic=True`` (default: the launcher's ACCELERATE_ELASTIC contract)
    survives **world-size changes**: when a
    :class:`~.faults.WorldSizeChange` (the deterministic ``shrink:N``/
    ``grow:N`` fault, or a real restart at a different device count)
    surfaces, the mesh is re-formed at the dp degree the surviving devices
    support — never below ``min_data_parallel`` (default: the
    ACCELERATE_MIN_DATA_PARALLEL contract, else 1) — training state is
    resharded onto it (from the health subsystem's in-memory last-known-good
    snapshot when one exists, else from the newest complete checkpoint via
    ``load_state(reshard=True)``), gradient accumulation is rescaled to
    preserve the global batch, and ``train_fn`` is re-entered to rebuild its
    compiled step for the new layout. Voluntary resizes are classified
    separately from crashes: they consume neither ``max_restarts`` nor the
    crash-loop budget, and their downtime books as ``reshard`` (not
    ``restart``) badput — a fleet that legitimately resizes twice is not one
    fault away from giving up.

    Returns whatever ``train_fn`` returns. Raises the last failure once
    ``max_restarts`` is exhausted.
    """
    if max_restarts < 0:
        raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
    from .elastic import elastic_from_env, min_data_parallel_from_env

    if elastic is None:
        elastic = elastic_from_env()
    if min_data_parallel is None:
        min_data_parallel = min_data_parallel_from_env()
    if min_data_parallel < 1:
        raise ValueError(f"min_data_parallel must be >= 1, got {min_data_parallel}")
    ledger = get_ledger()
    restart_times: collections.deque = collections.deque()
    attempt = 0
    watchdog = None
    prev_watchdog = None
    if hang_timeout_s is not None:
        from ..health import hang as hang_mod

        watchdog = hang_mod.HangWatchdog(timeout_s=hang_timeout_s, on_hang="raise")
        # Install as the process default so the per-step Accelerator hooks
        # (guard_step / checkpoint_on_preemption) heartbeat it with no loop
        # changes; the previous default is restored on the way out. The
        # previous watchdog must be SUSPENDED meanwhile — an armed exit-mode
        # watchdog that stops receiving beats would os._exit(113) a perfectly
        # healthy run.
        prev_watchdog = hang_mod.get_default_watchdog()
        if prev_watchdog is not None:
            prev_watchdog.stop()
        hang_mod.set_default_watchdog(watchdog)
        watchdog.start()
    try:
        return _run_resilient_loop(
            train_fn, accelerator, ledger, restart_times, attempt, max_restarts,
            backoff_base_s, backoff_max_s, backoff_jitter, restart_budget,
            restart_window_s, resume, checkpoint_dir, watchdog,
            elastic, min_data_parallel,
        )
    finally:
        if watchdog is not None:
            import threading

            from ..health import hang as hang_mod

            watchdog.stop()
            hang_mod.set_default_watchdog(prev_watchdog)
            if prev_watchdog is not None:
                # start() resumes it disarmed (re-arms on the next beat): the
                # env-installed deadline keeps guarding whatever follows.
                prev_watchdog.start(threading.main_thread())


def _run_resilient_loop(
    train_fn, accelerator, ledger, restart_times, attempt, max_restarts,
    backoff_base_s, backoff_max_s, backoff_jitter, restart_budget,
    restart_window_s, resume, checkpoint_dir, watchdog, elastic,
    min_data_parallel,
):
    from .faults import WorldSizeChange

    skip_resume_once = False
    while True:
        try:
            # Resume INSIDE the guarded region: a failing restore (torn array
            # file, transient filesystem error) must consume a retry like any
            # other failure, not bypass the backoff/budget machinery.
            if resume and not skip_resume_once:
                _try_resume(accelerator, checkpoint_dir, reshard=elastic)
            skip_resume_once = False
            result = _call_train_fn(train_fn, accelerator, attempt)
            accelerator.log_goodput()
            return result
        except (KeyboardInterrupt, SystemExit):
            raise
        except WorldSizeChange as exc:
            if watchdog is not None:
                watchdog.rearm()
            from ..telemetry.flight import get_flight_recorder

            get_flight_recorder().record(
                "world_size_change", step=exc.step,
                direction=exc.direction, factor=exc.factor,
            )
            if not elastic:
                raise RuntimeError(
                    f"World-size change at step {exc.step} ({exc.direction} by "
                    f"{exc.factor}x) but this run is not elastic: the fixed-size "
                    "gang cannot re-form on a different device count. Pass "
                    "run_resilient(elastic=True, min_data_parallel=...) — or "
                    "launch with --elastic — to reshard and resume."
                ) from exc
            # A voluntary resize is not a crash: it consumes neither
            # max_restarts nor the crash-loop budget, takes no exponential
            # backoff, and books its downtime as `reshard` (inside
            # reshard_accelerator), not `restart`.
            from .elastic import (
                agree_world_size,
                reshard_accelerator,
                resolve_resized_devices,
            )

            import jax

            # Resize relative to the world the run is ACTUALLY on — the live
            # mesh. It may cover a device subset (a prior manual or elastic
            # reshard); a cached set or jax.devices() can only desync from it.
            current = list(accelerator.mesh.devices.flat)
            new_devices = resolve_resized_devices(current, exc.direction, exc.factor)
            if (
                exc.direction == "grow"
                and len(new_devices) == len(current)
            ):
                # grow is capped at the devices the platform exposes; at full
                # capacity the cap makes the resize a no-op — keep training
                # from live state, don't rewind to a checkpoint.
                logger.warning(
                    f"World-size grow at step {exc.step} capped at the "
                    f"{len(current)} attached device(s); continuing at the "
                    "current size."
                )
                skip_resume_once = True
                continue
            # Multi-host: every rank must agree on the survivor count before
            # re-forming — one KV exchange (no device collectives needed,
            # they may be what just died). Single-process: a no-op echo.
            local = sum(
                1 for d in new_devices
                if getattr(d, "process_index", 0) == jax.process_index()
            )
            if local == 0 and getattr(accelerator.state, "num_processes", 1) > 1:
                # A count-only agreement would pass even when the shrunken
                # set excludes every device THIS live host owns — it could
                # never address the new mesh. Whole surviving hosts must own
                # a share; anything else needs a gang restart at the new size.
                raise RuntimeError(
                    f"Elastic shrink at step {exc.step} leaves process "
                    f"{jax.process_index()} with no devices in the surviving "
                    "set: an in-process resize must keep every live host in "
                    "the mesh. Restart the gang at the new size instead."
                ) from exc
            agreed = agree_world_size(accelerator.state, local_device_count=local)
            if agreed != len(new_devices):
                raise RuntimeError(
                    f"Elastic resize disagreement: this rank resolved "
                    f"{len(new_devices)} surviving device(s) but the gang "
                    f"agreed on {agreed}. The hosts see different worlds — "
                    "restart the gang instead of re-forming inconsistently."
                ) from exc
            restored_in_memory = _restore_from_snapshot(accelerator)
            logger.warning(
                f"World-size change at step {exc.step}: {exc.direction} "
                f"{len(current)} -> {len(new_devices)} device(s); resharding and "
                + ("replaying from the in-memory last-known-good snapshot."
                   if restored_in_memory else
                   "resuming from the newest complete checkpoint.")
            )
            reshard_accelerator(
                accelerator, devices=new_devices, min_data_parallel=min_data_parallel
            )
            # An in-memory restore already positioned the run (and postdates
            # any checkpoint restore would reach); re-loading on top of it
            # would rewind the replay.
            skip_resume_once = restored_in_memory
        except Exception as exc:
            if watchdog is not None:
                watchdog.rearm()  # the next attempt gets a fresh deadline
            attempt += 1
            # Black-box dump BEFORE the restart decision: whether this attempt
            # exhausts the budget or backs off and retries, the event ring at
            # the moment of failure is the post-mortem either way.
            from ..telemetry.flight import get_flight_recorder

            flight = get_flight_recorder()
            flight.record(
                "restart", attempt=attempt,
                error=f"{type(exc).__name__}: {exc}"[:300],
            )
            flight.dump("restart")
            if attempt > max_restarts:
                logger.error(
                    f"Training failed and the restart budget is exhausted "
                    f"({max_restarts} restarts): {exc!r}"
                )
                raise
            now = time.monotonic()
            restart_times.append(now)
            if restart_budget is not None:
                while restart_times and now - restart_times[0] > restart_window_s:
                    restart_times.popleft()
                if len(restart_times) > restart_budget:
                    raise RuntimeError(
                        f"Crash loop detected: {len(restart_times)} restarts within "
                        f"{restart_window_s:.0f}s exceeds the budget of {restart_budget}. "
                        "The job is failing deterministically, not being preempted — "
                        "fix the failure instead of restarting through it."
                    ) from exc
            delay = min(backoff_max_s, backoff_base_s * (2 ** (attempt - 1)))
            delay *= 1.0 + random.uniform(0.0, backoff_jitter)
            logger.warning(
                f"Attempt {attempt}/{max_restarts} failed ({type(exc).__name__}: {exc}); "
                f"resuming from the newest complete checkpoint in {delay:.1f}s."
            )
            t = time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            ledger.record_restart(time.perf_counter() - t)


def _call_train_fn(train_fn, accelerator, attempt):
    import inspect

    try:
        params = list(inspect.signature(train_fn).parameters.values())
        positional = [
            p for p in params if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
        ]
        # Only a second POSITIONAL slot (or *args) can receive attempt —
        # keyword-only params must not count toward the arity.
        takes_attempt = len(positional) >= 2 or any(
            p.kind == p.VAR_POSITIONAL for p in params
        )
    except (TypeError, ValueError):
        takes_attempt = True
    return train_fn(accelerator, attempt) if takes_attempt else train_fn(accelerator)


def _try_resume(accelerator, checkpoint_dir, reshard: bool = False):
    """Restore from the newest complete checkpoint if one exists; a fresh run
    (nothing saved yet) starts clean instead of failing. ``reshard=True``
    (the elastic path) accepts checkpoints written under a different mesh."""
    from ..checkpointing import _checkpoint_complete
    from ..utils.constants import CHECKPOINT_DIR_PREFIX

    project = accelerator.project_configuration
    # No ckpt_restore tracking here: load_accelerator_state records its own
    # elapsed time in the ledger — wrapping it again would double-count.
    if checkpoint_dir is not None:
        if os.path.isdir(checkpoint_dir) and _checkpoint_complete(checkpoint_dir, accelerator):
            accelerator.load_state(checkpoint_dir, reshard=reshard)
        return
    if not (project.automatic_checkpoint_naming and project.project_dir):
        return
    base = os.path.join(project.project_dir, "checkpoints")
    if not os.path.isdir(base) or not any(
        f.startswith(f"{CHECKPOINT_DIR_PREFIX}_") for f in os.listdir(base)
    ):
        return
    try:
        accelerator.load_state(reshard=reshard)  # newest COMPLETE folder; skips litter
    except FileNotFoundError:
        logger.warning(f"No complete checkpoint under {base}; starting fresh.")


def _restore_from_snapshot(accelerator) -> bool:
    """Elastic transitions where the process survives: restore from the health
    subsystem's in-memory last-known-good snapshot (newer than any checkpoint
    cadence, zero disk I/O) when one is held. The snapshot's arrays still lay
    on the OLD mesh — the caller reshards immediately after, and the
    now-stale ring is discarded there. Returns whether a restore happened."""
    guard = accelerator._health_guard
    if guard is None or guard.lkg.step is None:
        return False
    from ..health.rollback import restore_accelerator

    with get_ledger().track("reshard"):
        restore_accelerator(accelerator, guard.lkg)
    return True
