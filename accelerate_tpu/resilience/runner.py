"""Auto-resume runner — restart is the recovery primitive on TPU.

XLA collectives cannot survive a lost participant, so "elastic" on a slice
means: the whole gang dies, a new incarnation starts, and training resumes
from the newest *complete* checkpoint. :func:`run_resilient` is that loop in
process form — the in-process twin of ``accelerate-tpu launch --max_restarts``
(which relaunches whole processes). It wraps a user ``train_fn`` with

- **auto-resume**: before every attempt, restore from the newest complete
  checkpoint (``load_accelerator_state`` already skips partially-written
  folders and falls back), so ``train_fn`` only needs to start its loop at
  ``accelerator.step``;
- **bounded retries**: exponential backoff with jitter between attempts
  (restarting a whole slice-worth of hosts at the same instant is how
  coordinators get hammered), giving up after ``max_restarts``;
- **crash-loop detection**: a restart *budget per time window* — a job that
  dies instantly N times in a row is broken, not preempted, and burning the
  restart budget on it hides the real failure;
- **goodput accounting**: restore time and backoff downtime land in the
  :mod:`.goodput` ledger, and the final breakdown is pushed through
  ``accelerator.log_goodput()``.
"""

from __future__ import annotations

import collections
import os
import random
import time
from typing import Any, Callable

from ..logging import get_logger
from .goodput import get_ledger

logger = get_logger(__name__)


def run_resilient(
    train_fn: Callable,
    accelerator,
    *,
    max_restarts: int = 3,
    backoff_base_s: float = 1.0,
    backoff_max_s: float = 60.0,
    backoff_jitter: float = 0.25,
    restart_budget: int | None = None,
    restart_window_s: float = 600.0,
    resume: bool = True,
    checkpoint_dir: str | None = None,
) -> Any:
    """Run ``train_fn(accelerator, attempt)`` to completion through failures.

    ``train_fn`` must be written resumable: loop from ``accelerator.step``
    (restored by ``load_state``) and call ``accelerator.save_state()``
    periodically plus ``accelerator.checkpoint_on_preemption()`` each step.
    ``train_fn`` taking a single argument is also accepted.

    ``checkpoint_dir`` resumes from an explicit folder; the default resumes
    via the project configuration's ``automatic_checkpoint_naming`` layout.
    ``restart_budget`` restarts within ``restart_window_s`` seconds trip the
    crash-loop detector (a ``RuntimeError`` that preserves the original
    failure as its cause); ``None`` disables the window check.

    Returns whatever ``train_fn`` returns. Raises the last failure once
    ``max_restarts`` is exhausted.
    """
    if max_restarts < 0:
        raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
    ledger = get_ledger()
    restart_times: collections.deque = collections.deque()
    attempt = 0
    while True:
        try:
            # Resume INSIDE the guarded region: a failing restore (torn array
            # file, transient filesystem error) must consume a retry like any
            # other failure, not bypass the backoff/budget machinery.
            if resume:
                _try_resume(accelerator, checkpoint_dir)
            result = _call_train_fn(train_fn, accelerator, attempt)
            accelerator.log_goodput()
            return result
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as exc:
            attempt += 1
            if attempt > max_restarts:
                logger.error(
                    f"Training failed and the restart budget is exhausted "
                    f"({max_restarts} restarts): {exc!r}"
                )
                raise
            now = time.monotonic()
            restart_times.append(now)
            if restart_budget is not None:
                while restart_times and now - restart_times[0] > restart_window_s:
                    restart_times.popleft()
                if len(restart_times) > restart_budget:
                    raise RuntimeError(
                        f"Crash loop detected: {len(restart_times)} restarts within "
                        f"{restart_window_s:.0f}s exceeds the budget of {restart_budget}. "
                        "The job is failing deterministically, not being preempted — "
                        "fix the failure instead of restarting through it."
                    ) from exc
            delay = min(backoff_max_s, backoff_base_s * (2 ** (attempt - 1)))
            delay *= 1.0 + random.uniform(0.0, backoff_jitter)
            logger.warning(
                f"Attempt {attempt}/{max_restarts} failed ({type(exc).__name__}: {exc}); "
                f"resuming from the newest complete checkpoint in {delay:.1f}s."
            )
            t = time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            ledger.record_restart(time.perf_counter() - t)


def _call_train_fn(train_fn, accelerator, attempt):
    import inspect

    try:
        params = list(inspect.signature(train_fn).parameters.values())
        positional = [
            p for p in params if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
        ]
        # Only a second POSITIONAL slot (or *args) can receive attempt —
        # keyword-only params must not count toward the arity.
        takes_attempt = len(positional) >= 2 or any(
            p.kind == p.VAR_POSITIONAL for p in params
        )
    except (TypeError, ValueError):
        takes_attempt = True
    return train_fn(accelerator, attempt) if takes_attempt else train_fn(accelerator)


def _try_resume(accelerator, checkpoint_dir):
    """Restore from the newest complete checkpoint if one exists; a fresh run
    (nothing saved yet) starts clean instead of failing."""
    from ..checkpointing import _checkpoint_complete
    from ..utils.constants import CHECKPOINT_DIR_PREFIX

    project = accelerator.project_configuration
    # No ckpt_restore tracking here: load_accelerator_state records its own
    # elapsed time in the ledger — wrapping it again would double-count.
    if checkpoint_dir is not None:
        if os.path.isdir(checkpoint_dir) and _checkpoint_complete(checkpoint_dir, accelerator):
            accelerator.load_state(checkpoint_dir)
        return
    if not (project.automatic_checkpoint_naming and project.project_dir):
        return
    base = os.path.join(project.project_dir, "checkpoints")
    if not os.path.isdir(base) or not any(
        f.startswith(f"{CHECKPOINT_DIR_PREFIX}_") for f in os.listdir(base)
    ):
        return
    try:
        accelerator.load_state()  # newest COMPLETE folder; skips litter
    except FileNotFoundError:
        logger.warning(f"No complete checkpoint under {base}; starting fresh.")
