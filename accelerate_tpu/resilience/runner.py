"""Auto-resume runner — restart is the recovery primitive on TPU.

XLA collectives cannot survive a lost participant, so "elastic" on a slice
means: the whole gang dies, a new incarnation starts, and training resumes
from the newest *complete* checkpoint. :func:`run_resilient` is that loop in
process form — the in-process twin of ``accelerate-tpu launch --max_restarts``
(which relaunches whole processes). It wraps a user ``train_fn`` with

- **auto-resume**: before every attempt, restore from the newest complete
  checkpoint (``load_accelerator_state`` already skips partially-written
  folders and falls back), so ``train_fn`` only needs to start its loop at
  ``accelerator.step``;
- **bounded retries**: exponential backoff with jitter between attempts
  (restarting a whole slice-worth of hosts at the same instant is how
  coordinators get hammered), giving up after ``max_restarts``;
- **crash-loop detection**: a restart *budget per time window* — a job that
  dies instantly N times in a row is broken, not preempted, and burning the
  restart budget on it hides the real failure;
- **goodput accounting**: restore time and backoff downtime land in the
  :mod:`.goodput` ledger, and the final breakdown is pushed through
  ``accelerator.log_goodput()``;
- **hang conversion** (``hang_timeout_s``): a :class:`~..health.hang.
  HangWatchdog` in ``raise`` mode runs for the duration — when no step
  boundary beats it within the deadline it async-raises
  :class:`~..health.hang.HangDetected` in the training thread, turning a
  silent Python-level stall into an ordinary restartable failure. (A hang
  inside a C++ collective can't be preempted in-process: the default
  env-installed watchdog handles that by exiting with the distinct
  ``HANG_EXIT_CODE`` for a process-level supervisor to restart.)
"""

from __future__ import annotations

import collections
import os
import random
import time
from typing import Any, Callable

from ..logging import get_logger
from .goodput import get_ledger

logger = get_logger(__name__)


def run_resilient(
    train_fn: Callable,
    accelerator,
    *,
    max_restarts: int = 3,
    backoff_base_s: float = 1.0,
    backoff_max_s: float = 60.0,
    backoff_jitter: float = 0.25,
    restart_budget: int | None = None,
    restart_window_s: float = 600.0,
    resume: bool = True,
    checkpoint_dir: str | None = None,
    hang_timeout_s: float | None = None,
) -> Any:
    """Run ``train_fn(accelerator, attempt)`` to completion through failures.

    ``train_fn`` must be written resumable: loop from ``accelerator.step``
    (restored by ``load_state``) and call ``accelerator.save_state()``
    periodically plus ``accelerator.checkpoint_on_preemption()`` each step.
    ``train_fn`` taking a single argument is also accepted.

    ``checkpoint_dir`` resumes from an explicit folder; the default resumes
    via the project configuration's ``automatic_checkpoint_naming`` layout.
    ``restart_budget`` restarts within ``restart_window_s`` seconds trip the
    crash-loop detector (a ``RuntimeError`` that preserves the original
    failure as its cause); ``None`` disables the window check.

    Returns whatever ``train_fn`` returns. Raises the last failure once
    ``max_restarts`` is exhausted.
    """
    if max_restarts < 0:
        raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
    ledger = get_ledger()
    restart_times: collections.deque = collections.deque()
    attempt = 0
    watchdog = None
    prev_watchdog = None
    if hang_timeout_s is not None:
        from ..health import hang as hang_mod

        watchdog = hang_mod.HangWatchdog(timeout_s=hang_timeout_s, on_hang="raise")
        # Install as the process default so the per-step Accelerator hooks
        # (guard_step / checkpoint_on_preemption) heartbeat it with no loop
        # changes; the previous default is restored on the way out. The
        # previous watchdog must be SUSPENDED meanwhile — an armed exit-mode
        # watchdog that stops receiving beats would os._exit(113) a perfectly
        # healthy run.
        prev_watchdog = hang_mod.get_default_watchdog()
        if prev_watchdog is not None:
            prev_watchdog.stop()
        hang_mod.set_default_watchdog(watchdog)
        watchdog.start()
    try:
        return _run_resilient_loop(
            train_fn, accelerator, ledger, restart_times, attempt, max_restarts,
            backoff_base_s, backoff_max_s, backoff_jitter, restart_budget,
            restart_window_s, resume, checkpoint_dir, watchdog,
        )
    finally:
        if watchdog is not None:
            import threading

            from ..health import hang as hang_mod

            watchdog.stop()
            hang_mod.set_default_watchdog(prev_watchdog)
            if prev_watchdog is not None:
                # start() resumes it disarmed (re-arms on the next beat): the
                # env-installed deadline keeps guarding whatever follows.
                prev_watchdog.start(threading.main_thread())


def _run_resilient_loop(
    train_fn, accelerator, ledger, restart_times, attempt, max_restarts,
    backoff_base_s, backoff_max_s, backoff_jitter, restart_budget,
    restart_window_s, resume, checkpoint_dir, watchdog,
):
    while True:
        try:
            # Resume INSIDE the guarded region: a failing restore (torn array
            # file, transient filesystem error) must consume a retry like any
            # other failure, not bypass the backoff/budget machinery.
            if resume:
                _try_resume(accelerator, checkpoint_dir)
            result = _call_train_fn(train_fn, accelerator, attempt)
            accelerator.log_goodput()
            return result
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as exc:
            if watchdog is not None:
                watchdog.rearm()  # the next attempt gets a fresh deadline
            attempt += 1
            if attempt > max_restarts:
                logger.error(
                    f"Training failed and the restart budget is exhausted "
                    f"({max_restarts} restarts): {exc!r}"
                )
                raise
            now = time.monotonic()
            restart_times.append(now)
            if restart_budget is not None:
                while restart_times and now - restart_times[0] > restart_window_s:
                    restart_times.popleft()
                if len(restart_times) > restart_budget:
                    raise RuntimeError(
                        f"Crash loop detected: {len(restart_times)} restarts within "
                        f"{restart_window_s:.0f}s exceeds the budget of {restart_budget}. "
                        "The job is failing deterministically, not being preempted — "
                        "fix the failure instead of restarting through it."
                    ) from exc
            delay = min(backoff_max_s, backoff_base_s * (2 ** (attempt - 1)))
            delay *= 1.0 + random.uniform(0.0, backoff_jitter)
            logger.warning(
                f"Attempt {attempt}/{max_restarts} failed ({type(exc).__name__}: {exc}); "
                f"resuming from the newest complete checkpoint in {delay:.1f}s."
            )
            t = time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            ledger.record_restart(time.perf_counter() - t)


def _call_train_fn(train_fn, accelerator, attempt):
    import inspect

    try:
        params = list(inspect.signature(train_fn).parameters.values())
        positional = [
            p for p in params if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
        ]
        # Only a second POSITIONAL slot (or *args) can receive attempt —
        # keyword-only params must not count toward the arity.
        takes_attempt = len(positional) >= 2 or any(
            p.kind == p.VAR_POSITIONAL for p in params
        )
    except (TypeError, ValueError):
        takes_attempt = True
    return train_fn(accelerator, attempt) if takes_attempt else train_fn(accelerator)


def _try_resume(accelerator, checkpoint_dir):
    """Restore from the newest complete checkpoint if one exists; a fresh run
    (nothing saved yet) starts clean instead of failing."""
    from ..checkpointing import _checkpoint_complete
    from ..utils.constants import CHECKPOINT_DIR_PREFIX

    project = accelerator.project_configuration
    # No ckpt_restore tracking here: load_accelerator_state records its own
    # elapsed time in the ledger — wrapping it again would double-count.
    if checkpoint_dir is not None:
        if os.path.isdir(checkpoint_dir) and _checkpoint_complete(checkpoint_dir, accelerator):
            accelerator.load_state(checkpoint_dir)
        return
    if not (project.automatic_checkpoint_naming and project.project_dir):
        return
    base = os.path.join(project.project_dir, "checkpoints")
    if not os.path.isdir(base) or not any(
        f.startswith(f"{CHECKPOINT_DIR_PREFIX}_") for f in os.listdir(base)
    ):
        return
    try:
        accelerator.load_state()  # newest COMPLETE folder; skips litter
    except FileNotFoundError:
        logger.warning(f"No complete checkpoint under {base}; starting fresh.")
