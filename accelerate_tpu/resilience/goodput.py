"""Goodput accounting — where did the wall-clock go?

At pod scale the question that decides cost is not "how fast is a step" but
"what fraction of the job's wall-clock was spent stepping". Everything else —
XLA compiles, checkpoint saves, restores after a preemption, restart backoff —
is *badput*: time the chips were reserved but no tokens were trained. This
module keeps one process-wide ledger that the rest of the framework feeds
(``checkpointing`` times saves/restores, ``run_resilient`` times restart
downtime, ``bench.py`` times compiles and steps) and that surfaces in two
places: ``Accelerator.log_goodput()`` pushes the breakdown through the normal
tracker path, and ``bench.py`` embeds it in its JSON lines. The telemetry
registry (telemetry/metrics.py) additionally exports the summary as
``accelerate_goodput_*``/``accelerate_badput_seconds`` gauges via a
scrape-time collector, so the Prometheus endpoint and ``log_telemetry`` see
the same numbers with zero per-step cost.

The categories follow the goodput decomposition used by large TPU trainers
(productive step time vs program-acquisition and checkpoint overheads): one
goodput bucket (``step``) and nine badput buckets — ``compile``, ``ckpt_save``,
``ckpt_restore``, ``restart``, the health subsystem's ``rollback``
(last-known-good restores after a NaN/loss-spike trip, health/rollback.py) and
``hang`` (time a wedged run sat before the watchdog fired, health/hang.py),
plus ``reshard`` (elastic world-size transitions, resilience/elastic.py),
``profile`` (trace-capture start/stop/parse overhead, telemetry/profiler.py),
and ``tune`` (the autotuner's short-bench trials, tune/trials.py — reserved
chip time spent measuring candidate configs, not training).  Wall-clock not
attributed to any bucket is reported as ``other_s`` (data feeding, host-side
logging, eval, idle).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

GOODPUT_CATEGORY = "step"
# ``reshard`` is the elastic world-size transition (resilience/elastic.py):
# re-forming the mesh at a new dp degree and redistributing params/opt-state
# onto it — voluntary downtime, booked separately from crash ``restart``s.
# ``profile`` is trace-capture overhead (telemetry/profiler.py): starting/
# stopping an XLA trace and parsing it into the attribution report — booked so
# a profiled run's goodput/MFU accounting stays honest about what the
# diagnosis itself cost.
# ``tune`` is autotuner trial time (tune/trials.py): the whole wall-clock of a
# candidate's short-bench — build, compile, warmup, and measured steps — so
# trial steps never count as productive training and can't inflate MFU/goodput.
BADPUT_CATEGORIES = (
    "compile", "ckpt_save", "ckpt_restore", "restart", "rollback", "hang",
    "reshard", "profile", "tune",
)
CATEGORIES = (GOODPUT_CATEGORY,) + BADPUT_CATEGORIES


class GoodputLedger:
    """Wall-clock classifier. All methods are thread-safe (orbax background
    writers and async hosts may report concurrently with the train loop)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self):
        """Start a fresh accounting window (bench.py calls this per config)."""
        with self._lock:
            self._t0 = time.perf_counter()
            self.seconds = {c: 0.0 for c in CATEGORIES}
            self.counts = {c: 0 for c in CATEGORIES}
            self.restarts = 0

    # ------------------------------------------------------------- recording
    def add(self, category: str, seconds: float, count: int = 1):
        if category not in CATEGORIES:
            raise ValueError(f"unknown goodput category {category!r}; choose from {CATEGORIES}")
        with self._lock:
            self.seconds[category] += float(seconds)
            self.counts[category] += count
        # Durable delta (telemetry/journal.py): badput transitions (compiles,
        # checkpoint saves/restores, resharding, profiling overhead) land in
        # the per-host journal as they happen, so the fleet timeline renders
        # where the wall-clock went. ``step`` is excluded — the telemetry
        # hook journals every step boundary already, richer.
        if category != GOODPUT_CATEGORY:
            try:
                from ..telemetry.journal import journal_event

                journal_event("goodput", category=category,
                              seconds=round(float(seconds), 6), count=count)
            except Exception:
                pass

    @contextmanager
    def track(self, category: str):
        """Attribute the wall-clock of a ``with`` block to ``category``."""
        if category not in CATEGORIES:
            raise ValueError(f"unknown goodput category {category!r}; choose from {CATEGORIES}")
        t = time.perf_counter()
        try:
            yield
        finally:
            self.add(category, time.perf_counter() - t)

    def record_step(self, seconds: float, steps: int = 1):
        self.add(GOODPUT_CATEGORY, seconds, count=steps)

    def record_restart(self, downtime_s: float = 0.0):
        with self._lock:
            self.restarts += 1
            self.seconds["restart"] += float(downtime_s)
            self.counts["restart"] += 1
        try:
            from ..telemetry.journal import journal_event

            journal_event("goodput", category="restart",
                          seconds=round(float(downtime_s), 6), count=1)
        except Exception:
            pass

    def mark_process_start(self, attempt: int = 0):
        """Called by ``PartialState`` at process birth: a nonzero
        ACCELERATE_RESTART_ATTEMPT means the launcher relaunched the gang —
        count those incarnations even though their downtime was paid in a
        previous process we cannot measure from here."""
        if attempt > 0:
            with self._lock:
                self.restarts = max(self.restarts, int(attempt))

    # --------------------------------------------------------------- reading
    @property
    def wall_s(self) -> float:
        return time.perf_counter() - self._t0

    def summary(self) -> dict:
        """Flat goodput/badput breakdown — the schema shared by
        ``Accelerator.log_goodput()`` and ``bench.py``'s JSON lines."""
        with self._lock:
            wall = max(time.perf_counter() - self._t0, 1e-9)
            productive = self.seconds[GOODPUT_CATEGORY]
            badput = sum(self.seconds[c] for c in BADPUT_CATEGORIES)
            out = {
                "goodput_fraction": round(min(productive / wall, 1.0), 4),
                "badput_fraction": round(min(badput / wall, 1.0), 4),
                "wall_s": round(wall, 3),
                "productive_s": round(productive, 3),
                "badput_s": round(badput, 3),
                "other_s": round(max(wall - productive - badput, 0.0), 3),
                "steps": self.counts[GOODPUT_CATEGORY],
                "restarts": self.restarts,
            }
            for c in BADPUT_CATEGORIES:
                out[f"{c}_s"] = round(self.seconds[c], 3)
            return out


_LEDGER = GoodputLedger()


def get_ledger() -> GoodputLedger:
    """The process-wide ledger every layer reports into."""
    return _LEDGER
