"""Preemption detection — signals in, one agreed-on bit out.

TPU slices are preempted with a SIGTERM and a short grace window (spot/
preemptible VMs, maintenance events, pod evictions). Under single-program
multi-host execution the *whole slice* must act on it together: if only the
signaled host stops to checkpoint, every other host deadlocks in its next
collective. So detection is split in two:

- a :class:`PreemptionWatcher` turns SIGTERM/SIGINT into a **sticky local
  flag** (signal handlers must do nearly nothing — the actual checkpoint runs
  on the training thread at the next step boundary), optionally OR-ing in a
  pluggable *maintenance-event poller* (e.g. the GCE metadata server, polled
  at a bounded rate);
- :meth:`PreemptionWatcher.sync` turns the per-host flags into an all-host
  agreement with one tiny sum collective (the same idiom as
  ``Accelerator.check_trigger``): **any** flagged host means **every** host
  checkpoints and exits at the same step.

``Accelerator.checkpoint_on_preemption()`` drives this once per training step;
the launcher installs the default watcher early (ACCELERATE_HANDLE_PREEMPTION)
so a SIGTERM during compile or data loading is not lost.
"""

from __future__ import annotations

import signal
import threading
import time
from typing import Callable

import numpy as np

from ..logging import get_logger

logger = get_logger(__name__)

_WATCHER_SEQ = 0


class PreemptionWatcher:
    """Sticky preemption flag fed by signals and an optional poller.

    ``poller`` is any zero-arg callable returning truthy when the platform has
    announced an upcoming maintenance event; it is rate-limited to one call per
    ``poll_interval_s`` and its result is sticky (once preempting, always
    preempting — the grace window only shrinks).
    """

    def __init__(
        self,
        signals: tuple = (signal.SIGTERM, signal.SIGINT),
        poller: Callable[[], bool] | None = None,
        poll_interval_s: float = 5.0,
    ):
        self.signals = tuple(signals)
        self.poller = poller
        self.poll_interval_s = poll_interval_s
        self._flag = False
        self._signal_received = None
        self._prev_handlers = None
        self._last_poll = 0.0
        self._lock = threading.Lock()
        self._kv_sync = False
        self._sync_epoch = 0
        # KV namespaces must be unique per (watcher, sync) and identical
        # across ranks — same construction order, the SPMD contract.
        global _WATCHER_SEQ
        _WATCHER_SEQ += 1
        self._watcher_id = _WATCHER_SEQ

    # ------------------------------------------------------------- lifecycle
    def install(self) -> "PreemptionWatcher":
        """Install the signal handlers (idempotent; main thread only — the
        Python signal API's constraint, same as every trainer's)."""
        if self._prev_handlers is not None:
            return self
        self._prev_handlers = {}
        for sig in self.signals:
            self._prev_handlers[sig] = signal.signal(sig, self._handler)
        return self

    def uninstall(self):
        if self._prev_handlers is None:
            return
        for sig, prev in self._prev_handlers.items():
            signal.signal(sig, prev)
        self._prev_handlers = None

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    def _handler(self, signum, frame):
        # Handlers must be async-signal-safe-ish: set the flag, log, return.
        # The training thread acts at the next checkpoint_on_preemption().
        self._flag = True
        self._signal_received = signum
        logger.warning(
            f"Received signal {signal.Signals(signum).name}: preemption flagged; "
            "an emergency checkpoint will be taken at the next step boundary."
        )
        # A second SIGINT should still interrupt hard (developer Ctrl-C twice).
        if signum == signal.SIGINT and self._prev_handlers is not None:
            prev = self._prev_handlers.pop(signum, signal.default_int_handler)
            signal.signal(signum, prev)

    # ------------------------------------------------------------- detection
    @property
    def preemption_requested(self) -> bool:
        """This host's sticky flag (signal OR a previous positive poll)."""
        return self._flag

    def poll(self) -> bool:
        """Local flag, refreshed from the maintenance poller (rate-limited)."""
        if self._flag or self.poller is None:
            return self._flag
        now = time.monotonic()
        with self._lock:
            if now - self._last_poll < self.poll_interval_s:
                return self._flag
            self._last_poll = now
        try:
            if self.poller():
                self._flag = True
                logger.warning("Maintenance-event poller reported an upcoming event.")
        except Exception as exc:  # a flaky metadata server must not kill training
            logger.warning(f"Maintenance poller failed ({exc!r}); ignoring.")
        return self._flag

    def sync(self, state=None) -> bool:
        """All-host agreement: True everywhere iff ANY host is flagged.

        Single-process topologies short-circuit to the local flag (no device
        round-trip per step); multi-host runs pay one scalar sum collective —
        every process must therefore call ``sync`` at the same step boundary,
        which ``checkpoint_on_preemption``'s once-per-step contract provides.
        Backends that cannot run multiprocess computations (the 2-process CPU
        harness) fall back to the coordination-service KV exchange, same as
        the health guard's agreement.
        """
        local = self.poll()
        if state is None:
            from ..state import PartialState

            state = PartialState()
        if state.num_processes <= 1:
            return local
        agreed = None
        if not self._kv_sync:
            try:
                from ..utils import operations as ops

                total = ops.reduce(np.asarray(int(local), dtype=np.int32), reduction="sum")
                agreed = float(np.asarray(total)) >= 1
            except Exception as exc:
                logger.warning(
                    f"Device-collective preemption sync unavailable "
                    f"({type(exc).__name__}: {exc}); using the coordination-"
                    "service KV exchange instead."
                )
                self._kv_sync = True
        if agreed is None:
            from ..utils.agreement import kv_or_exchange

            self._sync_epoch += 1
            agreed = bool(
                kv_or_exchange(
                    int(local),
                    state.num_processes,
                    state.process_index,
                    namespace=f"at_preempt/{self._watcher_id}/{self._sync_epoch}",
                )
            )
        if agreed:
            self._flag = True  # agreement is sticky on every host
            from ..telemetry.flight import record_event

            record_event("preemption_agreed")
        return agreed


def gce_maintenance_poller(timeout_s: float = 0.5) -> bool:
    """Poll the GCE metadata server for an upcoming maintenance event — the
    pluggable poller for GCP TPU VMs (pass as ``PreemptionWatcher(poller=...)``).
    Returns False on any error: off-GCP hosts simply never fire."""
    import urllib.request

    req = urllib.request.Request(
        "http://metadata.google.internal/computeMetadata/v1/instance/maintenance-event",
        headers={"Metadata-Flavor": "Google"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return resp.read().decode().strip() != "NONE"
    except Exception:
        return False


_default_watcher: PreemptionWatcher | None = None


def get_default_watcher(install: bool = True) -> PreemptionWatcher:
    """The process-wide watcher shared by ``PartialState`` (env-driven install)
    and ``Accelerator.checkpoint_on_preemption``."""
    global _default_watcher
    if _default_watcher is None:
        _default_watcher = PreemptionWatcher()
    if install:
        _default_watcher.install()
    return _default_watcher


def reset_default_watcher():
    """Uninstall and forget the default watcher (tests)."""
    global _default_watcher
    if _default_watcher is not None:
        _default_watcher.uninstall()
    _default_watcher = None
