"""Resilience subsystem — preemption-aware training for multi-host SPMD jobs.

Four layers, smallest mechanism first:

- :mod:`.preemption` — SIGTERM/SIGINT → sticky flag, all-host agreement via a
  scalar collective, pluggable maintenance-event poller;
- :mod:`.faults` — deterministic, env-driven fault injection
  (``ACCELERATE_FAULT_PLAN``) so every recovery path below — and the health
  subsystem's (``nan``/``loss_spike``/``hang`` kinds, :mod:`..health`) — runs
  in CI;
- :mod:`.runner` — :func:`run_resilient`: resume from the newest complete
  checkpoint, exponential backoff + jitter, crash-loop budget, and optional
  hang conversion (``hang_timeout_s``, via the health watchdog);
- :mod:`.goodput` — the wall-clock ledger (productive step time vs compile /
  checkpoint / restart / rollback / hang badput) surfaced by
  ``Accelerator.log_goodput()`` and ``bench.py``.

Driven from training code via ``Accelerator.checkpoint_on_preemption()`` (one
call per step) and ``run_resilient(train_fn, accelerator)``; driven from the
CLI via ``accelerate-tpu launch --handle_preemption [--max_restarts N]``.
"""

from .faults import FaultPlan, SimulatedFault, active_plan, reset_active_plan, set_active_plan
from .goodput import GoodputLedger, get_ledger
from .preemption import PreemptionWatcher, gce_maintenance_poller, get_default_watcher, reset_default_watcher
from .runner import run_resilient

__all__ = [
    "FaultPlan",
    "GoodputLedger",
    "PreemptionWatcher",
    "SimulatedFault",
    "active_plan",
    "gce_maintenance_poller",
    "get_default_watcher",
    "get_ledger",
    "reset_active_plan",
    "reset_default_watcher",
    "run_resilient",
    "set_active_plan",
]
