"""Resilience subsystem — preemption-aware training for multi-host SPMD jobs.

Four layers, smallest mechanism first:

- :mod:`.preemption` — SIGTERM/SIGINT → sticky flag, all-host agreement via a
  scalar collective, pluggable maintenance-event poller;
- :mod:`.faults` — deterministic, env-driven fault injection
  (``ACCELERATE_FAULT_PLAN``) so every recovery path below — and the health
  subsystem's (``nan``/``loss_spike``/``hang`` kinds, :mod:`..health`) — runs
  in CI;
- :mod:`.runner` — :func:`run_resilient`: resume from the newest complete
  checkpoint, exponential backoff + jitter, crash-loop budget, and optional
  hang conversion (``hang_timeout_s``, via the health watchdog);
- :mod:`.elastic` — elastic world-size restarts (``elastic=True``):
  :func:`reshard_accelerator` re-forms the mesh at the dp degree the
  surviving devices support and redistributes params/opt-state onto it,
  rescaling gradient accumulation to preserve the global batch; the
  ``shrink:N``/``grow:N`` fault kinds make the transition a deterministic
  drill (docs/resilience.md "Elastic world size");
- :mod:`.goodput` — the wall-clock ledger (productive step time vs compile /
  checkpoint / restart / rollback / hang badput) surfaced by
  ``Accelerator.log_goodput()`` and ``bench.py``.

Driven from training code via ``Accelerator.checkpoint_on_preemption()`` (one
call per step) and ``run_resilient(train_fn, accelerator)``; driven from the
CLI via ``accelerate-tpu launch --handle_preemption [--max_restarts N]``.
"""

from .elastic import agree_world_size, reshard_accelerator
from .faults import (
    FaultPlan,
    SimulatedFault,
    WorldSizeChange,
    active_plan,
    reset_active_plan,
    set_active_plan,
)
from .goodput import GoodputLedger, get_ledger
from .preemption import PreemptionWatcher, gce_maintenance_poller, get_default_watcher, reset_default_watcher
from .runner import run_resilient

__all__ = [
    "FaultPlan",
    "GoodputLedger",
    "PreemptionWatcher",
    "SimulatedFault",
    "WorldSizeChange",
    "active_plan",
    "agree_world_size",
    "gce_maintenance_poller",
    "get_default_watcher",
    "get_ledger",
    "reset_active_plan",
    "reset_default_watcher",
    "reshard_accelerator",
    "run_resilient",
    "set_active_plan",
]
