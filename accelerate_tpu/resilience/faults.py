"""Deterministic fault injection — preemption you can run in CI.

Preemption handling that is only ever exercised by real TPU maintenance events
is untested code on the critical path. This module makes the failure modes
reproducible: a fault *plan* parsed from ``ACCELERATE_FAULT_PLAN`` names the
training step at which each fault fires, and ``Accelerator.
checkpoint_on_preemption()`` (called once per step) fires them. The grammar:

    ACCELERATE_FAULT_PLAN="step:37=kill;step:80=partial_ckpt"

i.e. ``;``-separated entries of ``step:<N>=<action>[:<arg>]`` with actions

- ``kill``          raise :class:`SimulatedFault` — the in-process stand-in for
                    a hard preemption (``run_resilient`` catches it and
                    restarts, exactly like a relaunched gang);
- ``sigterm``       deliver a real SIGTERM to this process — exercises the
                    :mod:`.preemption` watcher → emergency-checkpoint path;
- ``partial_ckpt``  make the NEXT checkpoint save commit only partially
                    (missing item dir + orbax tmp litter), the on-disk
                    signature of a save interrupted mid-write — exercises the
                    newest-complete-checkpoint fallback on resume;
- ``stall:<secs>``  sleep, simulating a straggling host / hung I/O;
- ``hang:<secs>``   sleep *interruptibly* for a long time (default 3600s),
                    simulating a deadlocked host — exercises the health
                    subsystem's hang watchdog (``docs/health.md``), whose
                    ``raise`` mode can preempt this Python-level stall;
- ``nan``           poison the step's observed loss with NaN — consumed by
                    ``Accelerator.guard_step()`` (NOT fired here), exercising
                    the numerics sentinel → rollback path;
- ``loss_spike:<mult>x``  multiply the step's observed loss (default 50x) —
                    consumed by ``guard_step()``, exercising the spike
                    detector → rollback path;
- ``shrink:<N>``    raise :class:`WorldSizeChange`: the in-process stand-in
                    for a preemption that takes 1/N of the devices away —
                    ``run_resilient(elastic=True)`` catches it and re-forms
                    the mesh at the smaller dp degree (docs/resilience.md
                    "Elastic world size");
- ``grow:<N>``      raise :class:`WorldSizeChange` in the other direction —
                    maintenance returned capacity, re-form N× wider (capped
                    at the devices actually available).

Each fault fires at most once per plan instance, so an auto-resumed run that
replays the faulting step does not crash-loop on its own injection. The data
faults (``nan``/``loss_spike``) fire only when the training loop calls
``guard_step`` — on a loop without the health guard they stay inert.

Serving chaos (docs/serving.md "Failure semantics"): ``req:<N>=<action>``
entries target the serving tier instead of the training loop — ``N`` is the
Nth /v1 request (0-based) the consuming component serves, and the actions are

- ``worker_kill``         the worker dies mid-stream while serving request N
                          (``os._exit(0)`` after the first token delta — the
                          real-death analog, run under the launcher; in-process
                          rigs set ``ServingFrontend.kill_mode = "stream"`` for
                          a survivable stand-in) — exercises router retry,
                          probe-failure breakers, and lease eviction;
- ``handoff_drop``        the Nth prefill→decode chain handoff is dropped in
                          transit — exercises free-on-ack re-handoff and the
                          orphaned chain's return to the free list;
- ``stall:<secs>``        the worker sleeps before admitting request N —
                          exercises deadline propagation;
- ``slow_worker:<mult>x`` the worker streams request N's events ``mult``×
                          slower — exercises retry budgets and SLO booking.

``maybe_fire`` never fires ``req:`` faults; serving components consume them
through :meth:`FaultPlan.take_serving_fault` (each counts its own requests).
"""

from __future__ import annotations

import os
import shutil
import signal
import time
from dataclasses import dataclass, field

from ..logging import get_logger
from ..utils.constants import ENV_FAULT_PLAN

logger = get_logger(__name__)

_ACTIONS = (
    "kill", "sigterm", "partial_ckpt", "stall", "hang", "nan", "loss_spike",
    "shrink", "grow",
)
# Data faults poison the step's observed loss; they are consumed by the health
# guard (Accelerator.guard_step) rather than fired by maybe_fire.
_DATA_ACTIONS = ("nan", "loss_spike")
# World-size faults change how many devices the next incarnation sees.
_RESIZE_ACTIONS = ("shrink", "grow")
# Serving-scope (``req:N=``) actions: consumed by serving_net components via
# take_serving_fault, never fired by maybe_fire. ``stall`` is shared with the
# step scope; the entry's scope decides who consumes it.
_SERVING_ACTIONS = ("worker_kill", "handoff_drop", "stall", "slow_worker")


class SimulatedFault(RuntimeError):
    """Raised by the ``kill`` action: the injectable analog of a preemption
    that kills the process before any handler runs."""

    def __init__(self, step: int):
        super().__init__(f"fault injection: simulated kill at step {step}")
        self.step = step


class WorldSizeChange(RuntimeError):
    """Raised by the ``shrink:N``/``grow:N`` actions: the gang dies AND the
    next incarnation will see a different device count (preemption took a
    slice away / maintenance gave one back). ``run_resilient(elastic=True)``
    converts it into a mesh re-form + reshard instead of a fixed-size restart."""

    def __init__(self, step: int, direction: str, factor: int):
        super().__init__(
            f"fault injection: world size {direction} by {factor}x at step {step}"
        )
        self.step = step
        self.direction = direction
        self.factor = factor


@dataclass
class Fault:
    step: int
    action: str
    arg: str | None = None
    fired: bool = False
    # "step" faults key on the training step; "req" faults key on the Nth
    # /v1 request the consuming serving component serves.
    scope: str = "step"

    @property
    def slow_factor(self) -> float:
        """The ``slow_worker:<mult>x`` multiplier (parse-validated > 0)."""
        return float((self.arg or "2").rstrip("xX"))

    @property
    def stall_s(self) -> float:
        """The ``stall:<secs>`` duration."""
        return float(self.arg) if self.arg else 1.0


@dataclass
class FaultPlan:
    faults: list[Fault] = field(default_factory=list)
    # Set by a fired ``partial_ckpt`` fault; consumed by the next save.
    _pending_partial_ckpt: bool = False

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        faults = []
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            try:
                lhs, action = entry.split("=", 1)
                kind, step = lhs.split(":", 1)
                kind = kind.strip()
                if kind not in ("step", "req"):
                    raise ValueError
                step = int(step)
                action, _, arg = action.strip().partition(":")
                if kind == "req":
                    if action not in _SERVING_ACTIONS:
                        raise ValueError
                    if action in ("worker_kill", "handoff_drop") and arg:
                        raise ValueError  # these take no argument
                    if action == "stall" and arg:
                        float(arg)
                    if action == "slow_worker" and arg:
                        # '4x' or '4' — the multiplier must be positive.
                        if float(arg.rstrip("xX")) <= 0:
                            raise ValueError
                elif action not in _ACTIONS:
                    raise ValueError
                elif action in ("stall", "hang") and arg:
                    float(arg)  # a bad duration must fail at parse, not mid-run
                elif action == "loss_spike" and arg:
                    # '50x' or '50' — the multiplier must be a positive number.
                    if float(arg.rstrip("xX")) <= 0:
                        raise ValueError
                elif action == "nan" and arg:
                    raise ValueError  # nan takes no argument
                elif action in _RESIZE_ACTIONS and arg:
                    # 'shrink:2' halves the device count; the factor must be
                    # an integer >= 2 (1 would be a no-op resize).
                    if int(arg) < 2:
                        raise ValueError
            except ValueError:
                raise ValueError(
                    f"Bad fault-plan entry {entry!r}: expected "
                    "'step:<N>=<action>[:<arg>]' with action in "
                    f"{'/'.join(_ACTIONS)} (e.g. 'step:37=kill;step:80=partial_ckpt') "
                    "or 'req:<N>=<action>[:<arg>]' with action in "
                    f"{'/'.join(_SERVING_ACTIONS)} (e.g. 'req:0=worker_kill')."
                ) from None
            faults.append(Fault(step=step, action=action, arg=arg or None,
                                scope=kind))
        return cls(faults=sorted(faults, key=lambda f: f.step))

    @classmethod
    def from_env(cls) -> "FaultPlan | None":
        spec = os.environ.get(ENV_FAULT_PLAN, "").strip()
        return cls.parse(spec) if spec else None

    # ------------------------------------------------------------------ fire
    def maybe_fire(self, step: int):
        """Fire every not-yet-fired (non-data) fault scheduled for ``step``."""
        for f in self.faults:
            if (f.fired or f.step != step or f.scope != "step"
                    or f.action in _DATA_ACTIONS):
                continue
            f.fired = True
            logger.warning(f"Fault injection: firing {f.action} at step {step}")
            # Black box: an injected fault must name itself in the flight
            # recorder so a drill's dump ends with the cause, not just the
            # symptom (the hang-drill acceptance in tests/test_profiling.py).
            from ..telemetry.flight import get_flight_recorder

            get_flight_recorder().record(
                "fault_injected", step=step, action=f.action,
                arg=f.arg if f.arg else None,
            )
            if f.action == "kill":
                raise SimulatedFault(step)
            if f.action in _RESIZE_ACTIONS:
                raise WorldSizeChange(step, f.action, int(f.arg) if f.arg else 2)
            if f.action == "sigterm":
                os.kill(os.getpid(), signal.SIGTERM)
            elif f.action == "partial_ckpt":
                self._pending_partial_ckpt = True
            elif f.action == "stall":
                time.sleep(float(f.arg) if f.arg else 1.0)
            elif f.action == "hang":
                # Interruptible stall: sleep in slices so the hang watchdog's
                # 'raise' mode can preempt it with an async HangDetected (a
                # single long sleep would absorb the exception until it ends).
                deadline = time.monotonic() + (float(f.arg) if f.arg else 3600.0)
                while time.monotonic() < deadline:
                    time.sleep(0.05)

    def take_data_fault(self, step: int):
        """Consume (at most) one data fault scheduled for ``step`` — called by
        the health guard, which applies it to the observed loss."""
        for f in self.faults:
            if (not f.fired and f.step == step and f.scope == "step"
                    and f.action in _DATA_ACTIONS):
                f.fired = True
                from ..telemetry.flight import get_flight_recorder

                get_flight_recorder().record(
                    "fault_injected", step=step, action=f.action,
                    arg=f.arg if f.arg else None,
                )
                return f
        return None

    def take_serving_fault(self, index: int, actions=_SERVING_ACTIONS):
        """Consume (at most) one unfired ``req:``-scope fault scheduled for
        serving-request ``index`` whose action is in ``actions`` — called by
        the serving components at their own consumption sites (the frontend
        counts the /v1 generate+import requests it serves; the handoff relay
        counts chain exports). Fired-once, like every other fault, and the
        injection names itself in the flight recorder before the consumer
        acts on it."""
        for f in self.faults:
            if (not f.fired and f.scope == "req" and f.step == index
                    and f.action in actions):
                f.fired = True
                logger.warning(
                    f"Fault injection: firing serving fault {f.action} at "
                    f"request {index}"
                )
                from ..telemetry.flight import get_flight_recorder

                get_flight_recorder().record(
                    "fault_injected", request=int(index), action=f.action,
                    arg=f.arg if f.arg else None,
                )
                return f
        return None

    def maybe_corrupt_checkpoint(self, output_dir: str) -> bool:
        """Consume a pending ``partial_ckpt`` fault: leave ``output_dir`` in
        the exact on-disk state of an interrupted non-blocking save — a
        manifest-listed item dir missing plus ``.orbax-checkpoint-tmp`` litter
        — so ``_checkpoint_complete`` rejects it and resume falls back."""
        if not self._pending_partial_ckpt:
            return False
        self._pending_partial_ckpt = False
        from ..utils.constants import MODEL_NAME

        item = os.path.join(output_dir, MODEL_NAME)
        shutil.rmtree(item, ignore_errors=True)
        os.makedirs(item + ".orbax-checkpoint-tmp-0", exist_ok=True)
        logger.warning(f"Fault injection: left {output_dir} partially written")
        return True


# ------------------------------------------------------- process-wide plan
# One plan per process so fired-state survives in-process restarts
# (run_resilient re-entering train_fn must not re-fire the same fault).
_UNSET = object()
_active_plan = _UNSET


def active_plan() -> FaultPlan | None:
    """The process's fault plan: lazily parsed from ACCELERATE_FAULT_PLAN on
    first use (None when the env is unset), or whatever ``set_active_plan``
    installed programmatically."""
    global _active_plan
    if _active_plan is _UNSET:
        _active_plan = FaultPlan.from_env()
    return _active_plan


def set_active_plan(plan: FaultPlan | None):
    global _active_plan
    _active_plan = plan


def reset_active_plan():
    """Forget the cached plan (tests); the next ``active_plan()`` re-reads env."""
    global _active_plan
    _active_plan = _UNSET


def serving_fault(index: int, *actions):
    """The serving components' one-line consumption hook: the process's
    active plan's :meth:`FaultPlan.take_serving_fault`, or None when no plan
    is armed (the overwhelmingly common case — one dict read)."""
    plan = active_plan()
    if plan is None:
        return None
    return plan.take_serving_fault(index, actions or _SERVING_ACTIONS)
