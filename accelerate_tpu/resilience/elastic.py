"""Elastic world-size training — survive shrink/grow, not just restart.

Production TPU fleets do not restart at a fixed size: preemption takes slices
away and maintenance gives them back. The fixed-size story (runner.py) can
only re-form the exact gang it lost; this module teaches the resilience
subsystem to re-form the mesh at whatever dp degree the surviving devices
support and *reshard* the training state onto it:

- :func:`reshard_accelerator` is the transition: resolve the new mesh shape
  (``parallel/mesh.py`` — tp/pp/fsdp/sp/ep and the slice axis stay fixed,
  only dp absorbs the difference), redistribute every model's params and
  every optimizer's state onto the new ``NamedSharding``s (a shard-to-shard
  ``device_put`` — the portable-redistribution property of arxiv 2112.01075;
  no host gather, no full-replication HBM spike), rescale gradient
  accumulation to preserve the global batch (erroring pointedly when it
  cannot divide), reassign data-loader shards with the sampler-RNG contract
  intact, discard health-guard snapshots captured on the old mesh, and book
  the whole transition as ``reshard`` badput plus world-size gauges in the
  metrics registry.
- ``run_resilient(elastic=True, min_data_parallel=...)`` (runner.py) drives
  it when a :class:`~.faults.WorldSizeChange` (the deterministic
  ``shrink:N``/``grow:N`` fault) or a real restart at a different device
  count occurs, restoring state from the health subsystem's in-memory
  last-known-good snapshot when the process survives, else from the newest
  complete checkpoint (``load_state(reshard=True)`` — checkpoints carry a
  mesh metadata record since this PR, see ``checkpointing.py``).
- :func:`agree_world_size` is the multi-host piece: before re-forming, every
  host must agree on the total surviving device count — one KV exchange over
  the coordination service (the same fallback transport the health guard and
  straggler monitor ride on collective-less rigs).
"""

from __future__ import annotations

import os

import jax

from ..logging import get_logger
from ..utils.constants import ENV_ELASTIC, ENV_MIN_DATA_PARALLEL
from .goodput import get_ledger

logger = get_logger(__name__)


def elastic_from_env() -> bool:
    """The launcher contract: ``--elastic`` → ACCELERATE_ELASTIC."""
    from ..utils.environment import parse_flag_from_env

    return parse_flag_from_env(ENV_ELASTIC)


def min_data_parallel_from_env() -> int:
    raw = os.environ.get(ENV_MIN_DATA_PARALLEL, "").strip()
    if not raw:
        return 1
    try:
        value = int(raw)
    except ValueError:
        raise ValueError(f"{ENV_MIN_DATA_PARALLEL}={raw!r} is not an integer") from None
    if value < 1:
        raise ValueError(f"{ENV_MIN_DATA_PARALLEL} must be >= 1, got {value}")
    return value


def agree_world_size(state, local_device_count: int | None = None) -> int:
    """Every host's surviving local device count, summed — and agreed.

    On a healthy backend ``jax.device_count()`` already answers this, but an
    elastic restart needs the answer *before* trusting the device set (and on
    collective-less rigs — multiprocess CPU — device collectives are
    unavailable entirely), so the exchange rides the coordination-service KV
    store: each rank posts its local count, all ranks read the same list back.
    Single-process: returns ``local_device_count`` unchanged."""
    from ..utils.agreement import kv_all_gather

    if local_device_count is None:
        local_device_count = jax.local_device_count()
    if state is None or getattr(state, "num_processes", 1) <= 1:
        return int(local_device_count)
    counts = kv_all_gather(
        str(int(local_device_count)),
        state.num_processes,
        state.process_index,
        namespace=f"accelerate_tpu/elastic/world_size/{_next_agreement_epoch()}",
    )
    return sum(int(c) for c in counts)


_AGREEMENT_EPOCH = 0


def _next_agreement_epoch() -> int:
    # KV namespaces are single-use and must be identical across ranks; ranks
    # agree in the same program order, so a process-wide counter lines up.
    global _AGREEMENT_EPOCH
    _AGREEMENT_EPOCH += 1
    return _AGREEMENT_EPOCH


def rescaled_accumulation(accum: int, old_dp: int, new_dp: int, *, context: str) -> int:
    """The global-batch invariant in one place: per-device batch is HBM-bound
    and fixed, so ``samples_per_update = per_device_batch × dp × accum`` must
    hold across any dp change — accumulation absorbs the difference or the
    transition refuses. Shared by the in-process reshard and the cross-mesh
    checkpoint restore so the two paths can never drift apart."""
    accum, old_dp, new_dp = int(accum), int(old_dp), int(new_dp)
    if old_dp == new_dp:
        return accum
    scaled = accum * old_dp
    if scaled % new_dp != 0:
        raise ValueError(
            f"{context} dp {old_dp} -> {new_dp} cannot preserve the global "
            f"batch: accumulation {accum} x dp {old_dp} = {scaled} "
            f"micro-gradients per update is not divisible by the new dp "
            f"degree. Use a dp that divides {scaled}, or change the global "
            "batch deliberately."
        )
    return scaled // new_dp


def resolve_resized_devices(devices, direction: str, factor: int):
    """The device set after a ``shrink:N``/``grow:N`` transition.

    Shrink keeps the leading ``len/N`` devices (the deterministic stand-in
    for "the surviving slice"); grow extends back toward the full device set,
    capped at what the platform actually exposes. Raises pointedly when a
    shrink factor does not divide the current count."""
    devices = list(devices)
    if direction == "shrink":
        if factor < 2 or len(devices) % factor != 0:
            raise ValueError(
                f"Cannot shrink {len(devices)} device(s) by {factor}x: the "
                "factor must divide the current device count (shrink in "
                "multiples of the slice size)."
            )
        return devices[: len(devices) // factor]
    if direction == "grow":
        # Capped at what the platform actually exposes; at full capacity the
        # cap makes the resize a no-op (the caller keeps training at the
        # current size — capacity that never materialized is not a fault).
        available = list(jax.devices())
        want = min(len(devices) * factor, len(available))
        if want <= len(devices):
            return devices
        return available[:want]
    raise ValueError(f"Unknown resize direction {direction!r}; use 'shrink' or 'grow'.")


def reshard_accelerator(accelerator, devices=None, min_data_parallel: int = 1):
    """Re-form the accelerator's mesh over ``devices`` and redistribute all
    live training state onto it. Returns the new mesh.

    Everything the training loop compiled against the old mesh is
    invalidated: the prepared models' jitted calls are dropped (they rebuild
    on next use) and the accelerator's mesh epoch is bumped so a stale
    ``build_train_step``/``build_train_window`` program raises a pointed
    error instead of silently feeding the wrong layout. The caller (normally
    ``run_resilient``) re-enters the training function, which rebuilds its
    fused step against the new mesh.
    """
    import dataclasses

    from ..parallel.mesh import build_elastic_mesh
    from ..parallel.sharding import (
        apply_shardings,
        data_parallel_degree,
        respec_shardings,
        transfer_to_mesh,
    )

    if devices is None:
        devices = list(jax.devices())
    old_mesh = accelerator.mesh
    ledger = get_ledger()
    with ledger.track("reshard"):
        new_mesh, new_config = build_elastic_mesh(
            old_mesh, devices, min_data_parallel=min_data_parallel
        )
        old_dp = data_parallel_degree(old_mesh)
        new_dp = data_parallel_degree(new_mesh)
        accum = accelerator.gradient_accumulation_steps
        accelerator.gradient_accumulation_steps = rescaled_accumulation(
            accum, old_dp, new_dp, context="Elastic resize"
        )
        # Swap the mesh into the process singletons BEFORE moving arrays, so
        # every layer that reads accelerator.mesh live (batch placement, the
        # sharding planner, telemetry) sees the new world.
        accelerator.state.replace_mesh(new_mesh, new_config)
        for model in accelerator._models:
            handle = model.handle
            handle.param_shardings = respec_shardings(handle.param_shardings, new_mesh)
            handle.params = transfer_to_mesh(handle.params, new_mesh)
            handle.rng = transfer_to_mesh(handle.rng, new_mesh)
            handle.mesh = new_mesh
            handle.pending = None
            if handle.pipeline_spec is not None:
                handle.pipeline_spec = dataclasses.replace(
                    handle.pipeline_spec, mesh=new_mesh
                )
            model._train_call = None
            model._eval_call = None
        for opt in accelerator._optimizers:
            # The cached plan anchored to the old mesh; replanned lazily from
            # the (already re-anchored) param shardings on next use. The
            # imperative update fn closes over the old plan too.
            opt.opt_shardings = None
            opt.zero_param_shardings = None
            opt._update_fn = None
            if opt.opt_state is not None:
                if opt.zero_sharding and opt.handle is not None:
                    # ZeRO state is dp-partitioned: a spec-preserving transfer
                    # could fail on GROW (a dim the old dp divided need not
                    # divide the new degree). Replan against the new mesh and
                    # move shard-to-shard onto the new plan — still the
                    # portable-redistribution property, no host gather.
                    opt.opt_shardings = opt._plan_opt_shardings()
                    opt.opt_state = apply_shardings(opt.opt_state, opt.opt_shardings)
                else:
                    opt.opt_state = transfer_to_mesh(opt.opt_state, new_mesh)
            if opt._accum_grads is not None:
                opt._accum_grads = transfer_to_mesh(opt._accum_grads, new_mesh)
        # Health-guard snapshots hold device arrays laid out on the OLD mesh:
        # restoring one after the transition would resurrect the dead layout.
        # They are discarded, never restored (the spike statistics — tiny
        # scalars — move with the guard).
        guard = accelerator._health_guard
        if guard is not None:
            guard.reset_after_reshard(new_mesh)
        reassign_data_shards(accelerator)
        accelerator._mesh_epoch += 1
        direction = "shrink" if new_dp < old_dp else "grow"
        _publish_transition(direction, new_mesh, new_dp)
        from ..telemetry.flight import get_flight_recorder

        get_flight_recorder().record(
            "reshard", direction=direction, old_dp=old_dp, new_dp=new_dp,
            devices=len(devices),
        )
        logger.warning(
            f"Elastic reshard: dp {old_dp} -> {new_dp} over "
            f"{len(devices)} device(s); gradient accumulation "
            f"{accum} -> {accelerator.gradient_accumulation_steps} "
            "(global batch preserved)."
        )
    return new_mesh


def reassign_data_shards(accelerator, num_processes: int | None = None,
                         process_index: int | None = None):
    """Point every prepared loader at the new world size.

    In-process (single-host drills) the process count does not change and
    batch *placement* already follows the live mesh — this keeps the loaders'
    shard bookkeeping (``BatchSamplerShard``/``IterableDatasetShard``
    ``num_processes``/``process_index``) in line when a multi-host restart
    re-enters with a different gang. The sampler-RNG contract is untouched:
    reassignment changes which rows a process draws, never the shuffle stream
    that orders them (the ``state_dict``/``load_state_dict`` snapshots keep
    resuming bit-exact)."""
    if num_processes is None:
        num_processes = max(jax.process_count(), 1)
    if process_index is None:
        process_index = jax.process_index() if num_processes > 1 else 0
    for loader in accelerator._dataloaders:
        reassign = getattr(loader, "reassign_shards", None)
        if reassign is not None:
            reassign(num_processes=num_processes, process_index=process_index)


def _publish_transition(direction: str, mesh, dp: int):
    from ..telemetry.metrics import get_registry

    registry = get_registry()
    registry.counter(
        "accelerate_reshard_transitions_total",
        "Elastic world-size transitions applied",
        labelnames=("direction",),
    ).inc(direction=direction)
    registry.gauge(
        "accelerate_world_size", "Devices in the current training mesh"
    ).set(float(mesh.size))
    registry.gauge(
        "accelerate_data_parallel_degree",
        "Data-parallel degree (dcn x dp x fsdp) of the current mesh",
    ).set(float(dp))
