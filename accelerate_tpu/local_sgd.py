"""Local SGD — reduce cross-device parameter sync frequency.

Reference parity: ``src/accelerate/local_sgd.py:36-106``. There, DDP gradient
allreduce is suppressed (``no_sync``) for ``local_sgd_steps`` steps and then the
*parameters* are averaged (``_sync_and_avg_model_params`` :100-106).

Two layers here:

- ``LocalSGDTrainer`` — the real thing, TPU-shaped. Parameters and optimizer
  state carry a leading replica dim ``R = dp_size`` sharded on ``dp``; the
  per-step update is ``jax.vmap`` over that dim, so between sync boundaries
  every step is embarrassingly parallel — *zero* cross-device traffic, exactly
  the property LocalSGD exists for (sync over slow DCN only every N steps).
  The boundary average is a mean over the replica dim inside the same compiled
  step (``lax.cond`` on the step counter). Optimizer state stays per-replica,
  matching the reference (only params are averaged).

- ``LocalSGD`` — the reference-shaped context manager for the imperative path.
  Under GSPMD the imperative path's parameters are single global arrays whose
  every update is already collective, so its "averaging" degenerates to a
  barrier + re-assertion of canonical shardings; use ``LocalSGDTrainer`` when
  you actually want desynchronized local steps.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .accelerator import Accelerator, PreparedModel


class LocalSGDTrainer:
    """Per-replica training with periodic parameter averaging.

    Usage::

        trainer = LocalSGDTrainer(accelerator, pmodel, optax.sgd(0.1), sync_every=8)
        for batch in loader:
            loss = trainer.step(batch)     # local update; averages every 8 steps
        params = trainer.final_params()    # replica-averaged pytree

    Replica placement:

    - **pure-dp mesh** — one replica per dp rank (the round-2 behavior);
      fsdp/tp/pp/sp/ep must be trivial.
    - **multi-slice mesh** (``dcn > 1``) — one replica per *slice*: the replica
      dim rides ``dcn`` and each replica's step runs GSPMD-sharded over its
      slice's ICI axes — dp/fsdp/tp, and ep/sp too (their batch specs consult
      ``data_batch_axes()``, which drops the claimed replica axis under the
      vmap; only pp's manual shard_map schedule is rejected). This is the
      canonical DCN strategy: zero cross-slice traffic between sync
      boundaries, one parameter average over the slow network every
      ``sync_every`` steps.

    The global batch is split replica-major: rows ``[r·B/R, (r+1)·B/R)`` feed
    replica ``r``.
    """

    def __init__(self, accelerator: Accelerator, model: PreparedModel, tx, sync_every: int):
        if not isinstance(model, PreparedModel):
            raise ValueError("LocalSGDTrainer requires a model from accelerator.prepare().")
        from .optimizer import AcceleratedOptimizer

        self._prepared_optimizer = None
        if isinstance(tx, AcceleratedOptimizer):
            # Reuse the prepared optimizer's transform; its state is superseded
            # by the trainer's per-replica state and re-synced in final_params().
            self._prepared_optimizer = tx
            tx = tx.tx
        if sync_every < 1:
            raise ValueError(f"sync_every must be >= 1, got {sync_every}")
        mesh = accelerator.mesh
        if mesh.shape.get("dcn", 1) > 1:
            self.replica_axis = "dcn"
            # dp/fsdp/tp/ep/sp all run inside each slice: the ep/sp paths'
            # batch specs consult data_batch_axes(), which drops the claimed
            # 'dcn' axis under the replica vmap (VERDICT r3 ask #5). Only pp's
            # manual shard_map schedule remains incompatible with
            # vmap(spmd_axis_name='dcn').
            if mesh.shape.get("pp", 1) != 1:
                raise ValueError(
                    "LocalSGDTrainer over dcn: the pipeline (pp) schedule does "
                    "not compose with the per-slice replica vmap; use "
                    "fsdp/tp/ep/sp inside each slice (or the fused train step)."
                )
        else:
            self.replica_axis = "dp"
            for ax in ("fsdp", "tp", "pp", "sp", "ep"):
                if mesh.shape.get(ax, 1) != 1:
                    raise ValueError(
                        f"LocalSGDTrainer needs a pure-dp mesh (or a dcn axis for "
                        f"per-slice replicas); axis {ax!r} has size "
                        f"{mesh.shape[ax]}. Use the fused train step for sharded models."
                    )
        self.accelerator = accelerator
        self.model = model
        self.sync_every = sync_every
        self.mesh = mesh
        self.R = R = mesh.shape.get(self.replica_axis, 1)
        replica_axis = self.replica_axis
        handle = model.handle

        # Per-replica stacking keeps each leaf's intra-replica sharding (fsdp/tp
        # dims stay sharded inside the slice) and adds the replica axis on dim 0.
        def stack(p, s):
            spec = P(replica_axis, *tuple(s.spec))
            return jax.device_put(
                jnp.broadcast_to(p[None], (R,) + p.shape), NamedSharding(mesh, spec)
            )

        self._params_rep = jax.tree_util.tree_map(stack, handle.params, handle.param_shardings)
        self._opt_rep = jax.vmap(tx.init)(self._params_rep)
        self._count = jnp.zeros((), jnp.int32)

        loss_of = model.training_loss_fn()
        inner_batch_axes = ("dp", "fsdp") if replica_axis == "dcn" else None

        import optax

        from .utils.environment import safe_donate_argnums

        @partial(jax.jit, donate_argnums=safe_donate_argnums((0, 1, 2)))
        def _step(params_rep, opt_rep, count, batch, rng):
            def one(params, opt, local_batch, r):
                loss, grads = jax.value_and_grad(loss_of)(
                    params, local_batch, jax.random.fold_in(rng, r)
                )
                updates, opt = tx.update(grads, opt, params)
                return optax.apply_updates(params, updates), opt, loss

            def split(x):
                x = x.reshape((R, x.shape[0] // R) + x.shape[1:])
                return jax.lax.with_sharding_constraint(
                    x,
                    NamedSharding(
                        mesh, P(replica_axis, inner_batch_axes, *([None] * (x.ndim - 2)))
                    ),
                )

            batch_rep = jax.tree_util.tree_map(split, batch)
            params_rep, opt_rep, losses = jax.vmap(one, spmd_axis_name=replica_axis)(
                params_rep, opt_rep, batch_rep, jnp.arange(R)
            )
            count = count + 1
            params_rep = jax.lax.cond(
                (count % sync_every) == 0,
                lambda p: jax.tree_util.tree_map(
                    lambda t: jnp.broadcast_to(t.mean(axis=0)[None], t.shape).astype(t.dtype), p
                ),
                lambda p: p,
                params_rep,
            )
            return params_rep, opt_rep, count, losses.mean()

        self._compiled = _step

    def step(self, batch) -> jax.Array:
        """One local step per replica (params averaged on sync boundaries).
        Returns the replica-mean loss."""
        handle = self.model.handle
        for leaf in jax.tree_util.tree_leaves(batch):
            if leaf.ndim >= 1 and leaf.shape[0] % self.R != 0:
                raise ValueError(
                    f"LocalSGDTrainer needs batch rows divisible by the replica "
                    f"count {self.R}; got {leaf.shape[0]}. Pad the final batch or "
                    f"use drop_last."
                )
        batch = self.accelerator._place_batch(batch)
        handle.step_counter += 1
        rng = jax.random.fold_in(handle.rng, handle.step_counter)
        from .parallel.sharding import claim_mesh_axes

        # Active during the (lazy) first-call trace: sharding constraints
        # built inside model/op code must not name the replica axis the vmap
        # already owns.
        with claim_mesh_axes(self.replica_axis):
            self._params_rep, self._opt_rep, self._count, loss = self._compiled(
                self._params_rep, self._opt_rep, self._count, batch, rng
            )
        return loss

    def replica_params(self):
        """The (R, ...)-stacked per-replica parameters (diagnostics/tests)."""
        return self._params_rep

    def final_params(self):
        """Replica-averaged parameters, written back to the prepared model. If
        the trainer was built from a prepared ``AcceleratedOptimizer``, its
        state is replaced by the replica-average too, so a later
        ``optimizer.step()`` continues from the trainer's trajectory instead of
        stale pre-trainer moments."""
        replica_mean = jax.jit(
            lambda p: jax.tree_util.tree_map(
                lambda t: t.mean(axis=0).astype(t.dtype), p  # keep int counts int
            )
        )
        mean = replica_mean(self._params_rep)
        handle = self.model.handle
        from .parallel.sharding import apply_shardings

        handle.params = apply_shardings(mean, handle.param_shardings)
        if self._prepared_optimizer is not None:
            self._prepared_optimizer.opt_state = replica_mean(self._opt_rep)
            self._prepared_optimizer._accum_grads = None
        return handle.params


class LocalSGD:
    """Context manager for Local SGD (reference ``local_sgd.py:36``).

    Usage parity with the reference::

        with LocalSGD(accelerator=accelerator, model=model, local_sgd_steps=8) as local_sgd:
            for batch in loader:
                with accelerator.accumulate(model):
                    ...
                    local_sgd.step()
    """

    def __init__(
        self,
        accelerator: Accelerator,
        model: PreparedModel,
        local_sgd_steps: int,
        enabled: bool = True,
    ):
        if not isinstance(model, PreparedModel):
            raise ValueError("LocalSGD requires a model returned by accelerator.prepare().")
        self.enabled = enabled and accelerator.distributed_type.value != "NO"
        self.accelerator = accelerator
        self.model = model
        self.local_sgd_steps = local_sgd_steps
        self.num_steps = 0

    def __enter__(self):
        if self.enabled:
            self.model_sync_obj = self.model.module
            self.accelerator.wait_for_everyone()
        return self

    def __exit__(self, type, value, tb):
        if self.enabled:
            # Sync once on exit so all replicas leave with identical params
            # (reference __exit__ :75-79).
            self._sync_and_avg_model_params()

    def step(self):
        """Count a local step; average params on the boundary (reference :81-98)."""
        self.num_steps += 1
        if not self.enabled:
            return
        if self.num_steps % self.local_sgd_steps == 0:
            self._sync_and_avg_model_params()

    def _sync_and_avg_model_params(self):
        """Average parameters across replicas (reference :100-106).

        With GSPMD global arrays, params *cannot* silently diverge across the data
        axes the way DDP replicas do under ``no_sync`` — a parameter is ONE logical
        array and every update to it is already collective. The averaging step is
        therefore a barrier plus a re-assertion of the canonical sharding (covering
        the case where a user swapped in host arrays between boundaries), which is
        exactly the invariant the reference's param-averaging restores.
        """
        handle = self.model.handle
        from .parallel.sharding import apply_shardings

        handle.params = apply_shardings(handle.params, handle.param_shardings)
        self.accelerator.wait_for_everyone()
