"""Local SGD — reduce cross-device parameter sync frequency.

Reference parity: ``src/accelerate/local_sgd.py:36-106``. There, DDP gradient
allreduce is suppressed (``no_sync``) for ``local_sgd_steps`` steps and then the
*parameters* are averaged (``_sync_and_avg_model_params`` :100-106).

TPU-native inversion: under GSPMD the per-step gradient reduce rides the compiled
train step and is effectively free on ICI, so the *divergence* LocalSGD exists to
repair cannot arise — a parameter is one global array and every update to it is
already collective. This context manager therefore keeps the reference's API and
cadence (``step()`` counting, sync on boundaries and on exit) while the "averaging"
degenerates to a barrier plus re-assertion of canonical shardings. True Local SGD
over a slow DCN axis would require per-slice parameter copies (a deliberate
departure from the single-global-array model) and is not implemented.
"""

from __future__ import annotations

from .accelerator import Accelerator, PreparedModel


class LocalSGD:
    """Context manager for Local SGD (reference ``local_sgd.py:36``).

    Usage parity with the reference::

        with LocalSGD(accelerator=accelerator, model=model, local_sgd_steps=8) as local_sgd:
            for batch in loader:
                with accelerator.accumulate(model):
                    ...
                    local_sgd.step()
    """

    def __init__(
        self,
        accelerator: Accelerator,
        model: PreparedModel,
        local_sgd_steps: int,
        enabled: bool = True,
    ):
        if not isinstance(model, PreparedModel):
            raise ValueError("LocalSGD requires a model returned by accelerator.prepare().")
        self.enabled = enabled and accelerator.distributed_type.value != "NO"
        self.accelerator = accelerator
        self.model = model
        self.local_sgd_steps = local_sgd_steps
        self.num_steps = 0

    def __enter__(self):
        if self.enabled:
            self.model_sync_obj = self.model.module
            self.accelerator.wait_for_everyone()
        return self

    def __exit__(self, type, value, tb):
        if self.enabled:
            # Sync once on exit so all replicas leave with identical params
            # (reference __exit__ :75-79).
            self._sync_and_avg_model_params()

    def step(self):
        """Count a local step; average params on the boundary (reference :81-98)."""
        self.num_steps += 1
        if not self.enabled:
            return
        if self.num_steps % self.local_sgd_steps == 0:
            self._sync_and_avg_model_params()

    def _sync_and_avg_model_params(self):
        """Average parameters across replicas (reference :100-106).

        With GSPMD global arrays, params *cannot* silently diverge across the data
        axes the way DDP replicas do under ``no_sync`` — a parameter is ONE logical
        array and every update to it is already collective. The averaging step is
        therefore a barrier plus a re-assertion of the canonical sharding (covering
        the case where a user swapped in host arrays between boundaries), which is
        exactly the invariant the reference's param-averaging restores.
        """
        handle = self.model.handle
        from .parallel.sharding import apply_shardings

        handle.params = apply_shardings(handle.params, handle.param_shardings)
        self.accelerator.wait_for_everyone()
