"""Per-architecture train-step library — the Megatron-parity layer.

Reference parity: ``src/accelerate/utils/megatron_lm.py`` ships per-arch train
steps (``BertTrainStep`` :445, ``GPTTrainStep`` :587, ``T5TrainStep`` ~:700)
that package batch keys, the loss function, and the forward driver for
Megatron's scheduler. Here the "scheduler" is ``Accelerator.build_train_step``'s
single compiled XLA program, so a TrainStep reduces to what it really is: the
arch's batch contract + loss — handed to ``build_train_step(loss_fn=...)`` or
``set_loss_fn``.

Usage::

    step_def = GPTTrainStep()
    step = accelerator.build_train_step(model, opt, loss_fn=step_def.loss_fn)
    loss = step(step_def.get_batch(raw))
"""

from __future__ import annotations

import jax.numpy as jnp

from .ops.losses import cross_entropy_loss, mse_loss


class AbstractTrainStep:
    """Base class mirroring ``AbstractTrainStep`` (megatron_lm.py:430-443)."""

    name = "abstract"
    batch_keys: tuple = ()

    def get_batch(self, data: dict) -> dict:
        """Project a raw example dict onto the model's batch contract."""
        return {k: data[k] for k in self.batch_keys if k in data}

    def loss_fn(self, outputs, batch):
        raise NotImplementedError


class GPTTrainStep(AbstractTrainStep):
    """Causal-LM step (reference ``GPTTrainStep`` :587): next-token cross-entropy
    with ignore-index masking; the shift lives here so models can emit aligned
    logits."""

    name = "gpt"
    batch_keys = ("input_ids", "labels", "attention_mask")

    def __init__(self, z_loss: float = 0.0, label_smoothing: float = 0.0):
        self.z_loss = z_loss
        self.label_smoothing = label_smoothing

    def get_batch(self, data: dict) -> dict:
        batch = super().get_batch(data)
        if "labels" not in batch:
            batch["labels"] = batch["input_ids"]
        return batch

    def loss_fn(self, outputs, batch):
        if "loss" in outputs and outputs["loss"] is not None:
            if self.z_loss or self.label_smoothing:
                # The model computed its loss in-graph (e.g. the fused
                # vocab-chunked head, which never materializes logits), so the
                # step-level regularizers cannot be applied — fail loudly
                # instead of silently training without them.
                raise ValueError(
                    "GPTTrainStep(z_loss/label_smoothing) cannot be applied: the "
                    "model already computed its loss in-graph (fused_loss head or "
                    "in-model labels). Configure the regularizer on the model "
                    "config, or run the dense head without in-model labels."
                )
            return outputs["loss"]
        logits = outputs["logits"][:, :-1]
        labels = batch["labels"][:, 1:]
        if "attention_mask" in batch and batch["attention_mask"] is not None:
            labels = jnp.where(batch["attention_mask"][:, 1:].astype(bool), labels, -100)
        return cross_entropy_loss(
            logits, labels, z_loss=self.z_loss, label_smoothing=self.label_smoothing
        )


class BertTrainStep(AbstractTrainStep):
    """BERT pretraining step (reference ``BertTrainStep`` :445): masked-LM loss
    plus optional next-sentence/classification loss when the model emits
    ``seq_logits``; plain classification loss for fine-tuning batches."""

    name = "bert"
    batch_keys = ("input_ids", "attention_mask", "token_type_ids", "labels", "next_sentence_label")

    def loss_fn(self, outputs, batch):
        if "loss" in outputs and outputs["loss"] is not None:
            return outputs["loss"]
        logits = outputs["logits"]
        labels = batch["labels"]
        if logits.ndim == 3:  # MLM: [B, S, V] vs token labels; mask padding
            mask = batch.get("attention_mask")
            if mask is not None:
                labels = jnp.where(mask.astype(bool), labels, -100)
            loss = cross_entropy_loss(logits, labels)
        else:  # sequence classification: [B, num_labels]
            loss = cross_entropy_loss(logits, labels)
        nsl = batch.get("next_sentence_label")
        if nsl is not None and "seq_logits" in outputs:
            loss = loss + cross_entropy_loss(outputs["seq_logits"], nsl)
        return loss


class T5TrainStep(AbstractTrainStep):
    """Seq2seq step (reference ``T5TrainStep`` ~:700): encoder/decoder batch keys,
    decoder-token cross-entropy with pad masking (the model applies it when given
    ``labels``)."""

    name = "t5"
    batch_keys = (
        "input_ids", "attention_mask", "decoder_input_ids", "decoder_attention_mask", "labels",
    )

    def loss_fn(self, outputs, batch):
        if "loss" in outputs and outputs["loss"] is not None:
            return outputs["loss"]
        return cross_entropy_loss(outputs["logits"], batch["labels"])


class RegressionTrainStep(AbstractTrainStep):
    """MSE step for the test fixtures (no reference analog; used by examples)."""

    name = "regression"
    batch_keys = ("x", "y")

    def loss_fn(self, outputs, batch):
        if "loss" in outputs and outputs["loss"] is not None:
            return outputs["loss"]
        return mse_loss(outputs["prediction"], batch["y"])


TRAIN_STEPS = {cls.name: cls for cls in (GPTTrainStep, BertTrainStep, T5TrainStep, RegressionTrainStep)}


def get_train_step(name: str) -> AbstractTrainStep:
    """Factory mirroring megatron's model-type dispatch (megatron_lm.py model_type
    switch in ``MegatronEngine``)."""
    if name not in TRAIN_STEPS:
        raise ValueError(f"Unknown train step {name!r}; available: {sorted(TRAIN_STEPS)}")
    return TRAIN_STEPS[name]()
