"""Triggered XLA trace capture — evidence, not guesses, about step time.

The step timeline says *that* steps are slow; the program auditor says *which*
collectives exist statically; neither attributes measured device time. This
module captures real XLA traces (``jax.profiler.start_trace`` /
``stop_trace``), aligned to step (and K-step window) boundaries so every
capture covers whole steps, armed four ways:

- **explicit step ranges** — ``ACCELERATE_PROFILE_STEPS="10-12"`` /
  ``launch --profile_steps`` (comma-separated ``a-b`` or single-step ``a``
  ranges); under windowed dispatch the capture starts at the last boundary
  before the range and runs until the range is covered;
- **slow-step trigger** — a host-side robust z-score over the timeline's
  per-step wall times (the EMA + MAD-proxy idiom of ``health/spike.py``,
  re-derived on host floats): when a step lands ``slow_zscore`` robust sigmas
  above the recent baseline, the *next* steps are captured — the trace shows
  the regime the outlier came from;
- **a straggler trip** — the cross-host monitor naming a slow host arms a
  capture on every host so the skew can be attributed;
- **on demand** — ``POST /profile?steps=N`` on the existing metrics HTTP
  server (the hook is registered via :func:`..telemetry.metrics.set_profile_trigger`).

Every path is rate-limited by a max-captures-per-run budget, and capture
overhead (trace start/stop plus parsing the result into the attribution
report) is booked as the ``profile`` badput class so goodput/MFU accounting
stays honest. Completed captures are parsed by :mod:`.traceview` into a
compute/collective/idle/host attribution report that surfaces in
``StepTimeline.summary()["profile"]``, on bench.py JSON lines as
``detail.profile``, and via ``accelerate-tpu profile report <dir>``.

Arming a trigger adds only host arithmetic per step boundary — no device
transfer, blocking or otherwise, until a capture actually engages.
"""

from __future__ import annotations

import contextlib
import logging
import os
import time

from ..logging import get_logger

logger = get_logger(__name__)

# health/spike.py's normal-consistency constant, reused for the host-side
# slow-step detector so the two robust z-scores mean the same thing.
_MAD_TO_SIGMA = 1.4826

DEFAULT_MAX_CAPTURES = 3
DEFAULT_SLOW_CAPTURE_STEPS = 2


def parse_profile_steps(spec) -> list:
    """``"10-12,50"`` → ``[(10, 12), (50, 50)]`` (sorted, validated).

    Grammar: comma-separated ranges, each ``<start>-<end>`` or a single
    ``<step>``; steps are 1-based and ranges inclusive. Empty/"off" → [].
    """
    if spec is None:
        return []
    if isinstance(spec, (list, tuple)):
        ranges = [(int(a), int(b)) for a, b in spec]
    else:
        text = str(spec).strip()
        if not text or text.lower() in ("off", "none", "0"):
            return []
        ranges = []
        for part in text.split(","):
            part = part.strip()
            if not part:
                continue
            a, sep, b = part.partition("-")
            try:
                start = int(a)
                end = int(b) if sep else start
            except ValueError:
                raise ValueError(
                    f"bad profile step range {part!r} in {text!r}: expected "
                    "'<start>-<end>' or '<step>' (e.g. '10-12' or '10-12,50')"
                ) from None
            ranges.append((start, end))
    for start, end in ranges:
        if start < 1 or end < start:
            raise ValueError(
                f"bad profile step range {start}-{end}: steps are 1-based and "
                "ranges inclusive (start <= end)"
            )
    return sorted(ranges)


class SlowStepDetector:
    """Host-side robust z-score over per-step wall times.

    The device-state twin lives in ``health/spike.py``; this one runs on the
    host floats the timeline already holds, so arming it costs a few float
    ops per boundary and no device work. Same correctness properties: the
    effective decay ``min(d, n/(n+1))`` makes the warmup a plain running mean,
    and a tripped observation does NOT update the statistics — the slow step
    must not drag the baseline toward itself (a sustained regression then
    keeps tripping instead of being normalized away).
    """

    def __init__(self, zscore: float, warmup_steps: int = 20, ema_decay: float = 0.9):
        if zscore <= 0:
            raise ValueError(f"zscore must be > 0, got {zscore}")
        if not 0.0 < ema_decay < 1.0:
            raise ValueError(f"ema_decay must be in (0, 1), got {ema_decay}")
        self.zscore = float(zscore)
        self.warmup_steps = int(warmup_steps)
        self.ema_decay = float(ema_decay)
        self._ema = 0.0
        self._mad = 0.0
        self._count = 0

    @property
    def trip_threshold(self) -> float:
        """The wall time a slow observation must exceed to trip
        (``EMA + zscore·σ̂``, σ̂ the MAD-proxy sigma) — the budget the SLO
        sentinel's auto-baseline records as breach evidence. Meaningful once
        warm; a tripped observation never updates the statistics, so reading
        this after a trip reports the threshold that was actually enforced."""
        return self._ema + self.zscore * _MAD_TO_SIGMA * self._mad

    def observe(self, wall_s: float) -> tuple:
        """One completed step's wall time → ``(tripped, z)``."""
        wall_s = float(wall_s)
        dev = abs(wall_s - self._ema)
        sigma = _MAD_TO_SIGMA * self._mad
        warm = self._count >= self.warmup_steps
        z = dev / (sigma + 1e-12) if warm else 0.0
        tripped = warm and z > self.zscore
        if not tripped:
            d = min(self.ema_decay, self._count / (self._count + 1.0))
            self._ema = d * self._ema + (1 - d) * wall_s
            self._mad = 0.0 if self._count == 0 else d * self._mad + (1 - d) * dev
            self._count += 1
        return tripped, z


def _default_start_trace(trace_dir: str):
    import jax

    jax.profiler.start_trace(trace_dir)


def _default_stop_trace():
    import jax

    jax.profiler.stop_trace()


class ProfileManager:
    """Step-aligned trace capture with triggers, budget, and attribution.

    ``output_dir`` roots triggered captures (each gets its own subdirectory);
    ``steps`` is the explicit-range grammar (string or ``[(a, b), ...]``);
    ``slow_zscore`` > 0 arms the slow-step trigger (capturing
    ``slow_capture_steps`` subsequent steps); ``max_captures`` is the
    per-run budget every trigger path shares. ``start_trace`` / ``stop_trace``
    / ``clock`` are injectable for deterministic tests.
    """

    def __init__(
        self,
        output_dir: str | None = None,
        steps=None,
        slow_zscore: float = 0.0,
        slow_capture_steps: int = DEFAULT_SLOW_CAPTURE_STEPS,
        slow_warmup_steps: int = 20,
        max_captures: int = DEFAULT_MAX_CAPTURES,
        registry=None,
        start_trace=None,
        stop_trace=None,
    ):
        from ..utils.constants import MITA_PROFILE_DIR
        from .metrics import get_registry

        self.output_dir = output_dir or MITA_PROFILE_DIR
        self._ranges = parse_profile_steps(steps)
        self._armed_steps = ",".join(
            f"{a}-{b}" if b != a else str(a) for a, b in self._ranges
        )
        self.slow_zscore = float(slow_zscore or 0.0)
        self.slow_capture_steps = max(int(slow_capture_steps), 1)
        self._slow = (
            SlowStepDetector(self.slow_zscore, warmup_steps=slow_warmup_steps)
            if self.slow_zscore > 0
            else None
        )
        self.max_captures = int(max_captures)
        self._budget = self.max_captures
        self._registry = registry if registry is not None else get_registry()
        self._captures_total = self._registry.counter(
            "accelerate_profile_captures_total",
            "Trace captures engaged, by trigger",
            labelnames=("trigger",),
        )
        self._start_trace = start_trace or _default_start_trace
        self._stop_trace = stop_trace or _default_stop_trace
        self._step = 0
        self._pending = None   # (n_steps, trigger) requested capture
        self._active = None    # dict while a capture is running
        self.captures: list = []

    # ------------------------------------------------------------- inspection
    @property
    def capturing(self) -> bool:
        return self._active is not None

    @property
    def budget_remaining(self) -> int:
        return self._budget

    def engaged(self) -> bool:
        """Whether any capture ran (or is running) this run — gates the
        ``profile`` key on timeline summaries and bench lines."""
        return bool(self.captures) or self._active is not None

    def summary(self) -> dict:
        out = {
            "captures": [dict(c) for c in self.captures],
            "capturing": self._active is not None,
            "budget_remaining": self._budget,
            "armed": {
                "steps": self._armed_steps or None,
                "slow_zscore": self.slow_zscore or None,
            },
        }
        return out

    # --------------------------------------------------------------- triggers
    def request_capture(self, steps: int = 1, trigger: str = "http") -> dict:
        """Arm a capture of the next ``steps`` step boundaries (the metrics
        server's POST /profile and the straggler trip route here). Returns a
        status dict the HTTP handler can serialize."""
        steps = max(int(steps), 1)
        if self._budget <= 0:
            return {"accepted": False, "reason": "capture budget exhausted"}
        if self._active is not None or self._pending is not None:
            return {"accepted": False, "reason": "a capture is already engaged"}
        self._pending = (steps, str(trigger))
        from .flight import record_event

        record_event("profile_request", step=self._step, trigger=trigger, steps=steps)
        return {"accepted": True, "steps": steps, "trigger": str(trigger)}

    def step_boundary(self, step=None, wall_s=None, steps: int = 1):
        """One completed step (or K-step window) boundary — the per-step feed
        Telemetry drives. ``step`` (when the loop's hooks provide it) pins the
        numbering explicit ranges refer to; fused loops without hooks count
        boundaries instead. Decides capture start/stop; costs a few compares
        when nothing is armed."""
        steps = max(int(steps), 1)
        prev = self._step
        s = int(step) if step is not None else prev + steps
        self._step = s
        just_finished = False
        if self._active is not None:
            until = self._active["until"]
            if until is None or s < until:
                return
            self._finish_capture()
            # Fall through: a back-to-back range (e.g. "3-4,5-6") may be due
            # at this very boundary — returning here would silently lose the
            # second range's first step.
            just_finished = True
        trigger = until = None
        if self._ranges:
            a, b = self._ranges[0]
            if prev >= b or s >= b:
                self._ranges.pop(0)
                if a > s - steps and prev < b:
                    # The range fell inside this very boundary's window (a
                    # first K-step window — or the very first step — swallowed
                    # it): those steps already ran untraced, and a capture can
                    # only engage at a completed boundary. Capture the next
                    # window as the closest available evidence, and say so —
                    # a silently shrunk range reads as a wrong-step trace.
                    logger.warning(
                        f"profile range {a}-{b}: step(s) through {s} completed "
                        "before the profiler could engage (captures start at "
                        f"step boundaries); capturing {s + 1}-{s + steps} "
                        "instead."
                    )
                    trigger, until = "steps", s + steps
                else:
                    # Wholly in the past (a resume landed beyond it) — it can
                    # never be captured.
                    logger.warning(
                        f"profile range {a}-{b} dropped: the run is already at "
                        f"step {s}."
                    )
            elif s >= a - steps:
                # The next boundary (assumed to cover ~`steps` steps, like
                # this one) reaches into [a, b]: start now so the capture is
                # aligned to whole windows and covers the range.
                self._ranges.pop(0)
                trigger, until = "steps", b
                if a <= s:
                    # The range's head already ran (a range starting at step 1
                    # can never be fully honored — captures engage at
                    # completed boundaries): shrink loudly, never silently.
                    logger.warning(
                        f"profile range {a}-{b}: step(s) {a}-{s} completed "
                        "before the profiler could engage (captures start at "
                        f"step boundaries); capturing {s + 1}-{b} only."
                    )
        if trigger is None and self._pending is not None:
            n, t = self._pending
            self._pending = None
            trigger, until = t, s + n
        if (trigger is None and not just_finished
                and self._slow is not None and wall_s is not None):
            # just_finished boundaries are excluded from the slow baseline:
            # their wall time carries the tracing overhead of the capture
            # that just ended and would poison (or spuriously re-trip) it.
            tripped, z = self._slow.observe(wall_s)
            if tripped:
                logger.warning(
                    f"slow-step trigger: step {s} took {wall_s * 1e3:.1f}ms "
                    f"(robust z={z:.1f} > {self.slow_zscore:g}); capturing the "
                    f"next {self.slow_capture_steps} step(s)."
                )
                trigger, until = "slow_step", s + self.slow_capture_steps
        if trigger is not None:
            self._begin_capture(trigger, until=until)

    def sync_step(self, step):
        """Pin the loop's step numbering WITHOUT marking a boundary — the
        per-step hooks call this when the fused program already fed the
        boundary, so explicit ranges track real step numbers (resumes jump
        the count) while each boundary is still counted exactly once."""
        self._step = int(step)

    # ---------------------------------------------------------------- capture
    def _book_overhead(self, seconds: float):
        from ..resilience.goodput import get_ledger

        try:
            get_ledger().add("profile", seconds)
        except Exception:
            pass  # accounting must not break capture

    def _begin_capture(self, trigger: str, until, trace_dir: str | None = None,
                       budgeted: bool = True) -> bool:
        """Start a capture; returns whether one actually engaged. ``budgeted``
        is False for manual captures — the user asked explicitly, so the
        triggered-capture budget neither refuses nor pays for it."""
        from .flight import get_flight_recorder

        if self._active is not None:
            # jax has one global trace; a second start would raise and (worse)
            # a paired stop would cut the running capture short mid-range.
            logger.warning(
                f"profile trigger {trigger!r} ignored: a capture is already "
                "engaged."
            )
            return False
        if budgeted and self._budget <= 0:
            logger.log_every_n(
                20, logging.WARNING,
                f"profile trigger {trigger!r} ignored: the max-captures-per-run "
                f"budget ({self.max_captures}) is spent.",
            )
            return False
        first_step = self._step + 1
        if trace_dir is None:
            tail = f"until{until}" if until is not None else "manual"
            trace_dir = os.path.join(
                self.output_dir,
                f"capture{len(self.captures) + 1:02d}_step{first_step}_{trigger}_{tail}",
            )
        t0 = time.perf_counter()
        try:
            self._start_trace(trace_dir)
        except Exception as exc:
            # Budget untouched: a failed start produced no capture, and the
            # trigger that asked already consumed itself (range popped,
            # request cleared) — no retry storm to guard against.
            self._book_overhead(time.perf_counter() - t0)
            logger.error(f"profile capture ({trigger}) could not start: {exc!r}")
            return False
        self._book_overhead(time.perf_counter() - t0)
        if budgeted:
            self._budget -= 1
        self._captures_total.inc(trigger=trigger)
        self._active = {
            "trigger": trigger,
            "trace_dir": trace_dir,
            "first_step": first_step,
            "until": until,
        }
        get_flight_recorder().record(
            "profile_start", step=self._step, trigger=trigger,
            trace_dir=trace_dir, until=until,
        )
        logger.warning(
            f"profile capture engaged ({trigger}): tracing from step "
            f"{first_step}"
            + (f" through {until}" if until is not None else "")
            + f" into {trace_dir}"
        )
        return True

    def _finish_capture(self) -> dict | None:
        from .flight import get_flight_recorder

        active, self._active = self._active, None
        if active is None:
            return None
        t0 = time.perf_counter()
        try:
            self._stop_trace()
        except Exception as exc:
            logger.error(f"profile capture could not stop cleanly: {exc!r}")
        stop_s = time.perf_counter() - t0
        t1 = time.perf_counter()
        report = None
        try:
            from .traceview import report_capture

            report = report_capture(active["trace_dir"])
        except Exception as exc:
            logger.warning(
                f"captured trace in {active['trace_dir']} could not be parsed "
                f"({type(exc).__name__}: {exc}); the raw trace is kept — "
                "`accelerate-tpu profile report` can retry."
            )
        parse_s = time.perf_counter() - t1
        self._book_overhead(stop_s + parse_s)
        record = {
            "trigger": active["trigger"],
            "trace_dir": active["trace_dir"],
            "first_step": active["first_step"],
            "last_step": self._step,
            "overhead_s": round(stop_s + parse_s, 4),
        }
        if report is not None:
            record["report"] = report
        self.captures.append(record)
        get_flight_recorder().record(
            "profile_stop", step=self._step, trigger=active["trigger"],
            trace_dir=active["trace_dir"],
        )
        return record

    @contextlib.contextmanager
    def manual_capture(self, trace_dir: str | None = None):
        """Capture exactly the wrapped block (``Accelerator.profile`` builds
        on this): same badput booking, flight events, and attribution parse
        as triggered captures, with the covered step range recorded from the
        boundaries observed while the block ran. Exempt from the triggered
        budget (the user asked explicitly) but NOT from exclusivity: while a
        triggered capture is running the block yields None and runs untraced
        rather than hijacking the capture in flight."""
        engaged = self._begin_capture(
            "manual", until=None, trace_dir=trace_dir, budgeted=False
        )
        try:
            yield self._active["trace_dir"] if engaged else None
        finally:
            if engaged:
                self._finish_capture()


# ------------------------------------------------------ process-wide default
_MANAGER: ProfileManager | None = None


def _install(manager: ProfileManager) -> ProfileManager:
    """Make ``manager`` the default and point the metrics server's
    POST /profile hook at it."""
    global _MANAGER
    _MANAGER = manager
    from .metrics import set_profile_trigger

    set_profile_trigger(manager.request_capture)
    return manager


def get_profile_manager() -> ProfileManager:
    """The process-wide manager, built from the launcher's env contract on
    first use (ACCELERATE_PROFILE_STEPS / ACCELERATE_PROFILE_SLOW_ZSCORE /
    ACCELERATE_PROFILE_DIR / ACCELERATE_PROFILE_MAX_CAPTURES)."""
    if _MANAGER is not None:
        return _MANAGER
    from ..utils.constants import (
        ENV_PROFILE_DIR,
        ENV_PROFILE_MAX_CAPTURES,
        ENV_PROFILE_SLOW_ZSCORE,
        ENV_PROFILE_STEPS,
    )

    zscore_raw = os.environ.get(ENV_PROFILE_SLOW_ZSCORE, "").strip()
    budget_raw = os.environ.get(ENV_PROFILE_MAX_CAPTURES, "").strip()
    return _install(ProfileManager(
        output_dir=os.environ.get(ENV_PROFILE_DIR, "").strip() or None,
        steps=os.environ.get(ENV_PROFILE_STEPS, ""),
        slow_zscore=float(zscore_raw) if zscore_raw else 0.0,
        max_captures=int(budget_raw) if budget_raw else DEFAULT_MAX_CAPTURES,
    ))


def set_profile_manager(manager: ProfileManager | None):
    """Install an explicitly-built manager (tests, notebooks)."""
    global _MANAGER
    if manager is None:
        _MANAGER = None
        from .metrics import set_profile_trigger

        set_profile_trigger(None)
    else:
        _install(manager)


def reset_profile_manager():
    """Drop the default manager — tests (an in-flight capture is stopped so a
    dangling jax trace cannot leak into the next test)."""
    global _MANAGER
    if _MANAGER is not None and _MANAGER.capturing:
        try:
            _MANAGER._finish_capture()
        except Exception:
            pass
    set_profile_manager(None)


def default_manager_summary() -> dict | None:
    """The default manager's summary IF one exists and a capture engaged —
    what ``StepTimeline.summary()`` folds in as ``profile`` (absent when
    profiling never ran, so un-profiled summaries don't grow a key)."""
    if _MANAGER is not None and _MANAGER.engaged():
        return _MANAGER.summary()
    return None
