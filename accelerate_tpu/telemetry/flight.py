"""Flight recorder — the always-on black box a dead run leaves behind.

When a run hangs, trips a health guard, restarts, or dies on an unhandled
exception, the logs say *that* it happened; the question that decides the fix
is what the run was doing in the seconds before. The flight recorder keeps a
bounded ring of structured events fed by the subsystems that already observe
the interesting transitions — step/window boundaries (with transfer-counter
deltas), span records, guard verdicts and trips, fault injections,
reshard/restart/preemption transitions, profile-capture triggers — and dumps
the ring to JSON at the moments a post-mortem needs it:

- on a hang-watchdog trip (hooked into :func:`...health.hang._dump_diagnostics`),
- on a health-guard trip / rollback (:meth:`...health.guard.HealthGuard._handle_trip`),
- on every ``run_resilient`` restart,
- on an unhandled exception (a chained ``sys.excepthook``),

plus on demand via :meth:`FlightRecorder.dump`. ``accelerate-tpu blackbox
<dump.json>`` renders a dump as a causal timeline.

Recording discipline matches the rest of the telemetry stack: one event is a
dict build plus a lock-free ``deque.append`` — no locks on the hot path, no
device transfers, ever. Dumps are rate-limited (:data:`MAX_AUTO_DUMPS` per
process) so a crash-looping job cannot fill a disk with black boxes.
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import sys
import threading
import time

FLIGHT_SCHEMA_VERSION = 1

# Automatic (reason-driven) dumps per process; FlightRecorder.dump with an
# explicit path is never rate-limited.
MAX_AUTO_DUMPS = 8

DEFAULT_DUMP_DIR = "flight_recorder"


class FlightRecorder:
    """Bounded overwrite-oldest event ring; see module docstring.

    ``capacity`` bounds retained events (the sequence number keeps counting so
    wraparound is observable in a dump). ``clock`` is injectable for
    deterministic tests; event records carry both the relative monotonic time
    and a wall-clock stamp so dumps from different hosts can be correlated.
    """

    def __init__(self, capacity: int = 2048, clock=time.monotonic):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = capacity
        self._clock = clock
        self._t0 = clock()
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._seq = itertools.count()  # atomic under the GIL (spans.py idiom)
        self._auto_dumps = 0
        self._last_transfers: dict = {}

    # -------------------------------------------------------------- recording
    def record(self, kind: str, step=None, **data):
        """Append one structured event. Safe on any thread (including signal
        handlers and the hang watchdog's daemon thread); never raises."""
        try:
            event = {
                "seq": next(self._seq),
                "t_s": round(self._clock() - self._t0, 6),
                "wall": time.time(),
                "kind": str(kind),
            }
            if step is not None:
                event["step"] = int(step)
            if data:
                event.update(data)
            self._ring.append(event)
            # Durable tee (telemetry/journal.py): every flight event also
            # lands in the per-host journal when one is armed — the ring is
            # scrape-or-lose, the journal survives the SIGKILL.
            tap = _JOURNAL_TAP
            if tap is not None:
                tap(kind, step, data)
        except Exception:
            pass  # the black box must never take the plane down

    def note_step(self, step=None, wall_s=None, steps: int = 1, transfers: dict | None = None):
        """A step/window boundary completed — the per-step feed Telemetry
        drives. ``transfers`` (a ``transfer_stats()`` snapshot) is diffed
        against the previous boundary so each event carries the *delta* the
        boundary produced, not the cumulative counters."""
        data = {}
        if wall_s is not None:
            data["wall_s"] = round(float(wall_s), 6)
        if steps != 1:
            data["steps"] = int(steps)
        if transfers:
            prev = self._last_transfers
            # A reset_transfer_stats() since the last boundary zeroed the
            # globals underneath the baseline (the timeline's re-anchor
            # problem): comparing against the stale baseline would log a
            # large negative delta into the black box. Re-anchor at zero.
            if transfers.get("resets", 0) != prev.get("resets", 0):
                prev = {}
            delta = {
                k: round(transfers[k] - prev.get(k, 0), 6)
                for k in ("fetches", "blocking", "h2d_puts", "h2d_blocking")
                if k in transfers and transfers[k] != prev.get(k, 0)
            }
            self._last_transfers = dict(transfers)
            if delta:
                data["transfers"] = delta
        self.record("step", step=step, **data)

    @property
    def total(self) -> int:
        """Events ever recorded (keeps growing after wraparound)."""
        ring = list(self._ring)
        return ring[-1]["seq"] + 1 if ring else 0

    def snapshot(self) -> list:
        """Retained events, oldest first."""
        return sorted(self._ring, key=lambda e: e["seq"])

    def clear(self):
        self._ring.clear()
        self._seq = itertools.count()
        self._last_transfers = {}

    # ----------------------------------------------------------------- dumps
    def dump(self, reason: str, path: str | None = None, extra: dict | None = None) -> str | None:
        """Write the black box to JSON; returns the path (None when the
        auto-dump budget is spent or the write failed — a dump failure must
        never mask the fault being dumped)."""
        try:
            if path is None:
                if self._auto_dumps >= MAX_AUTO_DUMPS:
                    return None
                self._auto_dumps += 1
                directory = dump_dir()
                os.makedirs(directory, exist_ok=True)
                stamp = time.strftime("%Y%m%d_%H%M%S")
                path = os.path.join(
                    directory,
                    f"flight_{stamp}_{reason}_{os.getpid()}_{self._auto_dumps}.json",
                )
            payload = self._payload(reason, extra)
            tmp = f"{path}.tmp"
            with open(tmp, "w") as fh:
                json.dump(payload, fh, indent=1, default=str)
            os.replace(tmp, path)  # a torn dump is worse than none
            return path
        except Exception:
            return None

    def _payload(self, reason: str, extra: dict | None) -> dict:
        from ..utils.constants import ENV_PROCESS_ID

        payload = {
            "schema_version": FLIGHT_SCHEMA_VERSION,
            "reason": reason,
            "dumped_at": time.time(),
            "pid": os.getpid(),
            "process_index": int(os.environ.get(ENV_PROCESS_ID, "0") or 0),
            "events_total": self.total,
            "events_retained": len(self._ring),
            "events": self.snapshot(),
        }
        if extra:
            payload["extra"] = {k: v for k, v in extra.items()}
        # Context snapshots from the sibling silos — best-effort: any of them
        # failing must not lose the event ring.
        try:
            from ..utils.transfer import transfer_stats

            payload["transfers"] = transfer_stats()
        except Exception:
            pass
        try:
            from ..resilience.goodput import get_ledger

            payload["goodput"] = get_ledger().summary()
        except Exception:
            pass
        try:
            from .spans import get_span_ring

            payload["spans"] = [
                {
                    "name": r.name,
                    "path": r.path,
                    "depth": r.depth,
                    "duration_s": round(r.duration_s, 6),
                }
                for r in get_span_ring().snapshot()[-64:]
            ]
        except Exception:
            pass
        return payload


def dump_dir() -> str:
    """Where automatic dumps land: ACCELERATE_FLIGHT_DIR, else
    ``./flight_recorder``."""
    from ..utils.constants import ENV_FLIGHT_DIR

    return os.environ.get(ENV_FLIGHT_DIR, "").strip() or DEFAULT_DUMP_DIR


# ------------------------------------------------------ process-wide default
_RECORDER: FlightRecorder | None = None
_EXCEPTHOOK_INSTALLED = False
_LOCK = threading.Lock()
_JOURNAL_TAP = None


def set_journal_tap(tap):
    """Install (or clear, with None) the journal's flight-event tee — called
    by telemetry/journal.py when a journal arms; the recorder itself imports
    nothing from the journal (injected-provider idiom, metrics.py:300)."""
    global _JOURNAL_TAP
    _JOURNAL_TAP = tap


def ring_capacity_from_env(env_name: str, default: int) -> int:
    """Resolve an event-ring capacity from the launch env (tri-state: unset
    or an explicit 0 → the library default; a positive int sets it). Garbage
    raises — ``accelerate-tpu launch`` validates before export, so a bad
    value fails at the front door, not inside a worker's telemetry stack."""
    raw = os.environ.get(env_name, "").strip()
    if not raw:
        return default
    value = int(raw)  # ValueError on garbage — launch-time validation's job
    if value < 0:
        raise ValueError(f"{env_name} must be >= 0, got {value}")
    return value if value > 0 else default


def get_flight_recorder() -> FlightRecorder:
    """The process-wide black box; created (and the crash excepthook
    installed) on first use. Ring size honors ACCELERATE_FLIGHT_RING."""
    global _RECORDER
    if _RECORDER is None:
        with _LOCK:
            if _RECORDER is None:
                from ..utils.constants import ENV_FLIGHT_RING

                _RECORDER = FlightRecorder(
                    capacity=ring_capacity_from_env(ENV_FLIGHT_RING, 2048)
                )
                _install_excepthook()
    return _RECORDER


def record_event(kind: str, step=None, **data):
    """Record into the default recorder IF one exists — the cheap spelling for
    call sites that must not force recorder creation (signal handlers)."""
    if _RECORDER is not None:
        _RECORDER.record(kind, step=step, **data)


def reset_flight_recorder():
    """Drop the default recorder — tests (the excepthook stays installed; it
    checks the live global on every crash)."""
    global _RECORDER
    _RECORDER = None


def _install_excepthook():
    """Chain a dump-on-unhandled-exception hook in front of the current
    ``sys.excepthook`` (once per process)."""
    global _EXCEPTHOOK_INSTALLED
    if _EXCEPTHOOK_INSTALLED:
        return
    _EXCEPTHOOK_INSTALLED = True
    previous = sys.excepthook

    def hook(exc_type, exc, tb):
        recorder = _RECORDER
        if recorder is not None and not issubclass(
            exc_type, (KeyboardInterrupt, SystemExit)
        ):
            recorder.record(
                "unhandled_exception",
                error=f"{exc_type.__name__}: {exc}"[:500],
            )
            recorder.dump("exception")
        previous(exc_type, exc, tb)

    sys.excepthook = hook
