"""Always-on per-step timeline — what is this run doing right now?

``StepTimeline`` records one sample per training step with the same
no-forced-host-sync discipline as the health guard: the only per-step work is
a ``perf_counter`` read, a deque append, and a couple of registry updates.
Device scalars (the step loss) are *retained*, not fetched — they drain
through :func:`...utils.transfer.host_fetch` only once materialized
(``summary()`` checks ``is_ready`` first), so a telemetry-enabled loop adds
ZERO blocking device→host transfers per step versus telemetry-off — the
acceptance bar tests/test_telemetry.py pins with the transfer counters.

A sample's wall time is the gap between consecutive step boundaries (the
first boundary only sets the baseline — it covers trace+compile, which the
goodput ledger already classifies). ``summary()`` folds in everything the
"which host / which step / which resource" questions need: step-time
quantiles, tokens/s, an achieved-MFU estimate from the model flop count
(``set_model_flops`` — ``Accelerator.build_train_step`` wires it from
``module.flops_per_token()``), compile events from the goodput ledger,
deliberate device→host transfer counts (and how many blocked) from
``utils/transfer.py``, and live/peak device memory via
``jax.local_devices()[*].memory_stats()``.
"""

from __future__ import annotations

import collections
import time
from dataclasses import dataclass

import jax

from ..utils.transfer import array_is_ready, host_fetch

# bf16 peak FLOPs per chip by generation (fallback: v5e) — the denominator of
# the MFU estimate; bench.py's peak_flops_per_chip delegates here.
_PEAK_FLOPS_BF16 = {
    "v5 lite": 197e12,
    "v5litepod": 197e12,
    "v4": 275e12,
    "v5p": 459e12,
    "v5": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}


def device_peak_flops(device=None) -> float:
    """bf16 peak for the local chip generation (fallback: v5e)."""
    try:
        if device is None:
            device = jax.devices()[0]
        kind = device.device_kind.lower()
    except Exception:
        return 197e12
    for key, val in _PEAK_FLOPS_BF16.items():
        if key in kind:
            return val
    return 197e12


def device_memory_stats() -> dict:
    """Summed ``memory_stats()`` over local devices; {} when the backend has
    none (CPU). A pure host call — never syncs the device stream."""
    in_use = peak = limit = 0
    found = False
    for device in jax.local_devices():
        stats_fn = getattr(device, "memory_stats", None)
        if stats_fn is None:
            continue
        try:
            stats = stats_fn() or {}
        except Exception:
            continue
        if not stats:
            continue
        found = True
        in_use += int(stats.get("bytes_in_use", 0))
        peak += int(stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0)))
        limit += int(stats.get("bytes_limit", 0))
    if not found:
        return {}
    return {"bytes_in_use": in_use, "peak_bytes_in_use": peak, "bytes_limit": limit}


def batch_token_count(batch) -> int | None:
    """Tokens in a language-model batch (``input_ids`` element count); None
    for batches without one — the timeline then reports step time only."""
    if isinstance(batch, dict):
        ids = batch.get("input_ids")
        if ids is not None and hasattr(ids, "shape"):
            count = 1
            for dim in ids.shape:
                count *= int(dim)
            return count
    return None


@dataclass
class StepSample:
    step: int | None
    wall_s: float
    tokens: int | None


def _quantile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[idx]


class StepTimeline:
    """See module docstring. ``clock`` is injectable for deterministic tests."""

    def __init__(self, capacity: int = 1024, registry=None, clock=time.perf_counter):
        from ..utils import transfer
        from .metrics import get_registry

        self._clock = clock
        self._registry = registry if registry is not None else get_registry()
        self._ring: collections.deque[StepSample] = collections.deque(maxlen=capacity)
        self._count = 0
        self._boundaries = 0
        self._dispatches = 0
        self._last_end = None
        self._last_step = None
        self._flops_per_token = None
        self._predicted_peak = None
        self._last_mfu = None
        # Retained (NOT fetched) device loss scalars; drained when materialized.
        self._pending_loss: collections.deque = collections.deque(maxlen=4)
        self._last_loss = None
        self._window_s = 0.0
        self._window_steps = 0
        self._transfer0 = transfer.transfer_stats()
        self._steps_total = self._registry.counter(
            "accelerate_steps_total", "Training steps observed by the timeline"
        )
        self._step_hist = self._registry.histogram(
            "accelerate_step_seconds", "Wall-clock per training step"
        )
        self._tokens_gauge = self._registry.gauge(
            "accelerate_tokens_per_second", "Instantaneous training throughput"
        )
        self._mfu_gauge = self._registry.gauge(
            "accelerate_mfu_estimate", "Achieved model-FLOPs utilization estimate"
        )

    # ------------------------------------------------------------- configure
    def set_model_flops(self, flops_per_token: float):
        """Forward+backward FLOPs per token — enables the MFU estimate."""
        self._flops_per_token = float(flops_per_token) if flops_per_token else None

    def set_predicted_peak(self, nbytes: int | None):
        """Static per-device peak-HBM prediction (analysis/memory.py, fed by
        ``Accelerator.audit``/``memory_report``) — ``summary()`` then carries
        it next to the observed ``memory_stats()`` peak so a prediction that
        drifts from reality is visible in every bench line and Prometheus
        scrape, not just at memcheck time."""
        self._predicted_peak = int(nbytes) if nbytes else None

    @property
    def count(self) -> int:
        """Completed step samples (the first boundary is baseline only)."""
        return self._count

    @property
    def boundaries(self) -> int:
        """Every ``step_end`` call, INCLUDING the baseline — what the hook
        dedupe compares, so a fused baseline still marks the step covered."""
        return self._boundaries

    @property
    def dispatches(self) -> int:
        """Program dispatches observed (each ``step_end`` boundary is one —
        a K-step window boundary counts once while contributing K steps)."""
        return self._dispatches

    @property
    def last_wall_s(self) -> float | None:
        return self._ring[-1].wall_s if self._ring else None

    @property
    def last_mfu(self) -> float | None:
        """Most recent per-boundary achieved-MFU estimate (None until tokens
        and a model flop count are both known) — the SLO sentinel's MFU feed."""
        return self._last_mfu

    @property
    def last_loss(self) -> float | None:
        """Most recently DRAINED loss (None until a retained device scalar
        materialized and a ``summary()`` drained it) — a plain attribute
        read, so hot-path consumers (the journal's step records) can carry a
        loss without ever forcing a device fetch."""
        return self._last_loss

    # ------------------------------------------------------------- recording
    def step_end(self, step: int | None = None, tokens: int | None = None,
                 loss=None, steps: int = 1) -> float | None:
        """Mark a step boundary; returns the per-step wall time (None on the
        baseline call). ``loss`` may be an in-flight device scalar — or, under
        windowed dispatch, a retained K-vector — it is never fetched here.

        ``steps`` is how many *training steps* this boundary covers: a K-step
        fused train window is ONE dispatch but K steps, so the boundary's wall
        time is split into K per-step samples and ``tokens`` (the boundary's
        TOTAL) into K per-step token counts — tokens/s, the MFU estimate, and
        the step-time quantiles stay per-step correct at any window size.
        """
        steps = max(int(steps), 1)
        now = self._clock()
        wall = None
        self._boundaries += 1
        self._dispatches += 1
        if self._last_end is not None:
            wall = (now - self._last_end) / steps
            per_tokens = tokens // steps if tokens else tokens
            first = None if step is None else step - steps + 1
            for i in range(steps):
                self._count += 1
                self._ring.append(StepSample(
                    step=None if first is None else first + i,
                    wall_s=wall, tokens=per_tokens,
                ))
                self._step_hist.observe(wall)
            self._window_s += wall * steps
            self._window_steps += steps
            self._steps_total.inc(steps)
            if per_tokens and wall > 0:
                tps = per_tokens / wall
                self._tokens_gauge.set(tps)
                if self._flops_per_token:
                    self._last_mfu = (
                        tps * self._flops_per_token
                        / (device_peak_flops() * jax.device_count())
                    )
                    self._mfu_gauge.set(self._last_mfu)
        self._last_end = now
        self._last_step = step if step is not None else self._last_step
        if loss is not None:
            self._pending_loss.append(loss)
        return wall

    def _drain_loss(self):
        """Fetch retained losses whose results have materialized (a counted
        copy via host_fetch, never a stall); unready ones stay queued. A
        windowed boundary retains a K-vector — its last element is the most
        recent step's loss."""
        import numpy as np

        while self._pending_loss:
            head = self._pending_loss[0]
            if not array_is_ready(head):
                break
            self._pending_loss.popleft()
            try:
                self._last_loss = float(host_fetch(head).reshape(-1)[-1])
            except Exception:
                self._last_loss = None

    def take_window(self) -> tuple[float, int]:
        """(seconds, steps) accumulated since the last take — the straggler
        monitor's per-report window."""
        out = (self._window_s, self._window_steps)
        self._window_s, self._window_steps = 0.0, 0
        return out

    # --------------------------------------------------------------- reading
    def summary(self) -> dict:
        """The step-timeline schema (docs/observability.md); also embedded in
        bench.py's per-config JSON lines as ``detail.telemetry``."""
        from ..resilience.goodput import get_ledger
        from ..utils import transfer

        samples = list(self._ring)
        walls = sorted(s.wall_s for s in samples)
        token_samples = [s for s in samples if s.tokens]
        tok_time = sum(s.wall_s for s in token_samples)
        tokens_per_s = (
            sum(s.tokens for s in token_samples) / tok_time if tok_time > 0 else None
        )
        mfu = None
        if tokens_per_s is not None and self._flops_per_token:
            mfu = (
                tokens_per_s * self._flops_per_token
                / (device_peak_flops() * jax.device_count())
            )
        self._drain_loss()
        now_stats = transfer.transfer_stats()
        # A reset_transfer_stats() since this timeline baselined its deltas
        # zeroed the global counters underneath the snapshot — comparing
        # against the stale baseline would go negative. Re-anchor at the
        # reset: deltas then cover counts since the reset, never below zero.
        if now_stats.get("resets", 0) != self._transfer0.get("resets", 0):
            self._transfer0 = {k: (0 if k != "resets" else now_stats["resets"])
                               for k in now_stats}
        ledger = get_ledger()
        from ..utils.xla_flags import active_preset

        out = {
            "steps": self._count,
            # Program dispatches vs steps: equal in step-per-dispatch training;
            # under K-step fused windows steps ≈ K × dispatches — the
            # amortization bench.py's detail.dispatches makes visible.
            "dispatches": self._dispatches,
            "last_step": self._last_step,
            "step_s": {
                "mean": sum(walls) / len(walls) if walls else 0.0,
                "p50": _quantile(walls, 0.50),
                "p90": _quantile(walls, 0.90),
                "max": walls[-1] if walls else 0.0,
            },
            "tokens_per_s": tokens_per_s,
            "mfu_estimate": mfu,
            "last_loss": self._last_loss,
            "compile": {
                "count": ledger.counts.get("compile", 0),
                "seconds": round(ledger.seconds.get("compile", 0.0), 3),
            },
            "transfers": {
                "fetches": now_stats["fetches"] - self._transfer0["fetches"],
                "blocking": now_stats["blocking"] - self._transfer0["blocking"],
                "h2d_puts": now_stats["h2d_puts"] - self._transfer0.get("h2d_puts", 0),
                "h2d_blocking": now_stats["h2d_blocking"]
                - self._transfer0.get("h2d_blocking", 0),
                "input_wait_s": round(
                    now_stats["input_wait_s"] - self._transfer0.get("input_wait_s", 0.0), 6
                ),
            },
            "xla_preset": active_preset(),
            "memory": self._memory_summary(),
        }
        # Profiling (telemetry/profiler.py): present only when a trace capture
        # engaged this run — un-profiled summaries keep their schema.
        from .profiler import default_manager_summary

        profile = default_manager_summary()
        if profile is not None:
            out["profile"] = profile
        return out

    def _memory_summary(self) -> dict:
        """Live ``memory_stats()`` plus, once a static audit armed it, the
        predicted per-device peak — and the predicted/observed ratio when the
        backend reports a peak (TPU/GPU; CPU devices have no memory_stats, so
        the prediction stands alone there). memory_stats() sums are TOTALS
        over local devices; the prediction is per device, so the ratio
        normalizes by the local device count."""
        out = device_memory_stats()
        if self._predicted_peak is not None:
            out["predicted_peak_bytes"] = self._predicted_peak
            observed = out.get("peak_bytes_in_use", 0)
            n_local = max(len(jax.local_devices()), 1)  # accelerate-lint: disable=raw-device-baseline
            if observed > 0:
                out["predicted_vs_observed"] = round(
                    self._predicted_peak / (observed / n_local), 3
                )
        return out

    def reset(self):
        from ..utils import transfer

        self._ring.clear()
        self._count = 0
        self._boundaries = 0
        self._dispatches = 0
        self._last_end = None
        self._last_step = None
        self._pending_loss.clear()
        self._last_loss = None
        self._predicted_peak = None
        self._last_mfu = None
        self._window_s, self._window_steps = 0.0, 0
        self._transfer0 = transfer.transfer_stats()
