"""Cross-host metric aggregation — one pane for an N-host job.

Every observability surface below this module is per-host: each worker runs
its own Prometheus endpoint and an operator of an N-host job has N scrape
targets and no single pane. This module joins them:

- **Registration** (:func:`publish_metrics_endpoint`): each worker publishes
  its *actually bound* ``host:port`` (ephemeral ports included — the bound
  port is read off the live server, never guessed from the env contract)
  into the JAX coordination-service KV namespace ``at_fleet/metrics`` — the
  same transport the ``utils/agreement`` fallbacks ride, so discovery works
  on collective-less rigs too. Single-process runs register in-module.
- **Discovery** (:func:`discover_endpoints`): the lead host blocks on every
  rank's key, so no operator-supplied address list exists anywhere.
- **Aggregation** (:class:`FleetAggregator`): scrape every registered
  endpoint, relabel every series with ``host="<process_index>"``, and fold
  the per-host series into fleet rollups — fleet MFU, tokens/s, the goodput
  split, step-time min/median/max/skew, KV-pool utilization, restart /
  reshard / health-trip / SLO-breach totals. Re-exported two ways on the
  existing HTTP server (``telemetry/metrics.py`` routes ``/fleet`` to the
  installed provider): ``GET /fleet`` returns the JSON snapshot
  (``accelerate-tpu top`` consumes it) and ``GET /fleet/metrics`` the joined
  per-host-labeled Prometheus exposition (one target for an external
  scraper).

Scrapes happen on demand (a ``/fleet`` request or ``snapshot()`` call) with a
short cache — no background thread, no per-step cost, and nothing here ever
touches a device.
"""

from __future__ import annotations

import json
import re
import socket
import statistics
import threading
import time
import urllib.error
import urllib.request

FLEET_SCHEMA_VERSION = 1

# Coordination-service KV namespace for endpoint registration. Deliberately
# NOT the agreement module's single-use-namespace contract: registrations are
# persistent facts (one key per rank for the life of the job), not a barrier
# exchange.
KV_NAMESPACE = "at_fleet/metrics"

_LOCK = threading.Lock()
_LOCAL_ENDPOINT: str | None = None
_KNOWN_ENDPOINTS: dict[int, str] = {}  # rank -> host:port (local + discovered)


def local_host_address() -> str:
    """The address other hosts can reach this worker's endpoint on: the
    interface that routes to the JAX coordinator when one is configured
    (a UDP connect pays no traffic), else loopback (single host / CPU-sim
    gangs share a machine)."""
    import os

    from ..utils.constants import ENV_COORDINATOR

    coordinator = os.environ.get(ENV_COORDINATOR, "").strip()
    if coordinator:
        host = coordinator.rsplit(":", 1)[0]
        try:
            probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            try:
                probe.connect((host, 1))
                return probe.getsockname()[0]
            finally:
                probe.close()
        except OSError:
            pass
    return "127.0.0.1"


def _kv_client():
    try:
        from jax._src.distributed import global_state as dist_state

        return dist_state.client
    except Exception:
        return None


def publish_metrics_endpoint(process_index: int = 0, server=None,
                             host: str | None = None) -> str | None:
    """Publish this worker's bound metrics endpoint into the fleet registry.

    ``server`` defaults to the running process-wide endpoint; the published
    port is the server's ACTUALLY bound port (``server.port`` — so a port-0
    ephemeral bind and the co-located-worker local-rank offset both publish
    the truth instead of the requested number). Returns the published
    ``host:port``, or None when no endpoint is serving. Registration is
    idempotent per process; re-publishing (an elastic restart re-binding the
    same port) overwrites the rank's key where the coordination service
    allows it and is best-effort otherwise — aggregation, not correctness,
    depends on it."""
    global _LOCAL_ENDPOINT
    if server is None:
        from .metrics import default_server

        server = default_server()
    if server is None or server.port is None:
        return None
    endpoint = f"{host or local_host_address()}:{server.port}"
    with _LOCK:
        _LOCAL_ENDPOINT = endpoint
        _KNOWN_ENDPOINTS[int(process_index)] = endpoint
    client = _kv_client()
    if client is not None:
        key = f"{KV_NAMESPACE}/{int(process_index)}"
        try:
            client.key_value_set(key, endpoint)
        except Exception:
            # A stale key from a prior incarnation: replace it.
            try:
                client.key_value_delete(key)
                client.key_value_set(key, endpoint)
            except Exception:
                pass
    return endpoint


def metrics_endpoint() -> str | None:
    """This process's published ``host:port`` (None before any publish) —
    surfaced as ``PartialState.metrics_endpoint``."""
    return _LOCAL_ENDPOINT


def cached_endpoint(process_index: int) -> str | None:
    """A rank's endpoint IF already known locally (published here or
    discovered by an aggregator) — non-blocking, for best-effort surfaces
    like the straggler warning naming the slow host's scrape address."""
    with _LOCK:
        return _KNOWN_ENDPOINTS.get(int(process_index))


def discover_endpoints(num_processes: int, timeout_ms: int = 60_000) -> dict:
    """``{rank: "host:port"}`` for every rank that HAS registered, read from
    the KV registry. ``timeout_ms`` is a TOTAL budget shared across the
    blocking reads (registered keys answer instantly), so N absent workers
    cost one window, not N stacked ones. A rank that never registered — its
    metrics bind failed, which ``start_endpoint_from_env`` deliberately
    degrades to a warning — is simply absent from the result, never an
    exception: the aggregator renders it as a down row instead of blanking
    the pane. Without a distributed client (single process) returns the
    local registration only."""
    client = _kv_client()
    if client is None or num_processes <= 1:
        with _LOCK:
            return dict(_KNOWN_ENDPOINTS)
    endpoints = {}
    ranks = list(range(int(num_processes)))
    deadline = time.monotonic() + timeout_ms / 1000.0
    for i, rank in enumerate(ranks):
        remaining_ms = int((deadline - time.monotonic()) * 1000)
        if remaining_ms <= 0:
            # Budget exhausted: stop reading. Already-cached ranks keep
            # their addresses (the caller merges), unread ranks stay absent
            # until the next refresh's budget.
            break
        # Fair slice of the remaining budget per still-unread rank, so a
        # missing LOW rank cannot starve the reads of registered higher
        # ranks (registered keys answer instantly and return their slice).
        slice_ms = max(50, remaining_ms // (len(ranks) - i))
        try:
            endpoints[rank] = client.blocking_key_value_get(
                f"{KV_NAMESPACE}/{rank}", slice_ms
            )
        except Exception:
            continue  # not registered (yet) — degradation, not failure
    with _LOCK:
        _KNOWN_ENDPOINTS.update(endpoints)
    return endpoints


def reset_fleet():
    """Drop registration/discovery state and any installed provider — tests."""
    global _LOCAL_ENDPOINT
    with _LOCK:
        _LOCAL_ENDPOINT = None
        _KNOWN_ENDPOINTS.clear()
    from .metrics import set_fleet_provider

    set_fleet_provider(None)


# ------------------------------------------------------------------- parsing
_SERIES_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)$"
)


def parse_prometheus_text(text: str) -> dict:
    """Prometheus text exposition → ``{family: {"kind": t, "series":
    {labels_str: value}}}`` (histogram ``_bucket``/``_sum``/``_count`` series
    keep their suffixed names inside the base family's series dict, so the
    join loses nothing)."""
    families: dict = {}
    kinds: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) >= 4:
                kinds[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue
        match = _SERIES_RE.match(line)
        if not match:
            continue
        name = match.group("name")
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in kinds:
                base = name[: -len(suffix)]
                break
        try:
            value = float(match.group("value"))
        except ValueError:
            continue
        family = families.setdefault(
            base, {"kind": kinds.get(base, "untyped"), "series": {}}
        )
        labels = match.group("labels") or ""
        family["series"][f"{name}{{{labels}}}" if labels else name] = value
    return families


def _series_value(families: dict, family: str, labels: str | None = None):
    fam = families.get(family)
    if not fam:
        return None
    key = f"{family}{{{labels}}}" if labels else family
    return fam["series"].get(key)


_HOST_LABEL_RE = re.compile(r'(^|,)host="')


def _relabel_host(labels: str) -> str:
    """A series that already carries a ``host`` label (the straggler's
    ``accelerate_host_step_seconds{host=}`` gauges) must not gain a duplicate
    — duplicate label names are an invalid exposition an external Prometheus
    rejects wholesale. The pre-existing label renames to ``exported_host``
    (the Prometheus honor_labels=false convention) before the scraped-rank
    ``host`` is injected."""
    return _HOST_LABEL_RE.sub(r'\1exported_host="', labels) if labels else labels


def _inject_host_label(line: str, host: str) -> str:
    """Rewrite one exposition series line with ``host="<h>"`` as the first
    label (comment lines pass through; a pre-existing ``host`` label renames
    to ``exported_host``)."""
    if not line or line.startswith("#"):
        return line
    match = _SERIES_RE.match(line.strip())
    if not match:
        return line
    name, labels, value = match.group("name"), match.group("labels"), match.group("value")
    labels = _relabel_host(labels)
    inner = f'host="{host}"' + (f",{labels}" if labels else "")
    return f"{name}{{{inner}}} {value}"


class FleetAggregator:
    """Scrape every registered worker endpoint and join the series; see
    module docstring.

    ``state`` (a ``PartialState``-like object) supplies ``num_processes`` for
    KV discovery; ``endpoints`` (``{rank: "host:port"}`` or a plain list)
    overrides discovery for tests and ad-hoc operator use. ``cache_s`` bounds
    scrape frequency under polling consoles; ``timeout_s`` bounds one
    endpoint's scrape so a dead host marks down instead of wedging the pane.
    """

    #: Total re-discovery budget on refreshes AFTER the first (registered
    #: keys answer instantly; permanently absent ranks SHARE this much per
    #: refresh, bounded by cache_s).
    REDISCOVER_TIMEOUT_MS = 2_000

    def __init__(self, state=None, endpoints=None, timeout_s: float = 3.0,
                 cache_s: float = 1.0, discover_timeout_ms: int = 60_000):
        self._state = state
        if isinstance(endpoints, (list, tuple)):
            endpoints = {i: ep for i, ep in enumerate(endpoints)}
        # An explicit endpoint map pins the fleet (tests, ad-hoc operator
        # use); otherwise discovery re-reads the KV registry on every
        # refresh so a worker that re-publishes after an elastic restart
        # (new bind, same rank) is picked up without restarting the lead.
        self._static = endpoints is not None
        self._endpoints = dict(endpoints) if endpoints else None
        self.timeout_s = float(timeout_s)
        self.cache_s = float(cache_s)
        self.discover_timeout_ms = int(discover_timeout_ms)
        self._lock = threading.Lock()
        # Serializes whole refreshes: the aggregator serves from a
        # ThreadingHTTPServer, and two concurrent cache misses (an external
        # scraper + a polling console) must coalesce into ONE fleet scrape,
        # not two — the cache_s bound is a promise to the workers.
        self._refresh_lock = threading.Lock()
        self._cached: dict | None = None
        self._cached_at = 0.0
        self._raw: dict = {}  # rank -> exposition text of the last scrape

    # ------------------------------------------------------------- discovery
    def _num_ranks(self) -> int:
        if self._static:
            return len(self._endpoints)
        n = int(getattr(self._state, "num_processes", 1) or 1) if self._state else 1
        return max(n, 1)

    def endpoints(self) -> dict:
        """``{rank: "host:port"}`` for every rank currently known. The first
        call blocks up to ``discover_timeout_ms`` TOTAL (workers register at
        init — normally instant); later calls re-read the registry inside a
        short shared budget so re-publications land and a still-missing rank
        degrades to a down row instead of wedging the pane."""
        if self._static:
            return self._endpoints
        n = self._num_ranks()
        with self._lock:
            known = dict(self._endpoints) if self._endpoints is not None else None
        timeout = (self.discover_timeout_ms if known is None
                   else self.REDISCOVER_TIMEOUT_MS)
        discovered = discover_endpoints(n, timeout_ms=timeout)
        merged = dict(known or {})
        merged.update(discovered)  # re-publication wins; a read miss keeps the cached address
        with self._lock:
            self._endpoints = merged
        return merged

    # --------------------------------------------------------------- scraping
    def _scrape(self, endpoint: str) -> str:
        with urllib.request.urlopen(
            f"http://{endpoint}/metrics", timeout=self.timeout_s
        ) as response:
            return response.read().decode("utf-8", "replace")

    def refresh(self) -> dict:
        """Scrape every endpoint now; returns the fresh snapshot. Down hosts
        degrade to ``up: false`` rows — one dead worker must not blank the
        pane for the rest of the fleet."""
        hosts: dict = {}
        raw: dict = {}
        series: dict = {}
        per_host: dict = {}
        endpoints = self.endpoints()
        # Every EXPECTED rank gets a row: one whose endpoint never registered
        # (its metrics bind failed at init) renders as down, same as a dead
        # scrape — never an exception, never a blank pane.
        ranks = sorted(set(range(self._num_ranks())) | set(endpoints))
        # Scrapes run concurrently so refresh wall time is bounded by ONE
        # timeout_s, not the sum over down hosts — otherwise two black-holed
        # workers push every /fleet response past the console's transport
        # timeout and the pane dies exactly when it matters.
        scraped: dict = {}
        to_scrape = [r for r in ranks if endpoints.get(r) is not None]
        if to_scrape:
            from concurrent.futures import ThreadPoolExecutor

            # One thread per endpoint (idle HTTP I/O, count == fleet size):
            # refresh wall time really is bounded by one timeout_s even when
            # most of the fleet is black-holed.
            with ThreadPoolExecutor(max_workers=len(to_scrape)) as pool:
                futures = {r: pool.submit(self._scrape, endpoints[r])
                           for r in to_scrape}
                for r, future in futures.items():
                    try:
                        scraped[r] = future.result()
                    except Exception as exc:
                        scraped[r] = exc
        for rank in ranks:
            endpoint = endpoints.get(rank)
            row: dict = {"endpoint": endpoint, "up": False}
            if endpoint is None:
                row["error"] = "no metrics endpoint registered for this rank"
                hosts[str(rank)] = row
                continue
            text = scraped[rank]
            if isinstance(text, Exception):
                row["error"] = f"{type(text).__name__}: {text}"[:200]
                hosts[str(rank)] = row
                continue
            raw[rank] = text
            families = parse_prometheus_text(text)
            per_host[rank] = families
            row["up"] = True
            hist = families.get("accelerate_step_seconds", {}).get("series", {})
            s_sum = hist.get("accelerate_step_seconds_sum", 0.0)
            s_count = hist.get("accelerate_step_seconds_count", 0.0)
            row["steps"] = int(s_count)
            row["step_s_mean"] = round(s_sum / s_count, 6) if s_count else None
            row["tokens_per_s"] = _series_value(
                families, "accelerate_tokens_per_second")
            row["mfu"] = _series_value(families, "accelerate_mfu_estimate")
            row["goodput_fraction"] = _series_value(
                families, "accelerate_goodput_fraction")
            row["restarts"] = _series_value(families, "accelerate_restarts")
            row["kv_pool_utilization"] = _series_value(
                families, "accelerate_serving_kv_pool_utilization")
            # Disaggregated-serving tier (serving_net/): the role gauge is a
            # labeled constant-1, so the label IS the datum — the row carries
            # it for the per-tier rollup join and the `top` tier column.
            row["serving_role"] = None
            for key in families.get(
                "accelerate_serving_role", {}
            ).get("series", {}):
                m = re.search(r'role="([^"]*)"', key)
                if m:
                    row["serving_role"] = m.group(1)
                    break
            breaches = {}
            for key, value in families.get(
                "accelerate_slo_breaches_total", {}
            ).get("series", {}).items():
                m = re.search(r'target="([^"]*)"', key)
                if m:
                    breaches[m.group(1)] = int(value)
            row["slo_breaches"] = breaches
            hosts[str(rank)] = row
            for family, payload in families.items():
                for key, value in payload["series"].items():
                    name, _, labels = key.partition("{")
                    labels = _relabel_host(labels[:-1] if labels else "")
                    inner = f'host="{rank}"' + (f",{labels}" if labels else "")
                    series[f"{name}{{{inner}}}"] = value
        snapshot = {
            "schema_version": FLEET_SCHEMA_VERSION,
            "generated_at": time.time(),
            "hosts": hosts,
            "fleet": self._rollups(hosts, per_host),
            "series": series,
        }
        with self._lock:
            self._raw = raw
            self._cached = snapshot
            self._cached_at = time.monotonic()
        return snapshot

    def _rollups(self, hosts: dict, per_host: dict) -> dict:
        """Fold per-host rows into the fleet view the control room reads."""
        up = [row for row in hosts.values() if row["up"]]
        step_means = [row["step_s_mean"] for row in up
                      if row.get("step_s_mean") is not None]
        mfus = [row["mfu"] for row in up if row.get("mfu") is not None]
        toks = [row["tokens_per_s"] for row in up
                if row.get("tokens_per_s") is not None]
        goodput = [row["goodput_fraction"] for row in up
                   if row.get("goodput_fraction") is not None]
        pools = [row["kv_pool_utilization"] for row in up
                 if row.get("kv_pool_utilization") is not None]
        badput: dict = {}
        trips = resharded = restarts = 0.0
        breaches: dict = {}
        for rank, families in per_host.items():
            for key, value in families.get(
                "accelerate_badput_seconds", {}
            ).get("series", {}).items():
                m = re.search(r'category="([^"]*)"', key)
                if m:
                    badput[m.group(1)] = round(
                        badput.get(m.group(1), 0.0) + value, 3
                    )
            for key, value in families.get(
                "accelerate_health_trips_total", {}
            ).get("series", {}).items():
                trips += value
            for key, value in families.get(
                "accelerate_reshard_transitions_total", {}
            ).get("series", {}).items():
                resharded += value
            restarts += _series_value(families, "accelerate_restarts") or 0.0
        for row in up:
            for target, count in row.get("slo_breaches", {}).items():
                breaches[target] = breaches.get(target, 0) + count
        step = {}
        if step_means:
            med = statistics.median(step_means)
            step = {
                "min": round(min(step_means), 6),
                "median": round(med, 6),
                "max": round(max(step_means), 6),
                "skew": round(max(step_means) / med, 4) if med > 0 else 1.0,
            }
        return {
            "hosts_total": len(hosts),
            "hosts_up": len(up),
            "mfu": round(sum(mfus) / len(mfus), 6) if mfus else None,
            "tokens_per_s": round(sum(toks), 3) if toks else None,
            "goodput": {
                "fraction": round(sum(goodput) / len(goodput), 6)
                if goodput else None,
                "badput_s": badput,
            },
            "step_s": step,
            "kv_pool_utilization": round(sum(pools) / len(pools), 6)
            if pools else None,
            "restarts": int(restarts),
            "reshard_transitions": int(resharded),
            "health_trips": int(trips),
            "slo_breaches": breaches,
            "serving_tiers": self._serving_tiers(hosts, per_host),
        }

    @staticmethod
    def _serving_tiers(hosts: dict, per_host: dict) -> dict:
        """Fold per-host serving series into per-TIER rollups keyed by the
        ``serving_role`` each row carries — the single pane where a
        disaggregated deployment's prefill and decode sides read side by
        side (requests, TTFT/TPOT means off the histogram sums, KV-chain
        handoff volume) and the router tier reports its routing split and
        prefix-affinity hit rate. Hosts with no role gauge (training jobs,
        pre-serving warmup) simply contribute nothing."""
        tiers: dict = {}
        for rank, families in per_host.items():
            role = hosts.get(str(rank), {}).get("serving_role")
            if role is None:
                continue
            tier = tiers.setdefault(role, {
                "hosts": 0, "requests": 0, "completed": 0,
                "ttft_sum": 0.0, "ttft_count": 0.0,
                "tpot_sum": 0.0, "tpot_count": 0.0,
                "handoff": {},
            })
            tier["hosts"] += 1
            tier["requests"] += int(_series_value(
                families, "accelerate_serving_requests_total") or 0)
            tier["completed"] += int(_series_value(
                families, "accelerate_serving_requests_completed_total") or 0)
            for metric, prefix in (("accelerate_serving_ttft_seconds", "ttft"),
                                   ("accelerate_serving_tpot_seconds", "tpot")):
                for key, value in families.get(metric, {}).get(
                        "series", {}).items():
                    if key.startswith(f"{metric}_sum"):
                        tier[f"{prefix}_sum"] += value
                    elif key.startswith(f"{metric}_count"):
                        tier[f"{prefix}_count"] += value
            for metric, field in (
                ("accelerate_serving_handoff_bytes_total", "bytes"),
                ("accelerate_serving_handoff_chains_total", "chains"),
                ("accelerate_serving_handoff_blocks_total", "blocks"),
            ):
                for key, value in families.get(metric, {}).get(
                        "series", {}).items():
                    m = re.search(r'direction="([^"]*)"', key)
                    direction = m.group(1) if m else "out"
                    leg = tier["handoff"].setdefault(
                        direction, {"bytes": 0, "chains": 0, "blocks": 0})
                    leg[field] += int(value)
            routed: dict = {}
            for key, value in families.get(
                "accelerate_serving_router_requests_total", {}
            ).get("series", {}).items():
                m = re.search(r'tier="([^"]*)"', key)
                if m:
                    routed[m.group(1)] = routed.get(m.group(1), 0) + int(value)
            if routed:
                prior = tier.get("routed", {})
                for k, v in routed.items():
                    prior[k] = prior.get(k, 0) + v
                tier["routed"] = prior
                hits = _series_value(
                    families, "accelerate_serving_router_affinity_hits_total")
                tier["affinity_hits"] = (
                    tier.get("affinity_hits", 0) + int(hits or 0))
            # Fault-tolerance rollups (docs/serving.md "Failure semantics"):
            # retry legs and eviction/degradation counts by labeled reason,
            # plus in-flight requests saved by graceful drains — the /fleet
            # pane where "did the fleet recover?" is answered.
            for metric, field, label in (
                ("accelerate_serving_retries_total", "retries", "reason"),
                ("accelerate_serving_evictions_total", "evictions", "reason"),
                ("accelerate_serving_degraded_total", "degraded", "mode"),
            ):
                for key, value in families.get(metric, {}).get(
                        "series", {}).items():
                    m = re.search(rf'{label}="([^"]*)"', key)
                    bucket = tier.setdefault(field, {})
                    name = m.group(1) if m else "unknown"
                    bucket[name] = bucket.get(name, 0) + int(value)
            drained = _series_value(
                families, "accelerate_serving_drained_inflight_total")
            if drained:
                tier["drained_in_flight"] = (
                    tier.get("drained_in_flight", 0) + int(drained))
        for tier in tiers.values():
            for prefix in ("ttft", "tpot"):
                count = tier.pop(f"{prefix}_count")
                total = tier.pop(f"{prefix}_sum")
                tier[f"{prefix}_s_mean"] = (
                    round(total / count, 6) if count else None)
            if "routed" in tier:
                total = sum(tier["routed"].values())
                tier["affinity_hit_rate"] = (
                    round(tier.get("affinity_hits", 0) / total, 4)
                    if total else None)
        return tiers

    # ---------------------------------------------------------------- exports
    def snapshot(self) -> dict:
        """The fleet snapshot (cached up to ``cache_s`` under polling) — the
        ``GET /fleet`` body and the ``accelerate-tpu top`` feed. Concurrent
        cache misses coalesce: one thread scrapes, the rest serve its
        result."""
        with self._lock:
            cached, at = self._cached, self._cached_at
        if cached is not None and time.monotonic() - at < self.cache_s:
            return cached
        with self._refresh_lock:
            with self._lock:  # another thread may have refreshed while we waited
                cached, at = self._cached, self._cached_at
            if cached is not None and time.monotonic() - at < self.cache_s:
                return cached
            return self.refresh()

    def prometheus_text(self) -> str:
        """The joined per-host-labeled exposition (``GET /fleet/metrics``):
        every scraped series re-emitted with ``host="<rank>"`` injected, one
        ``# TYPE`` header per family."""
        self.snapshot()  # ensure a scrape happened recently
        with self._lock:
            raw = dict(self._raw)
        lines: list[str] = []
        seen_types: set = set()
        for rank in sorted(raw):
            for line in raw[rank].splitlines():
                stripped = line.strip()
                if stripped.startswith("# TYPE "):
                    if stripped not in seen_types:
                        seen_types.add(stripped)
                        lines.append(stripped)
                    continue
                if not stripped or stripped.startswith("#"):
                    continue
                lines.append(_inject_host_label(stripped, str(rank)))
        return "\n".join(lines) + "\n"


def install_fleet_provider(aggregator: FleetAggregator) -> FleetAggregator:
    """Route the HTTP server's ``/fleet`` + ``/fleet/metrics`` to this
    aggregator (the lead-host install ``ACCELERATE_FLEET_METRICS=1`` performs
    at PartialState init)."""
    from .metrics import set_fleet_provider

    set_fleet_provider(aggregator)
    return aggregator


def fetch_fleet_snapshot(endpoint: str, timeout_s: float = 10.0) -> dict:
    """GET ``http://<endpoint>/fleet`` → snapshot dict (the ``top`` console's
    transport). Falls back to aggregating the single endpoint client-side
    when the server has no fleet provider (404/503) — a bare worker is then
    still inspectable as a one-host fleet."""
    endpoint = endpoint.strip()
    if endpoint.startswith("http://") or endpoint.startswith("https://"):
        endpoint = endpoint.split("://", 1)[1]
    endpoint = endpoint.rstrip("/")
    try:
        with urllib.request.urlopen(
            f"http://{endpoint}/fleet", timeout=timeout_s
        ) as response:
            return json.loads(response.read().decode("utf-8", "replace"))
    except urllib.error.HTTPError as exc:
        if exc.code not in (404, 503):
            raise
        return FleetAggregator(
            endpoints={0: endpoint}, timeout_s=timeout_s, cache_s=0.0
        ).refresh()
