"""Cross-host straggler detection — which host is slow?

SPMD training runs the same program everywhere, so one host's slow data feed,
thermal throttle, or flaky NIC shows up only as every OTHER host idling in
its next collective; nothing fails and nothing logs. The monitor makes the
skew measurable: every ``every_steps`` steps each host contributes its mean
step wall-time over the window and the per-host vector is exchanged — one
tiny collective over the existing machinery (the one-scalar-collective idiom
of the preemption/health agreement; backends without multiprocess
computations fall back to the coordination-service KV gather the same way the
health guard does). Every host then knows min/median/max and WHICH host is
slow, and a host exceeding ``slow_ratio`` × median raises a rate-limited log
warning (``MultiProcessAdapter.log_every_n`` — a flapping straggler cannot
flood a multi-thousand-step run).

The exchange is a collective: every host must drive it at the same step, the
contract all the per-step hooks (``guard_step``/``checkpoint_on_preemption``)
already obey.
"""

from __future__ import annotations

import logging
import statistics
from dataclasses import asdict, dataclass, field

import numpy as np

from ..logging import get_logger
from ..utils.transfer import host_fetch

logger = get_logger(__name__)

# KV namespaces must be unique per exchange AND identical across ranks
# (utils/agreement.py contract): ranks construct monitors in the same SPMD
# program order, so a process-wide construction counter lines up — the
# HealthGuard _GUARD_SEQ idiom. A per-instance epoch alone would reuse
# namespaces when a restart (or configure_telemetry) builds a fresh monitor.
_MONITOR_SEQ = 0


@dataclass
class SkewReport:
    """One straggler-exchange outcome, identical on every host."""

    step: int
    per_host_s: list = field(default_factory=list)
    min_s: float = 0.0
    median_s: float = 0.0
    max_s: float = 0.0
    slowest_host: int = 0
    ratio: float = 1.0  # max / median
    tripped: bool = False

    def to_dict(self) -> dict:
        return asdict(self)


class StragglerMonitor:
    """Periodic per-host step-time aggregation; see module docstring."""

    def __init__(self, every_steps: int = 50, slow_ratio: float = 1.5,
                 registry=None):
        if every_steps < 1:
            raise ValueError(f"every_steps must be >= 1, got {every_steps}")
        if slow_ratio < 1.0:
            raise ValueError(f"slow_ratio must be >= 1.0, got {slow_ratio}")
        from .metrics import get_registry

        self.every_steps = int(every_steps)
        self.slow_ratio = float(slow_ratio)
        self.last_report: SkewReport | None = None
        self._kv = False
        self._epoch = 0
        global _MONITOR_SEQ
        _MONITOR_SEQ += 1
        self._monitor_id = _MONITOR_SEQ
        registry = registry if registry is not None else get_registry()
        self._ratio_gauge = registry.gauge(
            "accelerate_step_time_skew_ratio",
            "Max/median cross-host step-time ratio from the last exchange",
        )
        self._slowest_gauge = registry.gauge(
            "accelerate_slowest_host", "Process index of the slowest host"
        )
        self._host_gauge = registry.gauge(
            "accelerate_host_step_seconds",
            "Per-host mean step time from the last exchange",
            labelnames=("host",),
        )

    def due(self, step: int, window: int = 1) -> bool:
        """Whether an exchange is due at this step boundary. ``window`` > 1 is
        the K-step fused-window case: boundaries advance by K, so the exchange
        fires when ANY in-window step crossed the cadence (no step 0: there is
        no step-time window to exchange before the first completed step)."""
        from ..utils.cadence import window_cadence_due

        return window_cadence_due(step, window, self.every_steps)

    # ---------------------------------------------------------------- report
    def report(self, state, local_mean_s: float, step: int = 0) -> SkewReport | None:
        """Exchange this host's window mean and return the agreed skew report.
        COLLECTIVE: every process must call at the same step."""
        if local_mean_s is None:
            return None
        values = self._exchange(float(local_mean_s), state)
        median = statistics.median(values)
        slowest = int(max(range(len(values)), key=values.__getitem__))
        ratio = (values[slowest] / median) if median > 0 else 1.0
        report = SkewReport(
            step=int(step),
            per_host_s=[round(v, 6) for v in values],
            min_s=min(values),
            median_s=median,
            max_s=values[slowest],
            slowest_host=slowest,
            ratio=ratio,
            tripped=len(values) > 1 and ratio > self.slow_ratio,
        )
        self._ratio_gauge.set(ratio)
        self._slowest_gauge.set(slowest)
        for host, v in enumerate(values):
            self._host_gauge.set(v, host=host)
        if report.tripped:
            # Name the slow host's scrape address too when the fleet registry
            # knows it (telemetry/fleet.py) — operators then go straight to
            # the evidence instead of guessing which port rank N bound.
            from .fleet import cached_endpoint

            endpoint = cached_endpoint(slowest)
            where = f" (metrics: http://{endpoint}/metrics)" if endpoint else ""
            logger.log_every_n(
                10,
                logging.WARNING,
                f"straggler: host {slowest}{where} mean step time "
                f"{values[slowest] * 1e3:.1f}ms is {ratio:.2f}x the median "
                f"{median * 1e3:.1f}ms (threshold {self.slow_ratio:.2f}x) at "
                f"step {step}",
            )
        self.last_report = report
        return report

    # -------------------------------------------------------------- exchange
    def _exchange(self, local: float, state) -> list[float]:
        """All-hosts gather of one float: a length-num_processes one-hot vector
        summed by a device collective; KV fallback where multiprocess
        computations are unimplemented (the 2-process CPU harness)."""
        n = int(getattr(state, "num_processes", 1) or 1)
        if n <= 1:
            return [local]
        idx = int(getattr(state, "process_index", 0))
        if not self._kv:
            try:
                from ..utils import operations as ops

                vec = np.zeros((n,), np.float32)
                vec[idx] = local
                total = host_fetch(ops.reduce(vec, reduction="sum"))
                return [float(x) for x in total]
            except Exception as exc:
                logger.warning(
                    f"Device-collective straggler exchange unavailable "
                    f"({type(exc).__name__}: {exc}); using the "
                    "coordination-service KV gather instead."
                )
                self._kv = True
        from ..utils.agreement import kv_all_gather

        self._epoch += 1
        raw = kv_all_gather(
            repr(local), n, idx,
            namespace=f"at_straggler/{self._monitor_id}/{self._epoch}",
        )
        return [float(v) for v in raw]
