"""Continuous SLO sentinel — watch the targets, not the logs.

The observability stack so far *records* everything (step timeline, serving
TTFT/TPOT histograms, goodput ledger) but nothing *watches* it: an operator
learns about a step-time or TTFT regression from a user, not a gauge. The
sentinel closes that loop with per-target evaluation on observations the
loops already produce:

- **step time / MFU** — fed one call per step (or K-step window) boundary by
  :class:`..telemetry.Telemetry`; an explicit ``step_time_s`` target trips on
  any per-step wall time over budget, and with no explicit target the
  ``health/spike.py`` EMA+MAD baseline idiom (re-derived host-side in
  :class:`..telemetry.profiler.SlowStepDetector`) trips on a robust-z
  outlier instead — a regression is caught relative to the run's own recent
  history. ``mfu_min`` trips when the timeline's achieved-MFU estimate
  drops below the floor.
- **TTFT / TPOT** — fed per request by the serving engine's
  :class:`..telemetry.requests.RequestTracer` (docs/serving.md).

Every breach books ONE place: :func:`record_breach` increments
``accelerate_slo_breaches_total{target=...}``, lands a ``slo_breach`` event in
the flight recorder (so a dump shows the breach next to what the run was
doing), and raises a rate-limited warning. Evaluation is pure host
arithmetic — no device work, no transfers, blocking or otherwise.

Launcher contract (tri-state, the profile_slow_zscore precedent):
``--slo_step_time`` / ``--slo_ttft`` / ``--slo_tpot`` export
``ACCELERATE_SLO_STEP_TIME/TTFT/TPOT`` (seconds); an explicit 0 scrubs an
inherited value and disables the dimension.
"""

from __future__ import annotations

import logging
import os

from ..logging import get_logger

logger = get_logger(__name__)

# Breach targets are a closed vocabulary so dashboards and the fleet
# aggregator can enumerate the label values. ``availability`` books a shed
# request (the serving degradation ladder's floor — router 503s because no
# decode-capable worker survived); its value/threshold are request counts,
# not seconds.
BREACH_TARGETS = ("step_time", "mfu", "ttft", "tpot", "availability")

_BREACH_HANDLES = None  # metrics.cached_handles accessor


def _breach_counter():
    global _BREACH_HANDLES
    if _BREACH_HANDLES is None:
        from .metrics import cached_handles

        _BREACH_HANDLES = cached_handles(lambda registry: registry.counter(
            "accelerate_slo_breaches_total",
            "SLO breaches observed by the sentinel, by target",
            labelnames=("target",),
        ))
    return _BREACH_HANDLES()


def record_breach(target: str, value: float, threshold: float,
                  step=None, rid=None) -> None:
    """Book one SLO breach everywhere it must land: the
    ``accelerate_slo_breaches_total{target}`` counter, a ``slo_breach``
    flight-recorder event, and a rate-limited warning. The single spelling
    the sentinel AND the serving request tracer share."""
    if target not in BREACH_TARGETS:
        raise ValueError(
            f"unknown SLO target {target!r}; expected one of {BREACH_TARGETS}"
        )
    _breach_counter().inc(target=target)
    # get_flight_recorder (not record_event): a breach must land in the black
    # box even when nothing else created the recorder yet.
    from .flight import get_flight_recorder

    data = {"target": target, "value": round(float(value), 6),
            "threshold": round(float(threshold), 6)}
    if rid is not None:
        data["rid"] = int(rid)
    get_flight_recorder().record("slo_breach", step=step, **data)
    extra = f" (request {rid})" if rid is not None else ""
    comparator = ">=" if target == "mfu" else "<="
    logger.log_every_n(
        10, logging.WARNING,
        f"SLO breach: {target}={value:.6g} vs target {comparator} "
        f"{threshold:.6g}{extra}"
        + (f" at step {step}" if step is not None else ""),
    )


def arbitrate_serving_tier(prompt_tokens: int, slo=None, *,
                           prefill_chunk: int = 0,
                           have_prefill_tier: bool = False) -> str:
    """Which tier a request should ENTER in a disaggregated serving fleet —
    the SLO sentinel's admission arbitration (serving_net/router.py calls
    this per request; docs/serving.md "Disaggregated serving").

    The trade the policy encodes: shipping a finished KV chain costs one
    handoff RTT (pure TTFT tax), while prefilling on the decode host stalls
    every in-flight decoder by the prompt's chunk count (pure TPOT tax).
    So a prompt that fits ONE prefill chunk decodes where it lands
    (``"decode"`` — its single chunk stalls decode no worse than an import
    would), and a multi-chunk prompt routes to the prefill tier when one
    exists (``"prefill"`` — the decode tier's TPOT is protected from the
    long prefill; TTFT pays the bounded transfer instead of an unbounded
    queue behind other prompts). An explicit ``slo.tpot_s`` target tightens
    nothing further — multi-chunk prompts already route away — and
    ``slo.ttft_s`` alone (no TPOT target, nothing to protect) keeps even
    long prompts on the decode host, where TTFT skips the handoff RTT.
    Without a prefill tier everything is ``"decode"``."""
    if not have_prefill_tier:
        return "decode"
    chunks = 1 if prefill_chunk <= 0 else -(-int(prompt_tokens) // int(prefill_chunk))
    if chunks <= 1:
        return "decode"
    ttft_only = (slo is not None
                 and getattr(slo, "ttft_s", None) is not None
                 and getattr(slo, "tpot_s", None) is None)
    return "decode" if ttft_only else "prefill"


def breach_counts(registry=None) -> dict:
    """``{target: count}`` from the registry's breach counter — what bench.py
    snapshots around its measured window (``detail.slo``) and the fleet
    aggregator rolls up."""
    from .metrics import get_registry

    registry = registry if registry is not None else get_registry()
    counter = registry.counter(
        "accelerate_slo_breaches_total",
        "SLO breaches observed by the sentinel, by target",
        labelnames=("target",),
    )
    return {key[0]: int(v) for key, v in counter.series_values().items()}


def slo_targets_from_env() -> dict:
    """The launcher's SLO env contract as floats (``None`` = dimension off):
    ``{"step_time_s": ..., "ttft_s": ..., "tpot_s": ...}``. 0/empty = off."""
    from ..utils.constants import ENV_SLO_STEP_TIME, ENV_SLO_TPOT, ENV_SLO_TTFT

    out = {}
    for key, env in (("step_time_s", ENV_SLO_STEP_TIME),
                     ("ttft_s", ENV_SLO_TTFT), ("tpot_s", ENV_SLO_TPOT)):
        raw = os.environ.get(env, "").strip()
        try:
            val = float(raw) if raw else 0.0
        except ValueError:
            raise ValueError(f"{env}={raw!r} must be a number of seconds") from None
        out[key] = val if val > 0 else None
    return out


def serving_slo_from_env():
    """An :class:`~..serving.SLOTargets` built from the env contract, or None
    when neither serving dimension is configured — what ``ContinuousBatcher``
    resolves when the caller passes ``slo=None``, so ``launch --slo_ttft``
    reaches a serving tier with zero code."""
    targets = slo_targets_from_env()
    if targets["ttft_s"] is None and targets["tpot_s"] is None:
        return None
    from ..serving import SLOTargets

    return SLOTargets(ttft_s=targets["ttft_s"], tpot_s=targets["tpot_s"])


class SLOSentinel:
    """Continuous target evaluation over the per-step feed; see module
    docstring. ``step_time_s``/``mfu_min`` are explicit targets;
    ``auto_zscore`` > 0 arms the EMA+MAD baseline fallback for step time when
    no explicit target is set (``health/spike.py`` idiom — a tripped
    observation never updates the baseline). ``ttft_s``/``tpot_s`` are
    carried for ``summary()``/serving construction; the request tracer books
    those breaches per request."""

    def __init__(self, step_time_s: float | None = None,
                 mfu_min: float | None = None,
                 ttft_s: float | None = None, tpot_s: float | None = None,
                 auto_zscore: float = 0.0, warmup_steps: int = 20):
        for name, val in (("step_time_s", step_time_s), ("mfu_min", mfu_min),
                          ("ttft_s", ttft_s), ("tpot_s", tpot_s)):
            if val is not None and val <= 0:
                raise ValueError(f"{name} must be > 0 (None disables), got {val}")
        self.step_time_s = step_time_s
        self.mfu_min = mfu_min
        self.ttft_s = ttft_s
        self.tpot_s = tpot_s
        self._detector = None
        if step_time_s is None and auto_zscore > 0:
            from .profiler import SlowStepDetector

            self._detector = SlowStepDetector(auto_zscore,
                                              warmup_steps=warmup_steps)
        self._breaches = 0

    @property
    def active(self) -> bool:
        return (self.step_time_s is not None or self.mfu_min is not None
                or self.ttft_s is not None or self.tpot_s is not None
                or self._detector is not None)

    # ---------------------------------------------------------------- feeding
    def observe_step(self, wall_s: float, steps: int = 1, step=None,
                     mfu: float | None = None) -> bool:
        """One step (or K-step window) boundary's per-step wall time; returns
        whether anything breached. Pure host arithmetic."""
        breached = False
        wall_s = float(wall_s)
        if self.step_time_s is not None:
            if wall_s > self.step_time_s:
                record_breach("step_time", wall_s, self.step_time_s, step=step)
                breached = True
        elif self._detector is not None:
            # No explicit target: the run's own recent history is the budget
            # (EMA + MAD-proxy robust z — the spike detector's idiom).
            tripped, z = self._detector.observe(wall_s)
            if tripped:
                # The budget actually enforced (EMA + z·σ̂), not the bare EMA
                # — a tripped observation never updates the statistics, so
                # the post-trip read reports the threshold this value beat.
                record_breach("step_time", wall_s,
                              self._detector.trip_threshold, step=step)
                breached = True
        if self.mfu_min is not None and mfu is not None and mfu < self.mfu_min:
            record_breach("mfu", float(mfu), self.mfu_min, step=step)
            breached = True
        if breached:
            self._breaches += 1
        return breached

    # --------------------------------------------------------------- reading
    def summary(self) -> dict:
        return {
            "targets": {
                "step_time_s": self.step_time_s,
                "mfu_min": self.mfu_min,
                "ttft_s": self.ttft_s,
                "tpot_s": self.tpot_s,
                "auto_baseline": self._detector is not None,
            },
            "breaches": breach_counts(),
        }


def sentinel_from_env() -> SLOSentinel | None:
    """A sentinel built from the launcher's SLO env contract, or None when no
    target is configured — what :class:`..telemetry.Telemetry` binds by
    default (its per-step hooks then feed ``observe_step``)."""
    targets = slo_targets_from_env()
    if all(v is None for v in targets.values()):
        return None
    return SLOSentinel(step_time_s=targets["step_time_s"],
                       ttft_s=targets["ttft_s"], tpot_s=targets["tpot_s"])
