"""Process-wide metrics registry + Prometheus exposition.

One registry per process that every subsystem publishes into — the goodput
ledger's wall-clock classes, health-guard trips, resilience restarts, the data
loader's batch counter, the optimizer's applied/skipped steps, the serving
engine's request/token counters, and the step timeline's per-step series. Two
export paths share it:

- **pull**: ``MetricsServer`` serves the Prometheus text exposition format on
  an opt-in HTTP port (``launch --metrics_port`` / ACCELERATE_METRICS_PORT) at
  ``/metrics`` (plus a trivial ``/healthz``), so a pod's hosts can be scraped
  like any other fleet service;
- **push**: ``MetricsRegistry.snapshot()`` flattens the same series into a
  dict ``Accelerator.log_telemetry()`` hands to the tracker stack
  (JSONTracker et al.), so runs without a scraper still persist the series.

Publishers push eagerly (a counter ``inc`` under one short lock); sources that
are cheaper to read than to track — the goodput ledger, the transfer
counters, device memory stats — register *collectors* instead, callables the
registry invokes right before each scrape/snapshot so exported gauges are
always current without any per-step work.

This module deliberately imports nothing from the rest of the framework so
any layer (state, optimizer, serving, data loader) can publish without import
cycles.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0,
)


def _escape_label(value) -> str:
    return str(value).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _series_suffix(labelnames, labelvalues) -> str:
    if not labelnames:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(v)}"' for k, v in zip(labelnames, labelvalues)
    )
    return "{" + inner + "}"


class _Metric:
    """One named metric holding a family of label-keyed series."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames, lock):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = lock
        self._series: dict = {}

    def _key(self, labels: dict) -> tuple:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.labelnames}, got "
                f"{tuple(labels)}"
            )
        return tuple(str(labels[k]) for k in self.labelnames)

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(self._key(labels), 0.0))

    def expose(self) -> list[str]:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        with self._lock:
            for key in sorted(self._series):
                lines.append(
                    f"{self.name}{_series_suffix(self.labelnames, key)} "
                    f"{self._series[key]}"
                )
        return lines

    def snapshot(self) -> dict:
        with self._lock:
            return {
                f"{self.name}{_series_suffix(self.labelnames, key)}": float(v)
                for key, v in self._series.items()
            }

    def series_values(self) -> dict:
        """``{label-values tuple: value}`` for every series — the public
        read face for consumers that aggregate by label (the SLO breach
        table), so nothing outside this module touches the storage layout."""
        with self._lock:
            return {key: float(v) for key, v in self._series.items()}


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels):
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease (inc {amount})")
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + float(amount)


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels):
        with self._lock:
            self._series[self._key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels):
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + float(amount)

    def dec(self, amount: float = 1.0, **labels):
        self.inc(-amount, **labels)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help, labelnames, lock, buckets=None):
        super().__init__(name, help, labelnames, lock)
        self.buckets = tuple(sorted(buckets or _DEFAULT_BUCKETS))

    def observe(self, value: float, **labels):
        key = self._key(labels)
        value = float(value)
        with self._lock:
            state = self._series.get(key)
            if state is None:
                state = self._series[key] = [[0] * len(self.buckets), 0.0, 0]
            counts, _, _ = state
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            state[1] += value
            state[2] += 1

    def value(self, **labels):
        """(sum, count) of the series — histograms have no single value."""
        with self._lock:
            state = self._series.get(self._key(labels))
            return (state[1], state[2]) if state else (0.0, 0)

    def expose(self) -> list[str]:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        with self._lock:
            for key in sorted(self._series):
                counts, total, n = self._series[key]
                # observe() fills every bucket the value fits in, so counts
                # are already cumulative — the exposition's le-semantics.
                for b, c in zip(self.buckets, counts):
                    suffix = _series_suffix(self.labelnames + ("le",), key + (b,))
                    lines.append(f"{self.name}_bucket{suffix} {c}")
                inf = _series_suffix(self.labelnames + ("le",), key + ("+Inf",))
                lines.append(f"{self.name}_bucket{inf} {n}")
                tail = _series_suffix(self.labelnames, key)
                lines.append(f"{self.name}_sum{tail} {total}")
                lines.append(f"{self.name}_count{tail} {n}")
        return lines

    def snapshot(self) -> dict:
        with self._lock:
            out = {}
            for key, (_, total, n) in self._series.items():
                tail = _series_suffix(self.labelnames, key)
                out[f"{self.name}_sum{tail}"] = float(total)
                out[f"{self.name}_count{tail}"] = float(n)
            return out


class MetricsRegistry:
    """Get-or-create metric families; see module docstring."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: dict[str, _Metric] = {}
        self._collectors: list = []

    def _get_or_make(self, cls, name, help, labelnames, **kwargs):
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = self._metrics[name] = cls(
                    name, help, labelnames, self._lock, **kwargs
                )
                return metric
            if not isinstance(metric, cls) or metric.labelnames != tuple(labelnames):
                raise ValueError(
                    f"metric {name!r} already registered as {metric.kind} with "
                    f"labels {metric.labelnames}"
                )
            return metric

    def counter(self, name: str, help: str = "", labelnames=()) -> Counter:
        return self._get_or_make(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "", labelnames=()) -> Gauge:
        return self._get_or_make(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "", labelnames=(), buckets=None) -> Histogram:
        return self._get_or_make(Histogram, name, help, labelnames, buckets=buckets)

    # ------------------------------------------------------------- collectors
    def register_collector(self, fn):
        """``fn(registry)`` runs before every scrape/snapshot; refresh gauges
        from sources that are cheaper to read than to track per event."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def collect(self):
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn(self)
            except Exception:  # a broken collector must not poison the scrape
                pass

    # ---------------------------------------------------------------- exports
    def prometheus_text(self) -> str:
        self.collect()
        with self._lock:
            metrics = [self._metrics[name] for name in sorted(self._metrics)]
        lines = []
        for metric in metrics:
            lines.extend(metric.expose())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """Flat ``{"name{label=\"v\"}": value}`` dict for the tracker stack."""
        self.collect()
        with self._lock:
            metrics = list(self._metrics.values())
        out = {}
        for metric in metrics:
            out.update(metric.snapshot())
        return out

    def reset(self):
        """Drop every metric and collector — tests only."""
        global _RESET_GENERATION
        with self._lock:
            self._metrics.clear()
            self._collectors.clear()
            # Let telemetry's install_default_collectors() re-register after
            # a reset (it guards on this marker attribute)...
            vars(self).pop("_at_default_collectors", None)
            # ...and invalidate every module-cached publisher handle (data
            # loader, optimizer, serving, spans) so they re-resolve against
            # the live registry instead of incrementing orphaned metrics.
            _RESET_GENERATION += 1


_REGISTRY = MetricsRegistry()
_RESET_GENERATION = 0


def get_registry() -> MetricsRegistry:
    """The process-wide registry every subsystem publishes into."""
    return _REGISTRY


def reset_generation() -> int:
    """Bumped by :meth:`MetricsRegistry.reset` — hot-path publishers cache
    their metric handles keyed on this so a reset rebuilds them instead of
    leaving increments on orphaned (unexported) objects."""
    return _RESET_GENERATION


def cached_handles(factory):
    """The hoisted-handle discipline for hot-path publishers, in one place:
    returns a zero-arg accessor memoizing ``factory(get_registry())`` keyed on
    :func:`reset_generation`, so the hot path pays only the cached-handle use
    while a registry reset transparently rebuilds."""
    state = [None]  # (generation, handles)

    def get():
        cached = state[0]
        if cached is None or cached[0] != _RESET_GENERATION:
            cached = state[0] = (_RESET_GENERATION, factory(get_registry()))
        return cached[1]

    return get


# ---------------------------------------------------------------- HTTP server
# On-demand profile trigger (telemetry/profiler.py registers the live
# ProfileManager's request_capture here via set_profile_trigger) — an
# injected hook so this module keeps importing nothing from the framework.
_PROFILE_TRIGGER = None


def set_profile_trigger(fn):
    """``fn(steps=N, trigger="http") -> dict`` serves POST /profile; None
    uninstalls (503 until a profiler is armed again)."""
    global _PROFILE_TRIGGER
    _PROFILE_TRIGGER = fn


def profile_trigger():
    """The installed capture trigger, if any — the hook a serving-side SLO
    breach uses to arm a trace of the windows right after the breach
    (telemetry/requests.py), without importing the profiler."""
    return _PROFILE_TRIGGER


# Fleet aggregation provider (telemetry/fleet.py installs the lead host's
# FleetAggregator here) — the same injected-hook pattern as the profile
# trigger, so this module keeps importing nothing from the framework. The
# provider answers GET /fleet (JSON snapshot) and GET /fleet/metrics (joined
# per-host-labeled Prometheus exposition).
_FLEET_PROVIDER = None


def set_fleet_provider(provider):
    """``provider.snapshot() -> dict`` / ``provider.prometheus_text() -> str``
    serve /fleet; None uninstalls (503 until an aggregator is installed)."""
    global _FLEET_PROVIDER
    _FLEET_PROVIDER = provider


def fleet_provider():
    return _FLEET_PROVIDER


# Serving front-end provider (serving_net/frontend.py installs the worker's
# ServingFrontend here; serving_net/router.py installs the router tier's) —
# the third injected hook, so the one HTTP server every worker already runs
# for /metrics also serves the /v1/* serving API (generate/prefixes/stats/
# import) with this module still importing nothing from the framework.
_SERVING_PROVIDER = None


def set_serving_provider(provider):
    """Route ``/v1/*`` to ``provider``; None uninstalls (503 until a serving
    front end is installed). The provider contract:

    - ``handle_get(path, query) -> (status, content_type, bytes) | None``
      (None = 404) serves GET /v1/... (prefix membership, load stats);
    - ``handle_post(path, query, body) -> ("json", status, dict) |
      ("sse", iterator_of_event_strings) | None`` serves POST /v1/...;
      an ``sse`` result streams each yielded string as one
      ``text/event-stream`` chunk (flushed per event — the streaming-token
      wire contract, docs/serving.md)."""
    global _SERVING_PROVIDER
    _SERVING_PROVIDER = provider


def serving_provider():
    return _SERVING_PROVIDER


# Telemetry-journal tail provider (telemetry/journal.py installs the armed
# journal's ``tail`` here) — the same injected-hook pattern as the profile
# trigger, so the collector (commands/timeline.py) can pull any live host's
# journal over the HTTP server every worker already runs, without this
# module importing the journal.
_JOURNAL_PROVIDER = None


def set_journal_provider(provider):
    """``provider(since: int) -> dict`` (a ``TelemetryJournal.tail`` payload:
    schema_version/host/next/records) serves GET /journal?since=N; None
    uninstalls (503 until a journal is armed)."""
    global _JOURNAL_PROVIDER
    _JOURNAL_PROVIDER = provider


def journal_provider():
    return _JOURNAL_PROVIDER


class _MetricsHandler(BaseHTTPRequestHandler):
    registry: MetricsRegistry = None

    def do_GET(self):  # noqa: N802 (http.server contract)
        path = self.path.split("?")[0].rstrip("/") or "/"
        if path == "/metrics":
            body = self.registry.prometheus_text().encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif path in ("/", "/healthz"):
            body, ctype = b"ok\n", "text/plain"
        elif path.startswith("/v1/"):
            self._serve_v1_get(path)
            return
        elif path == "/journal":
            provider = _JOURNAL_PROVIDER
            if provider is None:
                self._respond_json(
                    503,
                    {"error": "no telemetry journal armed in this process "
                              "(set ACCELERATE_JOURNAL_DIR / launch "
                              "--journal_dir)"},
                )
                return
            from urllib.parse import parse_qs, urlparse

            try:
                since = int(parse_qs(urlparse(self.path).query)
                            .get("since", ["0"])[0])
            except (ValueError, TypeError):
                self._respond_json(
                    400, {"error": "since must be an integer sequence number"}
                )
                return
            try:
                self._respond_json(200, provider(since))
            except Exception as exc:  # a bad tail must not kill the server
                self._respond_json(500, {"error": repr(exc)})
            return
        elif path in ("/fleet", "/fleet/metrics"):
            provider = _FLEET_PROVIDER
            if provider is None:
                self._respond_json(
                    503,
                    {"error": "no fleet aggregator installed in this process "
                              "(lead host with ACCELERATE_FLEET_METRICS=1)"},
                )
                return
            try:
                if path == "/fleet":
                    import json

                    body = json.dumps(provider.snapshot()).encode()
                    ctype = "application/json"
                else:
                    body = provider.prometheus_text().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
            except Exception as exc:  # a bad scrape must not kill the server
                self._respond_json(500, {"error": repr(exc)})
                return
        else:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # ------------------------------------------------------- serving (/v1/*)
    def _local_serving_provider(self):
        """THIS server's provider override when one is attached
        (``MetricsServer.set_serving`` — multi-role single-process rigs and
        tests), else the process-global install."""
        return getattr(self.server, "at_serving", None) or _SERVING_PROVIDER

    def _serve_v1_get(self, path: str):
        from urllib.parse import parse_qs, urlparse

        provider = self._local_serving_provider()
        if provider is None:
            self._respond_json(
                503, {"error": "no serving front end installed in this process "
                               "(serving_net.ServingFrontend.install())"},
            )
            return
        query = parse_qs(urlparse(self.path).query)
        try:
            result = provider.handle_get(path, query)
        except Exception as exc:  # the provider must not take the server down
            self._respond_json(500, {"error": repr(exc)})
            return
        if result is None:
            self.send_error(404)
            return
        status, ctype, body = result
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _serve_v1_post(self, path: str, query: dict):
        provider = self._local_serving_provider()
        if provider is None:
            self._respond_json(
                503, {"error": "no serving front end installed in this process "
                               "(serving_net.ServingFrontend.install())"},
            )
            return
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        try:
            result = provider.handle_post(path, query, body)
        except Exception as exc:
            self._respond_json(500, {"error": repr(exc)})
            return
        if result is None:
            self.send_error(404)
            return
        if result[0] == "json":
            _, status, payload = result
            self._respond_json(status, payload)
            return
        # ("sse", iterator): stream each yielded event string as one flushed
        # chunk — chunked transfer, no Content-Length, connection closes when
        # the iterator drains (the SSE wire contract, docs/serving.md).
        _, events = result
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Connection", "close")
        self.end_headers()
        try:
            for event in events:
                self.wfile.write(event.encode())
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            close = getattr(events, "close", None)
            if close is not None:
                close()  # unsubscribe: the client hung up mid-stream

    def do_POST(self):  # noqa: N802 (http.server contract)
        """POST /profile?steps=N — arm an on-demand trace capture of the next
        N step boundaries on THIS worker (each worker serves its own port).
        POST /v1/* routes to the installed serving provider (generate /
        import — the streaming front end)."""
        from urllib.parse import parse_qs, urlparse

        parsed = urlparse(self.path)
        if parsed.path.startswith("/v1/"):
            self._serve_v1_post(parsed.path.rstrip("/"),
                                parse_qs(parsed.query))
            return
        if parsed.path not in ("/profile", "/profile/"):
            self.send_error(404)
            return
        if _PROFILE_TRIGGER is None:
            self._respond_json(
                503, {"accepted": False, "reason": "no profiler armed in this process"}
            )
            return
        try:
            steps = int(parse_qs(parsed.query).get("steps", ["1"])[0])
            if steps < 1:
                raise ValueError
        except (ValueError, TypeError):
            self._respond_json(
                400, {"accepted": False, "reason": "steps must be a positive integer"}
            )
            return
        try:
            result = _PROFILE_TRIGGER(steps=steps, trigger="http")
        except Exception as exc:  # the trigger must not take the server down
            self._respond_json(500, {"accepted": False, "reason": repr(exc)})
            return
        self._respond_json(200 if result.get("accepted") else 409, result)

    def _respond_json(self, status: int, payload: dict):
        import json

        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # scrapes must not spam stderr
        pass


class MetricsServer:
    """Background Prometheus endpoint. ``port=0`` binds an ephemeral port
    (tests); ``start()`` returns the bound port."""

    def __init__(self, port: int, registry: MetricsRegistry | None = None,
                 host: str = "0.0.0.0"):
        self.registry = registry or get_registry()
        self._host = host
        self._requested_port = int(port)
        self._httpd = None
        self._thread = None

    @property
    def port(self) -> int | None:
        return self._httpd.server_address[1] if self._httpd is not None else None

    def start(self) -> int:
        if self._httpd is not None:
            return self.port
        handler = type("Handler", (_MetricsHandler,), {"registry": self.registry})
        self._httpd = ThreadingHTTPServer((self._host, self._requested_port), handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="at-metrics-server", daemon=True
        )
        self._thread.start()
        return self.port

    def set_serving(self, provider):
        """Route THIS server's ``/v1/*`` to ``provider``, overriding the
        process-global :func:`set_serving_provider` install — what lets one
        process host several serving roles on several ports (in-process
        tests; a colocated router + worker rig)."""
        if self._httpd is None:
            raise RuntimeError("start() the server before attaching a provider")
        self._httpd.at_serving = provider

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._thread = None


_SERVER: MetricsServer | None = None


def default_server() -> MetricsServer | None:
    """The running process-wide endpoint, if any (started by PartialState's
    env install or an earlier start_default_server)."""
    return _SERVER


def start_default_server(port: int, registry: MetricsRegistry | None = None) -> MetricsServer:
    """Idempotent process-wide endpoint: the first caller binds, later callers
    get the running server (a port mismatch is logged, not fatal — PartialState
    and an explicit Telemetry config may both ask)."""
    global _SERVER
    if _SERVER is not None:
        if port not in (_SERVER._requested_port, _SERVER.port):
            import logging

            logging.getLogger(__name__).warning(
                "metrics server already listening on port %s; ignoring request "
                "for port %s", _SERVER.port, port,
            )
        return _SERVER
    server = MetricsServer(port, registry=registry)
    # Publish the global only after a successful bind: a failed start must
    # not leave a zombie server that every later caller "reuses".
    server.start()
    _SERVER = server
    return _SERVER


def stop_default_server():
    global _SERVER
    if _SERVER is not None:
        _SERVER.stop()
        _SERVER = None
