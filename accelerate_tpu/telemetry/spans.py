"""Nestable host-side spans with XLA-trace name parity.

``span("data_load")`` times a block of host code into a lock-free ring buffer
AND enters a ``jax.profiler.TraceAnnotation`` of the same name, so the label a
user (or the framework — prepare/train_step/checkpoint/gather are
pre-instrumented) sees in the step timeline is the label they find in a
captured XLA/perfetto trace. Spans nest; each record carries its depth and its
``outer/inner`` path.

The ring is a fixed-size slot array indexed by an ``itertools.count`` — the
one CPython-atomic primitive that makes concurrent pushes (orbax background
writers, the serving loop, the train thread) safe without a lock on the hot
path. A full ring overwrites the oldest records; ``total`` keeps counting so
wraparound is observable.

Span durations also land in the shared metrics registry as the
``accelerate_span_seconds{name=...}`` histogram, so the Prometheus endpoint
answers "where does the wall-clock go" without a trace capture.
"""

from __future__ import annotations

import itertools
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass

try:  # host-side runtime trace annotation; absent on exotic builds
    from jax.profiler import TraceAnnotation as _TraceAnnotation
except Exception:  # pragma: no cover
    _TraceAnnotation = None


@dataclass
class SpanRecord:
    name: str
    start_s: float  # time.perf_counter() at entry
    duration_s: float
    depth: int  # 0 = top-level
    path: str  # "outer/inner"


class SpanRing:
    """Fixed-capacity overwrite-oldest span store; push is lock-free.

    Each slot stores ``(index, record)`` where the index comes from one
    ``itertools.count`` draw — the CPython-atomic primitive — and ordering /
    ``total`` are DERIVED from the stored indices at read time. There is no
    separate length bookkeeping a concurrent pusher could regress (the
    read-modify-write that a plain ``self._n = i + 1`` hides)."""

    def __init__(self, capacity: int = 4096):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = capacity
        self._slots = [None] * capacity
        self._ctr = itertools.count()

    def push(self, record: SpanRecord):
        i = next(self._ctr)  # atomic under the GIL: unique slot per push
        self._slots[i % self.capacity] = (i, record)

    @property
    def total(self) -> int:
        """Spans ever pushed (keeps growing after wraparound)."""
        return max((s[0] for s in self._slots if s is not None), default=-1) + 1

    def snapshot(self) -> list[SpanRecord]:
        """The retained records, oldest first."""
        kept = sorted((s for s in self._slots if s is not None), key=lambda s: s[0])
        return [record for _, record in kept]

    def clear(self):
        self._slots = [None] * self.capacity
        self._ctr = itertools.count()


_RING = SpanRing()
_tls = threading.local()
_SPAN_HIST = None


def get_span_ring() -> SpanRing:
    return _RING


def reset_spans():
    _RING.clear()


def _span_hist():
    global _SPAN_HIST
    if _SPAN_HIST is None:
        from .metrics import cached_handles

        _SPAN_HIST = cached_handles(lambda registry: registry.histogram(
            "accelerate_span_seconds",
            "Host wall-clock of instrumented spans",
            labelnames=("name",),
        ))
    return _SPAN_HIST()


@contextmanager
def span(name: str, ring: SpanRing | None = None, record_metric: bool = True):
    """Time a block into the span ring (and the XLA trace). Nestable; safe on
    any thread; never raises from instrumentation."""
    ring = _RING if ring is None else ring
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    path = "/".join(stack) + "/" + name if stack else name
    stack.append(name)
    ann = _TraceAnnotation(name) if _TraceAnnotation is not None else None
    if ann is not None:
        ann.__enter__()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        duration = time.perf_counter() - t0
        if ann is not None:
            ann.__exit__(None, None, None)
        stack.pop()
        ring.push(SpanRecord(name=name, start_s=t0, duration_s=duration,
                             depth=len(stack), path=path))
        if record_metric:
            try:
                _span_hist().observe(duration, name=name)
            except Exception:  # pragma: no cover - instrumentation never raises
                pass
            # Durable tee (telemetry/journal.py): no-op when journaling is
            # off; pure host bookkeeping (the record above) when on.
            try:
                from .journal import journal_event

                journal_event("span", name=name, path=path,
                              depth=len(stack), duration_s=round(duration, 6))
            except Exception:  # pragma: no cover - instrumentation never raises
                pass
