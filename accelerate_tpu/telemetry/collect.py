"""Fleet journal collection — merge per-host journals into ONE causal view.

The journal (:mod:`.journal`) leaves one JSONL file per host; this module is
the read side the CLIs drive:

- :func:`read_journal_dir` / :func:`fetch_journal` gather every rank's
  records (shared filesystem, or the ``GET /journal?since=`` tail each
  worker's metrics server exposes);
- :func:`clock_skew` recovers the per-host wall-clock skew from the latest
  barrier-aligned ``clock_sync`` record (journal.exchange_clock_sync);
- :func:`chrome_trace` renders the merged, skew-corrected fleet into one
  Chrome-trace/Perfetto JSON: one ``pid`` per host, lanes (``tid``) for
  steps / request legs / spans / flight events / goodput deltas, and flow
  arrows binding a request's router→prefill→handoff→decode legs under its
  rid — ``accelerate-tpu timeline``;
- :func:`latest_run_summary` / :func:`compare_runs` power ``accelerate-tpu
  report``: run-over-run deltas classified regression / improvement /
  benign (the analysis/fingerprint.py ``classify_drift`` idiom), exit 1 on
  regression.

Everything here is cold-path host code over already-written files — the
collector never touches a device.
"""

from __future__ import annotations

import glob
import json
import os
import re

# Chrome-trace lanes (tid) inside each host's pid row.
TID_STEPS = 0
TID_REQUESTS = 1
TID_SPANS = 2
TID_EVENTS = 3
TID_GOODPUT = 4

_TID_NAMES = {
    TID_STEPS: "steps",
    TID_REQUESTS: "requests",
    TID_SPANS: "spans",
    TID_EVENTS: "events",
    TID_GOODPUT: "goodput",
}

# run_summary fields by direction, for :func:`compare_runs` (the
# classify_drift idiom: one directional rule per field class).
LOWER_BETTER = ("step_p50", "step_p90", "step_mean", "step_max",
                "ttft_mean", "ttft_max", "tpot_mean", "tpot_max")
HIGHER_BETTER = ("mfu", "tokens_per_s", "goodput_fraction",
                 "spec_acceptance_rate", "accepted_tokens_per_s")
COUNT_WORSE = ("breaches", "retries", "restarts", "evictions")


# ------------------------------------------------------------------ gathering
def read_journal_dir(directory: str) -> dict[int, list]:
    """All retained records per host from ``journal_<rank>.jsonl`` files
    (rotated ``.1`` generations included), each host's records seq-ordered."""
    by_host: dict[int, list] = {}
    for path in sorted(glob.glob(os.path.join(directory, "journal_*.jsonl*"))):
        match = re.search(r"journal_(\d+)\.jsonl(\.1)?$", path)
        if match is None:
            continue
        host = int(match.group(1))
        records = by_host.setdefault(host, [])
        try:
            with open(path, encoding="utf-8") as fh:
                for raw in fh:
                    try:
                        records.append(json.loads(raw))
                    except ValueError:
                        continue  # torn tail line of a live file
        except OSError:
            continue
    for records in by_host.values():
        records.sort(key=lambda r: r.get("seq", 0))
    return {h: r for h, r in by_host.items() if r}


def fetch_journal(endpoint: str, since: int = 0, timeout_s: float = 10.0) -> dict:
    """One worker's journal tail over its metrics server
    (``GET http://<endpoint>/journal?since=N``) — the live-fleet gather path
    when the collector has no shared filesystem. Returns the tail payload
    (schema_version/host/next/records); raises on transport errors so the
    CLI can report which host was unreachable."""
    from urllib.request import urlopen

    url = f"http://{endpoint}/journal?since={int(since)}"
    with urlopen(url, timeout=timeout_s) as response:
        return json.loads(response.read().decode())


# ------------------------------------------------------------ clock alignment
def clock_skew(records_by_host: dict[int, list]) -> dict[int, float]:
    """Per-host wall-clock skew versus rank 0, from the LATEST ``clock_sync``
    record anywhere in the fleet (every rank journals the full map, so any
    surviving journal recovers it). Hosts absent from the map — or a fleet
    that never synced — correct by 0.0 (merge falls back to raw wall)."""
    best = None
    for records in records_by_host.values():
        for record in records:
            if record.get("kind") != "clock_sync":
                continue
            if best is None or record.get("wall", 0) > best.get("wall", 0):
                best = record
    skew: dict[int, float] = {}
    if best is not None:
        for rank, value in (best.get("skew") or {}).items():
            try:
                skew[int(rank)] = float(value)
            except (TypeError, ValueError):
                continue
    return skew


def corrected_wall(record: dict, skew: dict[int, float]) -> float:
    """A record's wall stamp mapped onto rank 0's clock."""
    return float(record.get("wall", 0.0)) - skew.get(int(record.get("host", 0)), 0.0)


def merge_records(records_by_host: dict[int, list]) -> list:
    """Every host's records in one skew-corrected causal order; each record
    gains ``t`` (corrected wall seconds)."""
    skew = clock_skew(records_by_host)
    merged = []
    for records in records_by_host.values():
        for record in records:
            merged.append(dict(record, t=corrected_wall(record, skew)))
    merged.sort(key=lambda r: r["t"])
    return merged


# ------------------------------------------------------------- chrome tracing
def _parse_steps(spec: str | None) -> tuple[int, int] | None:
    """``"A-B"`` / ``"A"`` → inclusive step range."""
    if not spec:
        return None
    match = re.fullmatch(r"(\d+)(?:-(\d+))?", spec.strip())
    if match is None:
        raise ValueError(f"--steps expects 'A' or 'A-B', got {spec!r}")
    lo = int(match.group(1))
    hi = int(match.group(2)) if match.group(2) else lo
    return (lo, hi)


def chrome_trace(records_by_host: dict[int, list], rid: int | None = None,
                 steps: str | None = None) -> dict:
    """The merged fleet as one Chrome-trace JSON (``chrome://tracing`` /
    Perfetto ``traceEvents`` format): pid = host rank, lanes per stream,
    ``ts``/``dur`` in microseconds rebased to the earliest corrected stamp.
    A request's legs carry flow arrows (``ph: s/t/f`` sharing ``id=rid``) so
    router→prefill→handoff→decode render causally linked across hosts.
    ``rid`` keeps one request's legs; ``steps`` ("A-B") keeps that step
    range plus everything inside its corrected time window."""
    skew = clock_skew(records_by_host)
    step_range = _parse_steps(steps)
    rows = []  # (host, corrected_t, record)
    for host, records in records_by_host.items():
        for record in records:
            rows.append((host, corrected_wall(record, skew), record))
    if not rows:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t_base = min(t for _, t, _ in rows)

    def us(t: float) -> float:
        return round((t - t_base) * 1e6, 1)

    if step_range is not None:
        window = [t for _, t, r in rows
                  if r.get("kind") == "step" and r.get("step") is not None
                  and step_range[0] <= r["step"] <= step_range[1]]
        if window:
            lo, hi = min(window), max(window)
            # A step record's stamp is the boundary END; open the window by
            # the longest kept step so the step's own body stays inside.
            pad = max((r.get("wall_s", 0.0) * r.get("steps", 1)
                       for _, t, r in rows
                       if r.get("kind") == "step" and t in window), default=0.0)
            rows = [(h, t, r) for h, t, r in rows if lo - pad - 1.0 <= t <= hi + 1.0]
        else:
            rows = []
    if rid is not None:
        rows = [(h, t, r) for h, t, r in rows if r.get("rid") == rid]

    events: list = []
    hosts_used: set[int] = set()
    lanes_used: set[tuple[int, int]] = set()
    rid_legs: dict[int, list] = {}
    for host, t, record in sorted(rows, key=lambda x: x[1]):
        kind = record.get("kind")
        args = {k: v for k, v in record.items()
                if k not in ("seq", "host", "t_s", "wall", "kind")}
        if kind == "step":
            dur = max(float(record.get("wall_s", 0.0)) * int(record.get("steps", 1)), 1e-6)
            step = record.get("step")
            name = f"step {step}" if step is not None else f"window x{record.get('steps', 1)}"
            tid = TID_STEPS
            events.append({"ph": "X", "pid": host, "tid": tid, "name": name,
                           "cat": "step", "ts": us(t - dur), "dur": round(dur * 1e6, 1),
                           "args": args})
        elif kind == "span":
            dur = max(float(record.get("duration_s", 0.0)), 1e-6)
            tid = TID_SPANS
            events.append({"ph": "X", "pid": host, "tid": tid,
                           "name": str(record.get("name")), "cat": "span",
                           "ts": us(t - dur), "dur": round(dur * 1e6, 1),
                           "args": args})
        elif kind == "request_leg":
            tid = TID_REQUESTS
            name = f"{record.get('tier', '?')}:{record.get('leg', '?')}"
            event = {"ph": "X", "pid": host, "tid": tid, "name": name,
                     "cat": "request", "ts": us(t), "dur": 1, "args": args}
            events.append(event)
            if record.get("rid") is not None:
                rid_legs.setdefault(int(record["rid"]), []).append(event)
        elif kind in ("flight", "handoff_wire", "goodput"):
            if kind == "goodput":
                dur = max(float(record.get("seconds", 0.0)), 1e-6)
                tid = TID_GOODPUT
                events.append({"ph": "X", "pid": host, "tid": tid,
                               "name": f"goodput:{record.get('category')}",
                               "cat": "goodput", "ts": us(t - dur),
                               "dur": round(dur * 1e6, 1), "args": args})
            else:
                tid = TID_EVENTS
                label = (record.get("event") if kind == "flight"
                         else f"handoff_wire:{record.get('direction')}")
                event = {"ph": "X", "pid": host, "tid": tid,
                         "name": str(label), "cat": "event",
                         "ts": us(t), "dur": 1, "args": args}
                events.append(event)
                if record.get("rid") is not None:
                    rid_legs.setdefault(int(record["rid"]), []).append(event)
        else:
            # journal_open / clock_sync / run_summary: bookkeeping, not lanes.
            continue
        hosts_used.add(host)
        lanes_used.add((host, tid))

    # Flow arrows: one chain per rid through its legs in corrected order —
    # the causal link a cross-host retry/handoff renders as.
    for rid_key, legs in rid_legs.items():
        if len(legs) < 2:
            continue
        for i, leg in enumerate(legs):
            phase = "s" if i == 0 else ("f" if i == len(legs) - 1 else "t")
            flow = {"ph": phase, "pid": leg["pid"], "tid": leg["tid"],
                    "name": f"rid {rid_key}", "cat": "request",
                    "id": rid_key, "ts": leg["ts"]}
            if phase == "f":
                flow["bp"] = "e"  # bind to the enclosing slice
            events.append(flow)

    metadata = []
    for host in sorted(hosts_used):
        metadata.append({"ph": "M", "pid": host, "name": "process_name",
                         "args": {"name": f"host {host}"}})
    for host, tid in sorted(lanes_used):
        metadata.append({"ph": "M", "pid": host, "tid": tid,
                         "name": "thread_name",
                         "args": {"name": _TID_NAMES.get(tid, str(tid))}})
    return {
        "traceEvents": metadata + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "hosts": sorted(hosts_used),
            "skew": {str(h): s for h, s in clock_skew(records_by_host).items()},
            "t_base_wall": t_base,
        },
    }


# ------------------------------------------------------------------ reporting
def latest_run_summary(records_by_host: dict[int, list]) -> dict | None:
    """The newest ``run_summary`` record in the fleet (rank 0's preferred on
    a wall-clock tie — it owns the canonical timeline)."""
    best = None
    for host in sorted(records_by_host):
        for record in records_by_host[host]:
            if record.get("kind") != "run_summary":
                continue
            if best is None or record.get("wall", 0) > best.get("wall", 0):
                best = record
    return best


def _fleet_leg_aggregates(records_by_host: dict[int, list]) -> dict:
    """TTFT/TPOT moments over EVERY host's request legs. A per-host
    ``run_summary`` only sees the legs its own process booked — on a
    disaggregated rig the router host finalizes but the decode tier owns
    first_token — so the collector recomputes the fleet truth."""
    aggregates: dict = {}
    for name, field in (("ttft", "ttft_s"), ("tpot", "tpot_s")):
        values = [record[field] for records in records_by_host.values()
                  for record in records
                  if record.get("kind") == "request_leg"
                  and isinstance(record.get(field), (int, float))]
        if values:
            aggregates[f"{name}_mean"] = round(sum(values) / len(values), 6)
            aggregates[f"{name}_max"] = round(max(values), 6)
            aggregates[f"{name}_count"] = len(values)
    return aggregates


def load_summary(path: str) -> dict:
    """A run summary from a journal directory (latest ``run_summary``
    record, its TTFT/TPOT fields widened to the whole fleet's legs) or a
    JSON file a previous ``report --out`` wrote."""
    if os.path.isdir(path):
        records_by_host = read_journal_dir(path)
        summary = latest_run_summary(records_by_host)
        if summary is None:
            raise ValueError(
                f"no run_summary record in {path!r} — the run never "
                "finalized (bench.py finalizes when journaling is armed; "
                "call TelemetryJournal.finalize_run from custom loops)"
            )
        return dict(summary, **_fleet_leg_aggregates(records_by_host))
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if not isinstance(data, dict):
        raise ValueError(f"{path!r} is not a run-summary JSON object")
    return data


def _row(field: str, kind: str, prev, current, detail: str) -> dict:
    return {"field": field, "kind": kind, "prev": prev, "current": current,
            "detail": detail}


def compare_runs(prev: dict, current: dict, tolerance: float = 0.1) -> list[dict]:
    """Classify run-over-run deltas (the classify_drift idiom): each
    comparable field becomes one row with ``kind`` regression / improvement
    / benign (or ``note`` for the fingerprint identity line). ``tolerance``
    is the relative slack both directions; count fields regress on ANY
    increase. The caller exits 1 when any row is a regression."""
    rows: list[dict] = []
    fp_prev, fp_cur = prev.get("fingerprint"), current.get("fingerprint")
    if fp_prev and fp_cur and fp_prev != fp_cur:
        rows.append(_row(
            "fingerprint", "note", fp_prev, fp_cur,
            "program identity changed between runs — deltas below may be "
            "intended",
        ))

    def numeric(summary, field):
        value = summary.get(field)
        return float(value) if isinstance(value, (int, float)) else None

    for field in LOWER_BETTER + HIGHER_BETTER:
        p, c = numeric(prev, field), numeric(current, field)
        if p is None or c is None:
            continue
        delta = (c - p) / max(abs(p), 1e-9)
        worse = delta > tolerance if field in LOWER_BETTER else delta < -tolerance
        better = delta < -tolerance if field in LOWER_BETTER else delta > tolerance
        kind = "regression" if worse else ("improvement" if better else "benign")
        rows.append(_row(field, kind, p, c,
                         f"{delta:+.1%} vs previous (tolerance ±{tolerance:.0%})"))
    for field in COUNT_WORSE:
        p, c = prev.get(field), current.get(field)
        if not isinstance(p, (int, float)) or not isinstance(c, (int, float)):
            continue
        if c > p:
            kind, detail = "regression", f"count rose {int(p)} → {int(c)}"
        elif c < p:
            kind, detail = "improvement", f"count fell {int(p)} → {int(c)}"
        else:
            kind, detail = "benign", f"unchanged at {int(c)}"
        rows.append(_row(field, kind, p, c, detail))
    return rows
