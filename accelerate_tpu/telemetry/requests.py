"""Per-request serving traces — the lifecycle record behind every TTFT.

The serving engine's histograms say *that* TTFT regressed; the question that
decides the fix is what happened to the slow requests: were they deferred at
admission, did their prefill chunk behind a long neighbor, did decode windows
stall? :class:`RequestTracer` keeps one structured record per request in a
bounded overwrite-oldest ring, fed by the host-side points the
``ContinuousBatcher`` loop already passes through:

``submit`` → admission decision (``admit``/``defer``/``escalate``, with queue
wait and aliased-block count) → prefill chunks (sizes, in dispatch order) →
first token (TTFT) → decode windows → ``finish``/``cancel`` (tokens out,
TPOT).

Recording discipline matches the engine's one-window-lookahead sync: every
hook fires from host bookkeeping the loop performs anyway (admission surgery,
the report processed one window AFTER it was dispatched), so tracing drains
only through the existing counted no-blocking-fetch discipline and adds ZERO
device transfers — the steady-state pin tests/test_fleet.py holds against
the transfer counters.

SLO coupling: when the engine carries :class:`~..serving.SLOTargets`, a
first-token observation over the TTFT budget (or a finish over the TPOT
budget) books through :func:`..telemetry.slo.record_breach` — counter +
flight-recorder event + rate-limited warning — and a TTFT breach arms an XLA
trace capture of the next decode windows via the profile trigger the metrics
server installs (:func:`..telemetry.metrics.set_profile_trigger`), so the
evidence for the breach is captured while the regression is still live.
"""

from __future__ import annotations

import time
from collections import OrderedDict

# Ring bound on retained request records (the serving engine's _SLO_HISTORY
# idiom): a long-lived engine serves unbounded requests; the Prometheus
# histograms keep the full distributions, the ring keeps the recent evidence.
# Tunable per launch via ACCELERATE_TRACE_RING (tri-state; an explicit 0
# scrubs an inherited value back to this default).
DEFAULT_CAPACITY = 1024

# Decode windows a TTFT-breach-armed capture traces.
BREACH_CAPTURE_STEPS = 2


# The step timeline's nearest-rank quantile — one implementation, so serving
# request quantiles can never diverge from step-time quantiles.
from .timeline import _quantile


class RequestTracer:
    """Bounded per-request lifecycle ring; see module docstring.

    ``slo`` is the engine's :class:`~..serving.SLOTargets` (None = no breach
    evaluation); ``arm_profile_on_breach`` lets a TTFT breach arm a trace
    capture through the installed profile trigger; ``clock`` is injectable
    for deterministic tests. ``capacity=None`` (the engine default) resolves
    from ACCELERATE_TRACE_RING, falling back to :data:`DEFAULT_CAPACITY`.
    """

    def __init__(self, capacity: int | None = None, slo=None,
                 arm_profile_on_breach: bool = True, clock=time.monotonic):
        if capacity is None:
            from .flight import ring_capacity_from_env
            from ..utils.constants import ENV_TRACE_RING

            capacity = ring_capacity_from_env(ENV_TRACE_RING, DEFAULT_CAPACITY)
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.slo = slo
        self.arm_profile_on_breach = bool(arm_profile_on_breach)
        self._clock = clock
        self._ring: OrderedDict[int, dict] = OrderedDict()
        self.total = 0       # records ever started (keeps counting past evictions)
        self.breaches = 0    # breaches this tracer booked

    # ------------------------------------------------------------- recording
    def _get(self, rid: int) -> dict | None:
        return self._ring.get(rid)

    def _journal(self, record: dict, leg: str, **fields):
        """Durable leg emission (telemetry/journal.py): each lifecycle point
        also lands a ``request_leg`` record — rid + tier are the causal keys
        the fleet-timeline collector joins cross-host legs on. Host
        bookkeeping only (the fields are already on the record); a no-op
        when journaling is off."""
        from .journal import journal_event

        journal_event("request_leg", rid=record["rid"], leg=leg,
                      tier=record["tier"], **fields)

    def submit(self, rid: int, prompt_tokens: int, submit_t: float | None = None,
               tier: str = "unified"):
        """``tier`` names which serving tier this record was made on
        (``unified`` / ``router`` / ``prefill`` / ``decode`` — serving_net
        roles): a disaggregated request keeps ONE rid across tiers (the
        router assigns it and threads it through every ``submit``), so the
        per-tier records join into one cross-host trace by rid, each tier
        attributing its own queue_wait/chunks/ttft share."""
        record = {
            "rid": int(rid),
            "tier": str(tier),
            "state": "queued",
            "prompt_tokens": int(prompt_tokens),
            "submit_t": float(submit_t if submit_t is not None else self._clock()),
            "decision": None,
            "queue_wait_s": None,
            "defers": 0,
            "aliased_blocks": 0,
            "planned_chunks": None,
            "chunks": [],
            "ttft_s": None,
            "decode_windows": 0,
            "spec_proposed": 0,
            "spec_accepted": 0,
            "tokens_out": None,
            "tpot_s": None,
            "total_s": None,
            "handoff": None,
            "retries": [],
            "breached": [],
        }
        self._ring[rid] = record
        self.total += 1
        while len(self._ring) > self.capacity:
            self._ring.popitem(last=False)  # overwrite-oldest
        self._journal(record, "submit", prompt_tokens=int(prompt_tokens))

    def admit(self, rid: int, decision: str = "admit", aliased_blocks: int = 0,
              chunks: int = 1):
        """The admission verdict (``admit`` or ``escalate``) — also a
        flight-recorder ``admission`` event, so a black-box dump shows the
        scheduling decisions around a fault."""
        record = self._get(rid)
        if record is None:
            return
        now = self._clock()
        record["state"] = "prefill"
        record["decision"] = str(decision)
        record["queue_wait_s"] = round(now - record["submit_t"], 6)
        record["aliased_blocks"] = int(aliased_blocks)
        record["planned_chunks"] = int(chunks)
        # get_flight_recorder (not record_event): admission decisions must
        # land in the black box even when nothing else created it yet.
        from .flight import get_flight_recorder

        get_flight_recorder().record(
            "admission", rid=int(rid), decision=str(decision),
            queue_wait_s=record["queue_wait_s"],
        )
        self._journal(record, "admit", decision=str(decision),
                      queue_wait_s=record["queue_wait_s"])

    def defer(self, rid: int):
        """A prefill chunk deferred in favor of decode (TPOT pacing). Counted
        per request; only the FIRST defer lands a flight event — a long
        deferral would otherwise flood the ring with one event per engine
        iteration."""
        record = self._get(rid)
        if record is None:
            return
        record["defers"] += 1
        if record["defers"] == 1:
            from .flight import get_flight_recorder

            get_flight_recorder().record("admission", rid=int(rid),
                                         decision="defer")

    def prefill_chunk(self, rid: int, tokens: int, final: bool):
        record = self._get(rid)
        if record is None:
            return
        record["chunks"].append(int(tokens))
        if final:
            record["state"] = "decode"
        self._journal(record, "prefill_chunk", tokens=int(tokens),
                      final=bool(final))

    def first_token(self, rid: int, at: float | None = None):
        """First sampled token observed for ``rid`` (the engine calls this
        from the host points it already pays — the admit return or the
        lookahead report). Evaluates the TTFT target and, on breach, arms a
        profile capture of the next decode windows."""
        record = self._get(rid)
        if record is None or record["ttft_s"] is not None:
            return
        now = float(at if at is not None else self._clock())
        record["ttft_s"] = round(max(0.0, now - record["submit_t"]), 6)
        self._journal(record, "first_token", ttft_s=record["ttft_s"])
        target = getattr(self.slo, "ttft_s", None) if self.slo is not None else None
        if target is not None and record["ttft_s"] > target:
            record["breached"].append("ttft")
            self.breaches += 1
            from .slo import record_breach

            record_breach("ttft", record["ttft_s"], target, rid=rid)
            if self.arm_profile_on_breach:
                self._arm_profile(rid)

    def decode_window(self, rid: int):
        record = self._get(rid)
        if record is not None:
            record["decode_windows"] += 1

    def spec_round(self, rid: int, *, proposed: int, accepted: int):
        """One speculative verify round for ``rid``: the draft proposed
        ``proposed`` tokens, the target accepted ``accepted`` of them (the
        window's +1 bonus token is NOT counted — acceptance rate stays the
        draft-quality signal). Host bookkeeping only; the counts ride the
        record so ``summary()`` and the journal ``run_summary`` can report
        per-run acceptance without another device fetch."""
        record = self._get(rid)
        if record is None:
            return
        record["spec_proposed"] += int(proposed)
        record["spec_accepted"] += int(accepted)

    def finish(self, rid: int, tokens_out: int, tpot_s: float | None = None,
               at: float | None = None):
        record = self._get(rid)
        if record is None:
            return
        now = float(at if at is not None else self._clock())
        record["state"] = "finished"
        record["tokens_out"] = int(tokens_out)
        record["total_s"] = round(max(0.0, now - record["submit_t"]), 6)
        if tpot_s is None and record["ttft_s"] is not None and tokens_out > 1:
            tpot_s = (now - record["submit_t"] - record["ttft_s"]) / (tokens_out - 1)
        if tpot_s is not None:
            record["tpot_s"] = round(max(0.0, float(tpot_s)), 6)
            target = getattr(self.slo, "tpot_s", None) if self.slo is not None else None
            if target is not None and record["tpot_s"] > target:
                record["breached"].append("tpot")
                self.breaches += 1
                from .slo import record_breach

                record_breach("tpot", record["tpot_s"], target, rid=rid)
        fields = dict(tokens_out=int(tokens_out), tpot_s=record["tpot_s"],
                      total_s=record["total_s"])
        if record["spec_proposed"]:
            # Spec tallies ride the finish leg (one field, not one record per
            # verify round) — finalize_run aggregates accepted-tokens/s from
            # here.
            fields["spec_proposed"] = record["spec_proposed"]
            fields["spec_accepted"] = record["spec_accepted"]
        self._journal(record, "finish", **fields)

    def handoff(self, rid: int, direction: str, bytes: int = 0, blocks: int = 0,
                endpoint: str | None = None):
        """Book a KV-chain handoff leg on the record (``direction``:
        ``out`` — this tier exported the chain, its record closes as
        ``handed_off``; ``in`` — this tier imported it and will decode).
        Also a flight-recorder event, so a black-box dump shows chain
        movement around a fault. The rid is router-assigned and shared
        across tiers, so /fleet consumers join the ``out`` and ``in`` legs
        into one trace."""
        record = self._get(rid)
        if record is None:
            return
        record["handoff"] = {
            "direction": str(direction), "bytes": int(bytes),
            "blocks": int(blocks), "endpoint": endpoint,
        }
        if direction == "out":
            record["state"] = "handed_off"
        elif direction == "in":
            # The imported chain arrives armed for decode: prefill happened
            # on another tier, so this record skips queued/prefill states.
            record["state"] = "decode"
        from .flight import get_flight_recorder

        get_flight_recorder().record(
            "handoff", rid=int(rid), direction=str(direction),
            bytes=int(bytes), blocks=int(blocks),
        )
        self._journal(record, "handoff", direction=str(direction),
                      bytes=int(bytes), blocks=int(blocks),
                      endpoint=endpoint)

    def retry(self, rid: int, attempt: int, reason: str,
              endpoint: str | None = None):
        """Book one retry leg on the record: the router re-dispatched ``rid``
        after ``endpoint`` failed it (``reason``: ``dispatch_failed`` /
        ``stream_broken`` / ``worker_error`` / ``handoff_failed``). The legs
        accumulate in dispatch order, so a trace shows WHERE each attempt
        died — and a flight-recorder event lands next to the fault that
        caused it."""
        record = self._get(rid)
        if record is None:
            return
        record.setdefault("retries", []).append({
            "attempt": int(attempt),
            "reason": str(reason),
            "endpoint": endpoint,
            "at_s": round(max(0.0, self._clock() - record["submit_t"]), 6),
        })
        from .flight import get_flight_recorder

        get_flight_recorder().record("serving_retry", rid=int(rid),
                                     attempt=int(attempt), reason=str(reason),
                                     endpoint=endpoint)
        self._journal(record, "retry", attempt=int(attempt),
                      reason=str(reason), endpoint=endpoint)

    def cancel(self, rid: int):
        """The request's engine state was wiped before it finished
        (``reset()`` mid-wave) — the record survives, marked cancelled."""
        record = self._get(rid)
        if record is not None and record["state"] not in ("finished", "cancelled"):
            record["state"] = "cancelled"
            self._journal(record, "cancel")

    def _arm_profile(self, rid: int):
        """Arm a trace capture through the trigger the profiler installed on
        the metrics server (set_profile_trigger) — best-effort: no profiler
        armed (or one already engaged) must never affect serving."""
        from .metrics import profile_trigger

        trigger = profile_trigger()
        if trigger is None:
            return
        try:
            trigger(steps=BREACH_CAPTURE_STEPS, trigger="slo")
        except Exception:
            pass

    # --------------------------------------------------------------- reading
    def records(self) -> list:
        """Retained records, oldest first (copies — the ring stays private)."""
        return [dict(r) for r in self._ring.values()]

    def slowest(self, n: int = 5) -> list:
        """Top-``n`` retained requests by TTFT (requests still waiting on
        their first token rank by their live wait) — the operator's
        where-did-the-latency-go table."""
        now = self._clock()

        def ttft_key(record):
            if record["ttft_s"] is not None:
                return record["ttft_s"]
            if record["state"] in ("queued", "prefill"):
                return now - record["submit_t"]
            return 0.0

        ranked = sorted(self._ring.values(), key=ttft_key, reverse=True)
        return [dict(r) for r in ranked[: max(int(n), 0)]]

    def summary(self, slowest_n: int = 3) -> dict:
        """TTFT/TPOT p50/p90/max over retained records plus the slowest-N
        table — ``detail.serving.requests`` on BENCH_SERVING bench lines."""
        records = list(self._ring.values())
        ttft = sorted(r["ttft_s"] for r in records if r["ttft_s"] is not None)
        tpot = sorted(r["tpot_s"] for r in records if r["tpot_s"] is not None)
        states: dict = {}
        for r in records:
            states[r["state"]] = states.get(r["state"], 0) + 1
        proposed = sum(r.get("spec_proposed", 0) for r in records)
        accepted = sum(r.get("spec_accepted", 0) for r in records)
        return {
            "total": self.total,
            "retained": len(records),
            "states": states,
            "breaches": self.breaches,
            "spec": {
                "proposed_tokens": proposed,
                "accepted_tokens": accepted,
                "acceptance_rate": (accepted / proposed) if proposed else None,
            },
            "ttft_s": {"p50": _quantile(ttft, 0.5), "p90": _quantile(ttft, 0.9),
                       "max": ttft[-1] if ttft else 0.0},
            "tpot_s": {"p50": _quantile(tpot, 0.5), "p90": _quantile(tpot, 0.9),
                       "max": tpot[-1] if tpot else 0.0},
            "slowest": [
                {k: r.get(k) for k in ("rid", "tier", "state", "decision",
                                       "defers", "queue_wait_s", "ttft_s",
                                       "tpot_s", "tokens_out", "breached")}
                for r in self.slowest(slowest_n)
            ],
        }
