"""Unified telemetry — one answer to "what is this run doing right now?"

The subsystems that grew their own observability silos — bench probes, the
goodput ledger, health verdicts, transfer counters — publish into ONE stack:

- :mod:`.spans` — nestable ``span("data_load")`` blocks recorded into a
  lock-free ring buffer AND a ``jax.profiler.TraceAnnotation``, so host-side
  and XLA-trace views share names (the framework pre-instruments
  prepare / train_step / checkpoint / gather);
- :mod:`.timeline` — the always-on per-step timeline: step wall time,
  tokens/s, achieved-MFU estimate, compile events, deliberate device→host
  transfer counts, device memory — with zero forced host syncs (device
  scalars drain only when materialized);
- :mod:`.metrics` — the process-wide counter/gauge/histogram registry every
  layer (goodput, health, resilience, data loader, optimizer, serving)
  publishes into, exported as a Prometheus endpoint
  (``launch --metrics_port``) and as structured records through the tracker
  stack (``Accelerator.log_telemetry``);
- :mod:`.straggler` — periodic cross-host step-time aggregation over the
  one-scalar-collective/KV-agreement machinery, naming the slow host;
- :mod:`.profiler` — triggered XLA trace capture aligned to step/window
  boundaries (explicit ranges, slow-step z-score, straggler trips, POST
  /profile), budgeted and booked as ``profile`` badput;
- :mod:`.traceview` — parses captured traces into the
  compute/collective/idle/host attribution report (with the measured
  compute↔collective overlap fraction);
- :mod:`.flight` — the always-on flight-recorder black box, dumped to JSON
  on hang/trip/restart/crash and rendered by ``accelerate-tpu blackbox``;
- :mod:`.fleet` — the fleet plane: every worker registers its bound metrics
  endpoint in the coordination-service KV registry, and the lead host's
  ``FleetAggregator`` scrapes them all into per-host-labeled series + fleet
  rollups at ``/fleet`` (``accelerate-tpu top`` is the console);
- :mod:`.requests` — per-request serving lifecycle traces (submit →
  admission decision → prefill chunks → first token → decode windows →
  finish/cancel) in a bounded ring, fed by ``ContinuousBatcher``;
- :mod:`.slo` — the continuous SLO sentinel: step-time/MFU/TTFT/TPOT targets
  (explicit or EMA+MAD self-baselined), every breach booked as
  ``accelerate_slo_breaches_total{target}`` + a flight-recorder event.

:class:`Telemetry` binds them behind ``Accelerator.telemetry``; the per-step
hooks loops already call (``guard_step`` / ``checkpoint_on_preemption``) and
the fused ``build_train_step`` feed it automatically. See
docs/observability.md.
"""

from __future__ import annotations

import os

from .fleet import (
    FleetAggregator,
    discover_endpoints,
    install_fleet_provider,
    metrics_endpoint,
    publish_metrics_endpoint,
    reset_fleet,
)
from .flight import (
    FlightRecorder,
    get_flight_recorder,
    record_event,
    reset_flight_recorder,
)
from .journal import (
    TelemetryJournal,
    exchange_clock_sync,
    get_journal,
    journal_event,
    reset_journal,
    set_journal,
)
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    MetricsServer,
    get_registry,
    start_default_server,
    stop_default_server,
)
from .requests import RequestTracer
from .slo import SLOSentinel, breach_counts, record_breach, slo_targets_from_env
from .profiler import (
    ProfileManager,
    SlowStepDetector,
    get_profile_manager,
    parse_profile_steps,
    reset_profile_manager,
    set_profile_manager,
)
from .spans import SpanRecord, SpanRing, get_span_ring, reset_spans, span
from .straggler import SkewReport, StragglerMonitor
from .timeline import StepTimeline, device_memory_stats, device_peak_flops

__all__ = [
    "Counter",
    "FleetAggregator",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "MetricsServer",
    "ProfileManager",
    "RequestTracer",
    "SLOSentinel",
    "SkewReport",
    "SlowStepDetector",
    "SpanRecord",
    "SpanRing",
    "StepTimeline",
    "StragglerMonitor",
    "Telemetry",
    "TelemetryJournal",
    "breach_counts",
    "device_memory_stats",
    "device_peak_flops",
    "discover_endpoints",
    "exchange_clock_sync",
    "get_flight_recorder",
    "get_journal",
    "get_profile_manager",
    "get_registry",
    "get_span_ring",
    "get_telemetry",
    "install_default_collectors",
    "install_fleet_provider",
    "journal_event",
    "live_telemetry",
    "metrics_endpoint",
    "metrics_port_from_env",
    "parse_profile_steps",
    "publish_metrics_endpoint",
    "record_breach",
    "record_event",
    "reset_fleet",
    "reset_flight_recorder",
    "reset_journal",
    "reset_profile_manager",
    "reset_spans",
    "reset_telemetry",
    "set_journal",
    "set_profile_manager",
    "set_telemetry",
    "slo_targets_from_env",
    "span",
    "start_default_server",
    "start_endpoint_from_env",
    "stop_default_server",
]


def install_default_collectors(registry: MetricsRegistry | None = None):
    """Register the pull-model publishers (idempotent per registry): the
    goodput ledger (goodput/badput classes + restarts), the transfer counters,
    and device memory — all refreshed at scrape/snapshot time, zero per-step
    cost."""
    registry = registry if registry is not None else get_registry()
    if getattr(registry, "_at_default_collectors", False):
        return
    registry._at_default_collectors = True

    def _goodput(reg: MetricsRegistry):
        from ..resilience.goodput import BADPUT_CATEGORIES, get_ledger

        summary = get_ledger().summary()
        reg.gauge(
            "accelerate_goodput_fraction",
            "Fraction of wall-clock spent in productive steps",
        ).set(summary["goodput_fraction"])
        reg.gauge(
            "accelerate_goodput_seconds", "Productive step wall-clock"
        ).set(summary["productive_s"])
        badput = reg.gauge(
            "accelerate_badput_seconds",
            "Wall-clock lost per badput class",
            labelnames=("category",),
        )
        for category in BADPUT_CATEGORIES:
            badput.set(summary[f"{category}_s"], category=category)
        reg.gauge(
            "accelerate_restarts", "Gang incarnations observed by the ledger"
        ).set(summary["restarts"])

    def _transfers(reg: MetricsRegistry):
        from ..utils.transfer import transfer_stats

        stats = transfer_stats()
        reg.gauge(
            "accelerate_host_fetches",
            "Deliberate device-to-host fetches (utils/transfer.py)",
        ).set(stats["fetches"])
        reg.gauge(
            "accelerate_host_fetches_blocking",
            "Device-to-host fetches that stalled on an unmaterialized result",
        ).set(stats["blocking"])
        reg.gauge(
            "accelerate_host_puts",
            "Deliberate host-to-device batch uploads (utils/transfer.py)",
        ).set(stats["h2d_puts"])
        reg.gauge(
            "accelerate_host_puts_blocking",
            "Input batches the train loop had to wait on (prefetch misses)",
        ).set(stats["h2d_blocking"])
        reg.gauge(
            "accelerate_input_wait_seconds",
            "Wall-clock the train loop spent waiting on input transfers",
        ).set(stats["input_wait_s"])

    def _memory(reg: MetricsRegistry):
        stats = device_memory_stats()
        if not stats:
            return
        reg.gauge("accelerate_device_bytes_in_use", "Live device memory").set(
            stats["bytes_in_use"]
        )
        reg.gauge("accelerate_device_peak_bytes", "Peak device memory").set(
            stats["peak_bytes_in_use"]
        )
        if stats.get("bytes_limit"):
            reg.gauge("accelerate_device_bytes_limit", "Device memory limit").set(
                stats["bytes_limit"]
            )

    registry.register_collector(_goodput)
    registry.register_collector(_transfers)
    registry.register_collector(_memory)


def metrics_port_from_env() -> int:
    """The ACCELERATE_METRICS_PORT contract, parsed in ONE place (the worker
    install, `launch --fleet_metrics` validation, and `accelerate-tpu top`'s
    default endpoint all call this, so the contract cannot drift): 0 means
    no endpoint is configured (unset/empty/explicit 0), garbage raises the
    same enumerating error everywhere."""
    from ..utils.constants import ENV_METRICS_PORT

    port_raw = os.environ.get(ENV_METRICS_PORT, "").strip()
    if not port_raw:
        return 0
    try:
        return int(port_raw)
    except ValueError:
        raise ValueError(
            f"{ENV_METRICS_PORT}={port_raw!r} must be an integer port"
        ) from None


def start_endpoint_from_env(local_rank: int | None = None) -> "MetricsServer | None":
    """Start the env-contract Prometheus endpoint (ACCELERATE_METRICS_PORT),
    shared by PartialState's init install and ``get_telemetry``'s fallback so
    the contract cannot drift between them: 0/unset = no endpoint, co-located
    workers offset the port by their local rank (``local_rank``; defaults to
    ACCELERATE_LOCAL_PROCESS_ID), and a bind failure degrades to a warning —
    never a training failure. Returns the running server, or None."""
    import logging

    port = metrics_port_from_env()
    if port <= 0:
        # Env contract: 0 = no HTTP endpoint (the registry still feeds
        # trackers). Ephemeral-port binding is the explicit-API path
        # (Telemetry(metrics_port=0)), never the env's.
        return None
    install_default_collectors()
    if local_rank is None:
        local_rank = int(os.environ.get("ACCELERATE_LOCAL_PROCESS_ID", "0") or 0)
    if local_rank:
        port += local_rank
    try:
        return start_default_server(port)
    except (OSError, OverflowError) as exc:
        # OverflowError: the local-rank offset pushed past 65535 — same
        # degradation as an in-use port.
        logging.getLogger(__name__).warning(
            "metrics endpoint could not bind port %s (%s); continuing without "
            "the HTTP exposition (the registry still feeds trackers).",
            port, exc,
        )
        return None


def _transfer_snapshot() -> dict:
    from ..utils.transfer import transfer_stats

    return transfer_stats()


class Telemetry:
    """Binds timeline + straggler monitor + registry (+ optional endpoint),
    plus the profiling/forensics pair: the process-wide
    :class:`~.profiler.ProfileManager` (triggered trace capture — fed one
    call per step/window boundary, so captures align to whole steps) and the
    :class:`~.flight.FlightRecorder` black box (every boundary lands in its
    event ring with the transfer-counter delta it produced).

    ``enabled=False`` turns every hook into a no-op (ACCELERATE_TELEMETRY=0)
    — including the profiler feed: trace triggers ride the telemetry hooks.
    ``metrics_port`` starts the process-wide Prometheus endpoint (0 binds an
    ephemeral port; None leaves HTTP off — the registry still feeds trackers).
    A custom ``registry`` scopes the timeline/straggler series only (tests);
    the framework-wide publishers (health guard, optimizer, data loader,
    serving, spans) always target the global ``get_registry()``.
    """

    def __init__(
        self,
        enabled: bool = True,
        timeline: StepTimeline | None = None,
        straggler: StragglerMonitor | None = None,
        straggler_every: int = 50,
        straggler_threshold: float = 1.5,
        metrics_port: int | None = None,
        registry: MetricsRegistry | None = None,
        profiler: "ProfileManager | None" = None,
        slo: "SLOSentinel | None" = None,
    ):
        self.enabled = bool(enabled)
        self.registry = registry if registry is not None else get_registry()
        install_default_collectors(self.registry)
        self.timeline = timeline or StepTimeline(registry=self.registry)
        self.straggler = straggler or StragglerMonitor(
            every_steps=straggler_every,
            slow_ratio=straggler_threshold,
            registry=self.registry,
        )
        if profiler is not None:
            set_profile_manager(profiler)
            self.profiler = profiler
        elif self.enabled:
            self.profiler = get_profile_manager()
        else:
            # Disabled telemetry never feeds step boundaries, so creating the
            # default manager here would also install a POST /profile trigger
            # whose accepted requests could never engage (and would wedge the
            # pending slot into permanent 409s). Leave it uninstalled — the
            # endpoint then answers 503 "no profiler armed", which is true.
            self.profiler = None
        self.flight = get_flight_recorder()
        # SLO sentinel (telemetry/slo.py): explicit instance wins; otherwise
        # the launcher's env contract (ACCELERATE_SLO_STEP_TIME/TTFT/TPOT)
        # arms one, or no target is configured and the sentinel stays off.
        # Disabled telemetry never feeds step boundaries, so no sentinel.
        if slo is not None:
            self.slo = slo
        elif self.enabled:
            from .slo import sentinel_from_env

            self.slo = sentinel_from_env()
        else:
            self.slo = None
        self.server: MetricsServer | None = None
        if metrics_port is not None:
            self.server = start_default_server(int(metrics_port), registry=self.registry)
        self._seen_timeline_n = 0
        self._last_hook_step = None

    # -------------------------------------------------------------- per-step
    def on_step(self, step: int, tokens: int | None = None, loss=None,
                state=None, window: int = 1) -> None:
        """Per-step hook (``guard_step``/``checkpoint_on_preemption`` call it).
        Records a timeline sample unless the fused path already did since the
        last hook; repeated hooks at one step (a loop calling both) count
        once. Drives the periodic straggler exchange when ``state`` is given —
        that exchange is a collective, so hooks must stay SPMD-aligned.
        Windowed loops hook once per K-step boundary with ``window=K`` so the
        straggler cadence stays per-STEP correct."""
        if not self.enabled:
            return
        step = int(step)
        if self.timeline.boundaries < self._seen_timeline_n:
            # The timeline was reset (bench.py does this per config): the
            # dedupe watermarks are from the old window and would silently
            # swallow the new window's first samples.
            self._seen_timeline_n = 0
            self._last_hook_step = None
        if step != self._last_hook_step:
            if self.timeline.boundaries == self._seen_timeline_n:
                # Fallback feed (the loop's fused program didn't): a windowed
                # boundary still covers `window` training steps.
                wall = self.timeline.step_end(step=step, tokens=tokens,
                                              loss=loss, steps=window)
                self.profiler.step_boundary(step=step, wall_s=wall, steps=window)
                self.flight.note_step(step=step, wall_s=wall, steps=window,
                                      transfers=_transfer_snapshot())
                self._journal_step(step, wall, window, tokens)
                if self.slo is not None and wall is not None:
                    self.slo.observe_step(wall, steps=window, step=step,
                                          mfu=self.timeline.last_mfu)
            else:
                # The fused program already marked this boundary (and fed the
                # profiler/black box); just pin the loop's step numbering so
                # explicit profile ranges refer to real steps.
                self.profiler.sync_step(step)
            self._seen_timeline_n = self.timeline.boundaries
            self._last_hook_step = step
        if state is not None and self.straggler.due(step, window):
            window_s, window_steps = self.timeline.take_window()
            if window_steps:
                report = self.straggler.report(
                    state, window_s / window_steps, step=step
                )
                if report is not None and report.tripped:
                    # Name the skew AND capture the evidence: a straggler trip
                    # arms a trace of the next steps on every host (the
                    # exchange is collective, so all hosts trip together) —
                    # budget/rate limits live in the manager.
                    self.flight.record(
                        "straggler_trip", step=step,
                        slowest_host=report.slowest_host,
                        ratio=round(report.ratio, 3),
                    )
                    self.profiler.request_capture(
                        steps=self.profiler.slow_capture_steps,
                        trigger="straggler",
                    )

    def on_fused_step(self, tokens: int | None = None, loss=None,
                      steps: int = 1) -> None:
        """Fed by ``build_train_step``'s compiled step — one call per
        microbatch dispatch, host-side cost of a clock read. Under windowed
        dispatch (``build_train_window``) one call covers ``steps`` training
        steps: ``tokens`` is the window TOTAL and ``loss`` the retained
        per-step K-vector — the timeline splits both so per-step statistics
        stay correct (see ``StepTimeline.step_end``)."""
        if not self.enabled:
            return
        wall = self.timeline.step_end(tokens=tokens, loss=loss, steps=steps)
        self.profiler.step_boundary(wall_s=wall, steps=steps)
        self.flight.note_step(wall_s=wall, steps=steps,
                              transfers=_transfer_snapshot())
        self._journal_step(None, wall, steps, tokens)
        if self.slo is not None and wall is not None:
            self.slo.observe_step(wall, steps=steps,
                                  mfu=self.timeline.last_mfu)

    def _journal_step(self, step, wall, steps, tokens):
        """Durable step-boundary record (telemetry/journal.py). Every field
        is host bookkeeping the boundary already produced — ``loss`` is the
        timeline's last DRAINED value (never a device fetch), so
        journaling-on adds zero blocking transfers versus journaling-off
        (the comparative pin in tests/test_journal.py). No-op when
        journaling is off (one global read)."""
        if wall is None:
            return  # baseline boundary: covers trace+compile, not a step
        journal_event(
            "step", step=step, wall_s=round(float(wall), 6), steps=int(steps),
            tokens=None if tokens is None else int(tokens),
            mfu=self.timeline.last_mfu, loss=self.timeline.last_loss,
        )

    # --------------------------------------------------------------- reading
    def summary(self) -> dict:
        out = {"timeline": self.timeline.summary()}
        if self.straggler.last_report is not None:
            out["straggler"] = self.straggler.last_report.to_dict()
        if self.slo is not None and self.slo.active:
            out["slo"] = self.slo.summary()
        return out

    def close(self):
        if self.server is not None:
            stop_default_server()
            self.server = None


_DEFAULT: Telemetry | None = None


def get_telemetry() -> Telemetry:
    """The process-wide default, built from the launcher's env contract on
    first use (ACCELERATE_TELEMETRY / ACCELERATE_METRICS_PORT /
    ACCELERATE_STRAGGLER_THRESHOLD)."""
    global _DEFAULT
    if _DEFAULT is None:
        from ..utils.constants import ENV_STRAGGLER_THRESHOLD, ENV_TELEMETRY

        from .metrics import default_server

        enabled = os.environ.get(ENV_TELEMETRY, "").strip().lower() not in (
            "0", "false", "no",
        )
        # Threshold 0/unset = library default 1.5 (the convention the config
        # wizard documents and prepare_launch_env's truthiness gate implies).
        threshold_raw = os.environ.get(ENV_STRAGGLER_THRESHOLD, "").strip()
        threshold = float(threshold_raw) if threshold_raw else 0.0
        telemetry = Telemetry(
            enabled=enabled,
            straggler_threshold=threshold if threshold > 0 else 1.5,
        )
        # Reuse the server PartialState already installed (its port carries
        # the co-located-worker offset — re-requesting the raw env port would
        # warn spuriously); otherwise run the same shared env install.
        telemetry.server = default_server() or start_endpoint_from_env()
        _DEFAULT = telemetry
    return _DEFAULT


def live_telemetry() -> Telemetry | None:
    """The default instance IF one exists — the peek cold paths use
    (journal.finalize_run) so assembling a run summary in a process that
    never built telemetry doesn't construct one as a side effect."""
    return _DEFAULT


def set_telemetry(telemetry: Telemetry | None):
    global _DEFAULT
    _DEFAULT = telemetry


def reset_telemetry():
    """Drop the default instance — tests."""
    set_telemetry(None)
