"""Durable telemetry journal — the run history a dead host leaves behind.

The live observability plane (metrics registry, span ring, flight recorder,
request tracer) is scrape-or-lose: a SIGKILL'd host takes its rings with it,
and a cross-host incident leaves three uncorrelated dumps. The journal fixes
both halves. Each host appends every stream the process ALREADY pays for —
step/window boundaries (tokens/MFU from the step timeline), span records,
flight-recorder events, request-trace legs incl. handoff/retry/drain, SLO
breaches, goodput ledger deltas — to one JSONL file under
``ACCELERATE_JOURNAL_DIR``, line-buffered and flushed per record exactly like
``tracking.JSONTracker`` (a preempted or OOM-killed run loses nothing), and
bounded by size-based rotation (one ``.1`` generation, so a host's journal
occupies at most ~2x :data:`DEFAULT_MAX_BYTES`).

Every record is stamped with the causal key the collector
(:mod:`.collect`) needs to reassemble a fleet: ``host`` (process index),
``t_s`` (host monotonic since journal open), ``wall`` (host wall clock), and
``rid``/``step`` where applicable. ``wall`` clocks skew across hosts, so
:func:`exchange_clock_sync` runs the coordination-KV barrier idiom
(utils/agreement.py — works on collective-less rigs): ranks align at a
barrier, stamp ``(monotonic, wall)`` on release, and all-gather the stamps;
the per-rank wall delta versus rank 0 IS the skew the collector subtracts.

Emission discipline matches the flight recorder: one record is a dict build,
a ``json.dumps``, and a buffered write — no locks beyond a short file mutex,
no device transfers, EVER (records carry only already-paid host bookkeeping;
tests/test_journal.py pins journaling-on == journaling-off blocking-transfer
counts). ``emit`` never raises: the journal must never take the run down.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time

JOURNAL_SCHEMA_VERSION = 1

# Rotation bound for the live file: crossing it moves the file to ``<name>.1``
# (replacing the previous generation), so retention is bounded at ~2x this.
DEFAULT_MAX_BYTES = 64 * 1024 * 1024

# Coordination-KV namespace of the clock exchange (fleet.py persistent-key
# idiom); a per-call counter keeps repeated syncs collision-free.
CLOCK_NAMESPACE = "at_journal/clock"

_SYNC_COUNT = itertools.count()


def _host_index() -> int:
    from ..utils.constants import ENV_PROCESS_ID

    try:
        return int(os.environ.get(ENV_PROCESS_ID, "0") or 0)
    except ValueError:
        return 0


class TelemetryJournal:
    """Append-only per-host JSONL journal; see module docstring.

    ``clock``/``wall_clock`` are injectable for deterministic tests (the
    multi-host drill injects an artificial wall skew per rank and asserts the
    collector corrects it). Reopening an existing journal resumes the ``seq``
    counter from the last retained record, so appends from a restarted
    process never reuse sequence numbers the ``since=`` tail contract relies
    on."""

    def __init__(self, directory: str, process_index: int | None = None,
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 clock=time.monotonic, wall_clock=time.time):
        self.directory = directory
        self.host = _host_index() if process_index is None else int(process_index)
        self.max_bytes = int(max_bytes)
        if self.max_bytes <= 0:
            raise ValueError(f"max_bytes must be positive, got {max_bytes}")
        self._clock = clock
        self._wall = wall_clock
        self._t0 = clock()
        self._lock = threading.Lock()
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, f"journal_{self.host}.jsonl")
        self._seq = itertools.count(_resume_seq(self.path))
        # Line-buffered handle, flushed per record — the JSONTracker
        # durability precedent: a SIGKILL'd host loses nothing.
        self._file = open(self.path, "a", buffering=1, encoding="utf-8")
        self.counts: dict[str, int] = {}
        self._ttft = [0, 0.0, 0.0]  # count, sum, max
        self._tpot = [0, 0.0, 0.0]
        self._spec = [0, 0]  # proposed, accepted draft tokens (finish legs)
        self.emit("journal_open", pid=os.getpid(),
                  schema_version=JOURNAL_SCHEMA_VERSION)

    # -------------------------------------------------------------- recording
    def emit(self, kind: str, step=None, rid=None, **data):
        """Append one record; returns it (None on failure — the journal must
        never take the run down). Safe on any thread."""
        try:
            with self._lock:
                record = {
                    "seq": next(self._seq),
                    "host": self.host,
                    "t_s": round(self._clock() - self._t0, 6),
                    "wall": round(self._wall(), 6),
                    "kind": str(kind),
                }
                if step is not None:
                    record["step"] = int(step)
                if rid is not None:
                    record["rid"] = int(rid)
                if data:
                    record.update(data)
                self._file.write(json.dumps(record, default=str) + "\n")
                self._file.flush()
                self._observe(kind, data)
                if self._file.tell() >= self.max_bytes:
                    self._rotate()
                return record
        except Exception:
            return None

    def _observe(self, kind: str, data: dict):
        """Running aggregates for :meth:`finalize_run` — count by kind (flight
        events and request legs sub-keyed) and TTFT/TPOT moments."""
        key = kind
        if kind == "flight":
            key = f"flight:{data.get('event')}"
        self.counts[key] = self.counts.get(key, 0) + 1
        if kind == "request_leg":
            leg = data.get("leg")
            lkey = f"leg:{leg}"
            self.counts[lkey] = self.counts.get(lkey, 0) + 1
            for field, agg in (("ttft_s", self._ttft), ("tpot_s", self._tpot)):
                value = data.get(field)
                if isinstance(value, (int, float)):
                    agg[0] += 1
                    agg[1] += float(value)
                    agg[2] = max(agg[2], float(value))
            if isinstance(data.get("spec_proposed"), int):
                self._spec[0] += data["spec_proposed"]
                self._spec[1] += int(data.get("spec_accepted", 0))

    def _rotate(self):
        """Size-based rotation: live file becomes ``.1`` (replacing the
        previous generation); ``seq`` keeps counting across the boundary so
        ``tail(since=)`` stays monotonic."""
        self._file.close()
        os.replace(self.path, self.path + ".1")
        self._file = open(self.path, "a", buffering=1, encoding="utf-8")

    # ---------------------------------------------------------------- reading
    def tail(self, since: int = 0, limit: int = 4096) -> dict:
        """Retained records with ``seq >= since`` (rotated generation
        included), oldest first, capped at ``limit`` — the payload behind
        ``GET /journal?since=`` on the metrics server."""
        records = []
        for path in (self.path + ".1", self.path):
            try:
                with open(path, encoding="utf-8") as fh:
                    for raw in fh:
                        try:
                            record = json.loads(raw)
                        except ValueError:
                            continue  # torn tail line of a live file
                        if int(record.get("seq", -1)) >= since:
                            records.append(record)
            except OSError:
                continue
        records.sort(key=lambda r: r.get("seq", 0))
        if limit and len(records) > limit:
            records = records[-limit:]
        nxt = records[-1]["seq"] + 1 if records else since
        return {
            "schema_version": JOURNAL_SCHEMA_VERSION,
            "host": self.host,
            "next": nxt,
            "records": records,
        }

    # ------------------------------------------------------------- run close
    def finalize_run(self, extra: dict | None = None) -> dict:
        """Assemble and journal this run's ``run_summary`` record — the unit
        ``accelerate-tpu report --compare`` classifies run-over-run. Cold
        path: pulls the live timeline/goodput summaries (which may drain a
        retained loss) plus the journal's own running aggregates."""
        summary: dict = {"records": self.counts.copy()}
        try:
            from . import live_telemetry

            telemetry = live_telemetry()
        except Exception:
            telemetry = None
        if telemetry is not None:
            try:
                tl = telemetry.timeline.summary()
                summary.update({
                    "steps": tl.get("steps"),
                    "dispatches": tl.get("dispatches"),
                    "step_p50": tl["step_s"]["p50"],
                    "step_p90": tl["step_s"]["p90"],
                    "step_mean": tl["step_s"]["mean"],
                    "step_max": tl["step_s"]["max"],
                    "tokens_per_s": tl.get("tokens_per_s"),
                    "mfu": tl.get("mfu_estimate"),
                    "loss": tl.get("last_loss"),
                })
            except Exception:
                pass
        try:
            from ..resilience.goodput import get_ledger

            ledger = get_ledger().summary()
            summary["goodput_fraction"] = ledger["goodput_fraction"]
            summary["restarts"] = ledger["restarts"]
            summary["wall_s"] = ledger["wall_s"]
        except Exception:
            pass
        for name, (count, total, peak) in (("ttft", self._ttft),
                                           ("tpot", self._tpot)):
            if count:
                summary[f"{name}_mean"] = round(total / count, 6)
                summary[f"{name}_max"] = round(peak, 6)
                summary[f"{name}_count"] = count
        if self._spec[0]:
            # Speculative decode: acceptance rate is the draft-quality
            # signal; accepted-tokens/s is the run-over-run speed unit
            # (tokens the target did NOT have to decode one-by-one).
            summary["spec_proposed_tokens"] = self._spec[0]
            summary["spec_accepted_tokens"] = self._spec[1]
            summary["spec_acceptance_rate"] = round(
                self._spec[1] / self._spec[0], 6)
            wall = summary.get("wall_s")
            if wall:
                summary["accepted_tokens_per_s"] = round(
                    self._spec[1] / wall, 6)
        summary["breaches"] = self.counts.get("flight:slo_breach", 0)
        summary["retries"] = max(self.counts.get("leg:retry", 0),
                                 self.counts.get("flight:serving_retry", 0))
        summary["evictions"] = sum(
            n for key, n in self.counts.items()
            if key.startswith("flight:") and
            ("evict" in key or "preempt" in key)
        )
        if extra:
            summary.update(extra)
        record = self.emit("run_summary", **summary)
        if record is None:
            record = dict(summary, kind="run_summary", host=self.host)
        return record

    def close(self):
        try:
            self._file.close()
        except Exception:
            pass

    # --------------------------------------------------------------- taps in
    def _flight_tap(self, kind: str, step, data: dict):
        """Mirror a flight-recorder event (installed via
        ``flight.set_journal_tap``). ``step`` boundary events are skipped —
        the telemetry hook journals a richer ``step`` record for the same
        boundary (tokens/MFU), and double-writing the steady state would
        halve retention for nothing."""
        if kind == "step":
            return
        rid = data.get("rid")
        payload = {k: v for k, v in data.items() if k != "rid"}
        self.emit("flight", step=step, rid=rid, event=kind, **payload)


def _resume_seq(path: str) -> int:
    """Next seq for an existing journal file (0 for a fresh one): read the
    last parseable line's seq from the file tail."""
    try:
        with open(path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            size = fh.tell()
            fh.seek(max(0, size - 65536))
            lines = fh.read().splitlines()
        for raw in reversed(lines):
            try:
                return int(json.loads(raw)["seq"]) + 1
            except Exception:
                continue
    except OSError:
        pass
    return 0


# ------------------------------------------------------ process-wide default
_JOURNAL: TelemetryJournal | None = None
_RESOLVED = False
_LOCK = threading.Lock()


def get_journal() -> TelemetryJournal | None:
    """The process-wide journal, created from ``ACCELERATE_JOURNAL_DIR`` on
    first use; None when the env is unset/empty (journaling off — the
    tri-state launch contract's disabled leg costs one global read)."""
    global _JOURNAL, _RESOLVED
    if _RESOLVED:
        return _JOURNAL
    with _LOCK:
        if _RESOLVED:
            return _JOURNAL
        from ..utils.constants import ENV_JOURNAL_DIR

        directory = os.environ.get(ENV_JOURNAL_DIR, "").strip()
        if directory:
            try:
                journal = TelemetryJournal(directory)
            except Exception:
                journal = None
            if journal is not None:
                _install(journal)
            _JOURNAL = journal
        _RESOLVED = True
    return _JOURNAL


def set_journal(journal: TelemetryJournal | None):
    """Install a specific journal instance (tests, custom clocks)."""
    global _JOURNAL, _RESOLVED
    _JOURNAL = journal
    _RESOLVED = True
    if journal is not None:
        _install(journal)


def reset_journal():
    """Drop (and close) the process journal and unhook its taps — tests."""
    global _JOURNAL, _RESOLVED
    journal = _JOURNAL
    _JOURNAL = None
    _RESOLVED = False
    if journal is not None:
        journal.close()
    try:
        from .flight import set_journal_tap

        set_journal_tap(None)
    except Exception:
        pass
    try:
        from .metrics import set_journal_provider

        set_journal_provider(None)
    except Exception:
        pass


def _install(journal: TelemetryJournal):
    """Wire the journal into the streams that push to it: the flight
    recorder's tee and the metrics server's ``GET /journal`` provider."""
    try:
        from .flight import set_journal_tap

        set_journal_tap(journal._flight_tap)
    except Exception:
        pass
    try:
        from .metrics import set_journal_provider

        set_journal_provider(journal.tail)
    except Exception:
        pass


def journal_event(kind: str, step=None, rid=None, **data):
    """Emit into the process journal IF journaling is armed — the cheap
    spelling hot paths use (disabled cost: one global read)."""
    journal = _JOURNAL if _RESOLVED else get_journal()
    if journal is None:
        return None
    return journal.emit(kind, step=step, rid=rid, **data)


# ------------------------------------------------------------ clock exchange
def exchange_clock_sync(num_processes: int | None = None,
                        process_index: int | None = None,
                        timeout_ms: int = 60_000) -> dict[int, float]:
    """Barrier-aligned wall-clock exchange: every rank stamps ``(monotonic,
    wall)`` immediately after a coordination-KV barrier releases (so all
    stamps are taken within the barrier's release jitter), all-gathers the
    stamps, and journals the resulting skew map. Returns ``{rank: skew_s}``
    — each rank's wall-clock delta versus rank 0, the correction
    :mod:`.collect` subtracts when merging fleets. Single-process (no
    distributed client): ``{0: 0.0}``."""
    from ..utils.agreement import kv_all_gather
    from ..utils.constants import ENV_NUM_PROCESSES, ENV_PROCESS_ID

    if num_processes is None:
        num_processes = int(os.environ.get(ENV_NUM_PROCESSES, "1") or 1)
    if process_index is None:
        process_index = int(os.environ.get(ENV_PROCESS_ID, "0") or 0)
    call = next(_SYNC_COUNT)
    journal = _JOURNAL if _RESOLVED else get_journal()
    wall_clock = journal._wall if journal is not None else time.time
    if num_processes > 1:
        # Phase 1 aligns the ranks; the stamp is taken the instant the
        # barrier releases, so phase 2 gathers near-simultaneous readings.
        kv_all_gather("ready", num_processes, process_index,
                      f"{CLOCK_NAMESPACE}/align{call}", timeout_ms=timeout_ms)
    mono, wall = time.monotonic(), wall_clock()
    stamps = kv_all_gather(
        f"{mono:.9f},{wall:.9f}", num_processes, process_index,
        f"{CLOCK_NAMESPACE}/stamp{call}", timeout_ms=timeout_ms,
    )
    offsets: dict[int, dict] = {}
    for rank, value in enumerate(stamps):
        m, w = (float(part) for part in str(value).split(","))
        offsets[rank] = {"mono": m, "wall": w}
    base = offsets.get(0, {"wall": wall})["wall"]
    skew = {rank: round(off["wall"] - base, 6) for rank, off in offsets.items()}
    journal_event(
        "clock_sync",
        offsets={str(rank): off for rank, off in offsets.items()},
        skew={str(rank): s for rank, s in skew.items()},
    )
    return skew
