"""Trace attribution — where did the captured step time actually go?

Parses a captured XLA trace (the ``*.trace.json.gz`` Chrome-trace-format file
``jax.profiler`` writes under ``<dir>/plugins/profile/<ts>/``) into a
per-step attribution report:

- **compute** — device/executor op events (rows carrying ``args.hlo_op``, or
  rows on a ``/device:*`` process) that are not collectives;
- **collective** — op events whose HLO op is an all-reduce / all-gather /
  reduce-scatter / collective-permute / all-to-all (async ``-start``/``-done``
  halves merge into one interval), attributed to named mesh axes by joining
  the op *kind* against the program auditor's collective inventory
  (:func:`collective_axes_from_audit` — ``Accelerator.audit`` attaches it
  automatically);
- **host/infeed** — infeed/outfeed/transfer events (the host feeding or
  draining the device);
- **idle** — window time covered by none of the above.

The reported ``fractions`` are *disjoint* — overlap is resolved toward
compute, so ``compute + collective + host + idle == 1`` by construction (the
acceptance bar) — while ``overlap_fraction`` separately reports how much of
the raw collective time was hidden under compute: the measured
compute↔collective overlap the ``xla_flags.py`` latency presets exist to
maximize. Step boundaries come from the framework's own
``train_step``/``train_window`` trace annotations (telemetry/spans.py), so a
multi-step capture also yields a per-step table.

Surfaces: ``accelerate-tpu profile report <dir>``, the ``profile`` key in
``StepTimeline.summary()``, and ``detail.profile`` on bench.py JSON lines.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from dataclasses import dataclass, field

TRACEVIEW_SCHEMA_VERSION = 1

_COLLECTIVE_RE = re.compile(
    r"\b(all-reduce|all-gather|reduce-scatter|collective-permute|all-to-all)"
)
_HOST_RE = re.compile(
    r"infeed|outfeed|transfer(?:to|from|buffer)?|h2d|d2h|copy[-_ ]?(?:start|done)",
    re.IGNORECASE,
)
# Step-boundary annotations the framework's fused builders emit (spans.py
# enters a jax.profiler.TraceAnnotation of the same name).
STEP_SPAN_NAMES = ("train_step", "train_window")

TOP_OPS = 10


# ------------------------------------------------------------------ loading
def find_trace_file(root: str) -> str:
    """Newest ``*.trace.json.gz`` under ``root`` (a capture dir, the
    ``plugins/profile/<ts>`` dir itself, or a direct file path)."""
    if os.path.isfile(root):
        return root
    candidates = sorted(
        glob.glob(os.path.join(root, "**", "*.trace.json.gz"), recursive=True),
        key=os.path.getmtime,
    )
    if not candidates:
        raise FileNotFoundError(
            f"no *.trace.json.gz under {root!r} — is this a capture directory "
            "written by jax.profiler (plugins/profile/<timestamp>/...)?"
        )
    return candidates[-1]


def load_trace_events(path: str) -> list:
    """The raw Chrome-trace event list from a ``.json``/``.json.gz`` file."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as fh:
        data = json.load(fh)
    events = data.get("traceEvents", data) if isinstance(data, dict) else data
    if not isinstance(events, list):
        raise ValueError(f"{path!r} is not a Chrome-trace file")
    return events


# ---------------------------------------------------------------- intervals
def _merge(intervals: list) -> list:
    """Overlapping/adjacent [start, end) intervals → disjoint sorted list."""
    merged = []
    for start, end in sorted(intervals):
        if merged and start <= merged[-1][1]:
            if end > merged[-1][1]:
                merged[-1][1] = end
        else:
            merged.append([start, end])
    return merged


def _total(merged: list) -> float:
    return sum(end - start for start, end in merged)


def _intersect(a: list, b: list) -> list:
    """Intersection of two DISJOINT-SORTED interval lists."""
    out, i, j = [], 0, 0
    while i < len(a) and j < len(b):
        start = max(a[i][0], b[j][0])
        end = min(a[i][1], b[j][1])
        if start < end:
            out.append([start, end])
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return out


def _clip(merged: list, lo: float, hi: float) -> list:
    return [
        [max(start, lo), min(end, hi)]
        for start, end in merged
        if min(end, hi) > max(start, lo)
    ]


# ------------------------------------------------------------------- report
@dataclass
class AttributionReport:
    """One analyzed window (whole capture or one step). Times in seconds."""

    wall_s: float = 0.0
    compute_s: float = 0.0
    collective_s: float = 0.0          # raw (overlapped or not)
    collective_exposed_s: float = 0.0  # not hidden under compute
    overlap_s: float = 0.0             # collective ∩ compute
    host_s: float = 0.0                # raw host/infeed time
    host_exposed_s: float = 0.0        # not hidden under device work
    idle_s: float = 0.0
    steps: list = field(default_factory=list)     # per-step sub-reports
    top_ops: list = field(default_factory=list)   # [{name, kind, total_s, count}]
    by_axis: dict = field(default_factory=dict)   # {axis: collective seconds}
    # {kernel name: seconds} — custom-call time attributed to the NAMED
    # Pallas kernels the program auditor inventoried (attach_kernel_names);
    # unmatched kernel-shaped events book under "unattributed-custom-call".
    kernels: dict = field(default_factory=dict)
    trace_path: str = ""

    @property
    def fractions(self) -> dict:
        """Disjoint attribution; sums to 1 by construction (idle is the
        remainder after compute / exposed-collective / exposed-host)."""
        wall = self.wall_s or 1e-12
        compute = self.compute_s / wall
        collective = self.collective_exposed_s / wall
        host = self.host_exposed_s / wall
        return {
            "compute": round(compute, 4),
            "collective": round(collective, 4),
            "host": round(host, 4),
            "idle": round(max(1.0 - compute - collective - host, 0.0), 4),
        }

    @property
    def overlap_fraction(self) -> float | None:
        """Measured compute↔collective overlap: what fraction of raw
        collective time was hidden under compute. None without collectives."""
        if self.collective_s <= 0:
            return None
        return round(self.overlap_s / self.collective_s, 4)

    def to_dict(self, with_steps: bool = True) -> dict:
        out = {
            "schema_version": TRACEVIEW_SCHEMA_VERSION,
            "wall_s": round(self.wall_s, 6),
            "fractions": self.fractions,
            "overlap_fraction": self.overlap_fraction,
            "compute_s": round(self.compute_s, 6),
            "collective_s": round(self.collective_s, 6),
            "collective_exposed_s": round(self.collective_exposed_s, 6),
            "overlap_s": round(self.overlap_s, 6),
            "host_s": round(self.host_s, 6),
            "idle_s": round(self.idle_s, 6),
            "top_ops": list(self.top_ops),
            "by_axis": dict(self.by_axis),
            "kernels": dict(self.kernels),
        }
        if self.trace_path:
            out["trace_path"] = self.trace_path
        if with_steps and self.steps:
            out["steps"] = [s.to_dict(with_steps=False) for s in self.steps]
            out["n_steps"] = len(self.steps)
        return out


class _Classified:
    """Events bucketed once; windows then attribute by interval arithmetic."""

    def __init__(self, events: list):
        pid_names, tid_names = {}, {}
        for e in events:
            if e.get("ph") == "M":
                if e.get("name") == "process_name":
                    pid_names[e.get("pid")] = e.get("args", {}).get("name", "")
                elif e.get("name") == "thread_name":
                    tid_names[(e.get("pid"), e.get("tid"))] = (
                        e.get("args", {}).get("name", "")
                    )
        self.compute: list = []
        self.collective: list = []
        self.host: list = []
        self.step_events: list = []
        self.op_events: list = []  # (start, end, label, kind) — kept per-event
        # so top_ops/by_axis can be clipped to the SAME window the headline
        # fractions use; whole-trace aggregates next to windowed fractions
        # would disagree with each other.
        lo, hi = None, None
        for e in events:
            if e.get("ph") != "X" or "ts" not in e or "dur" not in e:
                continue
            start = float(e["ts"]) * 1e-6
            end = start + float(e["dur"]) * 1e-6
            name = str(e.get("name", ""))
            args = e.get("args") or {}
            lo = start if lo is None else min(lo, start)
            hi = end if hi is None else max(hi, end)
            base = name.split("/")[-1]
            if base in STEP_SPAN_NAMES or name in STEP_SPAN_NAMES:
                self.step_events.append((start, end, name))
                continue
            op = str(args.get("hlo_op", "")) or None
            on_device = pid_names.get(e.get("pid"), "").startswith("/device:")
            if on_device and "module" in tid_names.get(
                (e.get("pid"), e.get("tid")), ""
            ).lower():
                # Whole-module rows span every op in the dispatch; counting
                # them alongside the per-op rows would double the busy time.
                continue
            if op is not None or on_device:
                label = op or name
                m = _COLLECTIVE_RE.search(label) or _COLLECTIVE_RE.search(name)
                kind = m.group(1) if m else "compute"
                # Carry the raw event name alongside the hlo_op label: kernel
                # attribution joins on whichever carries the kernel's name
                # (op_name scope paths ride the event name, not the hlo_op).
                self.op_events.append((start, end, label, kind, name))
                if m:
                    self.collective.append([start, end])
                else:
                    self.compute.append([start, end])
            elif _HOST_RE.search(name):
                self.host.append([start, end])
        self.bounds = (lo or 0.0, hi or 0.0)
        self.compute = _merge(self.compute)
        self.collective = _merge(self.collective)
        self.host = _merge(self.host)

    def window(self, lo: float, hi: float) -> AttributionReport:
        wall = max(hi - lo, 1e-12)
        compute = _clip(self.compute, lo, hi)
        collective = _clip(self.collective, lo, hi)
        host = _clip(self.host, lo, hi)
        overlap = _total(_intersect(compute, collective))
        device = _merge([list(x) for x in compute + collective])
        host_exposed = _total(host) - _total(_intersect(host, device))
        busy = _merge([list(x) for x in device + host])
        report = AttributionReport(
            wall_s=wall,
            compute_s=_total(compute),
            collective_s=_total(collective),
            collective_exposed_s=_total(collective) - overlap,
            overlap_s=overlap,
            host_s=_total(host),
            host_exposed_s=host_exposed,
            idle_s=max(wall - _total(busy), 0.0),
        )
        return report


def attribute_events(events: list, collective_axes: dict | None = None) -> AttributionReport:
    """Full attribution over a raw Chrome-trace event list; see module doc."""
    classified = _Classified(events)
    if classified.step_events:
        steps = sorted(classified.step_events)
        lo, hi = steps[0][0], max(end for _, end, _ in steps)
        report = classified.window(lo, hi)
        report.steps = [classified.window(s, e) for s, e, _ in steps]
    else:
        lo, hi = classified.bounds
        report = classified.window(lo, hi)
    # top_ops and by_axis clip to the SAME [lo, hi] window as the fractions —
    # a manual capture spanning pre-step work must not list ops (or axis
    # seconds) that contributed nothing to the attributed window.
    axes_map = collective_axes if collective_axes is not None else _ATTACHED_AXES
    op_durations: dict = {}
    by_axis: dict = {}
    kernels: dict = {}
    for start, end, label, kind, name in classified.op_events:
        clipped = min(end, hi) - max(start, lo)
        if clipped <= 0:
            continue
        entry = op_durations.setdefault(
            label, {"total_s": 0.0, "count": 0, "kind": kind}
        )
        entry["total_s"] += clipped
        entry["count"] += 1
        if kind != "compute" and axes_map:
            for axis in axes_map.get(kind, ()):  # kind-level join (audit.py)
                by_axis[axis] = by_axis.get(axis, 0.0) + clipped
        # Custom-kernel attribution: join the event (hlo_op label AND raw
        # name — op_name scope paths ride the name) against the auditor's
        # named-kernel inventory (name-level — per-instance HLO sites can't
        # be recovered from trace rows, same as the axis join).
        if kind == "compute":
            kname = _kernel_name_for_label(f"{label} {name}")
            if kname is not None:
                kernels[kname] = kernels.get(kname, 0.0) + clipped
    report.top_ops = [
        {
            "name": name,
            "kind": entry["kind"],
            "total_s": round(entry["total_s"], 6),
            "count": entry["count"],
        }
        for name, entry in sorted(
            op_durations.items(), key=lambda kv: kv[1]["total_s"], reverse=True,
        )[:TOP_OPS]
    ]
    if axes_map:
        report.by_axis = {a: round(s, 6) for a, s in sorted(by_axis.items())}
    if kernels:
        report.kernels = {k: round(s, 6) for k, s in sorted(kernels.items())}
    return report


def report_capture(trace_dir: str, collective_axes: dict | None = None) -> dict:
    """Locate + parse + attribute one capture directory → report dict (the
    schema docs/observability.md documents)."""
    path = find_trace_file(trace_dir)
    report = attribute_events(load_trace_events(path), collective_axes)
    report.trace_path = path
    return report.to_dict()


# Trace-event spellings of a compiled custom-kernel invocation (Mosaic on
# TPU; the generic custom-call row some backends emit instead).
_KERNEL_EVENT_RE = re.compile(r"tpu_custom_call|mosaic|custom-call", re.IGNORECASE)


def _kernel_name_for_label(label: str):
    """The audited kernel name an op-event label belongs to, or
    'unattributed-custom-call' for kernel-shaped events outside the attached
    inventory, or None for ordinary compute."""
    for name in _ATTACHED_KERNELS:
        if name and name in label:
            return name
    if _KERNEL_EVENT_RE.search(label):
        return "unattributed-custom-call"
    return None


# ------------------------------------------------------------- audit join
# Kind → mesh-axes mapping attached by the last program audit, so triggered
# captures (which never see an AuditReport) still attribute collectives to
# named axes. Kind-level: the trace's op instances can't be matched back to
# individual HLO sites, so each kind maps to the union of axes its audited
# sites vary along.
_ATTACHED_AXES: dict = {}
# Named-kernel inventory attached the same way (Accelerator.audit feeds the
# last report's kernel_counts): trace rows whose label carries a kernel's
# name attribute their time to it in AttributionReport.kernels.
_ATTACHED_KERNELS: tuple = ()


def collective_axes_from_audit(audit_report) -> dict:
    """``AuditReport`` (or its ``to_dict()``) → {collective kind: [axes]}."""
    sites = getattr(audit_report, "collectives", None)
    if sites is None and isinstance(audit_report, dict):
        sites = audit_report.get("collectives", {}).get("sites", [])
    mapping: dict = {}
    for site in sites or []:
        op = site.op if hasattr(site, "op") else site.get("op")
        axes = site.axes if hasattr(site, "axes") else site.get("axes", ())
        mapping.setdefault(op, set()).update(axes)
    return {op: sorted(axes) for op, axes in mapping.items()}


def attach_collective_axes(mapping_or_audit):
    """Install the axis join used by captures without an explicit mapping
    (``Accelerator.audit`` calls this with every report it builds)."""
    global _ATTACHED_AXES
    if mapping_or_audit is None:
        _ATTACHED_AXES = {}
        return
    if hasattr(mapping_or_audit, "collectives") or (
        isinstance(mapping_or_audit, dict) and "collectives" in mapping_or_audit
    ):
        mapping_or_audit = collective_axes_from_audit(mapping_or_audit)
    _ATTACHED_AXES = dict(mapping_or_audit)


def attach_kernel_names(names_or_audit):
    """Install the named-kernel join for later captures: an AuditReport (its
    ``kernel_counts()`` keys), a report dict, or an iterable of names.
    ``Accelerator.audit`` calls this with every report it builds — longest
    names first so the most specific kernel wins a substring match."""
    global _ATTACHED_KERNELS
    if names_or_audit is None:
        _ATTACHED_KERNELS = ()
        return
    if hasattr(names_or_audit, "kernel_counts"):
        names = names_or_audit.kernel_counts().keys()
    elif isinstance(names_or_audit, dict) and "kernels" in names_or_audit:
        entries = names_or_audit["kernels"]
        names = [e["name"] if isinstance(e, dict) else e for e in entries]
    else:
        names = names_or_audit
    _ATTACHED_KERNELS = tuple(sorted(map(str, names), key=len, reverse=True))
