"""Experiment trackers.

Reference parity: ``src/accelerate/tracking.py`` (1,127 LoC) — ``GeneralTracker``
(:93-172) with ``name``/``requires_logging_directory``/``main_process_only`` and a
start/log/finish lifecycle; implementations for TensorBoard (:174), WandB (:289),
CometML (:414), Aim (:508), MLflow (:611), ClearML (:818), DVCLive (:976); and
``filter_trackers`` (~:1090). All host-side Python — ported in design, with a
native always-available ``JSONTracker`` (metrics.jsonl) since TPU pods often run
without any tracking service installed.
"""

from __future__ import annotations

import json
import os
import time
from functools import wraps
from typing import Any

from .logging import get_logger
from .utils.imports import _is_package_available
from .state import PartialState

logger = get_logger(__name__)

_available_trackers = []


def _register(cls):
    _available_trackers.append(cls)
    return cls


def on_main_process(function):
    """Run only on the main process unless the tracker opts out (reference :55-76)."""

    @wraps(function)
    def execute_on_main_process(self, *args, **kwargs):
        if getattr(self, "main_process_only", True):
            state = PartialState()
            if state.is_main_process:
                return function(self, *args, **kwargs)
            return None
        return function(self, *args, **kwargs)

    return execute_on_main_process


class GeneralTracker:
    """Base tracker API (reference :93-172)."""

    main_process_only = True

    def __init__(self, _blank=False):
        self._started = False

    @property
    def name(self) -> str:
        raise NotImplementedError

    @property
    def requires_logging_directory(self) -> bool:
        raise NotImplementedError

    @property
    def tracker(self):
        raise NotImplementedError

    def store_init_configuration(self, values: dict):
        pass

    def log(self, values: dict, step: int | None = None, **kwargs):
        pass

    def log_images(self, values: dict, step: int | None = None, **kwargs):
        raise NotImplementedError(f"{self.name} does not support image logging")

    def log_table(self, table_name: str, columns: list | None = None,
                  data: list | None = None, dataframe=None, step: int | None = None,
                  **kwargs):
        """Log tabular data (reference wandb ``log_table`` :370-395). Either
        ``columns``+``data`` (list of rows) or a ``dataframe``."""
        raise NotImplementedError(f"{self.name} does not support table logging")

    def finish(self):
        pass


def _table_rows(columns, data, dataframe):
    """Normalize (columns, data, dataframe) to (columns, list-of-rows)."""
    if dataframe is not None:
        return list(dataframe.columns), dataframe.values.tolist()
    if data is None:
        raise ValueError("log_table needs either data or dataframe")
    return columns, data


def _markdown_table(columns, rows) -> str:
    cols = columns
    if not cols:
        cols = [f"c{i}" for i in range(len(rows[0]))] if rows else []
    lines = ["| " + " | ".join(str(c) for c in cols) + " |",
             "| " + " | ".join("---" for _ in cols) + " |"]
    lines += ["| " + " | ".join(str(v) for v in row) + " |" for row in rows]
    return "\n".join(lines)


@_register
class JSONTracker(GeneralTracker):
    """Native tracker: appends one JSON line per log call to
    ``<logging_dir>/<run_name>/metrics.jsonl``. Always available."""

    name = "json"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str, **kwargs):
        super().__init__()
        self.run_name = run_name
        self.dir = os.path.join(logging_dir, run_name)
        os.makedirs(self.dir, exist_ok=True)
        self.path = os.path.join(self.dir, "metrics.jsonl")
        # Persistent line-buffered handle, flushed per record: a preempted or
        # SIGKILLed run keeps every metric logged up to the kill — reopening
        # per call would also be an open/close syscall pair per step.
        self._file = open(self.path, "a", buffering=1)
        self._t0 = time.time()

    @property
    def tracker(self):
        return self.path

    @on_main_process
    def store_init_configuration(self, values: dict):
        with open(os.path.join(self.dir, "config.json"), "w") as f:
            json.dump(values, f, indent=2, default=str)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs):
        record = {"_step": step, "_time": round(time.time() - self._t0, 3)}
        record.update({k: (float(v) if hasattr(v, "__float__") else v) for k, v in values.items()})
        if self._file.closed:  # logging after finish() reopens rather than dies
            self._file = open(self.path, "a", buffering=1)
        self._file.write(json.dumps(record, default=str) + "\n")
        self._file.flush()

    @on_main_process
    def finish(self):
        if not self._file.closed:
            self._file.close()

    @on_main_process
    def log_images(self, values: dict, step: int | None = None, **kwargs):
        """File-path fallback (no image backend required): each array lands in
        ``<dir>/images/`` as ``.npy`` (plus ``.png`` when PIL is importable)
        and ``images.jsonl`` records the paths per step."""
        import numpy as _np

        img_dir = os.path.join(self.dir, "images")
        os.makedirs(img_dir, exist_ok=True)
        index = {"_step": step}
        for key, imgs in values.items():
            if hasattr(imgs, "ndim") and getattr(imgs, "ndim", 0) <= 3:
                imgs = [imgs]
            paths = []
            for i, img in enumerate(imgs):
                arr = _np.asarray(img)
                base = os.path.join(img_dir, f"{key.replace('/', '_')}_{step}_{i}")
                _np.save(base + ".npy", arr)
                paths.append(base + ".npy")
                try:
                    from PIL import Image  # optional

                    u8 = arr if arr.dtype == _np.uint8 else (
                        _np.clip(arr, 0, 1) * 255).astype(_np.uint8)
                    Image.fromarray(u8).save(base + ".png")
                    paths.append(base + ".png")
                except Exception:
                    pass
            index[key] = paths
        with open(os.path.join(self.dir, "images.jsonl"), "a") as f:
            f.write(json.dumps(index) + "\n")

    @on_main_process
    def log_table(self, table_name: str, columns: list | None = None,
                  data: list | None = None, dataframe=None, step: int | None = None,
                  **kwargs):
        columns, rows = _table_rows(columns, data, dataframe)
        with open(os.path.join(self.dir, "tables.jsonl"), "a") as f:
            f.write(json.dumps({"_step": step, "name": table_name,
                                "columns": columns, "rows": rows}, default=str) + "\n")


@_register
class TensorBoardTracker(GeneralTracker):
    """Reference :174-287; uses tensorboardX or torch.utils.tensorboard."""

    name = "tensorboard"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str, **kwargs):
        super().__init__()
        try:
            from torch.utils import tensorboard
        except ImportError:
            import tensorboardX as tensorboard  # type: ignore
        self.run_name = run_name
        self.logging_dir = os.path.join(logging_dir, run_name)
        self.writer = tensorboard.SummaryWriter(self.logging_dir, **kwargs)

    @classmethod
    def is_available(cls) -> bool:
        try:
            from torch.utils import tensorboard  # noqa

            return True
        except ImportError:
            try:
                import tensorboardX  # noqa

                return True
            except ImportError:
                return False

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.writer.add_hparams(
            {k: v for k, v in values.items() if isinstance(v, (int, float, str, bool))}, metric_dict={}
        )
        self.writer.flush()

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs):
        for k, v in values.items():
            if isinstance(v, (int, float)) or hasattr(v, "__float__"):
                self.writer.add_scalar(k, float(v), global_step=step, **kwargs)
            elif isinstance(v, str):
                self.writer.add_text(k, v, global_step=step, **kwargs)
            elif isinstance(v, dict):
                self.writer.add_scalars(k, {kk: float(vv) for kk, vv in v.items()}, global_step=step)
        self.writer.flush()

    @on_main_process
    def log_images(self, values: dict, step: int | None = None, **kwargs):
        """Reference TensorBoard ``log_images`` (:285): NHWC arrays per key."""
        import numpy as _np

        for key, imgs in values.items():
            arr = _np.asarray(imgs)
            if arr.ndim == 3:  # single HWC image
                self.writer.add_image(key, arr, global_step=step, dataformats="HWC")
            else:  # batch NHWC
                self.writer.add_images(key, arr, global_step=step, dataformats="NHWC")
        self.writer.flush()

    @on_main_process
    def log_table(self, table_name: str, columns: list | None = None,
                  data: list | None = None, dataframe=None, step: int | None = None,
                  **kwargs):
        """Rendered as a markdown table via add_text (TensorBoard has no native
        table artifact)."""
        columns, rows = _table_rows(columns, data, dataframe)
        self.writer.add_text(table_name, _markdown_table(columns, rows), global_step=step)
        self.writer.flush()

    @on_main_process
    def finish(self):
        self.writer.close()


@_register
class WandBTracker(GeneralTracker):
    """Reference :289-412."""

    name = "wandb"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__()
        import wandb

        self.run = wandb.init(project=run_name, **kwargs)

    @classmethod
    def is_available(cls) -> bool:
        return _is_package_available("wandb")

    @property
    def tracker(self):
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict):
        import wandb

        wandb.config.update(values, allow_val_change=True)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs):
        self.run.log(values, step=step, **kwargs)

    @on_main_process
    def log_images(self, values: dict, step: int | None = None, **kwargs):
        import wandb

        self.run.log({k: [wandb.Image(img) for img in v] for k, v in values.items()}, step=step)

    @on_main_process
    def log_table(self, table_name: str, columns: list | None = None,
                  data: list | None = None, dataframe=None, step: int | None = None,
                  **kwargs):
        """Reference wandb ``log_table`` (:370-395)."""
        import wandb

        table = wandb.Table(columns=columns, data=data, dataframe=dataframe)
        self.run.log({table_name: table}, step=step)

    @on_main_process
    def finish(self):
        self.run.finish()


@_register
class MLflowTracker(GeneralTracker):
    """Reference :611-816."""

    name = "mlflow"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str | None = None, **kwargs):
        super().__init__()
        import mlflow

        self.active_run = mlflow.start_run(run_name=run_name, **kwargs)

    @classmethod
    def is_available(cls) -> bool:
        return _is_package_available("mlflow")

    @property
    def tracker(self):
        return self.active_run

    @on_main_process
    def store_init_configuration(self, values: dict):
        import mlflow

        for k, v in values.items():
            mlflow.log_param(k, v)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs):
        import mlflow

        metrics = {k: float(v) for k, v in values.items() if isinstance(v, (int, float)) or hasattr(v, "__float__")}
        mlflow.log_metrics(metrics, step=step)

    @on_main_process
    def finish(self):
        import mlflow

        mlflow.end_run()


@_register
class CometMLTracker(GeneralTracker):
    """Reference :414-506 — Experiment lifecycle, log_metrics/log_parameters."""

    name = "comet_ml"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__()
        import comet_ml

        self.run_name = run_name
        self.writer = comet_ml.Experiment(project_name=run_name, **kwargs)

    @classmethod
    def is_available(cls) -> bool:
        return _is_package_available("comet_ml")

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.writer.log_parameters(values)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs):
        if step is not None:
            self.writer.set_step(step)
        for k, v in values.items():
            if isinstance(v, (int, float)) or hasattr(v, "__float__"):
                self.writer.log_metric(k, float(v), step=step, **kwargs)
            elif isinstance(v, str):
                self.writer.log_other(k, v)
            elif isinstance(v, dict):
                self.writer.log_metrics(v, step=step, **kwargs)

    @on_main_process
    def finish(self):
        self.writer.end()


@_register
class AimTracker(GeneralTracker):
    """Reference :508-609 — aim.Run with hparams + track()."""

    name = "aim"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str | None = ".", **kwargs):
        super().__init__()
        import aim

        self.run_name = run_name
        self.writer = aim.Run(repo=logging_dir, **kwargs)
        self.writer.name = run_name

    @classmethod
    def is_available(cls) -> bool:
        return _is_package_available("aim")

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.writer["hparams"] = values

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs):
        for k, v in values.items():
            self.writer.track(v, name=k, step=step, **kwargs)

    @on_main_process
    def finish(self):
        self.writer.close()


@_register
class ClearMLTracker(GeneralTracker):
    """Reference :818-974 — Task.init + report_scalar with title/series split."""

    name = "clearml"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str | None = None, **kwargs):
        super().__init__()
        from clearml import Task

        kwargs.setdefault("project_name", os.environ.get("CLEARML_PROJECT", run_name))
        kwargs.setdefault("task_name", os.environ.get("CLEARML_TASK", run_name))
        self.task = Task.init(**kwargs)

    @classmethod
    def is_available(cls) -> bool:
        return _is_package_available("clearml")

    @property
    def tracker(self):
        return self.task

    @on_main_process
    def store_init_configuration(self, values: dict):
        return self.task.connect_configuration(values)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs):
        clearml_logger = self.task.get_logger()
        for k, v in values.items():
            if not isinstance(v, (int, float)) and not hasattr(v, "__float__"):
                continue
            # Split only the known split prefixes (reference :969-973):
            # "train_loss" → title "loss", series "train"; everything else
            # keeps its full name as the title under the default "train" series.
            title, series = k, "train"
            for prefix in ("eval", "test", "train"):
                if k.startswith(prefix + "_"):
                    title, series = k[len(prefix) + 1 :], prefix
                    break
            if step is None:
                clearml_logger.report_single_value(name=k, value=float(v), **kwargs)
            else:
                clearml_logger.report_scalar(
                    title=title, series=series, value=float(v), iteration=step, **kwargs
                )

    @on_main_process
    def finish(self):
        if self.task:
            self.task.close()


@_register
class DVCLiveTracker(GeneralTracker):
    """Reference :976-1088 — dvclive.Live log_params/log_metric/next_step."""

    name = "dvclive"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str | None = None, live=None, **kwargs):
        super().__init__()
        from dvclive import Live

        self.live = live if live is not None else Live(**kwargs)

    @classmethod
    def is_available(cls) -> bool:
        return _is_package_available("dvclive")

    @property
    def tracker(self):
        return self.live

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.live.log_params(values)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs):
        if step is not None:
            self.live.step = step
        for k, v in values.items():
            if isinstance(v, (int, float)) or hasattr(v, "__float__"):
                self.live.log_metric(k, float(v), **kwargs)

    @on_main_process
    def finish(self):
        self.live.end()


LOGGER_TYPE_TO_CLASS = {
    "json": JSONTracker,
    "tensorboard": TensorBoardTracker,
    "wandb": WandBTracker,
    "mlflow": MLflowTracker,
    "comet_ml": CometMLTracker,
    "aim": AimTracker,
    "clearml": ClearMLTracker,
    "dvclive": DVCLiveTracker,
}


def filter_trackers(log_with, logging_dir: str | None = None):
    """Resolve requested trackers to available classes (reference ~:1090):
    'all' → every importable tracker; unavailable ones are skipped with a warning;
    a ``GeneralTracker`` instance passes through."""
    loggers = []
    if log_with is None:
        return []
    if not isinstance(log_with, (list, tuple)):
        log_with = [log_with]
    for tracker in log_with:
        if isinstance(tracker, GeneralTracker):
            loggers.append(tracker)
        elif str(tracker) == "all":
            for cls in _available_trackers:
                if getattr(cls, "is_available", lambda: True)():
                    loggers.append(cls.name)
        else:
            name = str(tracker).lower()
            if name not in LOGGER_TYPE_TO_CLASS:
                raise ValueError(f"Unknown tracker {name!r}; choose from {sorted(LOGGER_TYPE_TO_CLASS)}")
            cls = LOGGER_TYPE_TO_CLASS[name]
            if not getattr(cls, "is_available", lambda: True)():
                logger.warning(f"Tracker {name} requested but its package is not installed; skipping.")
                continue
            if cls.requires_logging_directory and logging_dir is None:
                raise ValueError(f"Tracker {name} requires a logging_dir/project_dir.")
            loggers.append(name)
    # dedup, keep order
    seen, out = set(), []
    for l in loggers:
        key = l if isinstance(l, str) else id(l)
        if key not in seen:
            seen.add(key)
            out.append(l)
    return out


def init_trackers(log_with, project_name, logging_dir, config, init_kwargs, accelerator):
    """Instantiate trackers & store the run config (driver for
    ``Accelerator.init_trackers``, reference ``accelerator.py:2954``)."""
    init_kwargs = init_kwargs or {}
    trackers = []
    for entry in log_with or []:
        if isinstance(entry, GeneralTracker):
            trackers.append(entry)
            continue
        cls = LOGGER_TYPE_TO_CLASS[entry]
        kwargs = init_kwargs.get(entry, {})
        if cls.requires_logging_directory:
            trackers.append(cls(project_name, logging_dir, **kwargs))
        else:
            trackers.append(cls(project_name, **kwargs))
    if config is not None:
        for tracker in trackers:
            tracker.store_init_configuration(config)
    return trackers
