"""Accelerator — the orchestration facade (L5).

Reference parity: ``src/accelerate/accelerator.py`` (3,952 LoC, class at :180).
The public surface is kept — ``prepare`` (:1289), ``backward`` (:2502),
``accumulate`` (:1122), ``gather``/``gather_for_metrics`` (:2719/:2751),
``clip_grad_norm_`` (:2630), ``save_state``/``load_state`` (:3260/:3426),
``autocast`` (:3770), ``set_trigger``/``check_trigger`` (:2536-2593) — but the
engine is inverted:

- The reference wraps live modules (DDP/FSDP/engine wrappers) and lets backward
  hooks fire NCCL collectives. Here ``prepare`` lowers the model into a **pure
  function + sharded param pytree** on the state's mesh, and every forward/backward
  is a cached, jitted XLA program in which GSPMD has already inserted the
  cross-device reductions. ``backward(loss)`` therefore doesn't *run* autodiff —
  gradients were produced by the same compiled call that produced ``loss``
  (``jax.value_and_grad``) — it *banks* them into the optimizer's accumulation
  buffer (the explicit-pytree analog of ``.grad +=``).
- DDP's ``no_sync`` dance (:1007-1045) vanishes: gradient accumulation is a
  device-side buffer add; the cross-device reduce rides each compiled step.
- The fused path ``build_train_step`` goes further and compiles forward+backward+
  accumulation+update into ONE XLA program with donated buffers — that is the
  shape the hardware wants, and what ``bench.py`` measures.

Imperative-compat contract (SURVEY.md §7 hard part 1): the pattern

    model, optimizer, loader, scheduler = accelerator.prepare(...)
    for batch in loader:
        with accelerator.accumulate(model):
            outputs = model(**batch)
            accelerator.backward(outputs.loss)
            optimizer.step(); scheduler.step(); optimizer.zero_grad()

works unmodified: prepared models in train mode compute grads at forward time
(same cost as torch's fwd+bwd — one fwd, one bwd, fused by XLA), and the loss
object returned carries the association to those banked grads.
"""

from __future__ import annotations

import contextlib
import logging
import math
import os
from functools import partial
from typing import Any, Callable

import numpy as np

import jax
import jax.numpy as jnp

from .data_loader import DataLoaderDispatcher, DataLoaderShard, prepare_data_loader, skip_first_batches
from .modules import Module, ModelOutput, as_module, default_loss_extractor
from .optimizer import AcceleratedOptimizer, GradScalerState
from .parallel.mesh import ParallelismConfig
from .parallel.sharding import (
    apply_shardings,
    batch_sharding,
    make_global_batch,
    plan_param_shardings,
)
from .scheduler import AcceleratedScheduler
from .state import AcceleratorState, DistributedType, GradientState, PartialState
from .utils.dataclasses import (
    AutocastKwargs,
    DataLoaderConfiguration,
    Fp8RecipeKwargs,
    FullyShardedDataParallelPlugin,
    GradientAccumulationPlugin,
    JaxShardingKwargs,
    KwargsHandler,
    ProfileKwargs,
    MegatronStylePlugin,
    PipelineParallelPlugin,
    ProjectConfiguration,
    SequenceParallelPlugin,
    TensorParallelPlugin,
)
from .utils import operations as ops

logger = logging.getLogger(__name__)


class TrainHandle:
    """Shared mutable cell binding a PreparedModel to its optimizer(s): holds the
    *current* sharded params so ``optimizer.step()`` visibly updates what
    ``model(...)`` uses next — the stateful shim over the functional core."""

    def __init__(self, module: Module, params, param_shardings, mesh, compute_dtype, rng,
                 pipeline_spec=None):
        self.module = module
        self.params = params
        self.param_shardings = param_shardings
        self.mesh = mesh
        self.compute_dtype = compute_dtype
        self.rng = rng
        # GPipe schedule over the pp axis (parallel/pipeline.py); None = the
        # GSPMD layer-dim sharding fallback (or no pp axis at all).
        self.pipeline_spec = pipeline_spec
        self.step_counter = 0
        self.last_grad_norm = None
        self.pending = None  # (loss jax.Array, grads pytree) from last train forward


def _grad_reduce_barrier(params, shardings, reduce_dtype):
    """Identity on the forward; on the backward, each leaf's cotangent is cast
    to ``reduce_dtype`` and pinned to the parameter's sharding — GSPMD then
    materializes the gradient reduction (all-reduce for dp, reduce-scatter for
    fsdp) at the reduced precision, halving the bytes on the wire. The cast
    back to the original dtype is local. TPU-native analog of the reference's
    fp16/bf16 gradient-compression comm hooks
    (``DistributedDataParallelKwargs``, reference dataclasses.py:130-226)."""

    def one(leaf, sharding):
        if not jnp.issubdtype(leaf.dtype, jnp.floating):
            return leaf

        @jax.custom_vjp
        def bridge(x):
            return x

        def fwd(x):
            return x, None

        def bwd(_, g):
            gc = jax.lax.with_sharding_constraint(g.astype(reduce_dtype), sharding)
            return (gc.astype(g.dtype),)

        bridge.defvjp(fwd, bwd)
        return bridge(leaf)

    return jax.tree_util.tree_map(one, params, shardings)


class PreparedModel:
    """The object handed back by ``prepare`` in a model's slot (reference returns
    the DDP/FSDP-wrapped module, ``accelerator.py:1515``)."""

    def __init__(self, handle: TrainHandle, accelerator: "Accelerator", loss_fn=None):
        self.handle = handle
        self.accelerator = accelerator
        self.loss_fn = loss_fn or default_loss_extractor
        self.training = True
        self._train_call = None
        self._eval_call = None

    # ------------------------------------------------------------------ modes
    def train(self, mode: bool = True):
        self.training = mode
        return self

    def eval(self):
        return self.train(False)

    # ------------------------------------------------------------- unwrapping
    @property
    def module(self) -> Module:
        return self.handle.module

    @property
    def params(self):
        return self.handle.params

    @params.setter
    def params(self, value):
        self.handle.params = value

    def state_dict(self):
        return self.handle.params

    def load_state_dict(self, params):
        self.handle.params = apply_shardings(params, self.handle.param_shardings)

    # ------------------------------------------------------------------ loss
    def training_loss_fn(self, extract=None):
        """The canonical ``loss_of(params, batch, rng)`` used by every compiled
        training path (fused step, LocalSGDTrainer) — one definition so the
        forward contract (train flag, rng collections, loss extraction) cannot
        diverge between them. ``extract`` overrides the model's loss extractor."""
        module = self.handle.module
        cast = self._cast
        extract = extract or self.loss_fn
        if self._uses_1f1b():
            # training_loss_fn consumers (LocalSGDTrainer, custom loops) drive
            # their own value_and_grad — they cannot honor the 1F1B schedule,
            # and silently running GPipe would deliver O(M) activation
            # liveness the user opted out of.
            raise ValueError(
                "schedule='1f1b' trains through build_train_step or the "
                "imperative prepared-model forward only; use "
                "PipelineParallelPlugin(schedule='gpipe') with this training path."
            )
        pipe = {"pipeline": self.handle.pipeline_spec} if self.handle.pipeline_spec is not None else {}

        def loss_of(params, batch, rng):
            outputs = module.apply(cast(params), train=True, rngs={"dropout": rng}, **pipe, **batch)
            return extract(outputs, batch)

        return loss_of

    # ---------------------------------------------------------------- compile
    def _cast(self, params):
        dtype = self.handle.compute_dtype
        if dtype != jnp.float32:
            params = jax.tree_util.tree_map(
                lambda p: p.astype(dtype) if jnp.issubdtype(p.dtype, jnp.floating) else p,
                params,
            )
        rd = self._grad_reduce_dtype()
        if rd is not None:
            params = _grad_reduce_barrier(params, self.handle.param_shardings, rd)
        return params

    def _grad_reduce_dtype(self):
        sk = getattr(self.accelerator, "sharding_kwargs", None)
        name = getattr(sk, "grad_reduce_dtype", None)
        if name is None:
            return None
        return {"bf16": jnp.bfloat16, "fp16": jnp.float16}[name]

    def _uses_1f1b(self):
        spec = self.handle.pipeline_spec
        return spec is not None and spec.schedule == "1f1b"

    def _check_1f1b_loss_fn(self, extract):
        if extract is not None and extract is not default_loss_extractor:
            raise ValueError(
                "schedule='1f1b' computes the loss on the last pipeline stage "
                "via the model's own head (labels in the batch) — a custom "
                "loss_fn cannot be honored. Drop set_loss_fn/loss_fn or use "
                "PipelineParallelPlugin(schedule='gpipe')."
            )

    def _build_calls(self):
        module = self.handle.module
        loss_fn = self.loss_fn
        cast = self._cast
        handle = self.handle
        # Training forwards route through the pipeline schedule when one
        # resolved; eval keeps the GSPMD path (eval batch sizes need not
        # divide the microbatch grid, and eval throughput is not
        # pipeline-bound).
        pipe = {"pipeline": handle.pipeline_spec} if handle.pipeline_spec is not None else {}

        def fwd(params, args, kwargs, rng):
            return module.apply(cast(params), *args, train=False, rngs=None, **kwargs)

        if self._uses_1f1b():
            self._check_1f1b_loss_fn(self.loss_fn)
            spec = handle.pipeline_spec

            def train_fwd(params, args, kwargs, rng, loss_scale):
                # The 1F1B schedule produces loss AND grads in one pass; the
                # outputs carry loss (and aux) but no logits — the same
                # contract as fused_loss. Positional args follow the model
                # apply() convention (input_ids, labels, attention_mask, ...).
                batch = dict(zip(("input_ids", "labels", "attention_mask", "positions"), args))
                batch.update(kwargs)
                loss, grads, aux = spec.train_grads(
                    module, params, batch,
                    compute_dtype=handle.compute_dtype, loss_scale=loss_scale,
                    param_shardings=handle.param_shardings,
                )
                outputs = ModelOutput(loss=loss)
                if aux:
                    outputs["aux_loss"] = sum(aux.values())
                return loss, outputs, grads
        else:

            def loss_and_out(params, args, kwargs, rng, loss_scale):
                outputs = module.apply(
                    cast(params), *args, train=True, rngs={"dropout": rng}, **pipe, **kwargs
                )
                loss = loss_fn(outputs, kwargs if kwargs else args)
                return loss * loss_scale, outputs

            def train_fwd(params, args, kwargs, rng, loss_scale):
                (scaled_loss, outputs), grads = jax.value_and_grad(loss_and_out, has_aux=True)(
                    params, args, kwargs, rng, loss_scale
                )
                return scaled_loss / loss_scale, outputs, grads

        self._eval_call = jax.jit(fwd)
        self._train_call = jax.jit(train_fwd)

    def __call__(self, *args, **kwargs):
        if self._train_call is None:
            self._build_calls()
        handle = self.handle
        handle.step_counter += 1
        rng = jax.random.fold_in(handle.rng, handle.step_counter)
        args, kwargs = self.accelerator._place_batch((args, kwargs))
        if self.training:
            scaler = self.accelerator.scaler
            if scaler is not None:
                # The previous step's deferred overflow outcome must land
                # before its scale seeds this forward (optimizer.py keeps the
                # hot path async by resolving found_inf lazily, here).
                opt = self.accelerator._optimizer_for_handle(handle)
                if opt is not None:
                    opt._resolve_pending_finite()
            loss_scale = jnp.float32(scaler.scale if scaler is not None else 1.0)
            loss, outputs, grads = self._train_call(handle.params, args, kwargs, rng, loss_scale)
            handle.pending = (loss, grads)
            if isinstance(outputs, dict) and "loss" in outputs:
                # Hand the *differentiated* loss object out so backward() can match it.
                outputs = ModelOutput(outputs)
                outputs["loss"] = loss
            return outputs
        return self._eval_call(handle.params, args, kwargs, rng)

    def forward(self, *args, **kwargs):
        return self(*args, **kwargs)


class Accelerator:
    """See module docstring. Constructor mirrors reference ``accelerator.py:271``."""

    def __init__(
        self,
        device_placement: bool = True,
        split_batches: bool = False,
        mixed_precision: str | None = None,
        gradient_accumulation_steps: int | None = None,  # None -> env, then 1
        cpu: bool = False,
        dataloader_config: DataLoaderConfiguration | None = None,
        fsdp_plugin: FullyShardedDataParallelPlugin | None = None,
        tp_plugin: TensorParallelPlugin | None = None,
        pp_plugin: PipelineParallelPlugin | None = None,
        sp_plugin: SequenceParallelPlugin | None = None,
        megatron_plugin: MegatronStylePlugin | None = None,
        parallelism_config: ParallelismConfig | None = None,
        rng_types: list | None = None,
        log_with=None,
        project_dir: str | os.PathLike | None = None,
        project_config: ProjectConfiguration | None = None,
        gradient_accumulation_plugin: GradientAccumulationPlugin | None = None,
        step_scheduler_with_optimizer: bool = True,
        kwargs_handlers: list | None = None,
        dynamo_backend=None,  # parity slot: XLA always compiles
    ):
        # Env contract extensions written by `accelerate-tpu config`'s guided
        # wizard and exported by the launcher (reference cluster.py:57 flow):
        # explicit constructor arguments always win over the env.
        from .utils.environment import parse_flag_from_env

        if project_config is None and project_dir is None:
            env_pdir = os.environ.get("ACCELERATE_PROJECT_DIR")
            if env_pdir:
                project_config = ProjectConfiguration(
                    project_dir=env_pdir,
                    automatic_checkpoint_naming=parse_flag_from_env(
                        "ACCELERATE_CHECKPOINT_AUTO_NAMING"
                    ),
                    total_limit=(
                        int(os.environ["ACCELERATE_CHECKPOINT_TOTAL_LIMIT"])
                        if os.environ.get("ACCELERATE_CHECKPOINT_TOTAL_LIMIT")
                        else None
                    ),
                )
        if fsdp_plugin is None and (
            os.environ.get("ACCELERATE_FSDP_MIN_SHARD_SIZE")
            or os.environ.get("ACCELERATE_FSDP_CPU_OFFLOAD")
        ):
            # Axis size comes from the mesh-shape env (the wizard writes both);
            # only the per-feature options live in these variables. fsdp_size
            # 1 stays 1 (disabled); 0/unset means full-shard (-1).
            env_mesh_fsdp = ParallelismConfig.from_env().fsdp_size
            fsdp_plugin = FullyShardedDataParallelPlugin(
                fsdp_size=env_mesh_fsdp or -1,
                min_shard_size=int(os.environ.get("ACCELERATE_FSDP_MIN_SHARD_SIZE", 2**14)),
                cpu_offload=parse_flag_from_env("ACCELERATE_FSDP_CPU_OFFLOAD"),
            )
        if pp_plugin is None and os.environ.get("ACCELERATE_PP_SCHEDULE"):
            # The pp axis size ALSO comes from the mesh-shape env; defaulting
            # the plugin's pp_size would override (and silently disable) it.
            pp_plugin = PipelineParallelPlugin(
                pp_size=max(ParallelismConfig.from_env().pp_size, 1),
                schedule=os.environ["ACCELERATE_PP_SCHEDULE"],
            )
        if log_with is None and os.environ.get("ACCELERATE_LOG_WITH"):
            log_with = [t.strip() for t in os.environ["ACCELERATE_LOG_WITH"].split(",") if t.strip()]

        self.project_configuration = project_config or ProjectConfiguration(project_dir=project_dir)
        if project_dir is not None and self.project_configuration.project_dir is None:
            self.project_configuration.set_directories(project_dir)
        self.sharding_kwargs = JaxShardingKwargs()
        self.autocast_handler = None
        self.profile_handler = None
        self.fp8_recipe_handler = None
        seen_handler_classes = set()
        for handler in kwargs_handlers or []:
            assert isinstance(handler, KwargsHandler), (
                f"Unsupported kwargs handler passed: {handler}, must be one that "
                "inherits `accelerate_tpu.utils.KwargsHandler`."
            )
            if type(handler) in seen_handler_classes:
                raise ValueError(
                    f"You can only pass one {type(handler).__name__} in `kwargs_handlers`."
                )
            seen_handler_classes.add(type(handler))
            if isinstance(handler, JaxShardingKwargs):
                self.sharding_kwargs = handler
            elif isinstance(handler, AutocastKwargs):
                self.autocast_handler = handler
            elif isinstance(handler, ProfileKwargs):
                self.profile_handler = handler
            elif isinstance(handler, Fp8RecipeKwargs):
                self.fp8_recipe_handler = handler

        if parallelism_config is None:
            parallelism_config = self._resolve_parallelism(
                fsdp_plugin, tp_plugin, pp_plugin, sp_plugin, megatron_plugin
            )
        self.fsdp_plugin = fsdp_plugin
        self.sp_plugin = sp_plugin
        self.pp_plugin = pp_plugin
        self.state = AcceleratorState(
            mixed_precision=mixed_precision, cpu=cpu, parallelism_config=parallelism_config
        )

        if gradient_accumulation_plugin is None:
            # The env is a default, not an override: any explicit constructor
            # value (including 1, via the None sentinel) wins over the
            # wizard's env.
            steps = gradient_accumulation_steps
            if steps is None:
                steps = int(os.environ.get("ACCELERATE_GRADIENT_ACCUMULATION_STEPS", 1))
            gradient_accumulation_plugin = GradientAccumulationPlugin(num_steps=steps)
        elif gradient_accumulation_steps is not None and gradient_accumulation_steps > 1:
            raise ValueError(
                "You can only pass one of `gradient_accumulation_steps` and "
                "`gradient_accumulation_plugin`. Please only pass in the created "
                "`GradientAccumulationPlugin` object."
            )
        self.gradient_state = GradientState(gradient_accumulation_plugin)

        self.device_placement = device_placement
        self.split_batches = split_batches
        self.dataloader_config = dataloader_config or DataLoaderConfiguration(split_batches=split_batches)
        self.step_scheduler_with_optimizer = step_scheduler_with_optimizer
        self.rng_types = rng_types or ["generator"]

        self.scaler = GradScalerState() if self.state.mixed_precision == "fp16" else None
        # FSDP plugin cpu_offload: optimizer state parks in host RAM (the
        # ZeRO-Offload trade — HBM for step latency). Applies to the imperative
        # optimizer path; the fused build_train_step keeps state device-resident
        # by design (donated buffers, zero host round-trips).
        self._offload_opt_state = bool(fsdp_plugin.cpu_offload) if fsdp_plugin is not None else False
        self.step = 0
        self.flag_tensor = None
        self._train_window = None  # lazy: ACCELERATE_TRAIN_WINDOW, then 1
        self._zero_sharding = None  # lazy: ACCELERATE_ZERO_SHARDING, then off
        self._kernels = None  # lazy: ACCELERATE_KERNELS, then reference
        self._resilience_step = 0
        # Bumped by every elastic reshard (resilience/elastic.py): fused
        # programs built before a transition compiled for a mesh that no
        # longer exists and must refuse to run.
        self._mesh_epoch = 0
        self._preemption_watcher = None
        self._health_guard = None
        self._telemetry = None
        self._models: list[PreparedModel] = []
        self._optimizers: list[AcceleratedOptimizer] = []
        self._schedulers: list[AcceleratedScheduler] = []
        self._dataloaders: list = []
        self._custom_objects: list = []
        self._loss_fn = None
        self._rng_seed_counter = 0

        self.log_with = []
        self.trackers = []
        if log_with is not None:
            from .tracking import filter_trackers

            self.log_with = filter_trackers(log_with, self.logging_dir)

    # ------------------------------------------------------------- properties
    def _resolve_parallelism(self, fsdp_plugin, tp_plugin, pp_plugin, sp_plugin, megatron_plugin):
        if megatron_plugin is not None:
            return ParallelismConfig(
                fsdp_size=megatron_plugin.fsdp_size,
                tp_size=megatron_plugin.tp_size,
                pp_size=megatron_plugin.pp_size,
                sp_size=megatron_plugin.sp_size,
            )
        cfg = ParallelismConfig.from_env()
        if fsdp_plugin is not None:
            # -1 = full-shard over all remaining devices; ParallelismConfig
            # resolves it against the device count at mesh-build time.
            cfg.fsdp_size = fsdp_plugin.fsdp_size if fsdp_plugin.fsdp_size > 0 else -1
        if tp_plugin is not None:
            cfg.tp_size = tp_plugin.tp_size
        if pp_plugin is not None:
            cfg.pp_size = pp_plugin.pp_size
        if sp_plugin is not None:
            cfg.sp_size = sp_plugin.sp_size
        return cfg

    @property
    def distributed_type(self) -> DistributedType:
        return self.state.distributed_type

    @property
    def mesh(self):
        return self.state.mesh

    @property
    def device(self):
        return self.state.device

    @property
    def num_processes(self):
        return self.state.num_processes

    @property
    def process_index(self):
        return self.state.process_index

    @property
    def local_process_index(self):
        return self.state.local_process_index

    @property
    def is_main_process(self):
        return self.state.is_main_process

    @property
    def is_local_main_process(self):
        return self.state.is_local_main_process

    @property
    def is_last_process(self):
        return self.state.is_last_process

    @property
    def mixed_precision(self):
        return self.state.mixed_precision

    @property
    def gradient_accumulation_steps(self):
        return self.gradient_state.num_steps

    @gradient_accumulation_steps.setter
    def gradient_accumulation_steps(self, value):
        self.gradient_state.plugin_kwargs.update({"num_steps": value})

    @property
    def train_window(self) -> int:
        """Dispatch-amortization window K: how many full train steps
        ``build_train_window`` fuses into ONE compiled program (1 = one
        dispatch per step, the ``build_train_step`` shape). Default comes from
        the launcher contract (``--train_window`` → ACCELERATE_TRAIN_WINDOW),
        else 1; ``build_train_window(window=K)`` pins it."""
        if self._train_window is None:
            from .utils.constants import ENV_TRAIN_WINDOW

            raw = os.environ.get(ENV_TRAIN_WINDOW, "").strip()
            try:
                value = int(raw) if raw else 1
            except ValueError:
                raise ValueError(
                    f"{ENV_TRAIN_WINDOW}={raw!r} is not an integer"
                ) from None
            if value < 1:
                raise ValueError(f"{ENV_TRAIN_WINDOW} must be >= 1, got {value}")
            self._train_window = value
        return self._train_window

    @train_window.setter
    def train_window(self, value):
        value = int(value)
        if value < 1:
            raise ValueError(f"train_window must be >= 1, got {value}")
        self._train_window = value

    @property
    def zero_sharding(self) -> bool:
        """Cross-replica (ZeRO-style) sharding of optimizer state and the
        weight update along the dp axis (arxiv 2004.13336; ROADMAP item 2):
        opt-state leaves take each param's layout further partitioned over
        ``dp``, and the fused update lowers as reduce-scatter(grads) →
        sharded clip+update → all-gather(new params), cutting dp-replicated
        opt-state HBM to ~1/dp (the ``memcheck --replicated-opt-gib`` gate).
        Default comes from the launcher contract (``--zero_sharding`` →
        ACCELERATE_ZERO_SHARDING), else off; set it before ``prepare()`` —
        prepared optimizers snapshot it."""
        if self._zero_sharding is None:
            from .utils.constants import ENV_ZERO_SHARDING
            from .utils.environment import parse_flag_from_env

            self._zero_sharding = parse_flag_from_env(ENV_ZERO_SHARDING)
        return self._zero_sharding

    @zero_sharding.setter
    def zero_sharding(self, value):
        self._zero_sharding = bool(value)
        # Propagate to optimizers prepared BEFORE the flip whose sharding
        # plan hasn't been realized yet (opt_state still None): once state
        # arrays exist on a plan, the flag is pinned for that optimizer.
        for opt in self._optimizers:
            if opt.opt_state is None:
                opt.zero_sharding = self._zero_sharding

    @property
    def kernels(self) -> str:
        """The Pallas kernel-layer backend spec (docs/kernels.md): a bare
        token (``pallas`` / ``interpret`` / ``reference``) or a per-op map
        (``paged_decode=pallas,int8_matmul=off``) resolved per op by
        ``ops/registry.py`` at build/trace time. Default comes from the
        launcher contract (``--kernels`` → ACCELERATE_KERNELS), else the
        reference lowerings. Set before building — compiled programs bake
        the resolved backend in (rebuild to switch, like train_window)."""
        if self._kernels is None:
            from .utils.constants import ENV_KERNELS

            self._kernels = os.environ.get(ENV_KERNELS, "") or ""
        return self._kernels

    @kernels.setter
    def kernels(self, value):
        from .ops.registry import parse_kernel_spec

        value = "" if value is None else str(value)
        parse_kernel_spec(value)  # validate eagerly: a typo dies here
        self._kernels = value
        # Propagate to optimizers prepared BEFORE the flip whose imperative
        # update hasn't been built yet (the zero_sharding precedent): once
        # _update_fn exists, the resolved backend is compiled in.
        for opt in self._optimizers:
            if opt._update_fn is None:
                opt.kernels = value

    @property
    def fp8_backend(self):
        """Which low-precision backend serves ``mixed_precision='fp8'`` (reference
        ``fp8_backend`` property :3939-3952): "INT8" (QAT matmuls) or "BF16"
        (cast-only fallback); None when fp8 isn't requested."""
        if self.state.mixed_precision != "fp8":
            return None
        recipe = self.fp8_recipe_handler or Fp8RecipeKwargs()
        return recipe.backend.upper()

    @property
    def sync_gradients(self):
        return self.gradient_state.sync_gradients

    @property
    def use_distributed(self):
        return self.state.use_distributed

    @property
    def project_dir(self):
        return self.project_configuration.project_dir

    @property
    def logging_dir(self):
        return self.project_configuration.logging_dir

    @property
    def save_iteration(self):
        return self.project_configuration.iteration

    # --------------------------------------------------------------- plumbing
    def print(self, *args, **kwargs):
        self.state.print(*args, **kwargs)

    def wait_for_everyone(self):
        self.state.wait_for_everyone()

    def on_main_process(self, f):
        return self.state.on_main_process(f)

    def on_local_main_process(self, f):
        return self.state.on_local_main_process(f)

    def on_process(self, f=None, process_index=None):
        return self.state.on_process(f, process_index)

    def split_between_processes(self, inputs, apply_padding: bool = False):
        return self.state.split_between_processes(inputs, apply_padding=apply_padding)

    @contextlib.contextmanager
    def main_process_first(self):
        with self.state.main_process_first():
            yield

    @contextlib.contextmanager
    def local_main_process_first(self):
        with self.state.local_main_process_first():
            yield

    def _place_batch(self, batch):
        """Ensure host arrays in a forward call are global mesh arrays."""
        return self._place_with(batch, make_global_batch)

    def _place_with(self, batch, placer):
        """Host ndarray leaves → ``placer(x, mesh)``; device-resident leaves
        (and non-arrays) pass through untouched."""
        if not self.device_placement:
            return batch

        mesh = self.mesh

        def _one(x):
            if isinstance(x, jax.Array):
                return x
            if isinstance(x, np.ndarray):
                return placer(x, mesh)
            return x

        return jax.tree_util.tree_map(_one, batch)

    # ---------------------------------------------------------------- prepare
    def prepare(self, *args, device_placement=None):
        """Classify & lower each object (reference ``prepare`` :1289-1443).

        models → ``PreparedModel`` (sharded params), optax transforms →
        ``AcceleratedOptimizer``, dataloaders → sharded device-feeding loaders,
        schedules → ``AcceleratedScheduler``. Order is preserved.
        """
        from .telemetry import span

        with span("prepare"):
            return self._prepare(*args, device_placement=device_placement)

    def _prepare(self, *args, device_placement=None):
        import optax

        result = []
        prepared_model = None
        prepared_opts = []
        for obj in args:
            kind = self._classify(obj)
            if kind == "model":
                prepared = self.prepare_model(obj)
                prepared_model = prepared
            elif kind == "optimizer":
                prepared = AcceleratedOptimizer(
                    obj, scaler=self.scaler, host_offload=self._offload_opt_state,
                    zero_sharding=self.zero_sharding, kernels=self.kernels,
                )
                prepared_opts.append(prepared)
                self._optimizers.append(prepared)
            elif kind == "dataloader":
                prepared = self.prepare_data_loader(obj)
            elif kind == "scheduler":
                prepared = obj  # bound after optimizers exist
            else:
                prepared = obj
            result.append((kind, obj, prepared))

        # Bind optimizers to the model handle (single-model case; multi-model users
        # call prepare separately per pair, as in the reference's deepspeed guard).
        if prepared_model is not None:
            for opt in prepared_opts:
                opt.handle = prepared_model.handle
        elif prepared_opts and self._models:
            for opt in prepared_opts:
                opt.handle = self._models[-1].handle

        final = []
        for kind, obj, prepared in result:
            if kind == "scheduler":
                opts = prepared_opts or self._optimizers
                prepared = AcceleratedScheduler(
                    obj,
                    opts,
                    step_with_optimizer=self.step_scheduler_with_optimizer,
                    split_batches=self.dataloader_config.split_batches,
                )
                self._schedulers.append(prepared)
            final.append(prepared)
        return final[0] if len(final) == 1 else tuple(final)

    def _classify(self, obj) -> str:
        import optax

        if isinstance(obj, optax.GradientTransformation):
            return "optimizer"
        if isinstance(obj, (PreparedModel,)):
            return "model"
        if isinstance(obj, Module) or type(obj).__module__.startswith("flax"):
            return "model"
        if isinstance(obj, tuple) and len(obj) == 2 and (
            isinstance(obj[0], Module) or hasattr(obj[0], "apply")
        ):
            return "model"
        if hasattr(obj, "init") and hasattr(obj, "apply"):
            return "model"
        from .modules import is_torch_module

        if is_torch_module(obj):
            # Route to prepare_model → as_module, whose error points at from_hf.
            return "model"
        if hasattr(obj, "__iter__") and not callable(obj):
            return "dataloader"
        if _is_torch_dataloader(obj):
            return "dataloader"
        if callable(obj):
            # Only a schedule (int step -> lr, optax convention) belongs here.
            # Anything else callable — a loss function, a metric, a model
            # factory — must not be silently wrapped in AcceleratedScheduler.
            if _looks_like_schedule(obj):
                return "scheduler"
            raise TypeError(
                f"prepare() received a callable ({getattr(obj, '__name__', type(obj).__name__)}) "
                "that does not look like an LR schedule (a schedule takes a single "
                "integer step count, e.g. optax.cosine_decay_schedule(...)). Loss "
                "functions are registered with accelerator.set_loss_fn(...), and "
                "models must expose init/apply (see accelerate_tpu.modules.as_module)."
            )
        return "other"

    def prepare_model(self, model, device_placement=None, evaluation_mode: bool = False):
        """Lower a model to (module, sharded params) and wrap (reference
        ``prepare_model`` :1515-1800 — where DDP/FSDP wrapping happened, here the
        param pytree is placed onto the mesh by the sharding planner)."""
        if isinstance(model, PreparedModel):
            return model
        params = None
        if isinstance(model, tuple) and len(model) == 2:
            model, params = model
        module = as_module(model)
        if params is None:
            params = getattr(model, "params", None)
        if params is None:
            raise ValueError(
                "Model has no parameters: pass `(module, params)` to prepare(), or set "
                "`model.params` (model-zoo modules do this via `model.init_params(rng, ...)`)."
            )
        rules = None
        if isinstance(module, Module):
            rules = module.sharding_rules()
        # fp8 mixed precision: swap eligible model matmuls to the int8 QAT path
        # (reference routes fp8 through TE/AO module conversion at prepare time,
        # accelerator.py:1802-1830 there; see Fp8RecipeKwargs for the TPU story).
        # Config-driven compute routing. replace() (not mutation) gives the
        # module its own config copy: a config shared with other models (or
        # serialized later) must not silently change precision or attention.
        import dataclasses as _dc

        model_cfg = getattr(module, "config", None)
        if self.fp8_backend == "INT8":
            if model_cfg is not None and getattr(model_cfg, "matmul_precision", None) == "default":
                model_cfg = _dc.replace(model_cfg, matmul_precision="int8")
        # Sequence parallelism: with an sp axis in the mesh, route the model's
        # attention through the sequence-parallel op — ppermute ring (default)
        # or Ulysses all-to-all (SequenceParallelPlugin(ring_attention=False)).
        if self.mesh.shape.get("sp", 1) > 1:
            lw = getattr(model_cfg, "layer_windows", None) if model_cfg is not None else None
            if lw is not None and any(w is not None for w in lw):
                raise ValueError(
                    "Sequence parallelism (sp>1) does not support per-layer "
                    "windowed attention (layer_windows); train with sp=1 or use "
                    "fsdp/tp for memory."
                )
            if model_cfg is not None and (
                getattr(model_cfg, "attn_logit_softcap", None) is not None
                or getattr(model_cfg, "query_pre_attn_scalar", None) is not None
            ):
                # Gemma-2 score shaping is dense-only; fail at prepare, not at
                # trace time inside the first compiled step.
                raise ValueError(
                    "Sequence parallelism (sp>1) does not support attention "
                    "softcapping / query_pre_attn_scalar (Gemma-2); train with "
                    "sp=1 and use fsdp/tp for memory."
                )
            if model_cfg is not None and getattr(model_cfg, "sliding_window", None):
                # Fail here, not deep inside the first compiled step: the
                # sequence-parallel attention paths reject window masks
                # (advisor r2 — windowed Mistral/Qwen2 checkpoints under sp).
                raise ValueError(
                    "Sequence parallelism (sp>1) does not support sliding-window "
                    f"attention (sliding_window={model_cfg.sliding_window}). Train "
                    "this model with sp=1 (use fsdp/tp for memory), or clear "
                    "config.sliding_window to use full attention."
                )
            if model_cfg is not None and getattr(model_cfg, "attention_impl", None) == "auto":
                ring = self.sp_plugin.ring_attention if self.sp_plugin is not None else True
                model_cfg = _dc.replace(model_cfg, attention_impl="ring" if ring else "ulysses")
        if model_cfg is not None and model_cfg is not getattr(module, "config", None):
            module.config = model_cfg
        min_shard = self.fsdp_plugin.min_shard_size if self.fsdp_plugin is not None else 2**14
        shardings = plan_param_shardings(params, self.mesh, rules=rules, min_shard_size=min_shard)
        params = apply_shardings(params, shardings)
        rng = jax.random.key(int(os.environ.get("ACCELERATE_SEED", 0)) + 7919)
        # AutocastKwargs(enabled=False) pins fp32 compute regardless of the
        # mixed-precision setting (reference autocast ctx with enabled=False).
        compute_dtype = self.state.compute_dtype
        if self.autocast_handler is not None and not self.autocast_handler.enabled:
            compute_dtype = jnp.float32
        # Pipeline-parallel training: with a pp axis and a stage-protocol model,
        # swap the GSPMD layer-dim sharding (which all-gathers stage weights)
        # for the GPipe schedule with stationary weights + ppermuted activations.
        from .parallel.pipeline import resolve_pipeline_spec

        mbs = self.pp_plugin.num_microbatches if self.pp_plugin is not None else 0
        if mbs <= 0:
            env_mbs = os.environ.get("ACCELERATE_PP_MICROBATCHES", "").strip()
            try:
                mbs = int(env_mbs) if env_mbs else 0
            except ValueError:
                raise ValueError(
                    f"ACCELERATE_PP_MICROBATCHES={env_mbs!r} is not an integer"
                ) from None
        schedule = self.pp_plugin.schedule if self.pp_plugin is not None else "gpipe"
        pipeline_spec = resolve_pipeline_spec(module, params, self.mesh, mbs, schedule=schedule)
        handle = TrainHandle(
            module, params, shardings, self.mesh, compute_dtype, rng,
            pipeline_spec=pipeline_spec,
        )
        prepared = PreparedModel(handle, self, loss_fn=self._loss_fn)
        prepared.train(not evaluation_mode)
        self._models.append(prepared)
        # Keep the user's handle usable: reflect params back onto the original
        # object so `model.params` stays meaningful after prepare.
        try:
            model.params = params
        except (AttributeError, TypeError):
            pass
        return prepared

    def prepare_data_loader(self, data_loader, device_placement=None, slice_fn_for_dispatch=None):
        if isinstance(data_loader, (DataLoaderShard, DataLoaderDispatcher)):
            self._dataloaders.append(data_loader)
            return data_loader
        cfg = self.dataloader_config
        prepared = prepare_data_loader(
            data_loader,
            device=self.device,
            split_batches=cfg.split_batches,
            put_on_device=self.device_placement if device_placement is None else device_placement,
            rng_types=self.rng_types if _is_torch_dataloader(data_loader) else None,
            dispatch_batches=cfg.dispatch_batches,
            even_batches=cfg.even_batches,
            slice_fn_for_dispatch=slice_fn_for_dispatch,
            use_seedable_sampler=cfg.use_seedable_sampler,
            data_seed=cfg.data_seed,
            non_blocking=cfg.non_blocking,
            use_stateful_dataloader=cfg.use_stateful_dataloader,
        )
        self._dataloaders.append(prepared)
        return prepared

    def prepare_optimizer(self, optimizer, device_placement=None):
        prepared = AcceleratedOptimizer(
            optimizer, scaler=self.scaler, host_offload=self._offload_opt_state,
            zero_sharding=self.zero_sharding, kernels=self.kernels,
        )
        if self._models:
            prepared.handle = self._models[-1].handle
        self._optimizers.append(prepared)
        return prepared

    def prepare_scheduler(self, scheduler):
        prepared = AcceleratedScheduler(
            scheduler,
            self._optimizers,
            step_with_optimizer=self.step_scheduler_with_optimizer,
            split_batches=self.dataloader_config.split_batches,
        )
        self._schedulers.append(prepared)
        return prepared

    def set_loss_fn(self, loss_fn: Callable):
        """Register a custom loss: ``loss_fn(outputs, batch) -> scalar`` (jittable).
        Needed when the model returns logits and the loss lives in user code —
        the analog of computing ``F.cross_entropy`` outside the model in torch."""
        self._loss_fn = loss_fn
        for m in self._models:
            m.loss_fn = loss_fn
            m._train_call = None  # force recompile with the new loss

    # ------------------------------------------------------- training facade
    def backward(self, loss, **kwargs):
        """Bank the gradients already produced with ``loss`` (see module docstring;
        reference ``backward`` :2502-2534 divides by accum steps — we fold that
        into the accumulation scale)."""
        model = self._find_model_for_loss(loss)
        if model is None or model.handle.pending is None:
            raise RuntimeError(
                "backward() found no gradients: call it with the loss from a train-mode "
                "forward of a prepared model (or use build_train_step for the fused path)."
            )
        _, grads = model.handle.pending
        model.handle.pending = None
        opt = self._optimizer_for_handle(model.handle)
        if opt is None:
            raise RuntimeError("No prepared optimizer is bound to this model.")
        opt._accumulate(grads, scale=1.0 / self.gradient_accumulation_steps)

    def _find_model_for_loss(self, loss):
        for m in self._models:
            if m.handle.pending is not None and m.handle.pending[0] is loss:
                return m
        pending = [m for m in self._models if m.handle.pending is not None]
        if len(pending) == 1:
            return pending[0]
        return None

    def _optimizer_for_handle(self, handle):
        for opt in self._optimizers:
            if opt.handle is handle:
                return opt
        return self._optimizers[-1] if self._optimizers else None

    def _do_sync(self):
        """Reference ``_do_sync`` :1096-1103."""
        if self.gradient_state.sync_with_dataloader and self.gradient_state.end_of_dataloader:
            self.step = 0
            self.gradient_state._set_sync_gradients(True)
        else:
            self.step += 1
            self.gradient_state._set_sync_gradients(
                (self.step % self.gradient_state.num_steps) == 0
            )

    @contextlib.contextmanager
    def accumulate(self, *models):
        """Reference ``accumulate`` :1122-1166."""
        self._do_sync()
        yield

    @contextlib.contextmanager
    def no_sync(self, model):
        """DDP ``no_sync`` parity (:1007-1045). Under GSPMD the grad reduction is
        part of the compiled step, so there is nothing to suppress — accumulation
        correctness comes from the buffer add, and this context is a no-op."""
        yield

    @contextlib.contextmanager
    def join_uneven_inputs(self, joinables, even_batches=None):
        """DDP-join parity (:1167-1265): uneven tails never reach the mesh — the
        data layer pads to static shapes and records ``remainder`` — so joining is
        a no-op context."""
        yield

    @contextlib.contextmanager
    def autocast(self, autocast_handler=None):
        """Parity context (:3770). The dtype policy is baked into compiled calls
        when a model is prepared, so this context cannot retroactively retune an
        already-compiled model; a handler passed here (or via ``kwargs_handlers``)
        governs models prepared inside the context."""
        prev = self.autocast_handler
        if autocast_handler is not None:
            self.autocast_handler = autocast_handler
        try:
            yield
        finally:
            self.autocast_handler = prev

    def _optimizer_for_parameters(self, parameters):
        """Resolve which prepared optimizer owns ``parameters`` (a PreparedModel,
        its params pytree, or None). With one optimizer None is unambiguous; with
        several it is an error — the reference clips exactly the tensors you pass
        (``accelerator.py:2630``), so silently picking one would clip the wrong
        model."""
        if parameters is None:
            if len(self._optimizers) > 1:
                raise ValueError(
                    "Multiple optimizers are prepared; pass the model (or its "
                    "params) whose gradients should be clipped."
                )
            return self._optimizers[-1] if self._optimizers else None
        handle = getattr(parameters, "handle", None)  # PreparedModel
        for opt in self._optimizers:
            if opt.handle is handle and handle is not None:
                return opt
            if opt.handle is not None and opt.handle.params is parameters:
                return opt
        # Match by pytree identity of any leaf (covers params trees that were
        # rebuilt but share buffers) before giving up.
        param_ids = {id(l) for l in jax.tree_util.tree_leaves(parameters)}
        for opt in self._optimizers:
            if opt.handle is None:
                continue
            opt_ids = {id(l) for l in jax.tree_util.tree_leaves(opt.handle.params)}
            if param_ids & opt_ids:
                return opt
        raise ValueError(
            "clip_grad_norm_ received parameters that do not belong to any "
            "prepared optimizer; pass a model returned by prepare()."
        )

    def clip_grad_norm_(self, parameters=None, max_norm: float = 1.0, norm_type: int = 2):
        """Register clipping for the pending update and return the pre-clip global
        norm of the currently-banked grads (reference :2630-2690; the XLA branch
        there hand-rolls all_reduce — GSPMD already made our grads global)."""
        if norm_type != 2:
            raise NotImplementedError("only the L2 global norm is supported on TPU")
        opt = self._optimizer_for_parameters(parameters)
        if opt is None or opt.grads is None:
            return jnp.float32(0.0)
        opt._pending_clip_norm = float(max_norm)
        from .optimizer import _global_norm

        return _global_norm(opt.grads)

    def clip_grad_value_(self, parameters, clip_value: float):
        opt = self._optimizer_for_parameters(parameters)
        if opt is None or opt.grads is None:
            return
        opt._accum_grads = jax.tree_util.tree_map(
            lambda g: jnp.clip(g, -clip_value, clip_value), opt._accum_grads
        )

    # ----------------------------------------------------------- fused step
    def _fused_value_and_grads(self, model: PreparedModel, loss_fn=None):
        """The ``(params, batch, rng) -> (loss, grads)`` core shared by
        ``build_train_step`` and ``build_train_window`` — one definition so
        the 1F1B/GSPMD routing and loss contract cannot diverge between the
        per-step and windowed programs."""
        handle = model.handle
        spec = handle.pipeline_spec
        if model._uses_1f1b():
            model._check_1f1b_loss_fn(loss_fn if loss_fn is not None else model.loss_fn)

            def value_and_grads(params, batch, rng):
                loss, grads, _aux = spec.train_grads(
                    handle.module, params, batch, compute_dtype=handle.compute_dtype,
                    param_shardings=handle.param_shardings,
                )
                return loss, grads
        else:
            loss_of = model.training_loss_fn(loss_fn)

            def value_and_grads(params, batch, rng):
                return jax.value_and_grad(loss_of)(params, batch, rng)

        return value_and_grads

    def _fused_step_body(self, model: PreparedModel, optimizer: AcceleratedOptimizer,
                         accum: int, loss_fn=None):
        """``(params, opt_state, accum_grads, count, batch, rng, clip_norm) ->
        (params, opt_state, accum_grads, count, loss)`` — the per-step math
        both fused programs compile: forward+backward via
        :meth:`_fused_value_and_grads`, grad accumulation at ``1/accum``
        scale, global-norm clip, conditional ``tx.update``/apply, buffer
        zero-reset. One definition so ``build_train_window``'s bit-exactness
        vs K sequential ``build_train_step`` calls is structural, not
        maintained by hand."""
        import optax

        tx = optimizer.tx
        value_and_grads = self._fused_value_and_grads(model, loss_fn)
        # ZeRO (cross-replica weight-update sharding, arxiv 2004.13336): when
        # the optimizer's dp plan is active, the update region is constrained
        # to it — GSPMD turns the gradient all-reduce + slice into a
        # reduce-scatter, runs clip+update on 1/dp of every param, and
        # all-gathers the new params back to their base layout. Inside a
        # K-step window the gather is async-schedulable against the NEXT
        # step's compute (the xla_flags latency presets overlap it). The
        # named scopes ride into collective op_name metadata so the program
        # auditor attributes the deliberate dp all-gather as ZeRO traffic.
        zero_specs = optimizer.zero_param_shardings
        base_specs = model.handle.param_shardings if zero_specs is not None else None
        # Pallas fused-update kernel (ops/pallas/fused_update.py): when the
        # registry resolves the `fused_update` op away from reference AND the
        # optimizer matches a supported optax family (adam/adamw/sgd — the
        # closure-introspected plan), the update region's per-leaf chain
        # (clip-scale + moments + apply + cast + buffer zero) runs as ONE
        # pallas pass per leaf. With ZeRO on it executes inside the
        # zero_update-constrained region, i.e. on the 1/dp shard between the
        # reduce-scatter and the param all-gather. An unsupported optimizer
        # falls back to the reference chain silently — per-instance, the
        # registry's clean-fallback contract.
        from .ops.registry import resolve_backend

        kernel_backend = resolve_backend("fused_update", self.kernels)
        fused_plan = None
        if kernel_backend != "reference":
            from .ops.pallas.fused_update import plan_fused_update

            fused_plan = plan_fused_update(tx)

        def step_body(params, opt_state, accum_grads, count, batch, rng, clip_norm):
            if zero_specs is not None:
                # GSPMD gives each HLO value ONE sharding: without this pin,
                # the update branch's dp constraint propagates back through
                # the shared `params` value into the forward/backward, which
                # would both re-materialize params every step AND change the
                # gradient reduction order (breaking bit-exactness vs the
                # replicated path). The pin anchors the value the forward
                # consumes at its base layout; the update-region constraint
                # below then lowers as a local slice at the region edge.
                params = jax.lax.with_sharding_constraint(params, base_specs)
                accum_grads = jax.lax.with_sharding_constraint(
                    accum_grads, base_specs
                )
            loss, grads = value_and_grads(params, batch, rng)
            accum_grads = jax.tree_util.tree_map(
                lambda a, g: a + g / accum, accum_grads, grads
            )
            if zero_specs is not None:
                # Same propagation block on the gradient side: the update
                # region's dp constraint must not reach back through this add
                # into the backward (which would re-partition the transpose
                # ops and change the gradient reduction order).
                accum_grads = jax.lax.with_sharding_constraint(
                    accum_grads, base_specs
                )
            count = count + 1
            do_update = (count % accum) == 0

            def upd(operand):
                params, opt_state, grads = operand
                if zero_specs is not None:
                    with jax.named_scope("zero_update"):
                        return _zero_upd(params, opt_state, grads)
                return _upd_math(params, opt_state, grads)

            def _zero_upd(params, opt_state, grads):
                # Entering the region: replicated → dp-sharded constraints
                # lower as local slices of the (already all-reduced) grads —
                # XLA's all-reduce+slice fusion turns the pair into the
                # reduce-scatter of the ZeRO schedule where profitable.
                grads = jax.lax.with_sharding_constraint(grads, zero_specs)
                params = jax.lax.with_sharding_constraint(params, zero_specs)
                new_params, new_opt, zero = _upd_math(params, opt_state, grads)
                with jax.named_scope("zero_gather_params"):
                    new_params = jax.lax.with_sharding_constraint(
                        new_params, base_specs
                    )
                # The accumulation buffer keeps its base layout (it was
                # seeded as zeros_like(params)): a constant, no traffic —
                # this just stops the donated buffer's alias from drifting
                # onto the dp-sharded layout across iterations.
                zero = jax.lax.with_sharding_constraint(zero, base_specs)
                return new_params, new_opt, zero

            def _upd_math(params, opt_state, grads):
                # With ZeRO on this is per-shard partial sums + ONE scalar
                # cross-replica reduce; the clip factor (and with it every
                # downstream op) stays elementwise either way, which is what
                # keeps the sharded path bit-exact vs the replicated one
                # whenever clipping is off (clip_norm <= 0 → factor == 1.0).
                gnorm = jnp.sqrt(
                    sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                        for g in jax.tree_util.tree_leaves(grads))
                )
                factor = jnp.where(
                    (clip_norm > 0) & (gnorm > clip_norm),
                    clip_norm / (gnorm + 1e-6), 1.0,
                )
                if fused_plan is not None:
                    from .ops.pallas.fused_update import fused_update_apply

                    return fused_update_apply(
                        params, opt_state, grads, plan=fused_plan,
                        clip_factor=factor,
                        interpret=(kernel_backend == "interpret"),
                        # Under ZeRO the kernel covers the 1/dp shard: the
                        # plan sizes its shard-local tile grid.
                        shardings=zero_specs,
                    )
                grads = jax.tree_util.tree_map(lambda g: g * factor, grads)
                updates, new_opt = tx.update(grads, opt_state, params)
                new_params = optax.apply_updates(params, updates)
                zero = jax.tree_util.tree_map(jnp.zeros_like, grads)
                return new_params, new_opt, zero

            def keep(operand):
                return operand

            params, opt_state, accum_grads = jax.lax.cond(
                do_update, upd, keep, (params, opt_state, accum_grads)
            )
            return params, opt_state, accum_grads, count, loss

        return step_body

    def _fused_build_prologue(self, handle, optimizer: AcceleratedOptimizer,
                              accum: int, builder: str):
        """Shared scaffolding for both fused builders: lazily zero the donated
        accumulation buffer, seed the device-resident micro-step count, feed
        the model's flop count to the timeline, and return ``(count_box,
        check_stale_accum)`` — the stale-accumulation guard each wrapper calls
        per dispatch. One definition so the builders' build-time contract
        cannot drift apart."""
        # A (re)build restarts the compiled program's accumulation state: the
        # device micro-step count seeds at 0 below, so the buffer must start
        # zeroed too — a partially-filled buffer left by a prior build (or by
        # imperative backward() calls) would desynchronize the boundary and
        # silently fold extra microbatches into the first update.
        optimizer._accum_grads = jax.tree_util.tree_map(jnp.zeros_like, handle.params)
        count_box = [jnp.int32(0)]
        # The MFU estimate needs the model's flop count; the zoo models expose
        # it, anything else leaves the timeline at tokens/s only.
        flops_fn = getattr(handle.module, "flops_per_token", None)
        if self.telemetry.enabled and callable(flops_fn):
            try:
                self.telemetry.timeline.set_model_flops(float(flops_fn()))
            except Exception:
                pass

        build_epoch = self._mesh_epoch

        def check_stale_accum():
            if self._mesh_epoch != build_epoch:
                # The program compiled for shardings on a mesh an elastic
                # transition has since replaced; running it would feed the
                # dead layout. run_resilient re-enters train_fn so the
                # rebuild is one call away.
                raise RuntimeError(
                    f"The device mesh was resharded (elastic world-size "
                    f"change) after {builder}; call {builder} again so the "
                    "program compiles for the new mesh and sharding layout."
                )
            if self.gradient_accumulation_steps != accum:
                # The compiled program bakes the accumulation scale in; a
                # mid-run change would silently diverge from the imperative
                # path (which reads GradientState live) — fail instead.
                raise RuntimeError(
                    f"gradient_accumulation_steps changed from {accum} to "
                    f"{self.gradient_accumulation_steps} after {builder}; "
                    f"call {builder} again to pick up the new value."
                )

        return count_box, check_stale_accum

    def build_train_step(self, model: PreparedModel, optimizer: AcceleratedOptimizer, loss_fn=None):
        """ONE compiled XLA program per microbatch: forward + backward + buffer
        accumulation + (conditional) optimizer update, with params/opt-state/grad
        buffers donated. This is the TPU-shaped hot loop — no host round-trips, no
        retraces across accumulation boundaries (SURVEY.md §7 hard part 3).

        Returns ``step(batch) -> loss`` operating on the shared handle state.
        """
        handle = model.handle
        optimizer._ensure_initialized()
        accum = self.gradient_accumulation_steps
        step_body = self._fused_step_body(model, optimizer, accum, loss_fn)

        from .utils.environment import safe_donate_argnums

        donate = safe_donate_argnums((0, 1, 2, 3))

        @partial(jax.jit, donate_argnums=donate)
        def _step(params, opt_state, accum_grads, count, batch, rng, clip_norm):
            return step_body(params, opt_state, accum_grads, count, batch, rng, clip_norm)

        from .telemetry import span
        from .telemetry.timeline import batch_token_count

        count_box, check_stale_accum = self._fused_build_prologue(
            handle, optimizer, accum, "build_train_step"
        )

        def _step_args(batch, rng, clip_norm):
            return (
                handle.params, optimizer.opt_state, optimizer._accum_grads,
                count_box[0], self._place_batch(batch), rng, jnp.float32(clip_norm),
            )

        def step(batch, clip_norm: float = 0.0):
            check_stale_accum()
            handle.step_counter += 1
            rng = jax.random.fold_in(handle.rng, handle.step_counter)
            # self.telemetry (not a build-time capture) so a later
            # configure_telemetry() redirects the feed, and ACCELERATE_
            # TELEMETRY=0 strips the per-step instrumentation entirely.
            telemetry = self.telemetry
            if not telemetry.enabled:
                (handle.params, optimizer.opt_state, optimizer._accum_grads,
                 count_box[0], loss) = _step(*_step_args(batch, rng, clip_norm))
                return loss
            with span("train_step"):
                (handle.params, optimizer.opt_state, optimizer._accum_grads,
                 count_box[0], loss) = _step(*_step_args(batch, rng, clip_norm))
            # Per-step timeline sample: a clock read + deque append; the loss
            # scalar is retained (never fetched) so the dispatch stays async.
            telemetry.on_fused_step(tokens=batch_token_count(batch), loss=loss)
            return loss

        def lower(batch, clip_norm: float = 0.0):
            """Lower (without running) the fused step for HLO inspection — used
            by the collective-count tests to pin each plan's communication
            pattern without multi-chip hardware."""
            return _step.lower(*_step_args(batch, handle.rng, clip_norm))

        step.lower = lower
        step._audit_meta = self._builder_audit_meta(
            "build_train_step", handle, optimizer, donate, (0, 1, 2, 3),
            lambda batch, clip_norm=0.0: jax.make_jaxpr(step_body)(
                *_step_args(batch, handle.rng, clip_norm)
            ),
        )
        return step

    # --------------------------------------------------------- fused windows
    def build_train_window(self, model: PreparedModel, optimizer: AcceleratedOptimizer,
                           window: int | None = None, loss_fn=None):
        """ONE compiled XLA program per K steps: ``lax.scan`` of K full train
        steps (forward + backward + accumulation + conditional update, buffers
        donated) over a K-stacked device-resident batch window — the
        dispatch-amortized hot loop (docs/performance.md "Dispatch
        amortization"). Each launch pays ONE program dispatch where
        ``build_train_step`` pays K, which is the whole game on a
        high-latency control path (the tunneled rig's ~0.5 s RTT per
        dispatch); the per-step math — accumulation scale, clip, RNG fold-in
        sequence — is bit-identical to K sequential fused steps.

        ``window`` defaults to (and pins) :attr:`train_window`
        (ACCELERATE_TRAIN_WINDOW / ``launch --train_window``); ``window=1``
        is exactly the ``build_train_step`` program with a leading length-1
        batch axis. Composes with gradient accumulation (K in-window
        micro-steps advance the same accumulation counter), the health guard
        (``guard_step(losses, step=..., window=K)`` dispatches one windowed
        verdict, quarantines the exact in-window step, and snapshots at
        window boundaries), preemption hooks
        (``checkpoint_on_preemption(window=K)``), and the 1F1B/fused-loss
        paths via the shared forward core.

        Returns ``step_window(window_batch) -> losses`` where ``window_batch``
        has a leading K axis on every leaf (``DeviceBatchPrefetcher(...,
        window=K)`` builds these, already on device, for K > 1; at
        ``window=1`` the prefetcher deliberately yields PLAIN batches shaped
        for ``build_train_step`` — the unwindowed async-prefetch pairing —
        so stack a length-1 leading axis yourself to feed a K=1 window
        program) and ``losses`` is the retained per-step K-vector — drain it
        through the timeline's no-blocking-fetch discipline, never
        ``float()`` it mid-loop.
        """
        window = self.train_window if window is None else int(window)
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        # Pin the accelerator-level knob so the stale-config check below has
        # one source of truth (mirrors gradient_accumulation_steps semantics).
        self.train_window = window
        handle = model.handle
        optimizer._ensure_initialized()
        accum = self.gradient_accumulation_steps
        step_body = self._fused_step_body(model, optimizer, accum, loss_fn)

        from .utils.environment import safe_donate_argnums

        donate = safe_donate_argnums((0, 1, 2, 3))

        @partial(jax.jit, donate_argnums=donate)
        def _window(params, opt_state, accum_grads, count, batches, counters,
                    base_rng, clip_norm):
            def body(carry, xs):
                params, opt_state, accum_grads, count = carry
                batch, counter = xs
                # Same stream as the per-step program: fold_in of the handle
                # key at this step's counter value.
                rng = jax.random.fold_in(base_rng, counter)
                params, opt_state, accum_grads, count, loss = step_body(
                    params, opt_state, accum_grads, count, batch, rng, clip_norm
                )
                return (params, opt_state, accum_grads, count), loss

            (params, opt_state, accum_grads, count), losses = jax.lax.scan(
                body, (params, opt_state, accum_grads, count), (batches, counters)
            )
            return params, opt_state, accum_grads, count, losses

        from .telemetry import span
        from .telemetry.timeline import batch_token_count

        count_box, check_stale_accum = self._fused_build_prologue(
            handle, optimizer, accum, "build_train_window"
        )

        def _check_leading_axis(batch):
            for leaf in jax.tree_util.tree_leaves(batch):
                if hasattr(leaf, "shape") and np.ndim(leaf) > 0:
                    if leaf.shape[0] != window:
                        hint = (
                            "Use DeviceBatchPrefetcher(..., window=K) or "
                            "np.stack K batches."
                            if window > 1 else
                            "Stack a length-1 leading axis (np.expand_dims, "
                            "axis=0) — DeviceBatchPrefetcher(window=1) yields "
                            "PLAIN batches shaped for build_train_step."
                        )
                        raise ValueError(
                            f"build_train_window(window={window}) expects every "
                            f"batch leaf stacked on a leading K axis; got leading "
                            f"dim {leaf.shape[0]} (shape {tuple(leaf.shape)}). "
                            + hint
                        )

        def _window_args(batch, clip_norm: float = 0.0):
            """The exact argument tuple the compiled window consumes — shared
            by step_window, lower(), and the audit jaxpr thunk so the audited
            program can never diverge from the program that actually runs.
            Counters derive from the CURRENT step_counter (callers advance it
            after assembling args)."""
            counters = jnp.arange(
                handle.step_counter + 1, handle.step_counter + window + 1,
                dtype=jnp.int32,
            )
            return (
                handle.params, optimizer.opt_state, optimizer._accum_grads,
                count_box[0], self._place_window_batch(batch), counters,
                handle.rng, jnp.float32(clip_norm),
            )

        def step_window(batch, clip_norm: float = 0.0):
            check_stale_accum()
            if self.train_window != window:
                raise RuntimeError(
                    f"train_window changed from {window} to {self.train_window} "
                    "after build_train_window; the compiled program scans exactly "
                    f"{window} steps per dispatch — call build_train_window again "
                    "to pick up the new value."
                )
            _check_leading_axis(batch)
            args = _window_args(batch, clip_norm)
            handle.step_counter += window
            telemetry = self.telemetry
            if not telemetry.enabled:
                (handle.params, optimizer.opt_state, optimizer._accum_grads,
                 count_box[0], losses) = _window(*args)
                return losses
            with span("train_window"):
                (handle.params, optimizer.opt_state, optimizer._accum_grads,
                 count_box[0], losses) = _window(*args)
            # One boundary, K steps: the timeline splits wall time and tokens
            # per step and retains the K-vector of losses (no fetch here).
            telemetry.on_fused_step(
                tokens=batch_token_count(batch), loss=losses, steps=window
            )
            return losses

        def lower(batch, clip_norm: float = 0.0):
            """Lower (without running) the fused window for HLO inspection /
            auditing — the window-builder analog of build_train_step's lower."""
            _check_leading_axis(batch)
            return _window.lower(*_window_args(batch, clip_norm))

        step_window.window = window
        step_window.lower = lower
        step_window._audit_meta = self._builder_audit_meta(
            "build_train_window", handle, optimizer, donate, (0, 1, 2, 3),
            lambda batch, clip_norm=0.0: jax.make_jaxpr(_window)(
                *_window_args(batch, clip_norm)
            ),
            window=window,
        )
        return step_window

    # ------------------------------------------------------------- audit
    def _builder_audit_meta(self, builder: str, handle, optimizer,
                            effective_donate: tuple, intended_donate: tuple,
                            jaxpr_thunk, window: int = 1):
        """Audit metadata the fused builders attach to their returned step fn:
        the donation contract (what was intended vs what safe_donate_argnums
        left after platform gating, plus how many flat buffers the donated
        pytrees flatten to — the count that catches PARTIAL donation
        regressions), the mesh for collective attribution, the compute dtype
        for upcast detection, a jaxpr thunk for the pre-partitioning walk, and
        the donated-pytree class join (``memory_classes``) the static memory
        auditor (analysis/memory.py) uses to attribute flat input buffers to
        param / opt-state / accum classes with their shardings. The class
        thunks read the LIVE handle/optimizer state so an audit after steps
        (donated buffers replaced) still sees current shapes."""
        try:
            compute_dtype = np.dtype(handle.compute_dtype).name
        except Exception:
            compute_dtype = None
        # Donated argnums (0,1,2,3) = params, opt_state, accum buffer, count.
        donated_leaves = (
            len(jax.tree_util.tree_leaves(handle.params))
            + len(jax.tree_util.tree_leaves(optimizer.opt_state))
            + len(jax.tree_util.tree_leaves(optimizer._accum_grads))
            + 1  # the device-resident micro-step count scalar
        )
        zero_meta = None
        if getattr(optimizer, "zero_active", False):
            from .analysis.audit import zero_gather_shapes

            zero_meta = {
                "axis": "dp",
                "param_shapes": zero_gather_shapes(
                    handle.params, handle.param_shardings, self.mesh
                ),
            }
        from .ops.registry import resolved_backends

        kernels_meta = {"spec": self.kernels,
                        "backends": resolved_backends(self.kernels)}
        try:
            from .ops.pallas.fused_update import plan_fused_update

            plan = (plan_fused_update(optimizer.tx)
                    if kernels_meta["backends"].get("fused_update") != "reference"
                    else None)
            kernels_meta["fused_update_plan"] = plan.describe() if plan else None
        except Exception:
            kernels_meta["fused_update_plan"] = None
        return {
            "builder": builder,
            "mesh": self.mesh,
            "compute_dtype": compute_dtype,
            "kernels": kernels_meta,
            "expected_donations": tuple(intended_donate),
            "expected_donated_leaves": donated_leaves,
            "donation_dropped_by_policy": (
                bool(intended_donate) and not effective_donate
            ),
            "jaxpr_thunk": jaxpr_thunk,
            "window": int(window),
            # Non-None when the optimizer's cross-replica plan engaged: the
            # auditor classifies the update's deliberate dp collectives
            # (zero_update / zero_gather_params scopes, or an all-gather
            # landing exactly on a param's base per-device shape) as ZeRO
            # traffic instead of zero-sync violations.
            "zero_sharding": zero_meta,
            "memory_classes": {
                "params": (lambda: handle.params,
                           lambda: handle.param_shardings),
                "opt_state": (lambda: optimizer.opt_state,
                              lambda: optimizer.opt_shardings),
                # The accumulation buffer is zeros_like(params): same
                # structure, same shardings.
                "accum": (lambda: optimizer._accum_grads,
                          lambda: handle.param_shardings),
            },
        }

    def audit(self, built, batch, clip_norm: float = 0.0,
              intermediate_threshold_bytes: int = 64 * 1024 * 1024,
              memory: bool = True):
        """Statically audit a built artifact (``build_train_step`` /
        ``build_train_window`` output, or any jitted fn exposing ``.lower``)
        against the framework's program-level invariants: collective inventory
        per mesh axis (dp-axis all-gathers flagged), donation effectiveness
        via input–output aliasing, host callbacks, dtype upcasts, and
        oversized per-device intermediates. Returns
        :class:`~.analysis.AuditReport`; see docs/analysis.md for the schema.

        For the fused builders the report additionally carries the static
        memory audit as ``report.memory`` (a
        :class:`~.analysis.MemoryReport`): per-device HBM bytes by class
        (param / opt-state / accum / batch / activation-workspace), the
        sharded-vs-replicated split per named mesh axis, implicit resharding
        copies, and the OOM-before-launch verdict. ``memory=False`` skips it.

        ``batch`` must be shaped as the artifact expects (window-stacked for a
        window program). Auditing lowers and compiles but never executes — no
        training state is touched."""
        from .analysis import audit_built

        report = audit_built(
            built, batch, clip_norm,
            mesh=self.mesh,
            intermediate_threshold_bytes=intermediate_threshold_bytes,
            memory=memory,
        )
        # Feed the trace attributor's axis join: a later profile capture can
        # then attribute measured collective time to the NAMED mesh axes this
        # program's inventory established (telemetry/traceview.py).
        from .telemetry.traceview import attach_collective_axes, attach_kernel_names

        attach_collective_axes(report)
        # Same join for named Pallas kernels: captured custom-call time then
        # attributes to the kernels this program's inventory established.
        attach_kernel_names(report)
        if report.memory is not None:
            # Arm the timeline's predicted-vs-observed peak cross-check: the
            # next summary() compares this static prediction to the live
            # memory_stats() peak on backends that report one.
            self.telemetry.timeline.set_predicted_peak(
                report.memory.predicted_peak_bytes
            )
        return report

    def fingerprint(self, built, batch, clip_norm: float = 0.0,
                    config: str = "unknown", report=None):
        """Canonical :class:`~.analysis.fingerprint.ProgramFingerprint` of a
        built artifact — the drift-gate identity (per-axis collective
        inventory with ZeRO attribution, donation contract + misses,
        per-class replication split, dtype-flow census/flags). ``report``
        reuses an :meth:`audit` already run on the SAME program so only a
        fresh lowering is paid; without it the program is lowered, compiled,
        and audited here. Never executes a step."""
        from .analysis.fingerprint import fingerprint_built

        return fingerprint_built(
            built, batch, clip_norm, config=config, mesh=self.mesh, report=report,
        )

    def memory_report(self, built, batch, clip_norm: float = 0.0,
                      budget_bytes: int | None = None):
        """Static HBM audit of a built artifact without the full program
        audit: returns the :class:`~.analysis.MemoryReport` directly (see
        :meth:`audit` for what it contains). ``budget_bytes`` overrides the
        per-generation HBM × headroom budget the OOM verdict gates on —
        the ``accelerate-tpu memcheck --budget-gib`` path."""
        from .analysis import memory_report_from_built

        report = memory_report_from_built(
            built, batch, clip_norm, mesh=self.mesh, budget_bytes=budget_bytes,
        )
        self.telemetry.timeline.set_predicted_peak(report.predicted_peak_bytes)
        return report

    def _place_window_batch(self, batch):
        """Host leaves of a K-stacked window → global mesh arrays (window axis
        replicated, batch axis — dim 1 — on the data axes). Device-resident
        leaves (the prefetcher's output) pass through untouched."""
        from .parallel.sharding import make_global_window_batch

        return self._place_with(batch, make_global_window_batch)

    # ------------------------------------------------------------ collectives
    def gather(self, tensor):
        return ops.gather(tensor)

    def gather_for_metrics(self, input_data, use_gather_object: bool = False):
        """Gather and drop the duplicated tail samples of the final batch
        (reference :2751-2823).

        Non-tensor payloads (strings, object-dtype arrays, arbitrary
        picklables) route through ``gather_object`` *by detection*, not by
        catching everything: a genuine collective failure (shape mismatch,
        dead host, backend error) on tensor data must surface, not silently
        degrade to the pickle path."""
        from .telemetry import span

        if not use_gather_object and self.num_processes > 1:
            use_gather_object = _has_object_leaves(input_data)
        with span("gather_for_metrics"):
            if use_gather_object:
                all_tensors = ops.gather_object(input_data)
            else:
                all_tensors = ops.gather(input_data)
        if not self.gradient_state.end_of_dataloader:
            return all_tensors
        remainder = self.gradient_state.remainder
        if remainder is None or remainder <= 0:
            return all_tensors
        if use_gather_object:
            return all_tensors[:remainder]

        def _trim(t):
            return t[:remainder] if hasattr(t, "shape") and np.ndim(t) > 0 else t

        return ops.recursively_apply(_trim, all_tensors)

    def reduce(self, tensor, reduction="sum", scale=1.0):
        return ops.reduce(tensor, reduction=reduction, scale=scale)

    def pad_across_processes(self, tensor, dim=0, pad_index=0, pad_first=False):
        return ops.pad_across_processes(tensor, dim=dim, pad_index=pad_index, pad_first=pad_first)

    # ------------------------------------------------------------ early stop
    def set_trigger(self):
        """Cross-process early-stop flag (reference :2536-2563)."""
        self.flag_tensor = np.ones((), dtype=np.int32)

    def check_trigger(self) -> bool:
        local = self.flag_tensor if self.flag_tensor is not None else np.zeros((), dtype=np.int32)
        total = ops.reduce(local, reduction="sum")
        from .utils.transfer import host_fetch

        if float(host_fetch(total)) >= 1:
            self.flag_tensor = None
            return True
        return False

    # -------------------------------------------------------------- unwrap &c
    def unwrap_model(self, model, keep_fp32_wrapper: bool = True):
        """Return (module, params) behind a PreparedModel (reference
        ``extract_model_from_parallel``, utils/other.py:197)."""
        if isinstance(model, PreparedModel):
            return model.module
        return model

    def get_state_dict(self, model, unwrap: bool = True):
        """Full (host) state dict — always gatherable here because params are
        global arrays (the zero3/FSDP special-casing at :3661 dissolves)."""
        if isinstance(model, PreparedModel):
            params = model.params
        else:
            params = getattr(model, "params", model)
        from .utils.transfer import host_fetch

        return jax.tree_util.tree_map(host_fetch, params)

    def free_memory(self, *objects):
        """Release prepared references & buffers (reference :3570-3608)."""
        self._models.clear()
        self._optimizers.clear()
        self._schedulers.clear()
        self._dataloaders.clear()
        self.step = 0
        import gc

        gc.collect()
        try:
            jax.clear_caches()
        except Exception:
            pass
        return objects

    def clear(self, *objects):
        return self.free_memory(*objects)

    # ------------------------------------------------------- trackers / log
    def init_trackers(self, project_name: str, config: dict | None = None, init_kwargs: dict | None = None):
        from .tracking import init_trackers as _init

        self.trackers = _init(self.log_with, project_name, self.logging_dir, config, init_kwargs, self)

    def get_tracker(self, name: str, unwrap: bool = False):
        for tracker in self.trackers:
            if tracker.name == name:
                return tracker.tracker if unwrap else tracker
        raise ValueError(f"Tracker {name} not found: available {[t.name for t in self.trackers]}")

    def log(self, values: dict, step: int | None = None, log_kwargs: dict | None = None):
        if self.is_main_process:
            for tracker in self.trackers:
                tracker.log(values, step=step, **((log_kwargs or {}).get(tracker.name, {})))

    def log_goodput(self, step: int | None = None):
        """Push the goodput/badput wall-clock breakdown (resilience/goodput.py)
        through the active trackers as ``goodput/*`` series — productive step
        time vs compile / checkpoint save / restore / restart downtime."""
        from .resilience.goodput import get_ledger

        self.log({f"goodput/{k}": v for k, v in get_ledger().summary().items()}, step=step)

    # -------------------------------------------------------------- telemetry
    @property
    def telemetry(self):
        """The process-wide :class:`~.telemetry.Telemetry` — always-on step
        timeline, span ring, metrics registry, straggler monitor — built from
        the launcher's env contract (ACCELERATE_TELEMETRY /
        ACCELERATE_METRICS_PORT / ACCELERATE_STRAGGLER_THRESHOLD) on first
        access; ``configure_telemetry`` overrides it."""
        if self._telemetry is None:
            from .telemetry import get_telemetry

            self._telemetry = get_telemetry()
        return self._telemetry

    def configure_telemetry(self, **kwargs):
        """Build the telemetry stack explicitly (kwargs go to
        :class:`~.telemetry.Telemetry`); replaces the lazy/env default for
        this process so framework-internal hooks see the same instance."""
        from .telemetry import Telemetry, set_telemetry

        previous = self._telemetry
        self._telemetry = Telemetry(**kwargs)
        # A fused step built before this call keeps feeding the (now current)
        # instance via the self.telemetry indirection; carry the model flop
        # count over so its MFU estimate survives the swap.
        if previous is not None and self._telemetry.timeline._flops_per_token is None:
            self._telemetry.timeline._flops_per_token = previous.timeline._flops_per_token
        set_telemetry(self._telemetry)
        return self._telemetry

    def log_telemetry(self, step: int | None = None):
        """Push the step-timeline summary and the metrics-registry snapshot
        through the active trackers — ``telemetry/*`` for the timeline schema
        (docs/observability.md) and ``metrics/*`` for every registered
        counter/gauge (goodput classes, health trips, restarts, ...)."""
        telemetry = self.telemetry
        values: dict = {}

        def flatten(prefix, value):
            if isinstance(value, dict):
                for key, inner in value.items():
                    flatten(f"{prefix}/{key}", inner)
            else:
                values[prefix] = value

        flatten("telemetry", telemetry.summary())
        for name, val in telemetry.registry.snapshot().items():
            values[f"metrics/{name}"] = val
        self.log(values, step=step if step is not None else self.step)

    def end_training(self):
        """Flush trackers AND join queued async checkpoint writes: a script
        that returns right after a non-blocking ``save_state`` must not drop
        shard writes still draining on orbax's background thread (an atexit
        hook in ``checkpointing`` is the backstop for scripts that never call
        this)."""
        if self.is_main_process:
            for tracker in self.trackers:
                tracker.finish()
        self.finish_pending_saves()
        self.wait_for_everyone()

    # ----------------------------------------------------------- checkpointing
    def register_for_checkpointing(self, *objects):
        """Objects with state_dict/load_state_dict saved in save_state (reference :3733)."""
        invalid = [o for o in objects if not (hasattr(o, "state_dict") and hasattr(o, "load_state_dict"))]
        if invalid:
            raise ValueError(f"Objects lack state_dict/load_state_dict: {invalid}")
        self._custom_objects.extend(objects)

    def save_state(self, output_dir: str | None = None, **save_model_func_kwargs):
        """``blocking=False`` queues the array writes in the background and
        returns immediately (training continues while HBM drains to disk);
        join with ``finish_pending_saves()`` or let ``load_state`` join."""
        from .checkpointing import save_accelerator_state
        from .telemetry import span

        with span("checkpoint_save"):
            return save_accelerator_state(self, output_dir, **save_model_func_kwargs)

    def finish_pending_saves(self):
        from .checkpointing import finish_pending_saves

        finish_pending_saves()

    def load_state(self, input_dir: str | None = None, **load_model_func_kwargs):
        from .checkpointing import load_accelerator_state
        from .telemetry import span

        with span("checkpoint_restore"):
            return load_accelerator_state(self, input_dir, **load_model_func_kwargs)

    def save_model(self, model, save_directory, max_shard_size="10GB", safe_serialization=True):
        from .checkpointing import save_model as _save_model

        return _save_model(self, model, save_directory, max_shard_size, safe_serialization)

    # -------------------------------------------------------------- resilience
    @property
    def preemption_watcher(self):
        """The process-wide :class:`~.resilience.preemption.PreemptionWatcher`,
        installed on first access (or earlier, by ``PartialState`` when the
        launcher exported ACCELERATE_HANDLE_PREEMPTION)."""
        if self._preemption_watcher is None:
            from .resilience.preemption import get_default_watcher

            self._preemption_watcher = get_default_watcher(install=True)
        return self._preemption_watcher

    def checkpoint_on_preemption(self, output_dir: str | None = None,
                                 step: int | None = None, window: int = 1) -> bool:
        """Call once per training step: emergency-checkpoint if preempted.

        Three things happen, in order: (1) the deterministic fault plan
        (ACCELERATE_FAULT_PLAN, resilience/faults.py) fires any fault scheduled
        for this step; (2) the preemption watcher's per-host flags (SIGTERM/
        SIGINT, maintenance poller) are combined into an all-host agreement —
        one scalar collective, so every process must call this at the same step
        boundary; (3) on agreement, a SYNCHRONOUS ``save_state`` runs (queued
        async writes joined too — the grace window is short and a half-written
        emergency checkpoint is worse than none) and True is returned so the
        training loop can exit cleanly for ``run_resilient`` / the launcher to
        restart-and-resume.

        ``step`` defaults to an internal once-per-call counter; pass the loop's
        own global step when resuming mid-plan so fault steps stay aligned.
        Windowed loops (``build_train_window``) call this once per window with
        ``window=K`` so the internal counter keeps per-STEP numbering (fault
        plans and resume positions stay window-size-independent); kill-style
        faults scheduled anywhere inside the window fire at its boundary — the
        earliest point host control returns from the fused program.
        """
        from .health.hang import beat_default
        from .resilience.faults import active_plan
        from .resilience.goodput import get_ledger

        window = max(int(window), 1)
        self._resilience_step += window
        step = self._resilience_step if step is None else step
        # A completed step boundary is a heartbeat: loops that only call this
        # hook (no guard_step) still keep the hang watchdog fed.
        beat_default(step)
        # ...and a telemetry boundary — but only when no health guard is in
        # play: guard_step is then the designated timeline feeder, and its
        # numbering (self.step) can diverge from the private resilience
        # counter here (resumes restore self.step; accumulation counts
        # micro-steps), which would defeat the per-step dedupe and
        # double-sample every step. Guard-less resilient loops keep their
        # timeline through this hook with its own consistent numbering.
        if self._health_guard is None:
            self.telemetry.on_step(step, state=self.state, window=window)
        # Install the watcher BEFORE the fault plan can deliver a signal: a
        # 'sigterm' fault at the first hooked step must hit the sticky-flag
        # handler, not the default disposition (process death).
        watcher = self.preemption_watcher
        plan = active_plan()
        if plan is not None:
            # Windowed loops: a kill/sigterm/stall scheduled at ANY in-window
            # step fires at this boundary, where host control first returns.
            for in_window in range(step - window + 1, step + 1):
                plan.maybe_fire(in_window)
        if not watcher.sync(self.state):
            return False
        logger.warning(f"Preemption agreed at step {step}: taking an emergency checkpoint.")
        self.save_state(output_dir)  # ckpt_save time recorded by checkpointing
        with get_ledger().track("ckpt_save"):
            self.finish_pending_saves()
        self.wait_for_everyone()
        return True

    def skip_first_batches(self, dataloader, num_batches: int = 0):
        return skip_first_batches(dataloader, num_batches)

    def reshard(self, devices=None, min_data_parallel: int = 1):
        """Re-form the mesh over a different device set (elastic world-size
        change) and redistribute all prepared state onto it — see
        :func:`~.resilience.elastic.reshard_accelerator` and
        docs/resilience.md "Elastic world size". Only the dp axis resizes;
        gradient accumulation rescales to preserve the global batch. Every
        fused program built before this call must be rebuilt (stale ones
        raise pointedly). Normally driven by ``run_resilient(elastic=True)``
        rather than called directly."""
        from .resilience.elastic import reshard_accelerator

        return reshard_accelerator(
            self, devices=devices, min_data_parallel=min_data_parallel
        )

    # -------------------------------------------------------------- health
    @property
    def health_guard(self):
        """The :class:`~.health.guard.HealthGuard` driven by ``guard_step``,
        built lazily from the env contract (ACCELERATE_GUARD_NUMERICS /
        ACCELERATE_SPIKE_ZSCORE — the launcher's --guard_numerics /
        --spike_zscore flags); ``configure_health`` overrides it."""
        if self._health_guard is None:
            self._health_guard = self._build_health_guard()
        return self._health_guard

    def configure_health(self, **kwargs):
        """Build the health guard explicitly (kwargs go to
        :class:`~.health.guard.HealthGuard`); replaces any lazy/env guard."""
        from .health.guard import HealthGuard

        self._health_guard = HealthGuard(**kwargs)
        return self._health_guard

    def _build_health_guard(self):
        from .health.guard import HealthGuard
        from .utils.constants import ENV_GUARD_NUMERICS, ENV_SPIKE_ZSCORE

        # The sentinel is always-on by default; the env can only widen or
        # disable it ("0"/"false"), mirroring the launch-flag semantics.
        kwargs: dict = {
            "numerics": os.environ.get(ENV_GUARD_NUMERICS, "").strip().lower()
            not in ("0", "false", "no")
        }
        zscore = os.environ.get(ENV_SPIKE_ZSCORE, "").strip()
        if zscore:
            kwargs["spike_zscore"] = float(zscore)
        return HealthGuard(**kwargs)

    def guard_step(self, loss=None, step: int | None = None, window: int = 1):
        """Call once per training step, after the optimizer step: run the
        training-health protocol (docs/health.md) on this step's ``loss``.

        Windowed loops (``build_train_window``) call this once per WINDOW:
        ``loss`` is the retained K-vector the window returned, ``step`` the
        last in-window step, and ``window=K`` — one verdict dispatch covers
        all K losses, a trip quarantines the exact in-window step, and
        last-known-good snapshots are captured at window boundaries.

        Heartbeats the hang watchdog, consumes any ``nan``/``loss_spike``
        fault scheduled for this step, folds the numerics + spike verdict
        into one on-device dispatch, drains prior verdicts without blocking,
        agrees any trip across hosts, and applies the recovery action —
        rollback to the last-known-good snapshot (quarantining the poisoned
        step so ``health_guard.should_skip`` excludes it on replay) or
        skip+quarantine. Returns a :class:`~.health.guard.HealthVerdict`;
        after ``verdict.rolled_back`` the loop must re-read ``self.step``.

        ``step`` defaults to ``self.step`` — the 1-based count the resilient
        loop convention maintains (the same numbering fault plans use).
        """
        from .health.hang import beat_default

        step = self.step if step is None else step
        beat_default(step)
        # Same-step telemetry sample BEFORE any rollback rewinds the count;
        # the straggler exchange inside is collective, and guard_step already
        # carries the every-host-same-step contract it needs. (Under windowed
        # dispatch the fused boundary already fed the timeline; the hook's
        # boundary-watermark dedupe makes this a no-op sample then.)
        # The K-vector rides through unchanged: step_end retains it unfetched
        # (drain takes the last element), and when build_train_window already
        # fed this boundary the dedupe watermark skips the fallback entirely.
        self.telemetry.on_step(step, state=self.state, loss=loss, window=window)
        return self.health_guard.guard_step(self, loss, step, window=window)

    # ---------------------------------------------------------------- profile
    @contextlib.contextmanager
    def profile(self, profile_handler=None):
        """Manual trace capture (reference ``profile`` :3797-3856 builds
        torch.profiler; output opens in TensorBoard/perfetto).

        Built on the same :class:`~.telemetry.profiler.ProfileManager` as the
        triggered captures (``--profile_steps``, the slow-step z-score, POST
        /profile), so a manual capture gets identical treatment: the covered
        step range is recorded from the boundaries observed inside the block,
        start/stop/parse overhead books as ``profile`` badput, the capture
        lands in the flight recorder and the
        ``accelerate_profile_captures_total{trigger="manual"}`` counter, and
        the parsed attribution report surfaces in
        ``telemetry.timeline.summary()["profile"]``. Manual captures are
        exempt from the triggered-capture budget. Yields the trace directory;
        yields None — and the block runs untraced — when no
        ``output_trace_dir`` is configured (reference parity) or when a
        triggered capture is already in flight (jax has one global trace;
        stealing it would cut the triggered range short)."""
        handler = profile_handler or self.profile_handler or ProfileKwargs()
        trace_dir = handler.output_trace_dir
        if trace_dir is None:
            yield None
            return
        from .telemetry.profiler import get_profile_manager

        with get_profile_manager().manual_capture(trace_dir) as capture_dir:
            yield capture_dir

    def __repr__(self):
        return f"Accelerator(state={self.state!r})"


def _looks_like_schedule(obj) -> bool:
    """Heuristic for optax-style LR schedules: a callable whose signature
    accepts exactly one required positional argument (the step count).
    Unsignaturable callables (C extensions) pass — AcceleratedScheduler's own
    ``schedule(0)`` probe is the backstop there."""
    import inspect

    try:
        sig = inspect.signature(obj)
    except (TypeError, ValueError):
        return True
    required = [
        p for p in sig.parameters.values()
        if p.default is inspect.Parameter.empty
        and p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
    ]
    has_varargs = any(p.kind == p.VAR_POSITIONAL for p in sig.parameters.values())
    return len(required) == 1 or (len(required) == 0 and has_varargs)


def _has_object_leaves(data) -> bool:
    """True when ``data`` contains a leaf the tensor all-gather cannot carry:
    an object/string-dtype array, or any non-array leaf (str, None, dataclass,
    ...) other than plain numbers inside the nested containers."""
    if isinstance(data, (list, tuple)):
        return any(_has_object_leaves(v) for v in data)
    if isinstance(data, dict):
        return any(_has_object_leaves(v) for v in data.values())
    if ops.is_tensor_like(data):
        from .utils.transfer import host_view

        dtype = host_view(data).dtype if not hasattr(data, "dtype") else data.dtype
        return dtype == object or np.issubdtype(dtype, np.str_) or np.issubdtype(dtype, np.bytes_)
    return not isinstance(data, (int, float, complex, bool, np.number))


def _is_torch_dataloader(obj) -> bool:
    try:
        import torch.utils.data as tud

        return isinstance(obj, tud.DataLoader)
    except ImportError:
        return False
