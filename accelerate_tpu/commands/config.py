"""`accelerate-tpu config` — interactive wizard + `--default` quick path.

Reference parity: ``src/accelerate/commands/config/cluster.py:57`` (an 869-LoC
questionnaire) and ``config/default.py``. The TPU build asks the questions that
matter on a pod: topology (hosts/coordinator), mesh axis sizes (dp/fsdp/tp/pp/sp),
and precision — there are no NCCL/fsdp/deepspeed backend menus because those
choices collapse into mesh shape under GSPMD.
"""

from __future__ import annotations

import argparse

from .config_args import ClusterConfig, default_config_file


def _ask(prompt: str, default, cast=str, choices=None):
    # Fixed-choice questions get the cursor menu on a real terminal (the
    # reference's selection-menu UX); free-form values and non-TTY sessions
    # (pipes, CI, tests mocking input()) keep the plain prompt contract.
    if choices is not None:
        from .menu import interactive_tty, select

        if interactive_tty():
            return select(prompt, choices, default=default)
    suffix = f" [{default}]" if default is not None else ""
    while True:
        raw = input(f"{prompt}{suffix}: ").strip()
        if not raw:
            return default
        try:
            val = cast(raw)
        except (TypeError, ValueError):
            print(f"  invalid value {raw!r}, expected {cast.__name__}")
            continue
        if choices is not None and val not in choices:
            print(f"  choose one of {choices}")
            continue
        return val


def _yesno(prompt: str, default: bool = False) -> bool:
    from .menu import interactive_tty, select

    if interactive_tty():
        order = ["yes", "no"]
        return select(prompt, order, default="yes" if default else "no") == "yes"
    raw = input(f"{prompt} [{'yes' if default else 'no'}]: ").strip().lower()
    if not raw:
        return default
    return raw in ("y", "yes", "true", "1")


def get_user_input() -> ClusterConfig:
    """The wizard (reference ``cluster.py:57`` `get_cluster_input`)."""
    compute_env = _ask(
        "In which compute environment are you running? (LOCAL_MACHINE/TPU_POD)",
        "LOCAL_MACHINE",
        str,
        ["LOCAL_MACHINE", "TPU_POD"],
    )
    use_cpu = _yesno("Do you want to run your training on CPU only (e.g. for debugging)?", False)
    distributed_type = "MULTI_CPU" if use_cpu else "JAX_TPU"
    num_machines, machine_rank, ip, port = 1, 0, None, None
    if compute_env == "TPU_POD":
        num_machines = _ask("How many hosts are in your TPU pod slice?", 1, int)
        if num_machines > 1:
            machine_rank = _ask("What is the rank of this host?", 0, int)
            ip = _ask("What is the IP address of the host that will run the JAX coordinator?", "127.0.0.1")
            port = _ask("What is the port the coordinator will listen on?", 8476, int)
    cpu_virtual = 0
    if use_cpu:
        cpu_virtual = _ask(
            "How many virtual devices should the CPU host expose (xla_force_host_platform_device_count)?",
            8,
            int,
        )
    dcn = 0
    if compute_env == "TPU_POD":
        if _yesno("Is this a MULTI-SLICE pod (slices connected over DCN)?", False):
            dcn = _ask("How many slices? (0 = auto-detect)", 0, int)
    print("Mesh axis sizes (1 disables an axis; dp=0 lets dp absorb all remaining devices):")
    dp = _ask("  data-parallel (dp) size", 0, int)
    fsdp = _ask("  fully-sharded (fsdp/ZeRO) size", 1, int)
    tp = _ask("  tensor-parallel (tp) size", 1, int)
    pp = _ask("  pipeline-parallel (pp) size", 1, int)
    sp = _ask("  sequence-parallel (sp) size", 1, int)
    ep = _ask("  expert-parallel (ep) size", 1, int)

    # ---- per-feature sections (reference cluster.py's guided flow) ----
    min_shard, cpu_offload = 0, False
    if fsdp > 1 or fsdp in (0, -1):  # 0/-1 = full-shard over remaining devices
        if _yesno("Do you want to configure FSDP options?", False):
            min_shard = _ask(
                "  minimum tensor size to shard (smaller stays replicated)", 2**14, int
            )
            cpu_offload = _yesno("  offload sharded optimizer state to host RAM?", False)
    pp_schedule, pp_mbs = "", 0
    if pp > 1:
        pp_schedule = _ask(
            "Pipeline schedule? (gpipe/1f1b — 1f1b caps activation memory at O(pp))",
            "gpipe", str, ["gpipe", "1f1b"],
        )
        pp_mbs = _ask("Pipeline microbatches? (0 = one per stage; >=4x pp for utilization)", 0, int)
    accum = _ask("How many gradient accumulation steps?", 1, int)
    project_dir, ckpt_limit, ckpt_auto, handle_preemption = None, 0, False, False
    # Elastic is tri-state like the health section below: skipping the
    # checkpointing section leaves None (nothing exported), an explicit
    # yes/no reaches the workers as ACCELERATE_ELASTIC=1/0.
    elastic, min_dp = None, 0
    if _yesno("Do you want to configure checkpointing?", False):
        project_dir = _ask("  project directory (checkpoints/logs root)", ".")
        ckpt_auto = _yesno("  automatic checkpoint naming (checkpoints/checkpoint_<n>)?", True)
        ckpt_limit = _ask("  how many checkpoints to keep? (0 = all)", 0, int)
        handle_preemption = _yesno(
            "  handle preemption (SIGTERM -> emergency checkpoint; resume via "
            "run_resilient)?", False
        )
        elastic = _yesno(
            "  elastic world size (run_resilient re-forms the mesh at the dp "
            "degree the surviving devices support and reshards the "
            "checkpoint onto it)?", False
        )
        if elastic:
            min_dp = _ask(
                "  minimum data-parallel degree a shrink may re-form at "
                "(0 = no floor)", 0, int
            )
    # Tri-state: skipping the section leaves None (nothing exported, library
    # defaults apply); explicit answers — including "no"/0 — reach the workers.
    guard_numerics, spike_zscore, hang_timeout = None, None, 0.0
    if _yesno(
        "Do you want to configure training-health guards (NaN sentinel, "
        "loss-spike rollback, hang watchdog)?", False
    ):
        guard_numerics = _yesno(
            "  always-on numerics sentinel (on-device finite loss/grad checks)?", True
        )
        spike_zscore = _ask(
            "  loss-spike robust z-score threshold (0 disables the detector)", 6.0, float
        )
        hang_timeout = _ask(
            "  hang watchdog timeout in seconds (0 = disabled; dumps stacks and "
            "exits 113 for the launcher to restart)", 0.0, float
        )
    # Tri-state like the health section: skipping leaves None (nothing
    # exported; telemetry defaults ON), explicit answers reach the workers.
    telemetry, metrics_port, straggler_threshold = None, 0, 0.0
    profile_steps, profile_slow_zscore = None, None
    fleet_metrics, slo_step_time, slo_ttft, slo_tpot = None, None, None, None
    journal_dir, trace_ring, flight_ring = None, None, None
    if _yesno(
        "Do you want to configure observability (step timeline, metrics "
        "endpoint, straggler alerts, profiling, fleet aggregation, SLOs)?",
        False,
    ):
        telemetry = _yesno(
            "  always-on telemetry (per-step timeline, spans, metrics registry)?",
            True,
        )
        metrics_port = _ask(
            "  Prometheus metrics port (0 = no HTTP endpoint; the registry "
            "still feeds trackers)", 0, int
        )
        straggler_threshold = _ask(
            "  straggler alert ratio vs the cross-host median step time "
            "(0 = library default 1.5)", 0.0, float
        )
        profile_steps = _ask(
            "  XLA trace capture step ranges (e.g. '10-12' or '10-12,50'; "
            "'off' = none)", "off"
        )
        profile_slow_zscore = _ask(
            "  slow-step trace trigger: robust z-score threshold over recent "
            "step times (0 = disabled)", 0.0, float
        )
        fleet_metrics = _yesno(
            "  fleet metric aggregation (the lead host scrapes every "
            "worker's registered endpoint into /fleet; `accelerate-tpu top` "
            "is the console)?", False
        )
        slo_step_time = _ask(
            "  SLO target: per-step wall time in seconds (0 = no target)",
            0.0, float,
        )
        slo_ttft = _ask(
            "  SLO target: serving time-to-first-token in seconds "
            "(0 = no target)", 0.0, float,
        )
        slo_tpot = _ask(
            "  SLO target: serving time-per-output-token in seconds "
            "(0 = no target)", 0.0, float,
        )
        journal_dir = _ask(
            "  durable telemetry journal directory (per-rank JSONL merged by "
            "`accelerate-tpu timeline`/`report`; '' = off)", ""
        )
        trace_ring = _ask(
            "  request-trace ring capacity (completed request records kept "
            "in memory; 0 = library default 1024)", 0, int
        )
        flight_ring = _ask(
            "  flight-recorder ring size (forensic events in the crash "
            "dump; 0 = library default 2048)", 0, int
        )
    # Disaggregated serving (serving_net/): declining leaves both None —
    # nothing exported, an inherited ACCELERATE_SERVING_ROLE /
    # ACCELERATE_ROUTER_ENDPOINT still flows through at launch. Answering
    # (even 'unified' / '') is an explicit choice that scrubs stale values.
    serving_role, router_endpoint = None, None
    serving_retry_budget, serving_lease_ttl, drain_grace_s = None, None, None
    if _yesno(
        "Do you want to configure disaggregated serving tiers (prefill/"
        "decode hosts with KV-chain handoff behind an affinity router)?",
        False,
    ):
        serving_role = _ask(
            "  serving role for the launched workers "
            "(unified/prefill/decode/router)",
            "unified", str, ["unified", "prefill", "decode", "router"],
        )
        router_endpoint = _ask(
            "  router endpoint host:port ('' = none)", ""
        )
        serving_retry_budget = _ask(
            "  router retry budget: re-dispatches per failed request "
            "(0 = library default 2)", 0.0, float,
        )
        serving_lease_ttl = _ask(
            "  worker discovery lease TTL in seconds "
            "(0 = library default 15)", 0.0, float,
        )
        drain_grace_s = _ask(
            "  SIGTERM drain grace in seconds "
            "(0 = library default 30)", 0.0, float,
        )
    # Serving decode-speed levers (serving.py): declining leaves all three
    # UNSPECIFIED so inherited ACCELERATE_SPECULATIVE_K / DRAFT_MODEL /
    # KV_QUANT flow through at launch; answering — even with the defaults
    # 0/''/'off' — is an explicit choice that scrubs stale values.
    speculative_k, draft_model, kv_quant = None, None, None
    if _yesno(
        "Do you want to configure serving decode-speed levers (speculative "
        "decoding, int8 KV-cache quantization)?", False,
    ):
        speculative_k = _ask(
            "  speculative draft depth k (draft tokens verified per window; "
            "0 = off)", 0, int,
        )
        draft_model = _ask(
            "  draft model preset (LlamaConfig classmethod, e.g. tiny; "
            "'' = engine default)", "",
        )
        kv_quant = _ask(
            "  KV-cache pool quantization (off = full precision; int8 = "
            "~2x tokens per HBM byte, dequant in the paged kernels)",
            "off", str, ["off", "int8"],
        )
    # Tri-state like the health section: declining leaves both UNSPECIFIED
    # (None / '') so an inherited ACCELERATE_TRAIN_WINDOW/XLA_PRESET still
    # flows through at launch; answering — even with the defaults 1/'off' —
    # is an explicit choice that scrubs stale inherited values.
    train_window, xla_preset, zero_sharding, tune_budget = None, "", None, None
    kernels = None
    if _yesno(
        "Do you want to configure dispatch amortization (fused train windows, "
        "XLA latency-hiding presets, ZeRO optimizer sharding, Pallas kernels, "
        "autotuner)?", False
    ):
        train_window = _ask(
            "  train window K (steps fused into one XLA program per dispatch; "
            "1 = one dispatch per step)", 1, int
        )
        xla_preset = _ask(
            "  XLA latency-hiding preset (off/latency/collective_matmul)",
            "off", str, ["off", "latency", "collective_matmul"],
        )
        zero_sharding = _yesno(
            "  ZeRO cross-replica sharding (optimizer state + weight update "
            "sharded over the dp axis; ~1/dp opt-state HBM per chip)?", False
        )
        kernels = _ask(
            "  Pallas kernel layer (off = reference lowerings; pallas = "
            "custom kernels for paged decode / fused optimizer update / "
            "int8 matmul — Mosaic on TPU, interpreter elsewhere)",
            "off", str, ["off", "pallas", "interpret", "reference"],
        )
        tune_budget = _ask(
            "  autotuner trial budget (max short-bench trials an "
            "`accelerate-tpu tune` run may spend; 0 = library default)", 0, int
        )
    log_with = ""
    if _yesno("Do you want to configure experiment tracking?", False):
        log_with = _ask(
            "  trackers to log to (comma-separated: json,tensorboard,wandb,csv,aim,"
            "mlflow,comet_ml,clearml,dvclive or 'all')", "json"
        )
        if log_with and not project_dir:
            # File-backed trackers need a logging root; without one every
            # launched process would fail at Accelerator() startup.
            project_dir = _ask("  trackers need a logging root — project directory", ".")
    compile_cache_dir = ""
    if _yesno(
        "Enable the persistent XLA compilation cache (restarted jobs skip recompiles)?",
        False,
    ):
        compile_cache_dir = _ask(
            "  compilation cache directory", "~/.cache/accelerate_tpu/xla_cache"
        )
    mixed_precision = _ask(
        "Do you wish to use mixed precision? (no/bf16/fp16/fp8)", "bf16", str, ["no", "bf16", "fp16", "fp8"]
    )
    return ClusterConfig(
        compute_environment=compute_env,
        distributed_type=distributed_type,
        num_machines=num_machines,
        machine_rank=machine_rank,
        num_processes=max(num_machines, 1),
        main_process_ip=ip,
        main_process_port=port,
        mixed_precision=mixed_precision,
        use_cpu=use_cpu,
        cpu_virtual_devices=cpu_virtual,
        dp_size=dp,
        fsdp_size=fsdp,
        tp_size=tp,
        pp_size=pp,
        sp_size=sp,
        ep_size=ep,
        dcn_size=dcn,
        gradient_accumulation_steps=accum,
        fsdp_min_shard_size=min_shard,
        fsdp_cpu_offload=cpu_offload,
        pp_schedule=pp_schedule,
        pp_microbatches=pp_mbs,
        project_dir=project_dir,
        checkpoint_total_limit=ckpt_limit,
        checkpoint_auto_naming=ckpt_auto,
        log_with=log_with,
        compile_cache_dir=compile_cache_dir,
        handle_preemption=handle_preemption,
        elastic=elastic,
        min_data_parallel=min_dp,
        guard_numerics=guard_numerics,
        spike_zscore=spike_zscore,
        hang_timeout=hang_timeout,
        telemetry=telemetry,
        metrics_port=metrics_port,
        straggler_threshold=straggler_threshold,
        fleet_metrics=fleet_metrics,
        slo_step_time=slo_step_time,
        slo_ttft=slo_ttft,
        slo_tpot=slo_tpot,
        journal_dir=journal_dir,
        trace_ring=trace_ring,
        flight_ring=flight_ring,
        speculative_k=speculative_k,
        draft_model=draft_model,
        kv_quant=kv_quant,
        serving_role=serving_role,
        router_endpoint=router_endpoint,
        serving_retry_budget=serving_retry_budget,
        serving_lease_ttl=serving_lease_ttl,
        drain_grace_s=drain_grace_s,
        train_window=train_window,
        xla_preset=xla_preset,
        zero_sharding=zero_sharding,
        kernels=kernels,
        tune_budget=tune_budget,
        profile_steps=profile_steps,
        profile_slow_zscore=profile_slow_zscore,
    )


def write_default_config(path: str | None = None) -> str:
    """`accelerate-tpu config --default` (reference ``config/default.py:28-107``)."""
    cfg = ClusterConfig()
    path = path or default_config_file
    if path.endswith(".json"):
        cfg.to_json_file(path)
    else:
        cfg.to_yaml_file(path)
    return path


def config_command_parser(subparsers=None) -> argparse.ArgumentParser:
    description = "Create a launch configuration for accelerate-tpu"
    if subparsers is not None:
        parser = subparsers.add_parser("config", description=description)
    else:
        parser = argparse.ArgumentParser("accelerate-tpu config", description=description)
    parser.add_argument(
        "--config_file",
        default=None,
        help=f"Where to save the config (default: {default_config_file})",
    )
    parser.add_argument(
        "--default", action="store_true", help="Write the default config without prompting"
    )
    if subparsers is not None:
        parser.set_defaults(func=config_command)
    return parser


def config_command(args) -> None:
    if args.default:
        path = write_default_config(args.config_file)
    else:
        cfg = get_user_input()
        path = args.config_file or default_config_file
        if path.endswith(".json"):
            cfg.to_json_file(path)
        else:
            cfg.to_yaml_file(path)
    print(f"accelerate-tpu configuration saved at {path}")


def main() -> None:  # pragma: no cover - thin shim
    parser = config_command_parser()
    config_command(parser.parse_args())


if __name__ == "__main__":  # pragma: no cover
    main()
