"""`accelerate-tpu` — top-level CLI dispatcher.

Reference parity: ``src/accelerate/commands/accelerate_cli.py:28-50``.
"""

from __future__ import annotations

import argparse

from .analysis import (
    audit_command_parser,
    lint_command_parser,
    memcheck_command_parser,
)
from .config import config_command_parser
from .env import env_command_parser
from .estimate import estimate_command_parser
from .fingerprint import fingerprint_command_parser
from .launch import launch_command_parser
from .merge import merge_command_parser
from .profile import blackbox_command_parser, profile_command_parser
from .report import report_command_parser
from .test import test_command_parser
from .timeline import timeline_command_parser
from .top import top_command_parser
from .tpu import tpu_command_parser
from .tune import tune_command_parser


def main() -> None:
    parser = argparse.ArgumentParser(
        "accelerate-tpu", usage="accelerate-tpu <command> [<args>]", allow_abbrev=False
    )
    subparsers = parser.add_subparsers(help="accelerate-tpu command helpers")

    config_command_parser(subparsers=subparsers)
    env_command_parser(subparsers=subparsers)
    launch_command_parser(subparsers=subparsers)
    estimate_command_parser(subparsers=subparsers)
    merge_command_parser(subparsers=subparsers)
    test_command_parser(subparsers=subparsers)
    tpu_command_parser(subparsers=subparsers)
    lint_command_parser(subparsers=subparsers)
    audit_command_parser(subparsers=subparsers)
    memcheck_command_parser(subparsers=subparsers)
    fingerprint_command_parser(subparsers=subparsers)
    profile_command_parser(subparsers=subparsers)
    blackbox_command_parser(subparsers=subparsers)
    tune_command_parser(subparsers=subparsers)
    top_command_parser(subparsers=subparsers)
    timeline_command_parser(subparsers=subparsers)
    report_command_parser(subparsers=subparsers)

    args = parser.parse_args()
    if not hasattr(args, "func"):
        parser.print_help()
        raise SystemExit(1)
    args.func(args)


if __name__ == "__main__":
    main()
