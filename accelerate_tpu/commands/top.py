"""`accelerate-tpu top` — the live fleet console.

Polls the lead host's ``/fleet`` endpoint (telemetry/fleet.py: the joined
per-host series + fleet rollups the FleetAggregator builds from every
worker's KV-registered metrics endpoint) and renders a control-room view:
fleet rollups (MFU, tokens/s, goodput split, step-time skew, SLO breaches),
then one row per host. ``--once`` prints a single frame and exits;
``--once --json`` prints the raw snapshot for CI consumption. Against a
worker with no aggregator installed, the snapshot is aggregated client-side
from that one endpoint's ``/metrics`` — a bare worker is still inspectable.

Pure HTTP post-processing: no backend, no devices, safe to run anywhere that
can reach the endpoint.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def default_endpoint() -> str:
    """Where to look when ``--endpoint`` is omitted: the local worker's env
    contract (ACCELERATE_METRICS_PORT) on loopback. Unset/0 means no
    endpoint is configured (the shared env-contract parser) — a pointed
    error beats probing a port nothing serves."""
    from ..telemetry import metrics_port_from_env

    port = metrics_port_from_env()
    if port <= 0:
        raise SystemExit(
            "accelerate-tpu top: no --endpoint given and ACCELERATE_METRICS_PORT "
            "is unset/0 (no metrics endpoint configured) — pass --endpoint "
            "host:port of the lead worker's metrics server (launch "
            "--metrics_port N starts one)."
        )
    return f"127.0.0.1:{port}"


def top_command_parser(subparsers=None) -> argparse.ArgumentParser:
    description = "Live fleet console over the /fleet aggregation endpoint"
    if subparsers is not None:
        parser = subparsers.add_parser("top", description=description)
    else:
        parser = argparse.ArgumentParser("accelerate-tpu top", description=description)
    parser.add_argument(
        "--endpoint", default=None,
        help="Lead host's metrics endpoint (host:port or URL; default "
             "127.0.0.1:$ACCELERATE_METRICS_PORT). /fleet is fetched from it; "
             "a worker without an aggregator is rendered as a one-host fleet "
             "from its /metrics.",
    )
    parser.add_argument(
        "--interval", type=float, default=2.0,
        help="Refresh interval in seconds for the live view (default 2.0)",
    )
    parser.add_argument(
        "--once", action="store_true",
        help="Render one frame and exit (with --json: machine-readable)",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="Print the raw fleet snapshot JSON instead of the console view",
    )
    if subparsers is not None:
        parser.set_defaults(func=top_command)
    return parser


def _fmt(value, spec: str = "", none: str = "-") -> str:
    if value is None:
        return none
    return format(value, spec)


def render_snapshot(snapshot: dict) -> str:
    """One console frame from a fleet snapshot — pure, for tests."""
    fleet = snapshot.get("fleet", {})
    hosts = snapshot.get("hosts", {})
    lines = []
    when = time.strftime(
        "%H:%M:%S", time.localtime(snapshot.get("generated_at", time.time()))
    )
    lines.append(
        f"fleet @ {when}  hosts {fleet.get('hosts_up', 0)}/"
        f"{fleet.get('hosts_total', 0)} up  "
        f"restarts {fleet.get('restarts', 0)}  "
        f"reshards {fleet.get('reshard_transitions', 0)}  "
        f"health trips {fleet.get('health_trips', 0)}"
    )
    step = fleet.get("step_s") or {}
    lines.append(
        f"  mfu {_fmt(fleet.get('mfu'), '.4f')}  "
        f"tokens/s {_fmt(fleet.get('tokens_per_s'), ',.1f')}  "
        f"step s min/med/max {_fmt(step.get('min'), '.4f')}/"
        f"{_fmt(step.get('median'), '.4f')}/{_fmt(step.get('max'), '.4f')}  "
        f"skew {_fmt(step.get('skew'), '.2f')}x"
    )
    goodput = fleet.get("goodput") or {}
    badput = goodput.get("badput_s") or {}
    badput_txt = " ".join(
        f"{k}={v:.1f}s" for k, v in sorted(badput.items()) if v
    ) or "none"
    lines.append(
        f"  goodput {_fmt(goodput.get('fraction'), '.1%')}  badput: {badput_txt}"
    )
    breaches = fleet.get("slo_breaches") or {}
    lines.append(
        "  slo breaches: "
        + (" ".join(f"{k}={v}" for k, v in sorted(breaches.items())) or "none")
        + f"  kv pool {_fmt(fleet.get('kv_pool_utilization'), '.1%')}"
    )
    # Disaggregated-serving tiers (telemetry/fleet.py _serving_tiers): one
    # line per role so prefill and decode read side by side; the router line
    # swaps latency columns for its routing split + affinity hit rate.
    for role, tier in sorted((fleet.get("serving_tiers") or {}).items()):
        if "routed" in tier:
            routed_txt = " ".join(
                f"{k}={v}" for k, v in sorted(tier["routed"].items())
            ) or "none"
            lines.append(
                f"  serving[{role}] hosts {tier.get('hosts', 0)}  "
                f"routed: {routed_txt}  affinity "
                f"{_fmt(tier.get('affinity_hit_rate'), '.1%')}"
            )
            continue
        handoff = tier.get("handoff") or {}
        handoff_txt = " ".join(
            f"{direction}={leg.get('chains', 0)}ch/{leg.get('bytes', 0)}B"
            for direction, leg in sorted(handoff.items())
        ) or "none"
        lines.append(
            f"  serving[{role}] hosts {tier.get('hosts', 0)}  "
            f"req {tier.get('requests', 0)}/{tier.get('completed', 0)} done  "
            f"ttft {_fmt(tier.get('ttft_s_mean'), '.3f')}s  "
            f"tpot {_fmt(tier.get('tpot_s_mean'), '.4f')}s  "
            f"handoff: {handoff_txt}"
        )
    lines.append(
        f"  {'host':<6}{'endpoint':<24}{'up':<4}{'steps':>8}{'step_s':>10}"
        f"{'tok/s':>12}{'mfu':>8}{'goodput':>9}{'restarts':>9}  slo"
    )
    for host in sorted(hosts, key=lambda h: int(h) if h.isdigit() else 0):
        row = hosts[host]
        slo_txt = " ".join(
            f"{k}={v}" for k, v in sorted((row.get("slo_breaches") or {}).items())
        ) or "-"
        if row.get("serving_role"):
            slo_txt += f"  [{row['serving_role']}]"
        lines.append(
            f"  {host:<6}{(row.get('endpoint') or '-'):<24}"
            f"{'up' if row.get('up') else 'DOWN':<4}"
            f"{_fmt(row.get('steps'), 'd'):>8}"
            f"{_fmt(row.get('step_s_mean'), '.4f'):>10}"
            f"{_fmt(row.get('tokens_per_s'), ',.1f'):>12}"
            f"{_fmt(row.get('mfu'), '.3f'):>8}"
            f"{_fmt(row.get('goodput_fraction'), '.1%'):>9}"
            f"{_fmt(row.get('restarts'), '.0f'):>9}  {slo_txt}"
        )
        if not row.get("up") and row.get("error"):
            lines.append(f"         {row['error']}")
    return "\n".join(lines)


def top_command(args) -> None:
    from ..telemetry.fleet import fetch_fleet_snapshot

    endpoint = args.endpoint or default_endpoint()
    if args.once:
        snapshot = fetch_fleet_snapshot(endpoint)
        print(json.dumps(snapshot, indent=1) if args.as_json
              else render_snapshot(snapshot))
        return
    if args.interval <= 0:
        raise ValueError(f"--interval must be > 0, got {args.interval}")
    try:
        while True:
            try:
                snapshot = fetch_fleet_snapshot(endpoint)
                # --json streams one machine-readable snapshot per interval
                # (no screen clearing — built for pipes, not terminals).
                frame = (json.dumps(snapshot) if args.as_json
                         else render_snapshot(snapshot))
            except Exception as exc:
                frame = (json.dumps({"error": repr(exc), "endpoint": endpoint})
                         if args.as_json
                         else f"fleet endpoint {endpoint} unreachable: {exc!r}")
            # Clear + home, then the frame (plain stdout when not a TTY).
            if not args.as_json and sys.stdout.isatty():
                sys.stdout.write("\x1b[2J\x1b[H")
            print(frame, flush=True)
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass


def main() -> None:  # pragma: no cover - thin shim
    parser = top_command_parser()
    top_command(parser.parse_args())


if __name__ == "__main__":  # pragma: no cover
    main()
