"""`accelerate-tpu lint` / `audit` / `memcheck` — the static-analysis CLI.

``lint`` runs the invariant linter (analysis/lint.py) over source paths and
exits non-zero on any finding that is neither inline-suppressed nor
baselined. ``audit`` builds the tiny training config on the local backend,
lowers the fused train step (or a K-step window), and prints the program
audit report (analysis/audit.py) as JSON — exit status reflects the
zero-tolerance invariants (dp-axis all-gathers, host callbacks, donation
misses). ``memcheck`` lowers the same artifact through the static memory
auditor (analysis/memory.py) and prints the per-device HBM attribution —
param / opt-state / accum / batch / activation-workspace bytes, the
sharded-vs-replicated split per mesh axis, implicit resharding copies, and
the OOM verdict — exiting 1 on a predicted OOM (``--budget-gib`` overrides
the generation-table budget) or an over-threshold dp-replicated opt-state
footprint (``--replicated-opt-gib``). All three are pre-chip gates: they
inspect programs and source, never run a training step.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


# --------------------------------------------------------------------- lint
def lint_command_parser(subparsers=None) -> argparse.ArgumentParser:
    description = (
        "Statically lint source for violations of the framework's "
        "zero-sync / shim / donation disciplines"
    )
    if subparsers is not None:
        parser = subparsers.add_parser("lint", description=description)
    else:
        parser = argparse.ArgumentParser("accelerate-tpu lint", description=description)
    parser.add_argument(
        "paths", nargs="*", default=None,
        help="Files or directories to lint (default: the installed accelerate_tpu package)",
    )
    parser.add_argument(
        "--baseline", default=None,
        help="Baseline JSON of grandfathered findings (default: "
             ".accelerate-lint-baseline.json next to the scanned package or in CWD)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="Ignore any baseline file — report every finding",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="Write the current findings to the baseline file and exit 0",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="Print the rule table and exit"
    )
    parser.add_argument(
        "--json", action="store_true", help="Machine-readable findings on stdout"
    )
    if subparsers is not None:
        parser.set_defaults(func=lint_command)
    return parser


def _default_paths() -> list:
    import accelerate_tpu

    return [os.path.dirname(os.path.abspath(accelerate_tpu.__file__))]


def _default_baseline(paths: list) -> str:
    from ..analysis.lint import DEFAULT_BASELINE_NAME

    candidates = [os.path.join(os.getcwd(), DEFAULT_BASELINE_NAME)]
    for p in paths:
        p = os.path.abspath(p)
        root = p if os.path.isdir(p) else os.path.dirname(p)
        candidates.append(os.path.join(os.path.dirname(root), DEFAULT_BASELINE_NAME))
        candidates.append(os.path.join(root, DEFAULT_BASELINE_NAME))
    for c in candidates:
        if os.path.exists(c):
            return c
    return candidates[0]


def lint_command(args) -> None:
    from ..analysis.lint import (
        RULES, lint_paths, load_baseline, write_baseline,
    )

    if args.list_rules:
        for rule in RULES:
            scope = ", ".join(rule.include) if rule.include else "whole package"
            print(f"{rule.name}\n  what:  {rule.summary}\n  fix:   {rule.remedy}"
                  f"\n  scope: {scope}\n")
        return

    paths = args.paths or _default_paths()
    baseline_path = args.baseline or _default_baseline(paths)
    baseline = set() if (args.no_baseline or args.write_baseline) else load_baseline(
        baseline_path
    )
    findings = lint_paths(paths, baseline=baseline)
    live = [f for f in findings if not f.suppressed and not f.baselined]
    suppressed = sum(1 for f in findings if f.suppressed)
    baselined = sum(1 for f in findings if f.baselined)

    if args.write_baseline:
        write_baseline(baseline_path, findings)
        print(f"wrote {len({f.key() for f in findings if not f.suppressed})} "
              f"grandfathered findings to {baseline_path}")
        return

    if args.json:
        print(json.dumps({
            "findings": [
                {"path": f.path, "rule": f.rule, "line": f.line,
                 "message": f.message}
                for f in live
            ],
            "suppressed": suppressed,
            "baselined": baselined,
        }, indent=1))
    else:
        for f in live:
            print(f.format())
        print(
            f"accelerate-lint: {len(live)} finding(s) "
            f"({suppressed} suppressed, {baselined} baselined)"
        )
    if live:
        raise SystemExit(1)


# -------------------------------------------------------------------- audit
def audit_command_parser(subparsers=None) -> argparse.ArgumentParser:
    description = (
        "Build the tiny train config, lower the fused step, and audit the "
        "program: collectives per mesh axis, donation aliasing, host "
        "callbacks, dtype upcasts"
    )
    if subparsers is not None:
        parser = subparsers.add_parser("audit", description=description)
    else:
        parser = argparse.ArgumentParser("accelerate-tpu audit", description=description)
    parser.add_argument(
        "--window", type=int, default=1,
        help="Audit a K-step fused train window instead of the per-step program",
    )
    parser.add_argument(
        "--batch", type=int, default=8, help="Batch rows for the lowered program"
    )
    parser.add_argument(
        "--seq", type=int, default=16, help="Sequence length for the lowered program"
    )
    parser.add_argument(
        "--threshold-mb", type=float, default=64.0,
        help="Large-intermediate report threshold (per-device MiB)",
    )
    parser.add_argument(
        "--summary", action="store_true",
        help="Print the compact summary (bench.py detail.audit form) instead "
             "of the full report",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="Machine-readable output: a schema'd verdict document "
             "({verdict, failures, report}) instead of the bare report, so "
             "the autotuner and CI consume the result without scraping "
             "stdout. Exit codes are unchanged.",
    )
    if subparsers is not None:
        parser.set_defaults(func=audit_command)
    return parser


# Schema of the ``--json`` verdict document shared by ``audit`` and
# ``memcheck``: bump when its structure changes so machine consumers (the
# autotuner, CI) can gate on compatibility.
VERDICT_SCHEMA_VERSION = 1


def _verdict_doc(command: str, failures: list, report: dict) -> dict:
    return {
        "schema_version": VERDICT_SCHEMA_VERSION,
        "command": command,
        "verdict": "fail" if failures else "pass",
        "failures": list(failures),
        "report": report,
    }


def _build_tiny_artifact(window: int, batch_rows: int, seq: int,
                         optimizer: str = "sgd"):
    """The shared audit/memcheck fixture: the tiny training config built on
    the local backend, as a (accelerator, built_artifact, batch) triple —
    window-stacked when ``window > 1``."""
    import numpy as np
    import jax
    import optax

    from ..accelerator import Accelerator
    from ..models import Llama, LlamaConfig

    accelerator = Accelerator()
    cfg = LlamaConfig.tiny()
    model = Llama(cfg)
    model.init_params(jax.random.key(0))
    tx = {
        "sgd": lambda: optax.sgd(0.1),
        "adamw": lambda: optax.adamw(3e-4),
        "adafactor": lambda: optax.adafactor(3e-4),
    }[optimizer]()
    pmodel, popt = accelerator.prepare(model, tx)
    ids = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (batch_rows, seq)
    ).astype(np.int32)
    batch = {"input_ids": ids, "labels": ids}
    if window > 1:
        built = accelerator.build_train_window(pmodel, popt, window=window)
        batch = {k: np.stack([v] * window) for k, v in batch.items()}
    else:
        built = accelerator.build_train_step(pmodel, popt)
    return accelerator, built, batch


def audit_command(args) -> None:
    if args.window < 1:
        raise SystemExit("--window must be >= 1")
    accelerator, built, batch = _build_tiny_artifact(args.window, args.batch, args.seq)
    report = accelerator.audit(
        built, batch,
        intermediate_threshold_bytes=int(args.threshold_mb * 1024 * 1024),
    )
    payload = report.summary_dict() if args.summary else report.to_dict()
    if getattr(args, "json", False):
        failures = [] if report.clean else [
            "program audit: zero-tolerance invariant violated "
            "(dp all-gathers / host callbacks / donation misses — see report)"
        ]
        payload = _verdict_doc("audit", failures, payload)
    print(json.dumps(payload, indent=1))
    if not report.clean:
        raise SystemExit(1)


# ----------------------------------------------------------------- memcheck
def memcheck_command_parser(subparsers=None) -> argparse.ArgumentParser:
    description = (
        "Static HBM audit of the tiny train config: per-device bytes by "
        "class (param/opt-state/accum/batch/activation-workspace), "
        "sharded-vs-replicated split per mesh axis, implicit resharding "
        "copies, and an OOM-before-launch verdict. --serving audits the "
        "paged serving decode window instead (per-device KV-pool bytes "
        "against the HBM budget)."
    )
    if subparsers is not None:
        parser = subparsers.add_parser("memcheck", description=description)
    else:
        parser = argparse.ArgumentParser(
            "accelerate-tpu memcheck", description=description
        )
    parser.add_argument(
        "--window", type=int, default=1,
        help="Audit a K-step fused train window instead of the per-step program",
    )
    parser.add_argument(
        "--batch", type=int, default=8, help="Batch rows for the lowered program"
    )
    parser.add_argument(
        "--seq", type=int, default=16, help="Sequence length for the lowered program"
    )
    parser.add_argument(
        "--optimizer", choices=("adamw", "sgd", "adafactor"), default="adamw",
        help="Optimizer whose state is audited (default adamw: the "
             "2-moments-per-param worst case the replication findings target)",
    )
    parser.add_argument(
        "--budget-gib", type=float, default=None,
        help="Per-device HBM budget override (GiB); default is the chip "
             "generation's HBM x the 90%% headroom contract. Exit 1 when the "
             "predicted peak exceeds it.",
    )
    parser.add_argument(
        "--replicated-opt-gib", type=float, default=None,
        help="Exit 1 when opt-state bytes replicated on the dp axis exceed "
             "this many GiB per chip (the ZeRO-sharding acceptance gate — "
             "pair with ACCELERATE_ZERO_SHARDING=1 to prove the fix; "
             "default: report only)",
    )
    parser.add_argument(
        "--cpu-virtual-devices", type=int, default=0,
        help="Pin an N-device virtual CPU mesh before building (launcher "
             "flag's analog): dp-axis findings — the --replicated-opt-gib "
             "gate above — are vacuous on a 1-device backend, so single-"
             "host rigs need this to make the gate enforceable.",
    )
    parser.add_argument(
        "--serving", action="store_true",
        help="Audit the paged ContinuousBatcher decode window instead of the "
             "train step: predicted per-device KV-pool bytes (plus params "
             "and the gather-view workspace) gate against the HBM budget "
             "BEFORE a serving launch — the OOM-before-launch discipline for "
             "the decode path (docs/serving.md).",
    )
    parser.add_argument(
        "--serving-slots", type=int, default=4,
        help="Serving mode: engine batch slots (decode rows)",
    )
    parser.add_argument(
        "--serving-blocks", type=int, default=64,
        help="Serving mode: KV-pool blocks (per-device pool capacity = "
             "blocks x block size)",
    )
    parser.add_argument(
        "--serving-block-size", type=int, default=16,
        help="Serving mode: tokens per pool block (16 = the bf16 sublane "
             "multiple the future Pallas kernel wants)",
    )
    parser.add_argument(
        "--serving-kv-quant", choices=("none", "int8"), default="none",
        help="Serving mode: price the pool at this storage dtype. int8 "
             "stores blocks quantized with per-token f32 scales (k_scale/"
             "v_scale ride the kv_pool class), roughly doubling tokens per "
             "HBM byte — the audit prices blocks AND scales, so the budget "
             "gate covers the real layout, not the naive blocks/2 estimate.",
    )
    parser.add_argument(
        "--serving-spec-k", type=int, default=0,
        help="Serving mode: audit with speculative decoding at this draft "
             "depth. Prices the draft model's weights and its mirror KV "
             "pool (the draft_params/draft_pool classes of the verify "
             "program) — residency a spec-decode launch pays on top of the "
             "target's, and the OOM-before-launch gate must see it.",
    )
    parser.add_argument(
        "--serving-role", choices=("unified", "prefill", "decode"),
        default="unified",
        help="Serving mode: size the pool for this disaggregated tier "
             "(docs/serving.md 'Disaggregated serving'). prefill audits the "
             "chunked-prefill program instead of the decode window (a "
             "prefill host never compiles decode, so its peak excludes the "
             "decode lookahead buffers); decode audits the decode window "
             "AND gates on import headroom — the pool must hold a full "
             "complement of imported chains (slots x max_blocks_per_slot "
             "+ trash block) or chain imports from the prefill tier will "
             "be refused at runtime.",
    )
    parser.add_argument(
        "--summary", action="store_true",
        help="Print the compact summary (bench.py detail.memory form) instead "
             "of the full report",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="Machine-readable output: a schema'd verdict document "
             "({verdict, failures, report}) instead of the bare report — the "
             "failures stdout-vs-stderr split stays for humans, but machine "
             "consumers get everything in one parseable doc. Exit codes are "
             "unchanged.",
    )
    if subparsers is not None:
        parser.set_defaults(func=memcheck_command)
    return parser


def _build_serving_artifact(slots: int, blocks: int, block_size: int,
                            role: str = "unified", kv_quant: str | None = None,
                            speculative_k: int = 0):
    """The serving analog of ``_build_tiny_artifact``: a tiny paged
    ContinuousBatcher whose compiled decode window is the audited program.
    Returns ``(engine, built, args)`` — the pool rides the program's
    ``_audit_meta.memory_classes`` join as the ``kv_pool`` class. A
    ``prefill`` role audits the chunked-prefill program instead: that is
    the ONLY program a disaggregated prefill host compiles, so its peak
    deliberately excludes the decode window's lookahead buffers. With
    ``speculative_k`` the audited decode program is the verify window —
    the one that holds target pool + draft pool + both param sets live —
    so the gate prices the draft model's full residency."""
    import jax

    from ..models import Llama, LlamaConfig
    from ..serving import ContinuousBatcher

    model = Llama(LlamaConfig.tiny())
    model.init_params(jax.random.key(0))
    engine = ContinuousBatcher(
        model, batch_slots=slots, max_new_tokens=32,
        max_cache_len=blocks * block_size, bucket_sizes=(16, 32, 64),
        sync_every=4, paged=True, block_size=block_size, num_blocks=blocks,
        kv_quant=kv_quant, speculative_k=speculative_k,
    )
    if role == "prefill":
        P = engine.prefill_chunk
        return engine, engine._chunk_fn(P), engine._chunk_args(P)
    if speculative_k:
        return engine, engine._spec_verify(), engine._verify_args()
    return engine, engine._decode(), engine._decode_args()


def memcheck_command(args) -> None:
    if args.window < 1:
        raise SystemExit("--window must be >= 1")
    if getattr(args, "cpu_virtual_devices", 0):
        if args.cpu_virtual_devices < 1:
            raise SystemExit("--cpu-virtual-devices must be >= 1")
        from ..utils.environment import pin_cpu_platform

        # Must precede the first backend touch (_build_tiny_artifact's
        # Accelerator() below); pin_cpu_platform documents the contract.
        pin_cpu_platform(args.cpu_virtual_devices)
    budget = int(args.budget_gib * (1 << 30)) if args.budget_gib is not None else None
    if getattr(args, "serving", False):
        from ..analysis.memory import memory_report_from_built

        role = getattr(args, "serving_role", "unified")
        kv_quant = getattr(args, "serving_kv_quant", "none")
        spec_k = getattr(args, "serving_spec_k", 0)
        engine, built, built_args = _build_serving_artifact(
            args.serving_slots, args.serving_blocks, args.serving_block_size,
            role=role, kv_quant=None if kv_quant == "none" else kv_quant,
            speculative_k=spec_k,
        )
        report = memory_report_from_built(built, *built_args, budget_bytes=budget)
        failures = []
        pool_bytes = (
            report.classes["kv_pool"].per_device_bytes
            if "kv_pool" in report.classes else 0
        )
        program = "chunked-prefill" if role == "prefill" else (
            "verify-window" if spec_k else "decode-window")
        if not report.fits:
            failures.append(
                f"predicted serving OOM: {program} peak "
                f"{report.predicted_peak_bytes} B/device (KV pool {pool_bytes} B) "
                f"exceeds budget {report.budget_bytes} B — shrink "
                "--serving-blocks/--serving-slots or raise the budget"
            )
        payload = report.summary_dict() if args.summary else report.to_dict()
        payload["kv_pool_bytes_per_device"] = pool_bytes
        payload["pool"] = engine.pool_stats()
        payload["serving_role"] = role
        if spec_k:
            # Draft residency the spec launch pays on top of the target's —
            # priced from the verify program's memory classes, not estimated.
            payload["draft_pool_bytes_per_device"] = (
                report.classes["draft_pool"].per_device_bytes
                if "draft_pool" in report.classes else 0
            )
            payload["draft_params_bytes_per_device"] = (
                report.classes["draft_params"].per_device_bytes
                if "draft_params" in report.classes else 0
            )
        if role == "decode":
            # Import headroom: a decode tier refuses a chain import
            # (serving_net/handoff.py) when the free list cannot cover the
            # exporter's reservation — worst case max_blocks_per_slot blocks
            # per slot, plus the pinned trash block. Gate it at audit time,
            # not at the first mid-traffic refusal.
            required = args.serving_slots * engine.max_blocks_per_slot + 1
            payload["import_headroom"] = {
                "pool_blocks": engine.num_blocks,
                "required_blocks": required,
                "max_blocks_per_slot": engine.max_blocks_per_slot,
            }
            if engine.num_blocks < required:
                failures.append(
                    f"decode tier lacks import headroom: pool has "
                    f"{engine.num_blocks} blocks but a full complement of "
                    f"imported chains needs {required} "
                    f"({args.serving_slots} slots x "
                    f"{engine.max_blocks_per_slot} blocks + trash) — raise "
                    "--serving-blocks or shrink --serving-slots"
                )
        if getattr(args, "json", False):
            payload = _verdict_doc("memcheck", failures, payload)
        print(json.dumps(payload, indent=1))
        if not getattr(args, "json", False):
            for f in failures:
                print(f"memcheck: {f}", file=sys.stderr)
        if failures:
            raise SystemExit(1)
        return
    accelerator, built, batch = _build_tiny_artifact(
        args.window, args.batch, args.seq, optimizer=args.optimizer
    )
    report = accelerator.memory_report(built, batch, budget_bytes=budget)
    failures = []
    if not report.fits:
        failures.append(
            f"predicted OOM: peak {report.predicted_peak_bytes} B/device "
            f"exceeds budget {report.budget_bytes} B"
        )
    if args.replicated_opt_gib is not None:
        rep = report.replicated_bytes("opt_state", "dp")
        limit = int(args.replicated_opt_gib * (1 << 30))
        if rep > limit:
            failures.append(
                f"opt_state replicated on dp: {rep} B/chip exceeds "
                f"--replicated-opt-gib {args.replicated_opt_gib}"
            )
    payload = report.summary_dict() if args.summary else report.to_dict()
    if getattr(args, "json", False):
        payload = _verdict_doc("memcheck", failures, payload)
    print(json.dumps(payload, indent=1))
    if not getattr(args, "json", False):
        for f in failures:
            print(f"memcheck: {f}", file=sys.stderr)
    if failures:
        raise SystemExit(1)


def lint_main() -> None:
    """Console-script entry (`accelerate-tpu-lint`, pyproject [project.scripts])."""
    lint_command(lint_command_parser().parse_args())


def audit_main() -> None:
    """Console-script entry (`accelerate-tpu-audit`, pyproject [project.scripts])."""
    audit_command(audit_command_parser().parse_args())


def memcheck_main() -> None:
    """Console-script entry (`accelerate-tpu-memcheck`, pyproject [project.scripts])."""
    memcheck_command(memcheck_command_parser().parse_args())


if __name__ == "__main__":
    # Three commands share this module; `python -m` can't pick one.
    sys.exit("Run via `accelerate-tpu lint` / `audit` / `memcheck` "
             "(or the accelerate-tpu-lint / -audit / -memcheck scripts).")
