"""`accelerate-tpu estimate-memory` — parameter/gradient/optimizer memory table.

Reference parity: ``src/accelerate/commands/estimate.py:230-312`` loads a model on
the meta device and prints per-dtype size tables via ``calculate_maximum_sizes``.
Here the meta device is ``jax.eval_shape`` — shapes come from the model zoo's
abstract init, so nothing touches HBM. Accepts a zoo preset name (``llama-7b``),
a local HF-format ``config.json``, or any Hub model id with a supported
architecture (``meta-llama/Llama-2-7b-hf`` — config fetched via AutoConfig,
cache-first, never the weights).
"""

from __future__ import annotations

import argparse
import json
import os

from ..utils.modeling import calculate_maximum_sizes
from ..utils.other import convert_bytes

# Zoo presets: name → (family, config kwargs). Sizes follow the public LLaMA /
# BERT architecture tables.
PRESETS = {
    # LlamaConfig.tiny()'s exact shape: the cross-validation anchor pinning
    # this abstract-init estimate to the static memory auditor's param-class
    # bytes (analysis/memory.py; tests/test_memory_analysis.py) — the two
    # surfaces must not drift.
    "tiny": ("llama", dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                           num_hidden_layers=2, num_attention_heads=4,
                           num_key_value_heads=2, max_position_embeddings=128)),
    "llama-7b": ("llama", dict(vocab_size=32000, hidden_size=4096, intermediate_size=11008,
                               num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=32)),
    "llama-13b": ("llama", dict(vocab_size=32000, hidden_size=5120, intermediate_size=13824,
                                num_hidden_layers=40, num_attention_heads=40, num_key_value_heads=40)),
    "llama-70b": ("llama", dict(vocab_size=32000, hidden_size=8192, intermediate_size=28672,
                                num_hidden_layers=80, num_attention_heads=64, num_key_value_heads=8)),
    "bert-base": ("bert", dict(vocab_size=30522, hidden_size=768, num_hidden_layers=12,
                               num_attention_heads=12, intermediate_size=3072)),
    "bert-large": ("bert", dict(vocab_size=30522, hidden_size=1024, num_hidden_layers=24,
                                num_attention_heads=16, intermediate_size=4096)),
    "t5-small": ("t5", dict(vocab_size=32128, d_model=512, d_kv=64, d_ff=2048,
                            num_layers=6, num_decoder_layers=6, num_heads=8)),
    "t5-base": ("t5", dict(vocab_size=32128, d_model=768, d_kv=64, d_ff=3072,
                           num_layers=12, num_decoder_layers=12, num_heads=12)),
    "t5-large": ("t5", dict(vocab_size=32128, d_model=1024, d_kv=64, d_ff=4096,
                            num_layers=24, num_decoder_layers=24, num_heads=16)),
    # The reference's BASELINE.md big-model-inference trio (models/gptx.py).
    "gpt-j-6b": ("gptx", dict(vocab_size=50400, hidden_size=4096, intermediate_size=16384,
                              num_hidden_layers=28, num_attention_heads=16,
                              position_style="rotary_gptj", rotary_dim=64,
                              shared_layernorm=True, attention_bias=False, lm_head_bias=True)),
    "gpt-neox-20b": ("gptx", dict(vocab_size=50432, hidden_size=6144, intermediate_size=24576,
                                  num_hidden_layers=44, num_attention_heads=64,
                                  position_style="rotary_neox", rotary_dim=24)),
    "opt-30b": ("gptx", dict(vocab_size=50272, hidden_size=7168, intermediate_size=28672,
                             num_hidden_layers=48, num_attention_heads=56,
                             position_style="learned", position_offset=2,
                             parallel_residual=False, hidden_act="relu",
                             tie_word_embeddings=True)),
}

DTYPE_BYTES = {"float32": 4, "bf16": 2, "int8": 1, "int4": 0.5}


def _model_from_hf_config(hf: dict):
    """An (uninitialized) zoo model from an HF config dict, routed through the
    converter registry — one mapping shared with ``from_hf`` for every
    supported family (llama/mistral/qwen2/gemma/gemma-2/mixtral/gpt2/
    gpt_neox/gptj/opt/bert/t5).

    Estimation needs SHAPES only, so converter numerics guards (unsupported
    activation/rope recipes) fall back to a size-keys-only Llama mapping
    instead of failing the estimate."""
    from ..models.convert import _get_converter

    model_type = hf.get("model_type")
    if model_type is None:
        arch = (hf.get("architectures") or [""])[0].lower()
        for known, mtype in (("mixtral", "mixtral"), ("gemma2", "gemma2"),
                             ("gemma", "gemma"), ("qwen2", "qwen2"),
                             ("mistral", "mistral"), ("llama", "llama"),
                             ("gptneox", "gpt_neox"), ("gptj", "gptj"),
                             ("gpt2", "gpt2"), ("opt", "opt"),
                             ("qwen3", "qwen3"), ("phi3", "phi3"),
                             ("whisper", "whisper"), ("vit", "vit"),
                             ("bert", "bert"), ("t5", "t5")):
            if known in arch:
                model_type = mtype
                break
    cls, config_fn, _params_fn = _get_converter(model_type)
    try:
        return cls(config_fn(hf))
    except (ValueError, KeyError) as exc:
        size_keys = ("vocab_size", "hidden_size", "intermediate_size",
                     "num_hidden_layers", "num_attention_heads")
        if not all(k in hf for k in size_keys):
            raise
        if "num_local_experts" in hf or "num_experts" in hf:
            raise ValueError(
                "MoE config rejected by its converter and the dense-Llama "
                "size fallback would silently drop the expert FFNs "
                "(Mixtral-8x7B would read ~3.6x small) — fix the config "
                f"feature the converter flagged: {exc}"
            ) from exc
        from ..models import Llama, LlamaConfig

        return Llama(LlamaConfig(
            **{k: hf[k] for k in size_keys},
            num_key_value_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
            head_dim=hf.get("head_dim"),
            tie_word_embeddings=hf.get("tie_word_embeddings", False),
        ))


def _hub_config(model_name: str) -> dict:
    """config.json (ONLY — no weights) for a Hub model id, via transformers'
    AutoConfig: cache-first so the offline/zero-egress path is instant, then
    a live fetch (reference ``estimate.py:230-312`` accepts any Hub id)."""
    try:
        import transformers
    except ImportError as exc:  # pragma: no cover - transformers is baked in
        raise ValueError(
            f"{model_name!r} looks like a Hub model id, which needs the "
            "'transformers' package to resolve its config."
        ) from exc
    # ValueError also covers huggingface_hub's HFValidationError (a mistyped
    # local path is not a valid repo id) — both get the actionable message.
    try:
        cfg = transformers.AutoConfig.from_pretrained(model_name, local_files_only=True)
    except (OSError, ValueError):
        try:
            cfg = transformers.AutoConfig.from_pretrained(model_name)
        except (OSError, ValueError) as exc:
            raise ValueError(
                f"Could not resolve {model_name!r}: not a local file, not a zoo "
                f"preset ({sorted(PRESETS)}), not in the local HF cache, and the "
                "Hub is unreachable. Download the model's config.json and pass "
                "its path instead."
            ) from exc
    return cfg.to_dict()


def create_empty_model(model_name: str):
    """Abstract (shape-only) params for a preset, a local config.json, or a
    Hub model id — the ``jax.eval_shape`` analog of reference
    ``estimate.py:60-150`` meta-device load (config only, never weights)."""
    import jax

    if os.path.isfile(model_name):
        with open(model_name, encoding="utf-8") as f:
            hf = json.load(f)
        model = _model_from_hf_config(hf)
    elif model_name in PRESETS:
        family, kw = PRESETS[model_name]
        if family == "llama":
            from ..models import Llama, LlamaConfig

            model = Llama(LlamaConfig(**kw))
        elif family == "t5":
            from ..models import T5Config, T5ForConditionalGeneration

            model = T5ForConditionalGeneration(T5Config(**kw))
        elif family == "gptx":
            from ..models import GPTX, GPTXConfig

            model = GPTX(GPTXConfig(**kw))
        else:
            from ..models import BertConfig, BertForSequenceClassification

            model = BertForSequenceClassification(BertConfig(**kw))
    else:
        model = _model_from_hf_config(_hub_config(model_name))
    return jax.eval_shape(lambda: model.init_params(jax.random.key(0)))


def abstract_param_bytes(model_name: str) -> int:
    """fp32 parameter bytes of a preset/config/Hub id from the abstract
    (eval_shape) init — the number ``estimate-memory``'s table is built on.
    The static memory auditor's ``params`` class must agree with this within
    tolerance for the same config (the cross-validation test pins it), so the
    planning-time estimate and the compile-time audit can't silently drift."""
    params = create_empty_model(model_name)
    total, _ = calculate_maximum_sizes(params)
    return int(total)


def estimate_command_parser(subparsers=None) -> argparse.ArgumentParser:
    description = "Estimate model memory per dtype (params / gradients / optimizer states)"
    if subparsers is not None:
        parser = subparsers.add_parser("estimate-memory", description=description)
    else:
        parser = argparse.ArgumentParser("accelerate-tpu estimate-memory", description=description)
    parser.add_argument(
        "model_name",
        help="Zoo preset (e.g. llama-7b), path to a config.json, or a Hub "
             "model id (e.g. meta-llama/Llama-2-7b-hf; config only, no weights)",
    )
    parser.add_argument(
        "--dtypes", nargs="+", default=list(DTYPE_BYTES), choices=list(DTYPE_BYTES),
        help="Dtypes to include in the table",
    )
    if subparsers is not None:
        parser.set_defaults(func=estimate_command)
    return parser


def estimate_training_usage(total_fp32: int, dtype: str) -> int:
    """Rough Adam training footprint (reference ``estimate.py`` table's 'Total Size
    Using Adam' column): params + grads in compute dtype, fp32 master + 2 moments."""
    scale = DTYPE_BYTES[dtype] / 4
    return int(total_fp32 * scale * 2 + total_fp32 * 3)


def gather_data(args):
    params = create_empty_model(args.model_name)
    total_size, largest_layer = calculate_maximum_sizes(params)
    rows = []
    for dtype in args.dtypes:
        scale = DTYPE_BYTES[dtype] / 4
        rows.append(
            [
                dtype,
                int(largest_layer[0] * scale),
                int(total_size * scale),
                estimate_training_usage(total_size, dtype),
            ]
        )
    return rows, largest_layer


def estimate_command(args) -> None:
    rows, largest_layer = gather_data(args)
    header = ["dtype", "Largest Layer", "Total Size", "Training w/ Adam"]
    widths = [max(len(header[i]), 14) for i in range(4)]
    print(f"Memory estimate for {args.model_name} (largest layer: {largest_layer[1]})")
    print("  ".join(h.ljust(w) for h, w in zip(header, widths)))
    for row in rows:
        cells = [row[0]] + [convert_bytes(v) for v in row[1:]]
        print("  ".join(str(c).ljust(w) for c, w in zip(cells, widths)))


def main() -> None:  # pragma: no cover
    parser = estimate_command_parser()
    estimate_command(parser.parse_args())


if __name__ == "__main__":  # pragma: no cover
    main()
