"""`accelerate-tpu estimate-memory` — parameter/gradient/optimizer memory table.

Reference parity: ``src/accelerate/commands/estimate.py:230-312`` loads a model on
the meta device and prints per-dtype size tables via ``calculate_maximum_sizes``.
Here the meta device is ``jax.eval_shape`` — shapes come from the model zoo's
abstract init, so nothing touches HBM. Accepts either a zoo preset name
(``llama-7b``) or a local HF-format ``config.json``.
"""

from __future__ import annotations

import argparse
import json
import os

from ..utils.modeling import calculate_maximum_sizes
from ..utils.other import convert_bytes

# Zoo presets: name → (family, config kwargs). Sizes follow the public LLaMA /
# BERT architecture tables.
PRESETS = {
    "llama-7b": ("llama", dict(vocab_size=32000, hidden_size=4096, intermediate_size=11008,
                               num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=32)),
    "llama-13b": ("llama", dict(vocab_size=32000, hidden_size=5120, intermediate_size=13824,
                                num_hidden_layers=40, num_attention_heads=40, num_key_value_heads=40)),
    "llama-70b": ("llama", dict(vocab_size=32000, hidden_size=8192, intermediate_size=28672,
                                num_hidden_layers=80, num_attention_heads=64, num_key_value_heads=8)),
    "bert-base": ("bert", dict(vocab_size=30522, hidden_size=768, num_hidden_layers=12,
                               num_attention_heads=12, intermediate_size=3072)),
    "bert-large": ("bert", dict(vocab_size=30522, hidden_size=1024, num_hidden_layers=24,
                                num_attention_heads=16, intermediate_size=4096)),
    "t5-small": ("t5", dict(vocab_size=32128, d_model=512, d_kv=64, d_ff=2048,
                            num_layers=6, num_decoder_layers=6, num_heads=8)),
    "t5-base": ("t5", dict(vocab_size=32128, d_model=768, d_kv=64, d_ff=3072,
                           num_layers=12, num_decoder_layers=12, num_heads=12)),
    "t5-large": ("t5", dict(vocab_size=32128, d_model=1024, d_kv=64, d_ff=4096,
                            num_layers=24, num_decoder_layers=24, num_heads=16)),
}

DTYPE_BYTES = {"float32": 4, "bf16": 2, "int8": 1, "int4": 0.5}


def create_empty_model(model_name: str):
    """Abstract (shape-only) params for a preset or local config.json — the
    ``jax.eval_shape`` analog of reference ``estimate.py:60-150`` meta-device load."""
    import jax

    if os.path.isfile(model_name):
        with open(model_name, encoding="utf-8") as f:
            hf = json.load(f)
        arch = (hf.get("architectures") or [""])[0].lower()
        if "llama" in arch or hf.get("model_type") == "llama":
            family, kw = "llama", dict(
                vocab_size=hf["vocab_size"], hidden_size=hf["hidden_size"],
                intermediate_size=hf["intermediate_size"], num_hidden_layers=hf["num_hidden_layers"],
                num_attention_heads=hf["num_attention_heads"],
                num_key_value_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
            )
        elif "t5" in arch or hf.get("model_type") == "t5":
            family, kw = "t5", dict(
                vocab_size=hf["vocab_size"], d_model=hf["d_model"], d_kv=hf["d_kv"],
                d_ff=hf["d_ff"], num_layers=hf["num_layers"],
                num_decoder_layers=hf.get("num_decoder_layers", hf["num_layers"]),
                num_heads=hf["num_heads"],
            )
        elif "bert" in arch or hf.get("model_type") == "bert":
            family, kw = "bert", dict(
                vocab_size=hf["vocab_size"], hidden_size=hf["hidden_size"],
                num_hidden_layers=hf["num_hidden_layers"],
                num_attention_heads=hf["num_attention_heads"],
                intermediate_size=hf["intermediate_size"],
            )
        else:
            raise ValueError(f"Unsupported architecture in {model_name}: {arch or hf.get('model_type')}")
    elif model_name in PRESETS:
        family, kw = PRESETS[model_name]
    else:
        raise ValueError(
            f"Unknown model {model_name!r}. Pass a config.json path or one of {sorted(PRESETS)}"
        )

    if family == "llama":
        from ..models import Llama, LlamaConfig

        model = Llama(LlamaConfig(**kw))
    elif family == "t5":
        from ..models import T5Config, T5ForConditionalGeneration

        model = T5ForConditionalGeneration(T5Config(**kw))
    else:
        from ..models import BertConfig, BertForSequenceClassification

        model = BertForSequenceClassification(BertConfig(**kw))
    return jax.eval_shape(lambda: model.init_params(jax.random.key(0)))


def estimate_command_parser(subparsers=None) -> argparse.ArgumentParser:
    description = "Estimate model memory per dtype (params / gradients / optimizer states)"
    if subparsers is not None:
        parser = subparsers.add_parser("estimate-memory", description=description)
    else:
        parser = argparse.ArgumentParser("accelerate-tpu estimate-memory", description=description)
    parser.add_argument("model_name", help="Zoo preset (e.g. llama-7b) or path to a config.json")
    parser.add_argument(
        "--dtypes", nargs="+", default=list(DTYPE_BYTES), choices=list(DTYPE_BYTES),
        help="Dtypes to include in the table",
    )
    if subparsers is not None:
        parser.set_defaults(func=estimate_command)
    return parser


def estimate_training_usage(total_fp32: int, dtype: str) -> int:
    """Rough Adam training footprint (reference ``estimate.py`` table's 'Total Size
    Using Adam' column): params + grads in compute dtype, fp32 master + 2 moments."""
    scale = DTYPE_BYTES[dtype] / 4
    return int(total_fp32 * scale * 2 + total_fp32 * 3)


def gather_data(args):
    params = create_empty_model(args.model_name)
    total_size, largest_layer = calculate_maximum_sizes(params)
    rows = []
    for dtype in args.dtypes:
        scale = DTYPE_BYTES[dtype] / 4
        rows.append(
            [
                dtype,
                int(largest_layer[0] * scale),
                int(total_size * scale),
                estimate_training_usage(total_size, dtype),
            ]
        )
    return rows, largest_layer


def estimate_command(args) -> None:
    rows, largest_layer = gather_data(args)
    header = ["dtype", "Largest Layer", "Total Size", "Training w/ Adam"]
    widths = [max(len(header[i]), 14) for i in range(4)]
    print(f"Memory estimate for {args.model_name} (largest layer: {largest_layer[1]})")
    print("  ".join(h.ljust(w) for h, w in zip(header, widths)))
    for row in rows:
        cells = [row[0]] + [convert_bytes(v) for v in row[1:]]
        print("  ".join(str(c).ljust(w) for c, w in zip(cells, widths)))


def main() -> None:  # pragma: no cover
    parser = estimate_command_parser()
    estimate_command(parser.parse_args())


if __name__ == "__main__":  # pragma: no cover
    main()
