"""`accelerate-tpu merge-weights` — consolidate a sharded checkpoint to safetensors.

Reference parity: ``src/accelerate/commands/merge.py:26-61`` →
``merge_fsdp_weights`` (``utils/fsdp_utils.py:354-407``), which gathers FSDP
distributed-checkpoint shards into one ``model.safetensors``. Here the sharded
format is an orbax/tensorstore directory written by ``save_accelerator_state``;
restore runs on host CPU so no accelerator is needed to merge.
"""

from __future__ import annotations

import argparse
import os


def merge_command_parser(subparsers=None) -> argparse.ArgumentParser:
    description = "Merge a sharded (orbax) model checkpoint into safetensors/msgpack files"
    if subparsers is not None:
        parser = subparsers.add_parser("merge-weights", description=description)
    else:
        parser = argparse.ArgumentParser("accelerate-tpu merge-weights", description=description)
    parser.add_argument("checkpoint_dir", help="Sharded checkpoint directory (e.g. .../checkpoint_0/model)")
    parser.add_argument("output_path", help="Directory to write the merged weights into")
    parser.add_argument(
        "--unsafe_serialization", action="store_true",
        help="Write msgpack instead of safetensors",
    )
    parser.add_argument("--max_shard_size", default="10GB", help="Split output above this size")
    if subparsers is not None:
        parser.set_defaults(func=merge_command)
    return parser


def merge_weights(checkpoint_dir: str, output_path: str, safe_serialization: bool = True,
                  max_shard_size: str = "10GB") -> None:
    """Restore the sharded tree on host CPU and export consolidated weights
    (reference ``merge_fsdp_weights`` fsdp_utils.py:354-407)."""
    import jax
    import orbax.checkpoint as ocp

    from ..checkpointing import export_full_weights

    checkpoint_dir = os.path.abspath(checkpoint_dir)
    if not os.path.isdir(checkpoint_dir):
        raise FileNotFoundError(f"No sharded checkpoint at {checkpoint_dir}")
    with jax.default_device(jax.devices("cpu")[0]):
        ckptr = ocp.StandardCheckpointer()
        params = ckptr.restore(checkpoint_dir)
    os.makedirs(output_path, exist_ok=True)
    export_full_weights(params, output_path, max_shard_size=max_shard_size,
                        safe_serialization=safe_serialization)
    print(f"Merged weights from {checkpoint_dir} written to {output_path}")


def merge_command(args) -> None:
    merge_weights(
        args.checkpoint_dir,
        args.output_path,
        safe_serialization=not args.unsafe_serialization,
        max_shard_size=args.max_shard_size,
    )


def main() -> None:  # pragma: no cover
    parser = merge_command_parser()
    merge_command(parser.parse_args())


if __name__ == "__main__":  # pragma: no cover
    main()
