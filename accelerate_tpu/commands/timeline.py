"""`accelerate-tpu timeline` — assemble the fleet's journals into one trace.

Merges every rank's durable telemetry journal (telemetry/journal.py) into a
single Chrome-trace/Perfetto JSON where a request's router→prefill→handoff→
decode legs render as causally linked flow arrows under its rid, per-host
wall-clock skew corrected via the journaled ``clock_sync`` exchange. Input
is either a shared journal directory (``--journal-dir``, defaulting to
``ACCELERATE_JOURNAL_DIR``) or live worker metrics endpoints
(``--endpoints host:port,...`` → ``GET /journal?since=``). Pure host-side
post-processing — no backend, no devices touched.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from ..utils.constants import ENV_JOURNAL_DIR


def timeline_command_parser(subparsers=None) -> argparse.ArgumentParser:
    description = "Merge per-host telemetry journals into one Chrome-trace timeline"
    if subparsers is not None:
        parser = subparsers.add_parser("timeline", description=description)
    else:
        parser = argparse.ArgumentParser("accelerate-tpu timeline", description=description)
    parser.add_argument(
        "--journal-dir", default=None,
        help="Directory of journal_<rank>.jsonl files "
             f"(default: ${ENV_JOURNAL_DIR})",
    )
    parser.add_argument(
        "--endpoints", default=None,
        help="Comma-separated host:port metrics endpoints to tail over HTTP "
             "instead of (or in addition to) --journal-dir",
    )
    parser.add_argument(
        "--out", default="trace.json",
        help="Output Chrome-trace file (open in chrome://tracing or Perfetto)",
    )
    parser.add_argument(
        "--rid", type=int, default=None,
        help="Keep only this request id's legs (plus their flow links)",
    )
    parser.add_argument(
        "--steps", default=None,
        help="Keep only step range 'A-B' (or a single step 'A') and events "
             "inside its corrected time window",
    )
    if subparsers is not None:
        parser.set_defaults(func=timeline_command)
    return parser


def _gather(args) -> dict[int, list]:
    from ..telemetry.collect import fetch_journal, read_journal_dir

    journal_dir = args.journal_dir or os.environ.get(ENV_JOURNAL_DIR, "").strip()
    by_host: dict[int, list] = {}
    if journal_dir:
        by_host.update(read_journal_dir(journal_dir))
    if args.endpoints:
        for endpoint in args.endpoints.split(","):
            endpoint = endpoint.strip()
            if not endpoint:
                continue
            try:
                payload = fetch_journal(endpoint)
            except Exception as exc:  # noqa: BLE001 - surface which host failed
                print(f"timeline: endpoint {endpoint} unreachable: {exc}",
                      file=sys.stderr)
                continue
            host = int(payload.get("host", 0))
            merged = by_host.setdefault(host, [])
            seen = {r.get("seq") for r in merged}
            merged.extend(r for r in payload.get("records", [])
                          if r.get("seq") not in seen)
            merged.sort(key=lambda r: r.get("seq", 0))
    return by_host


def timeline_command(args) -> None:
    from ..telemetry.collect import chrome_trace

    if not (args.journal_dir or os.environ.get(ENV_JOURNAL_DIR, "").strip()
            or args.endpoints):
        raise SystemExit(
            "timeline: no journal source — pass --journal-dir / --endpoints "
            f"or set {ENV_JOURNAL_DIR}"
        )
    by_host = _gather(args)
    if not by_host:
        raise SystemExit("timeline: no journal records found")
    trace = chrome_trace(by_host, rid=args.rid, steps=args.steps)
    with open(args.out, "w", encoding="utf-8") as fh:
        json.dump(trace, fh)
    slices = sum(1 for e in trace["traceEvents"] if e.get("ph") == "X")
    flows = sum(1 for e in trace["traceEvents"] if e.get("ph") in ("s", "t", "f"))
    hosts = trace.get("otherData", {}).get("hosts", [])
    skew = trace.get("otherData", {}).get("skew", {})
    print(f"timeline: {slices} slices / {flows} flow links from "
          f"{len(hosts)} host(s) -> {args.out}")
    if any(abs(s) > 1e-6 for s in skew.values()):
        corrected = " ".join(f"host{h}={s:+.3f}s" for h, s in sorted(skew.items()))
        print(f"timeline: clock skew corrected: {corrected}")


def main() -> None:  # pragma: no cover - thin shim
    parser = timeline_command_parser()
    args = parser.parse_args()
    timeline_command(args)


if __name__ == "__main__":  # pragma: no cover
    main()
