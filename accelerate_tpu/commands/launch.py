"""`accelerate-tpu launch` — set the env contract and start worker processes.

Reference parity: ``src/accelerate/commands/launch.py:141-1198``. The reference
merges config-yaml defaults with CLI flags (:993-1174) then dispatches to
torchrun / deepspeed / xmp.spawn launchers. The JAX-native topology is simpler:

- **one process per host** owns all local chips (vs one process per GPU), so a
  single-host TPU run needs no spawning at all — we exec the script with the env
  contract set;
- **multi-host** runs exec one process too, pointing every host at the JAX
  coordinator (``ACCELERATE_COORDINATOR_ADDRESS``) — the pod runtime or gcloud
  fans the same command out to each host (reference's xla_dist ssh fan-out,
  launch.py:914-970);
- **CPU simulation** (`--cpu --num_processes N` or `--cpu_virtual_devices M`)
  spawns N local processes rendezvousing on localhost and/or exposes M virtual
  XLA host devices — the no-hardware test path (reference's gloo-on-CPU trick).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

from ..utils.constants import (
    ENV_COMPILE_CACHE_DIR,
    ENV_COORDINATOR,
    ENV_CPU,
    ENV_DEBUG_MODE,
    ENV_ELASTIC,
    ENV_FAULT_PLAN,
    ENV_FLEET_METRICS,
    ENV_FLIGHT_RING,
    ENV_GUARD_NUMERICS,
    ENV_HANDLE_PREEMPTION,
    ENV_HANG_TIMEOUT,
    ENV_JOURNAL_DIR,
    ENV_MESH_SHAPE,
    ENV_METRICS_PORT,
    ENV_MIN_DATA_PARALLEL,
    ENV_MIXED_PRECISION,
    ENV_KERNELS,
    ENV_NUM_PROCESSES,
    ENV_PROCESS_ID,
    ENV_PROFILE_SLOW_ZSCORE,
    ENV_PROFILE_STEPS,
    ENV_DRAIN_GRACE_S,
    ENV_RESTART_ATTEMPT,
    ENV_ROUTER_ENDPOINT,
    ENV_SERVING_LEASE_TTL,
    ENV_SERVING_RETRY_BUDGET,
    ENV_SERVING_ROLE,
    ENV_SLO_STEP_TIME,
    ENV_SLO_TPOT,
    ENV_SLO_TTFT,
    ENV_SPECULATIVE_K,
    ENV_DRAFT_MODEL,
    ENV_KV_QUANT,
    ENV_SPIKE_ZSCORE,
    ENV_STRAGGLER_THRESHOLD,
    ENV_TELEMETRY,
    ENV_TRACE_RING,
    ENV_TRAIN_WINDOW,
    ENV_TUNE_BUDGET,
    ENV_XLA_PRESET,
    ENV_ZERO_SHARDING,
)
from .config_args import ClusterConfig, load_config_from_file


def launch_command_parser(subparsers=None) -> argparse.ArgumentParser:
    description = "Launch a script on TPU (or simulated CPU devices) with accelerate-tpu"
    if subparsers is not None:
        parser = subparsers.add_parser("launch", description=description, allow_abbrev=False)
    else:
        parser = argparse.ArgumentParser(
            "accelerate-tpu launch", description=description, allow_abbrev=False
        )
    parser.add_argument("--config_file", default=None, help="Config yaml to read defaults from")
    # Hardware/topology group (reference launch.py:160-258)
    parser.add_argument("--cpu", action="store_true", default=None, help="Force CPU platform")
    parser.add_argument("--num_processes", type=int, default=None, help="Total processes (hosts)")
    parser.add_argument("--num_machines", type=int, default=None, help="Number of hosts")
    parser.add_argument("--machine_rank", type=int, default=None, help="Rank of this host")
    parser.add_argument("--main_process_ip", default=None, help="JAX coordinator host IP")
    parser.add_argument("--main_process_port", type=int, default=None, help="JAX coordinator port")
    parser.add_argument(
        "--cpu_virtual_devices",
        type=int,
        default=None,
        help="Expose N virtual XLA host devices per process (CPU simulation)",
    )
    # Precision / debug
    parser.add_argument("--mixed_precision", choices=["no", "bf16", "fp16", "fp8"], default=None)
    parser.add_argument("--debug", action="store_true", default=None, help="Enable collective shape checks")
    parser.add_argument(
        "--max_restarts", type=int, default=None,
        help="Relaunch the whole process gang up to N times after a failure "
             "(full-gang restart is the TPU elastic model: collectives cannot "
             "survive a lost participant, so recovery = restart + resume from "
             "the latest checkpoint via save_state/load_state).",
    )
    # Mesh axes (reference buries these in plugin args; first-class here)
    for axis, helptext in (
        ("dp", "data-parallel size (0 = absorb remaining devices)"),
        ("fsdp", "fully-sharded (ZeRO-3-like) size"),
        ("tp", "tensor-parallel size"),
        ("pp", "pipeline-parallel size"),
        ("sp", "sequence-parallel size"),
        ("ep", "expert-parallel size"),
        ("dcn", "multi-slice count (0 = auto-detect slices)"),
    ):
        parser.add_argument(f"--{axis}_size", type=int, default=None, help=helptext)
    parser.add_argument(
        "--compile_cache_dir", default=None,
        help="Persistent XLA compilation cache directory (exported as "
             "ACCELERATE_COMPILE_CACHE_DIR; restarted jobs skip recompiles)",
    )
    parser.add_argument(
        "--handle_preemption", action="store_true", default=None,
        help="Install the SIGTERM/SIGINT preemption watcher at startup "
             "(ACCELERATE_HANDLE_PREEMPTION): scripts calling "
             "Accelerator.checkpoint_on_preemption() each step then take an "
             "emergency checkpoint and exit cleanly when the platform preempts.",
    )
    parser.add_argument(
        "--fault_plan", default=None,
        help="Deterministic fault-injection plan for resilience/health drills, "
             "e.g. 'step:37=kill;step:40=loss_spike:50x;step:80=hang:600' "
             "(exported as ACCELERATE_FAULT_PLAN; see docs/resilience.md and "
             "docs/health.md for the grammar).",
    )
    parser.add_argument(
        "--elastic", action=argparse.BooleanOptionalAction, default=None,
        help="Elastic world-size training (ACCELERATE_ELASTIC): "
             "run_resilient re-forms the mesh at whatever dp degree the "
             "surviving devices support after a shrink/grow (preemption took "
             "a slice / maintenance returned one), reshards params+optimizer "
             "state onto it, and rescales gradient accumulation to preserve "
             "the global batch (docs/resilience.md 'Elastic world size'). "
             "--no-elastic pins fixed-size restarts explicitly.",
    )
    parser.add_argument(
        "--min_data_parallel", type=int, default=None,
        help="Floor for the elastic dp degree (ACCELERATE_MIN_DATA_PARALLEL): "
             "a shrink that would drop data parallelism below this refuses to "
             "re-form — the job queues for capacity instead of limping on too "
             "few replicas.",
    )
    parser.add_argument(
        "--guard_numerics", action="store_true", default=None,
        help="Always-on training-health guard (ACCELERATE_GUARD_NUMERICS): "
             "on-device finite checks of loss/grad-norm plus the loss-spike "
             "detector, driven by Accelerator.guard_step() each step "
             "(docs/health.md). The sentinel defaults on for loops that call "
             "guard_step; this flag pins it on explicitly.",
    )
    parser.add_argument(
        "--spike_zscore", type=float, default=None,
        help="Robust z-score threshold for the loss-spike detector "
             "(ACCELERATE_SPIKE_ZSCORE; library default 6.0; 0 disables).",
    )
    parser.add_argument(
        "--telemetry", action=argparse.BooleanOptionalAction, default=None,
        help="Pin the telemetry stack on (or, --no-telemetry, off) explicitly "
             "(ACCELERATE_TELEMETRY; on by default — the always-on per-step "
             "timeline, span ring, metrics registry, and straggler monitor "
             "behind Accelerator.telemetry, docs/observability.md).",
    )
    parser.add_argument(
        "--metrics_port", type=int, default=None,
        help="Serve the Prometheus metrics endpoint on this port on every "
             "worker (ACCELERATE_METRICS_PORT): /metrics exposes the shared "
             "registry — step time, tokens/s, MFU, goodput/badput classes, "
             "health trips, restarts, straggler skew. Co-located workers "
             "(CPU-sim gangs) serve on port + local_process_index.",
    )
    parser.add_argument(
        "--fleet_metrics", action=argparse.BooleanOptionalAction, default=None,
        help="Fleet metric aggregation (ACCELERATE_FLEET_METRICS): every "
             "worker registers its bound metrics endpoint in the "
             "coordination-service KV registry and the lead host scrapes "
             "them all into per-host-labeled series + fleet rollups at "
             "/fleet on its own endpoint — `accelerate-tpu top` is the "
             "console. Requires --metrics_port. --no-fleet_metrics pins it "
             "off explicitly.",
    )
    parser.add_argument(
        "--slo_step_time", type=float, default=None,
        help="SLO sentinel: target per-step wall time in seconds "
             "(ACCELERATE_SLO_STEP_TIME). Every breach books "
             "accelerate_slo_breaches_total{target=\"step_time\"}, a "
             "flight-recorder event, and a rate-limited warning. 0 scrubs an "
             "inherited value (dimension off).",
    )
    parser.add_argument(
        "--slo_ttft", type=float, default=None,
        help="SLO sentinel: serving time-to-first-token target in seconds "
             "(ACCELERATE_SLO_TTFT). Reaches ContinuousBatcher as its "
             "SLOTargets default (admission escalates at-risk prefills) and "
             "arms per-request breach booking in the request tracer. 0 "
             "scrubs an inherited value.",
    )
    parser.add_argument(
        "--slo_tpot", type=float, default=None,
        help="SLO sentinel: serving time-per-output-token target in seconds "
             "(ACCELERATE_SLO_TPOT; the decode-pacing twin of --slo_ttft). "
             "0 scrubs an inherited value.",
    )
    parser.add_argument(
        "--serving_role", default=None,
        help="Disaggregated-serving tier for the launched workers "
             "(ACCELERATE_SERVING_ROLE; docs/serving.md 'Disaggregated "
             "serving'): unified (default — each host prefills AND decodes), "
             "prefill (chunked prefill only, finished KV chains ship to a "
             "decode host), decode (decodes imported chains + short local "
             "prompts), router (no engine; admits requests and routes by "
             "prefix-cache affinity). Tri-state: unset inherits; an explicit "
             "'unified' scrubs a stale inherited role.",
    )
    parser.add_argument(
        "--router_endpoint", default=None,
        help="host:port of the serving router tier workers should announce "
             "to / clients should target (ACCELERATE_ROUTER_ENDPOINT). "
             "Tri-state: unset inherits, '' scrubs an inherited value.",
    )
    parser.add_argument(
        "--serving_retry_budget", type=float, default=None,
        help="Serving fault tolerance: how many times the router re-dispatches "
             "a failed request on a surviving worker under the SAME rid "
             "before surfacing the error (ACCELERATE_SERVING_RETRY_BUDGET; "
             "library default 2; docs/serving.md 'Failure semantics'). "
             "Tri-state per the SLO precedent: unset inherits, an explicit 0 "
             "scrubs an inherited value back to the default.",
    )
    parser.add_argument(
        "--serving_lease_ttl", type=float, default=None,
        help="Serving fault tolerance: seconds a worker's heartbeat-refreshed "
             "discovery lease stays valid without a refresh — an expired "
             "lease is an eviction (ACCELERATE_SERVING_LEASE_TTL; library "
             "default 15). Tri-state: unset inherits, an explicit 0 scrubs "
             "an inherited value back to the default.",
    )
    parser.add_argument(
        "--drain_grace_s", type=float, default=None,
        help="Serving fault tolerance: seconds a SIGTERM'd serving worker "
             "waits for in-flight requests to finish before exiting — "
             "admission stops immediately, the lease is revoked after "
             "(ACCELERATE_DRAIN_GRACE_S; library default 30). Tri-state: "
             "unset inherits, an explicit 0 scrubs an inherited value back "
             "to the default.",
    )
    parser.add_argument(
        "--speculative_k", type=int, default=None,
        help="Speculative decoding draft depth for the paged serving engine "
             "(ACCELERATE_SPECULATIVE_K; docs/serving.md 'Speculative "
             "decoding'): a draft model proposes k tokens per slot and the "
             "target verifies the whole window in one paged forward — greedy "
             "outputs stay bit-identical to non-speculative decode. "
             "Tri-state: unset inherits, an explicit 0 scrubs an inherited "
             "value (speculation off).",
    )
    parser.add_argument(
        "--draft_model", default=None,
        help="Draft model preset for speculative decoding "
             "(ACCELERATE_DRAFT_MODEL): a LlamaConfig classmethod name, e.g. "
             "'tiny' (the default when --speculative_k is set). The engine "
             "builds the draft at the target's vocab. Tri-state: unset "
             "inherits, '' scrubs an inherited value.",
    )
    parser.add_argument(
        "--kv_quant", default=None,
        help="KV-cache pool storage quantization for the paged serving "
             "engine (ACCELERATE_KV_QUANT; docs/serving.md 'Quantized KV "
             "cache'): 'int8' stores pool blocks int8 with per-token scales "
             "(~2x tokens per HBM byte; dequantized in the paged kernels' "
             "DMA step). Tri-state: unset inherits, an explicit 'off'/'none' "
             "scrubs an inherited value (full-precision pool).",
    )
    parser.add_argument(
        "--journal_dir", default=None,
        help="Durable telemetry journal directory (ACCELERATE_JOURNAL_DIR; "
             "docs/observability.md 'Telemetry journal'): each worker "
             "appends its step/span/request/flight/goodput streams to "
             "journal_<rank>.jsonl here, flushed per record so the tail "
             "survives SIGKILL; `accelerate-tpu timeline`/`report` read it "
             "back. Tri-state: unset inherits, '' scrubs an inherited value "
             "(journaling off).",
    )
    parser.add_argument(
        "--trace_ring", type=int, default=None,
        help="RequestTracer ring capacity — completed request records "
             "retained in memory (ACCELERATE_TRACE_RING; library default "
             "1024). Tri-state: unset inherits, an explicit 0 scrubs an "
             "inherited value back to the default.",
    )
    parser.add_argument(
        "--flight_ring", type=int, default=None,
        help="Flight-recorder ring size — forensic events retained for the "
             "crash dump (ACCELERATE_FLIGHT_RING; library default 2048). "
             "Tri-state: unset inherits, an explicit 0 scrubs an inherited "
             "value back to the default.",
    )
    parser.add_argument(
        "--straggler_threshold", type=float, default=None,
        help="Cross-host slowness ratio that raises a straggler alert "
             "(ACCELERATE_STRAGGLER_THRESHOLD; library default 1.5): a host "
             "whose mean step time exceeds threshold x the cross-host median "
             "is named in a rate-limited warning and the skew gauges.",
    )
    parser.add_argument(
        "--train_window", type=int, default=None,
        help="Dispatch-amortization window K (ACCELERATE_TRAIN_WINDOW): "
             "Accelerator.build_train_window fuses K full train steps into "
             "ONE compiled program per dispatch — the per-step dispatch RTT "
             "is paid once per K steps (docs/performance.md 'Dispatch "
             "amortization'). 1 = one dispatch per step.",
    )
    parser.add_argument(
        "--xla_preset", default=None,
        help="Curated XLA latency-hiding flag preset installed into "
             "LIBTPU_INIT_ARGS before backend creation "
             "(ACCELERATE_XLA_PRESET): off | latency (latency-hiding "
             "scheduler + async all-gather/reduce-scatter/collective-permute "
             "fusion) | collective_matmul (latency + windowed-einsum). "
             "Echoed into telemetry snapshots.",
    )
    parser.add_argument(
        "--zero_sharding", action=argparse.BooleanOptionalAction, default=None,
        help="Cross-replica (ZeRO-style) sharding of optimizer state and the "
             "weight update along the dp axis (ACCELERATE_ZERO_SHARDING): "
             "opt-state HBM drops to ~1/dp and the fused update lowers as "
             "reduce-scatter(grads) -> sharded clip+update -> all-gather(new "
             "params), overlapped by the --xla_preset latency schedules. "
             "Gate the win with `accelerate-tpu memcheck "
             "--replicated-opt-gib` (docs/performance.md).",
    )
    parser.add_argument(
        "--kernels", default=None,
        help="Pallas kernel-layer backend spec (ACCELERATE_KERNELS; "
             "docs/kernels.md): 'pallas' (compiled Mosaic on TPU, "
             "interpreter elsewhere), 'interpret' (force the interpreter — "
             "CPU parity testing), 'reference'/'off' (the always-available "
             "reference lowerings; an explicit off scrubs an inherited "
             "value), or a per-op map like "
             "'paged_decode=pallas,int8_matmul=off'. Resolved per op at "
             "build time by ops/registry.py.",
    )
    parser.add_argument(
        "--profile_steps", default=None,
        help="Capture an XLA trace over these training steps "
             "(ACCELERATE_PROFILE_STEPS): comma-separated 1-based inclusive "
             "ranges, e.g. '10-12' or '10-12,50'. Captures align to step "
             "(and K-step window) boundaries; overhead books as `profile` "
             "badput and the parsed attribution lands in telemetry summaries "
             "(docs/observability.md 'Profiling'). 'off' scrubs an inherited "
             "value.",
    )
    parser.add_argument(
        "--tune_budget", type=int, default=None,
        help="Short-bench trial budget for `accelerate-tpu tune` runs in the "
             "launched job's environment (ACCELERATE_TUNE_BUDGET): tri-state "
             "— unset inherits, > 0 caps the trials, an explicit 0 scrubs a "
             "stale inherited value (library default applies). See "
             "docs/tuning.md.",
    )
    parser.add_argument(
        "--profile_slow_zscore", type=float, default=None,
        help="Slow-step trace trigger (ACCELERATE_PROFILE_SLOW_ZSCORE): when "
             "a step's wall time lands this many robust sigmas (EMA+MAD "
             "z-score, health/spike.py's idiom host-side) above the recent "
             "baseline, the next steps are captured automatically. 0 "
             "disables; captures share the max-captures-per-run budget.",
    )
    parser.add_argument(
        "--hang_timeout", type=float, default=None,
        help="Hang-watchdog deadline in seconds (ACCELERATE_HANG_TIMEOUT): "
             "when no training step completes within the deadline, every "
             "thread's stack is dumped and the process exits with code 113 "
             "so --max_restarts (or the scheduler) can restart the gang "
             "instead of burning reserved chips on a deadlock.",
    )
    parser.add_argument("-m", "--module", action="store_true", help="Run script as a python module")
    parser.add_argument("training_script", help="Path to the script to launch")
    parser.add_argument(
        "training_script_args", nargs=argparse.REMAINDER, help="Arguments for the script"
    )
    if subparsers is not None:
        parser.set_defaults(func=launch_command)
    return parser


def _merge_config(args) -> ClusterConfig:
    """Merge yaml defaults with CLI flags — flags win (reference :993-1174)."""
    cfg = load_config_from_file(args.config_file) or ClusterConfig()
    for flag, attr in [
        ("cpu", "use_cpu"),
        ("num_processes", "num_processes"),
        ("num_machines", "num_machines"),
        ("machine_rank", "machine_rank"),
        ("main_process_ip", "main_process_ip"),
        ("main_process_port", "main_process_port"),
        ("cpu_virtual_devices", "cpu_virtual_devices"),
        ("mixed_precision", "mixed_precision"),
        ("debug", "debug"),
        ("dp_size", "dp_size"),
        ("fsdp_size", "fsdp_size"),
        ("tp_size", "tp_size"),
        ("pp_size", "pp_size"),
        ("sp_size", "sp_size"),
        ("ep_size", "ep_size"),
        ("dcn_size", "dcn_size"),
        ("max_restarts", "max_restarts"),
        ("compile_cache_dir", "compile_cache_dir"),
        ("handle_preemption", "handle_preemption"),
        ("fault_plan", "fault_plan"),
        ("elastic", "elastic"),
        ("min_data_parallel", "min_data_parallel"),
        ("guard_numerics", "guard_numerics"),
        ("spike_zscore", "spike_zscore"),
        ("hang_timeout", "hang_timeout"),
        ("telemetry", "telemetry"),
        ("metrics_port", "metrics_port"),
        ("straggler_threshold", "straggler_threshold"),
        ("fleet_metrics", "fleet_metrics"),
        ("slo_step_time", "slo_step_time"),
        ("slo_ttft", "slo_ttft"),
        ("slo_tpot", "slo_tpot"),
        ("serving_role", "serving_role"),
        ("router_endpoint", "router_endpoint"),
        ("serving_retry_budget", "serving_retry_budget"),
        ("serving_lease_ttl", "serving_lease_ttl"),
        ("drain_grace_s", "drain_grace_s"),
        ("speculative_k", "speculative_k"),
        ("draft_model", "draft_model"),
        ("kv_quant", "kv_quant"),
        ("journal_dir", "journal_dir"),
        ("trace_ring", "trace_ring"),
        ("flight_ring", "flight_ring"),
        ("train_window", "train_window"),
        ("xla_preset", "xla_preset"),
        ("zero_sharding", "zero_sharding"),
        ("kernels", "kernels"),
        ("profile_steps", "profile_steps"),
        ("profile_slow_zscore", "profile_slow_zscore"),
        ("tune_budget", "tune_budget"),
    ]:
        val = getattr(args, flag, None)
        if val is not None:
            setattr(cfg, attr, val)
    return cfg


def prepare_launch_env(cfg: ClusterConfig, process_id: int | None = None, attempt: int = 0) -> dict:
    """Build the ACCELERATE_* env contract (reference ``utils/launch.py:100-352``).

    ``attempt`` is the gang incarnation (0 = first launch); scripts key
    resume-vs-fresh decisions off it the way torchrun scripts use
    TORCHELASTIC_RESTART_COUNT."""
    env = dict(os.environ)
    env[ENV_RESTART_ATTEMPT] = str(attempt)
    # Make sure workers can import accelerate_tpu even without a pip install.
    import accelerate_tpu

    pkg_parent = os.path.dirname(os.path.dirname(os.path.abspath(accelerate_tpu.__file__)))
    if pkg_parent not in env.get("PYTHONPATH", "").split(os.pathsep):
        env["PYTHONPATH"] = pkg_parent + os.pathsep + env.get("PYTHONPATH", "")
    env[ENV_MIXED_PRECISION] = cfg.mixed_precision
    env[ENV_MESH_SHAPE] = cfg.mesh_shape_env()
    # Per-feature sections from the guided wizard; Accelerator() reads these
    # when the corresponding constructor argument is not given.
    if cfg.gradient_accumulation_steps and cfg.gradient_accumulation_steps > 1:
        env["ACCELERATE_GRADIENT_ACCUMULATION_STEPS"] = str(cfg.gradient_accumulation_steps)
    if cfg.fsdp_min_shard_size:
        env["ACCELERATE_FSDP_MIN_SHARD_SIZE"] = str(cfg.fsdp_min_shard_size)
    if cfg.fsdp_cpu_offload:
        env["ACCELERATE_FSDP_CPU_OFFLOAD"] = "1"
    if cfg.pp_schedule:
        env["ACCELERATE_PP_SCHEDULE"] = cfg.pp_schedule
    if cfg.pp_microbatches:
        env["ACCELERATE_PP_MICROBATCHES"] = str(cfg.pp_microbatches)
    if cfg.project_dir:
        env["ACCELERATE_PROJECT_DIR"] = cfg.project_dir
    if cfg.checkpoint_total_limit:
        env["ACCELERATE_CHECKPOINT_TOTAL_LIMIT"] = str(cfg.checkpoint_total_limit)
    if cfg.checkpoint_auto_naming:
        env["ACCELERATE_CHECKPOINT_AUTO_NAMING"] = "1"
    if cfg.log_with:
        env["ACCELERATE_LOG_WITH"] = cfg.log_with
    if cfg.compile_cache_dir:
        env[ENV_COMPILE_CACHE_DIR] = os.path.expanduser(cfg.compile_cache_dir)
    if cfg.handle_preemption:
        env[ENV_HANDLE_PREEMPTION] = "1"
    if cfg.fault_plan:
        env[ENV_FAULT_PLAN] = cfg.fault_plan
    # Elastic is tri-state like the health knobs: None = not configured
    # (nothing exported, run_resilient's default applies), and an explicit
    # --no-elastic must reach the workers as a disable.
    if cfg.elastic is not None:
        env[ENV_ELASTIC] = "1" if cfg.elastic else "0"
    if cfg.min_data_parallel:
        env[ENV_MIN_DATA_PARALLEL] = str(int(cfg.min_data_parallel))
    # Tri-state health knobs: None = not configured (export nothing, library
    # defaults apply); an explicit False / 0 must reach the workers as a
    # disable, not vanish behind a truthiness check.
    if cfg.guard_numerics is not None:
        env[ENV_GUARD_NUMERICS] = "1" if cfg.guard_numerics else "0"
    if cfg.spike_zscore is not None:
        env[ENV_SPIKE_ZSCORE] = str(cfg.spike_zscore)
    if cfg.hang_timeout:
        env[ENV_HANG_TIMEOUT] = str(cfg.hang_timeout)
    # Telemetry is tri-state like the health knobs: None exports nothing
    # (library default: ON), an explicit disable must reach the workers.
    if cfg.telemetry is not None:
        env[ENV_TELEMETRY] = "1" if cfg.telemetry else "0"
    if cfg.metrics_port:
        env[ENV_METRICS_PORT] = str(int(cfg.metrics_port))
    if cfg.straggler_threshold:
        env[ENV_STRAGGLER_THRESHOLD] = str(cfg.straggler_threshold)
    # Fleet aggregation is tri-state like telemetry: None exports nothing,
    # an explicit --no-fleet_metrics reaches the workers as a disable.
    if cfg.fleet_metrics is not None:
        env[ENV_FLEET_METRICS] = "1" if cfg.fleet_metrics else "0"
    # SLO targets are tri-state per the profile_slow_zscore precedent: an
    # explicit 0 must SCRUB a stale inherited value, not forward it.
    for value, env_name in ((cfg.slo_step_time, ENV_SLO_STEP_TIME),
                            (cfg.slo_ttft, ENV_SLO_TTFT),
                            (cfg.slo_tpot, ENV_SLO_TPOT)):
        if value:
            env[env_name] = str(value)
        elif value is not None:
            env.pop(env_name, None)
    # Disaggregated-serving tier (serving_net/roles.py): tri-state per the
    # xla_preset precedent — an explicit 'unified' (the library default)
    # scrubs a stale inherited role instead of forwarding it.
    if cfg.serving_role and cfg.serving_role.strip().lower() != "unified":
        env[ENV_SERVING_ROLE] = cfg.serving_role.strip().lower()
    elif cfg.serving_role is not None:
        env.pop(ENV_SERVING_ROLE, None)
    if cfg.router_endpoint and cfg.router_endpoint.strip():
        env[ENV_ROUTER_ENDPOINT] = cfg.router_endpoint.strip()
    elif cfg.router_endpoint is not None:
        env.pop(ENV_ROUTER_ENDPOINT, None)
    # Serving fault-tolerance knobs (serving_net/lease.py): tri-state per the
    # SLO precedent — an explicit 0 scrubs a stale inherited value back to
    # the library default instead of forwarding it.
    for value, env_name in (
        (cfg.serving_retry_budget, ENV_SERVING_RETRY_BUDGET),
        (cfg.serving_lease_ttl, ENV_SERVING_LEASE_TTL),
        (cfg.drain_grace_s, ENV_DRAIN_GRACE_S),
    ):
        if value:
            env[env_name] = str(value)
        elif value is not None:
            env.pop(env_name, None)
    # Speculative decoding + KV quantization (serving.py decode-speed
    # levers): tri-state per the SLO precedent — an explicit 0 / 'off'
    # scrubs a stale inherited value instead of forwarding it.
    if cfg.speculative_k and cfg.speculative_k > 0:
        env[ENV_SPECULATIVE_K] = str(int(cfg.speculative_k))
    elif cfg.speculative_k is not None:
        env.pop(ENV_SPECULATIVE_K, None)
    if cfg.draft_model and cfg.draft_model.strip():
        env[ENV_DRAFT_MODEL] = cfg.draft_model.strip()
    elif cfg.draft_model is not None:
        env.pop(ENV_DRAFT_MODEL, None)
    if cfg.kv_quant and cfg.kv_quant.strip().lower() not in ("off", "none"):
        env[ENV_KV_QUANT] = cfg.kv_quant.strip().lower()
    elif cfg.kv_quant is not None:
        env.pop(ENV_KV_QUANT, None)
    # Telemetry journal (telemetry/journal.py): tri-state per the
    # router_endpoint precedent — a path arms per-rank journaling, an
    # explicit '' scrubs a stale inherited directory (journaling off).
    if cfg.journal_dir and cfg.journal_dir.strip():
        env[ENV_JOURNAL_DIR] = os.path.expanduser(cfg.journal_dir.strip())
    elif cfg.journal_dir is not None:
        env.pop(ENV_JOURNAL_DIR, None)
    # Forensic ring capacities: tri-state per the tune_budget precedent —
    # an explicit 0 scrubs a stale inherited value back to the defaults.
    for value, env_name in ((cfg.trace_ring, ENV_TRACE_RING),
                            (cfg.flight_ring, ENV_FLIGHT_RING)):
        if value:
            env[env_name] = str(int(value))
        elif value is not None:
            env.pop(env_name, None)
    # Dispatch amortization: the window K reaches Accelerator.train_window;
    # the XLA preset is installed by PartialState BEFORE backend creation in
    # the worker (libtpu reads LIBTPU_INIT_ARGS once at init).
    if cfg.train_window and cfg.train_window > 1:
        env[ENV_TRAIN_WINDOW] = str(int(cfg.train_window))
    elif cfg.train_window is not None:
        # An explicit --train_window 1 beats a stale inherited env value —
        # env = dict(os.environ) above would otherwise forward it silently.
        env.pop(ENV_TRAIN_WINDOW, None)
    if cfg.xla_preset and cfg.xla_preset not in ("off", "none"):
        env[ENV_XLA_PRESET] = cfg.xla_preset
    elif cfg.xla_preset:
        # Same for an explicit --xla_preset off/none.
        env.pop(ENV_XLA_PRESET, None)
    # ZeRO sharding is tri-state like telemetry/elastic: None exports nothing
    # (an inherited env flows; library default off), and an explicit
    # --no-zero_sharding reaches the workers as a disable.
    if cfg.zero_sharding is not None:
        env[ENV_ZERO_SHARDING] = "1" if cfg.zero_sharding else "0"
    # Pallas kernel layer: tri-state per the xla_preset precedent — None =
    # unspecified (an inherited ACCELERATE_KERNELS flows through), an
    # explicit spec reaches the workers, and an explicit 'off'/'reference'
    # scrubs a stale inherited value (workers then run the reference
    # lowerings, the library default).
    if cfg.kernels and cfg.kernels.strip().lower() not in ("off", "none", "reference"):
        env[ENV_KERNELS] = cfg.kernels.strip()
    elif cfg.kernels is not None:
        env.pop(ENV_KERNELS, None)
    # Profiling (telemetry/profiler.py): tri-state per the telemetry
    # precedent — None exports nothing (an inherited env flows through), an
    # explicit value reaches the workers, and an explicit disable
    # ('off'/''/0) scrubs a stale inherited value.
    if cfg.profile_steps and cfg.profile_steps.strip().lower() not in ("off", "none", "0"):
        env[ENV_PROFILE_STEPS] = cfg.profile_steps.strip()
    elif cfg.profile_steps is not None:
        env.pop(ENV_PROFILE_STEPS, None)
    if cfg.profile_slow_zscore:
        env[ENV_PROFILE_SLOW_ZSCORE] = str(cfg.profile_slow_zscore)
    elif cfg.profile_slow_zscore is not None:
        env.pop(ENV_PROFILE_SLOW_ZSCORE, None)
    # Autotuner trial budget: tri-state like train_window — an explicit 0
    # ("library default") must scrub a stale inherited value, not forward it.
    if cfg.tune_budget and cfg.tune_budget > 0:
        env[ENV_TUNE_BUDGET] = str(int(cfg.tune_budget))
    elif cfg.tune_budget is not None:
        env.pop(ENV_TUNE_BUDGET, None)
    # Plugins (e.g. the axon tunnel) may have pinned JAX_PLATFORMS in *this*
    # process's environ at jax-import time; children must re-discover their own
    # backend, so only forward the value we set deliberately.
    env.pop("JAX_PLATFORMS", None)
    if cfg.use_cpu:
        env[ENV_CPU] = "1"
        env["JAX_PLATFORMS"] = "cpu"
    if cfg.debug:
        env[ENV_DEBUG_MODE] = "1"
    if cfg.cpu_virtual_devices and cfg.cpu_virtual_devices > 1:
        flags = env.get("XLA_FLAGS", "")
        token = f"--xla_force_host_platform_device_count={cfg.cpu_virtual_devices}"
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (flags + " " + token).strip()
    nproc = max(cfg.num_processes, cfg.num_machines, 1)
    if nproc > 1:
        ip = cfg.main_process_ip or "127.0.0.1"
        port = cfg.main_process_port or 8476
        env[ENV_COORDINATOR] = f"{ip}:{port}"
        env[ENV_NUM_PROCESSES] = str(nproc)
        if process_id is not None:
            env[ENV_PROCESS_ID] = str(process_id)
            env["ACCELERATE_LOCAL_PROCESS_ID"] = str(process_id if cfg.num_machines <= 1 else 0)
    return env


def _script_cmd(args) -> list:
    cmd = [sys.executable]
    if args.module:
        cmd.append("-m")
    cmd.append(args.training_script)
    cmd.extend(args.training_script_args)
    return cmd


def simple_launcher(args, cfg: ClusterConfig) -> int:
    """Single process on this host (reference ``launch.py:778-788``)."""
    rank = cfg.machine_rank if cfg.num_machines > 1 else None
    for attempt in range(cfg.max_restarts + 1):
        env = prepare_launch_env(cfg, process_id=rank, attempt=attempt)
        proc = subprocess.run(_script_cmd(args), env=env)
        if proc.returncode == 0:
            return 0
        if attempt < cfg.max_restarts:
            print(
                f"Process failed (rc={proc.returncode}){_rc_hint(proc.returncode)}; "
                f"restart {attempt + 1}/{cfg.max_restarts} (resume from the latest "
                "checkpoint is the script's responsibility via load_state)."
            )
    return proc.returncode


def multi_process_launcher(args, cfg: ClusterConfig) -> int:
    """Spawn N local processes rendezvousing on localhost — the CPU-sim multi-host
    path (reference's multi-CPU gloo path, ``launchers.py:269-302``). On failure
    with ``max_restarts`` > 0, the WHOLE gang is relaunched: collectives cannot
    survive a lost participant, so TPU-elastic = full-gang restart + checkpoint
    resume (the torchrun-restart analog the reference delegates to)."""
    rc = 1
    for attempt in range(cfg.max_restarts + 1):
        rc = _run_gang_once(args, cfg, attempt)
        if rc == 0:
            return 0
        if attempt < cfg.max_restarts:
            print(
                f"Gang failed (rc={rc}){_rc_hint(rc)}; restarting all ranks "
                f"{attempt + 1}/{cfg.max_restarts}."
            )
    return rc


def _rc_hint(rc: int) -> str:
    """Name the exit codes with framework-defined meaning."""
    from ..health.hang import HANG_EXIT_CODE

    if rc == HANG_EXIT_CODE:
        return " [hang watchdog: no step within --hang_timeout; stacks on stderr]"
    return ""


def _run_gang_once(args, cfg: ClusterConfig, attempt: int = 0) -> int:
    import time

    nproc = cfg.num_processes
    procs = []
    for rank in range(nproc):
        env = prepare_launch_env(cfg, process_id=rank, attempt=attempt)
        procs.append(subprocess.Popen(_script_cmd(args), env=env))
    # Poll rather than wait sequentially: if one rank dies before the JAX
    # rendezvous completes, the others would block in initialize() forever —
    # kill the survivors and report the failure instead.
    rc = 0
    while True:
        codes = [p.poll() for p in procs]
        failed = [c for c in codes if c not in (None, 0)]
        if failed:
            rc = failed[0]
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            for p in procs:
                p.wait()
            break
        if all(c == 0 for c in codes):
            break
        time.sleep(0.2)
    return rc


def launch_command(args) -> None:
    cfg = _merge_config(args)
    if cfg.max_restarts < 0:
        raise ValueError(f"--max_restarts must be >= 0, got {cfg.max_restarts}")
    if cfg.fault_plan:
        # Fail a malformed plan at launch, not after every worker has paid the
        # XLA compile and hit its first checkpoint_on_preemption call. Covers
        # the health kinds (nan/loss_spike/hang) and their arguments too.
        from ..resilience.faults import FaultPlan

        FaultPlan.parse(cfg.fault_plan)
    if cfg.min_data_parallel and cfg.min_data_parallel < 1:
        raise ValueError(
            f"--min_data_parallel must be >= 1, got {cfg.min_data_parallel}"
        )
    if cfg.spike_zscore and cfg.spike_zscore < 0:
        raise ValueError(f"--spike_zscore must be >= 0, got {cfg.spike_zscore}")
    if cfg.hang_timeout and cfg.hang_timeout < 0:
        raise ValueError(f"--hang_timeout must be >= 0, got {cfg.hang_timeout}")
    if cfg.metrics_port and not (0 < cfg.metrics_port < 65536):
        raise ValueError(f"--metrics_port must be in [1, 65535], got {cfg.metrics_port}")
    if cfg.straggler_threshold and cfg.straggler_threshold < 1.0:
        raise ValueError(
            f"--straggler_threshold must be >= 1.0 (a ratio to the cross-host "
            f"median step time), got {cfg.straggler_threshold}"
        )
    for name, value in (("--slo_step_time", cfg.slo_step_time),
                        ("--slo_ttft", cfg.slo_ttft),
                        ("--slo_tpot", cfg.slo_tpot)):
        if value is not None and value < 0:
            raise ValueError(f"{name} must be >= 0 seconds (0 = off), got {value}")
    if cfg.serving_role:
        from ..serving_net.roles import SERVING_ROLES

        if cfg.serving_role.strip().lower() not in SERVING_ROLES:
            raise ValueError(
                f"--serving_role must be one of {'/'.join(SERVING_ROLES)}, "
                f"got {cfg.serving_role!r}"
            )
    for name, value in (
        ("--serving_retry_budget", cfg.serving_retry_budget),
        ("--serving_lease_ttl", cfg.serving_lease_ttl),
        ("--drain_grace_s", cfg.drain_grace_s),
    ):
        if value is not None and value < 0:
            raise ValueError(
                f"{name} must be >= 0 (0 = library default), got {value}"
            )
    for name, value in (("--trace_ring", cfg.trace_ring),
                        ("--flight_ring", cfg.flight_ring)):
        if value is not None and value < 0:
            raise ValueError(
                f"{name} must be >= 0 entries (0 = library default), got {value}"
            )
    if cfg.speculative_k is not None and cfg.speculative_k < 0:
        raise ValueError(
            f"--speculative_k must be >= 0 draft tokens (0 = off), got "
            f"{cfg.speculative_k}"
        )
    if cfg.kv_quant and cfg.kv_quant.strip().lower() not in ("int8", "off",
                                                             "none"):
        raise ValueError(
            f"--kv_quant must be int8 or off/none, got {cfg.kv_quant!r}"
        )
    from ..telemetry import metrics_port_from_env

    # An inherited ACCELERATE_METRICS_PORT of "0" means "no endpoint"
    # (the shared env-contract parser) — it must not satisfy the fleet
    # requirement just by being a non-empty string.
    if cfg.fleet_metrics and not cfg.metrics_port and metrics_port_from_env() <= 0:
        raise ValueError(
            "--fleet_metrics aggregates the workers' Prometheus endpoints, "
            "which --metrics_port starts: pass --metrics_port too (the lead "
            "host serves /fleet on its own endpoint)."
        )
    if cfg.train_window is not None and cfg.train_window < 1:
        raise ValueError(f"--train_window must be >= 1, got {cfg.train_window}")
    if cfg.tune_budget is not None and cfg.tune_budget < 0:
        raise ValueError(
            f"--tune_budget must be >= 0 (0 = library default), got "
            f"{cfg.tune_budget}"
        )
    if cfg.profile_steps:
        # Fail a malformed range grammar at launch, not mid-run when the
        # profiler first arms (the fault-plan validation precedent).
        from ..telemetry.profiler import parse_profile_steps

        parse_profile_steps(cfg.profile_steps)
    if cfg.profile_slow_zscore and cfg.profile_slow_zscore < 0:
        raise ValueError(
            f"--profile_slow_zscore must be >= 0, got {cfg.profile_slow_zscore}"
        )
    profiling_armed = (
        (cfg.profile_steps and cfg.profile_steps.strip().lower()
         not in ("off", "none", "0"))
        or (cfg.profile_slow_zscore and cfg.profile_slow_zscore > 0)
    )
    if profiling_armed and cfg.telemetry is False:
        raise ValueError(
            "--profile_steps/--profile_slow_zscore ride the telemetry step "
            "hooks, which --no-telemetry disables: the requested captures "
            "could never engage. Drop --no-telemetry (or the profiling flags)."
        )
    if cfg.xla_preset:
        # Fail an unknown preset at launch, not after every worker compiled —
        # normalize_preset_name's error enumerates the valid names (the same
        # message install_xla_preset raises inside a worker).
        from ..utils.xla_flags import normalize_preset_name

        normalize_preset_name(cfg.xla_preset)
    if cfg.kernels:
        # Same discipline for the kernel spec: parse_kernel_spec's error
        # enumerates the valid backend tokens (the message the registry
        # would raise at first build inside a worker).
        from ..ops.registry import parse_kernel_spec

        if cfg.kernels.strip().lower() not in ("off", "none", "reference"):
            parse_kernel_spec(cfg.kernels)
    if cfg.max_restarts > 0 and cfg.num_machines > 1:
        raise ValueError(
            "--max_restarts only applies to single-machine jobs: on a pod, a "
            "per-host restart cannot re-rendezvous with live ranks from the "
            "old incarnation. Restart the WHOLE pod launch (e.g. via "
            "`accelerate-tpu tpu-config` or your scheduler) and resume with "
            "load_state."
        )
    if cfg.num_machines <= 1 and cfg.num_processes > 1:
        if not cfg.main_process_ip:
            cfg.main_process_ip = "127.0.0.1"
        rc = multi_process_launcher(args, cfg)
    else:
        rc = simple_launcher(args, cfg)
    if rc:
        raise SystemExit(rc)


def main() -> None:  # pragma: no cover - thin shim
    parser = launch_command_parser()
    launch_command(parser.parse_args())


if __name__ == "__main__":  # pragma: no cover
    main()
