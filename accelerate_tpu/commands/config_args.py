"""Config-file dataclasses + yaml round-trip.

Reference parity: ``src/accelerate/commands/config/config_args.py:44-252`` —
``BaseConfig``/``ClusterConfig`` persisted as yaml at
``~/.cache/huggingface/accelerate/default_config.yaml`` (:30-41). Same idea here
with TPU-pod fields: mesh axis sizes instead of fsdp/deepspeed plugin blobs, and
a JAX coordinator address instead of MASTER_ADDR/PORT.
"""

from __future__ import annotations

import json
import os
from dataclasses import asdict, dataclass, field

from ..utils.constants import DEFAULT_CONFIG_FILE, DEFAULT_CONFIG_FOLDER

try:
    import yaml

    _HAS_YAML = True
except Exception:  # pragma: no cover - yaml ships with the image
    _HAS_YAML = False

cache_home = os.environ.get(
    "ACCELERATE_TPU_HOME",
    os.path.join(os.environ.get("XDG_CACHE_HOME", os.path.join(os.path.expanduser("~"), ".cache")), DEFAULT_CONFIG_FOLDER),
)
default_config_file = os.path.join(cache_home, DEFAULT_CONFIG_FILE)


def load_config_from_file(config_file: str | None):
    """Load a ClusterConfig from yaml/json (reference ``config_args.py:44-75``)."""
    path = config_file if config_file is not None else default_config_file
    if config_file is not None and not os.path.isfile(path):
        raise FileNotFoundError(
            f"The passed configuration file `{path}` does not exist. "
            "Run `accelerate-tpu config` to create one."
        )
    if not os.path.isfile(path):
        return None
    with open(path, encoding="utf-8") as f:
        if path.endswith(".json"):
            data = json.load(f)
        else:
            if not _HAS_YAML:
                raise ImportError("pyyaml is required to read yaml config files")
            data = yaml.safe_load(f)
    if data is None:
        return None
    known = {f_.name for f_ in ClusterConfig.__dataclass_fields__.values()}
    extras = {k: v for k, v in data.items() if k not in known}
    kept = {k: v for k, v in data.items() if k in known}
    cfg = ClusterConfig(**kept)
    cfg.extra = extras
    return cfg


@dataclass
class ClusterConfig:
    """One host-cluster launch configuration (reference ``ClusterConfig`` :116-252)."""

    compute_environment: str = "LOCAL_MACHINE"
    distributed_type: str = "JAX_TPU"  # JAX_TPU | MULTI_CPU | NO
    num_machines: int = 1
    machine_rank: int = 0
    num_processes: int = 1  # processes per launch on this machine (CPU sim) or total hosts
    main_process_ip: str | None = None
    main_process_port: int | None = None
    mixed_precision: str = "no"  # no | bf16 | fp16 | fp8
    use_cpu: bool = False
    debug: bool = False
    # Mesh axis sizes; 0/1 = unused axis. The launcher exports these as
    # ACCELERATE_MESH_SHAPE for AcceleratorState to build the default mesh.
    dp_size: int = 0  # 0 → infer (fill remaining devices)
    fsdp_size: int = 1
    tp_size: int = 1
    pp_size: int = 1
    sp_size: int = 1
    ep_size: int = 1
    dcn_size: int = 0  # multi-slice count (0 → auto-detect slices)
    max_restarts: int = 0  # full-gang relaunch attempts after failure
    # Host-side virtual device count for CPU simulation (xla_force_host_platform_device_count)
    cpu_virtual_devices: int = 0
    downcast_bf16: bool = False
    # Per-feature sections (the guided wizard; reference cluster.py:57 flow).
    gradient_accumulation_steps: int = 1
    fsdp_min_shard_size: int = 0  # 0 = plugin default (2**14)
    fsdp_cpu_offload: bool = False
    pp_schedule: str = ""  # '' = gpipe default; or 'gpipe'/'1f1b'
    pp_microbatches: int = 0  # 0 = one per stage
    project_dir: str | None = None  # checkpoints/logs root
    checkpoint_total_limit: int = 0  # 0 = keep all
    checkpoint_auto_naming: bool = False
    log_with: str = ""  # comma-separated tracker names ('' = none)
    # Persistent XLA compilation cache directory ('' = disabled). Exported as
    # ACCELERATE_COMPILE_CACHE_DIR so restarted jobs load compiled programs
    # instead of re-paying minutes of XLA compiles per process start.
    compile_cache_dir: str = ""
    # Resilience (resilience/): install the SIGTERM/SIGINT preemption watcher
    # at startup (ACCELERATE_HANDLE_PREEMPTION), and an optional deterministic
    # fault-injection plan for drills/CI (ACCELERATE_FAULT_PLAN, e.g.
    # "step:37=kill;step:80=partial_ckpt").
    handle_preemption: bool = False
    fault_plan: str = ""
    # Elastic world-size training (resilience/elastic.py): TRI-state —
    # None = not configured (nothing exported; run_resilient defaults off),
    # an explicit True/False reaches workers as ACCELERATE_ELASTIC=1/0.
    # ``min_data_parallel`` floors the dp degree a shrink may re-form at
    # (0 = unspecified, library default 1; ACCELERATE_MIN_DATA_PARALLEL).
    elastic: bool | None = None
    min_data_parallel: int = 0
    # Training-health guards (health/): numerics sentinel + spike detector
    # driven by Accelerator.guard_step, and the hang watchdog's heartbeat
    # deadline (ACCELERATE_HANG_TIMEOUT; 0.0 = disabled). The first two are
    # TRI-state: None = not configured (nothing exported; guard_step's own
    # defaults apply — sentinel on, z=6.0), True/False and a float (0 =
    # detector off) are explicit answers that must reach the workers.
    guard_numerics: bool | None = None
    spike_zscore: float | None = None
    hang_timeout: float = 0.0
    # Telemetry (telemetry/): the always-on step timeline/span/metrics stack.
    # ``telemetry`` is TRI-state like the health knobs (None = not configured,
    # nothing exported, library default ON; an explicit False must reach the
    # workers as ACCELERATE_TELEMETRY=0). ``metrics_port`` > 0 starts the
    # Prometheus endpoint on every worker (ACCELERATE_METRICS_PORT);
    # ``straggler_threshold`` tunes the cross-host slowness ratio that raises
    # an alert (0.0 = library default 1.5; ACCELERATE_STRAGGLER_THRESHOLD).
    telemetry: bool | None = None
    metrics_port: int = 0
    straggler_threshold: float = 0.0
    # Fleet observability plane (telemetry/fleet.py / slo.py;
    # docs/observability.md "Fleet aggregation" / "SLO sentinel").
    # ``fleet_metrics`` is TRI-state like telemetry (None = unspecified, an
    # explicit False reaches workers as ACCELERATE_FLEET_METRICS=0); the SLO
    # targets are TRI-state floats per the profile_slow_zscore precedent
    # (None = unspecified, inherited env flows; an explicit 0 scrubs it and
    # disables the dimension; > 0 exported in seconds).
    fleet_metrics: bool | None = None
    slo_step_time: float | None = None
    slo_ttft: float | None = None
    slo_tpot: float | None = None
    # Disaggregated serving (serving_net/; docs/serving.md "Disaggregated
    # serving"): ``serving_role`` names the tier the launched workers join
    # (unified | prefill | decode | router). TRI-state per the xla_preset
    # precedent — None = unspecified (an inherited ACCELERATE_SERVING_ROLE
    # flows through), an explicit 'unified' scrubs a stale inherited role.
    # ``router_endpoint`` is the router tier's host:port
    # (ACCELERATE_ROUTER_ENDPOINT; None = unspecified, '' scrubs).
    serving_role: str | None = None
    router_endpoint: str | None = None
    # Serving fault tolerance (serving_net/lease.py; docs/serving.md
    # "Failure semantics"): router retry budget per request, worker lease
    # TTL seconds, and SIGTERM drain grace seconds. TRI-state floats per the
    # SLO precedent — None = unspecified (inherited env flows), an explicit
    # 0 scrubs a stale inherited value back to the library default.
    serving_retry_budget: float | None = None
    serving_lease_ttl: float | None = None
    drain_grace_s: float | None = None
    # Serving decode-speed levers (serving.py; docs/serving.md "Speculative
    # decoding" / "Quantized KV cache"). ``speculative_k`` is TRI-state per
    # the tune_budget precedent (None = unspecified, > 0 exported as
    # ACCELERATE_SPECULATIVE_K, an explicit 0 scrubs — speculation off);
    # ``draft_model`` names the LlamaConfig preset the engine builds the
    # draft from (None = unspecified, '' scrubs; ACCELERATE_DRAFT_MODEL);
    # ``kv_quant`` is the pool storage dtype ('int8'; None = unspecified,
    # an explicit 'off'/'none' scrubs; ACCELERATE_KV_QUANT).
    speculative_k: int | None = None
    draft_model: str | None = None
    kv_quant: str | None = None
    # Durable telemetry journal (telemetry/journal.py; docs/observability.md
    # "Telemetry journal & fleet timeline"). ``journal_dir`` is TRI-state per
    # the router_endpoint precedent: None = unspecified (inherited
    # ACCELERATE_JOURNAL_DIR flows), a path arms per-rank journaling, an
    # explicit '' scrubs a stale inherited directory. The ring capacities
    # are TRI-state ints per the tune_budget precedent: None = unspecified,
    # > 0 exported (ACCELERATE_TRACE_RING / ACCELERATE_FLIGHT_RING), an
    # explicit 0 scrubs back to the library defaults (1024 / 2048).
    journal_dir: str | None = None
    trace_ring: int | None = None
    flight_ring: int | None = None
    # Dispatch amortization (docs/performance.md): ``train_window`` is the K
    # Accelerator.build_train_window fuses per dispatch (tri-state like
    # ``telemetry``: None = unspecified, an inherited ACCELERATE_TRAIN_WINDOW
    # flows through; an EXPLICIT 1 = per-step dispatch, scrubbed from the
    # worker env; > 1 exported as ACCELERATE_TRAIN_WINDOW); ``xla_preset``
    # names the curated latency-hiding LIBTPU_INIT_ARGS preset installed at
    # PartialState init before backend creation ('' = unspecified, 'off' =
    # explicitly none; utils/xla_flags.py: latency | collective_matmul).
    train_window: int | None = None
    xla_preset: str = ""
    # Cross-replica (ZeRO-style) optimizer-state + weight-update sharding on
    # the dp axis (tri-state like telemetry/elastic: None = unspecified, an
    # inherited ACCELERATE_ZERO_SHARDING flows; an explicit False reaches the
    # workers as a disable).
    zero_sharding: bool | None = None
    # Pallas kernel layer (ops/registry.py; docs/kernels.md): the per-op
    # backend spec exported as ACCELERATE_KERNELS. TRI-state per the
    # xla_preset precedent — None = unspecified (an inherited env flows
    # through), 'pallas'/'interpret'/a per-op map = explicit spec, an
    # explicit 'off' scrubs a stale inherited value (reference lowerings).
    kernels: str | None = None
    # Profiling (telemetry/profiler.py; docs/observability.md "Profiling"):
    # TRI-state per the telemetry precedent. ``profile_steps`` is the
    # explicit trace-capture range grammar ("10-12,50"; None = unspecified,
    # an inherited ACCELERATE_PROFILE_STEPS flows through; an explicit
    # ''/'off' scrubs it); ``profile_slow_zscore`` arms the slow-step
    # capture trigger (None = unspecified; an explicit 0 reaches the
    # workers as a disable; ACCELERATE_PROFILE_SLOW_ZSCORE).
    profile_steps: str | None = None
    profile_slow_zscore: float | None = None
    # Profile-guided autotuner (tune/; docs/tuning.md): the short-bench trial
    # budget one `accelerate-tpu tune` run may spend. TRI-state per the
    # train_window precedent — None = unspecified (nothing exported, an
    # inherited ACCELERATE_TUNE_BUDGET flows through), > 0 exported, an
    # EXPLICIT 0 = "library default" and scrubs a stale inherited value.
    tune_budget: int | None = None
    extra: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        d = asdict(self)
        extras = d.pop("extra", {}) or {}
        d.update(extras)
        return {k: v for k, v in d.items() if v is not None}

    def to_yaml_file(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            if _HAS_YAML:
                yaml.safe_dump(self.to_dict(), f, sort_keys=True)
            else:  # pragma: no cover
                json.dump(self.to_dict(), f, indent=2)

    def to_json_file(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_dict(), f, indent=2)

    def mesh_shape_env(self) -> str:
        """Serialize mesh axes for ACCELERATE_MESH_SHAPE (`axis:size,...`)."""
        from ..utils.constants import MESH_AXIS_ORDER

        axes = []
        for name in MESH_AXIS_ORDER:
            size = getattr(self, f"{name}_size")
            axes.append(f"{name}:{size}")
        return ",".join(axes)
