"""`accelerate-tpu report` — run-over-run regression reports from journals.

Every journaled run finalizes with a ``run_summary`` record (step-time
quantiles, MFU, goodput fraction, TTFT/TPOT, breach/retry/restart counts,
fingerprint hash — telemetry/journal.py:finalize_run). This command
extracts it (``--journal`` accepts a journal directory or a summary JSON a
previous ``--out`` wrote), optionally compares against a previous run
(``--compare``) with deltas classified regression / improvement / benign
(the analysis/fingerprint.py classify_drift idiom), and exits 1 when any
field regressed — the CI gate shape.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from ..utils.constants import ENV_JOURNAL_DIR


def report_command_parser(subparsers=None) -> argparse.ArgumentParser:
    description = "Summarize a journaled run; compare against a previous one"
    if subparsers is not None:
        parser = subparsers.add_parser("report", description=description)
    else:
        parser = argparse.ArgumentParser("accelerate-tpu report", description=description)
    parser.add_argument(
        "--journal", default=None,
        help="Journal directory (or a summary JSON from a previous --out); "
             f"default: ${ENV_JOURNAL_DIR}",
    )
    parser.add_argument(
        "--compare", default=None,
        help="Previous run to diff against (journal directory or summary JSON); "
             "exits 1 if any field regressed",
    )
    parser.add_argument(
        "--tolerance", type=float, default=0.1,
        help="Relative slack before a metric delta counts as a "
             "regression/improvement (default: 0.10)",
    )
    parser.add_argument(
        "--out", default=None,
        help="Write the current run's summary JSON here (feed to a later --compare)",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="Machine-readable output on stdout",
    )
    if subparsers is not None:
        parser.set_defaults(func=report_command)
    return parser


_SUMMARY_ORDER = (
    "steps", "wall_s", "step_p50", "step_p90", "step_mean", "step_max",
    "tokens_per_s", "mfu", "loss", "goodput_fraction", "restarts",
    "ttft_mean", "ttft_max", "ttft_count", "tpot_mean", "tpot_max",
    "spec_proposed_tokens", "spec_accepted_tokens", "spec_acceptance_rate",
    "accepted_tokens_per_s",
    "breaches", "retries", "evictions", "fingerprint",
)


def _print_summary(summary: dict) -> None:
    print("run summary:")
    for field in _SUMMARY_ORDER:
        value = summary.get(field)
        if value is None:
            continue
        if isinstance(value, float):
            value = f"{value:.6g}"
        print(f"  {field:<18} {value}")


def report_command(args) -> None:
    from ..telemetry.collect import compare_runs, load_summary

    source = args.journal or os.environ.get(ENV_JOURNAL_DIR, "").strip()
    if not source:
        raise SystemExit(
            f"report: no journal source — pass --journal or set {ENV_JOURNAL_DIR}"
        )
    summary = load_summary(source)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            json.dump(summary, fh, indent=1)

    rows: list[dict] = []
    if args.compare:
        rows = compare_runs(load_summary(args.compare), summary,
                            tolerance=args.tolerance)
    regressions = [r for r in rows if r["kind"] == "regression"]

    if args.as_json:
        print(json.dumps({"summary": summary, "comparison": rows,
                          "regressions": len(regressions)}, indent=1))
    else:
        _print_summary(summary)
        if args.compare:
            print(f"comparison vs {args.compare} (tolerance ±{args.tolerance:.0%}):")
            for row in rows:
                marker = {"regression": "!", "improvement": "+",
                          "note": "*"}.get(row["kind"], " ")
                print(f"  {marker} {row['field']:<18} {row['kind']:<12} {row['detail']}")
            if regressions:
                fields = ", ".join(r["field"] for r in regressions)
                print(f"REGRESSION: {fields}", file=sys.stderr)
            else:
                print("no regressions")
    if regressions:
        raise SystemExit(1)


def main() -> None:  # pragma: no cover - thin shim
    parser = report_command_parser()
    args = parser.parse_args()
    report_command(args)


if __name__ == "__main__":  # pragma: no cover
    main()
