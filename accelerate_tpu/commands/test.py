"""`accelerate-tpu test` — run the bundled smoke script through the launcher.

Reference parity: ``src/accelerate/commands/test.py:84-95`` runs
``test_utils/scripts/test_script.py`` via `accelerate launch` so users can verify
their install + config end-to-end.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys


def test_command_parser(subparsers=None) -> argparse.ArgumentParser:
    description = "Run accelerate-tpu's install/config smoke test"
    if subparsers is not None:
        parser = subparsers.add_parser("test", description=description)
    else:
        parser = argparse.ArgumentParser("accelerate-tpu test", description=description)
    parser.add_argument("--config_file", default=None, help="Config file to test with")
    if subparsers is not None:
        parser.set_defaults(func=test_command)
    return parser


def test_command(args) -> None:
    import accelerate_tpu.test_utils as test_utils

    script = os.path.join(os.path.dirname(test_utils.__file__), "test_script.py")
    cmd = [sys.executable, "-m", "accelerate_tpu.commands.launch"]
    if args.config_file is not None:
        cmd += ["--config_file", args.config_file]
    cmd.append(script)
    result = subprocess.run(cmd)
    if result.returncode == 0:
        print("Test is a success! You are ready for your distributed training!")
    else:
        raise SystemExit(result.returncode)


def main() -> None:  # pragma: no cover
    parser = test_command_parser()
    test_command(parser.parse_args())


if __name__ == "__main__":  # pragma: no cover
    main()
