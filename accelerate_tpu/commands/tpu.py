"""``accelerate-tpu tpu-config`` — fan a command out to every pod host.

Reference parity: ``src/accelerate/commands/tpu.py:29-152`` (gcloud tpu-vm ssh
--worker=all). TPU-first extension: pods not managed through gcloud (bare-metal
SSH lists, k8s jump hosts) are covered by ``--pod_hosts host1,host2,...`` which
fans the same command over plain ``ssh``. ``--debug`` prints the exact
command(s) without executing — the testable dry-run mode.

Config-file defaults come from the ``accelerate-tpu config`` yaml: keys
``tpu_name``, ``tpu_zone``, ``pod_hosts``, ``commands``, ``command_file`` are
read from the file's extra fields.
"""

from __future__ import annotations

import argparse
import os
import subprocess

from .config_args import default_config_file, load_config_from_file

_description = "Run commands on a TPU pod (every worker at once)."


def tpu_command_parser(subparsers=None):
    if subparsers is not None:
        parser = subparsers.add_parser("tpu-config", description=_description)
    else:
        parser = argparse.ArgumentParser("accelerate-tpu tpu-config", description=_description)
    config_args = parser.add_argument_group(
        "Config Arguments", "Arguments that can be configured through `accelerate-tpu config`."
    )
    config_args.add_argument("--config_file", type=str, default=None, help="Config yaml to read defaults from.")
    config_args.add_argument("--tpu_name", default=None, help="Name of the (gcloud) TPU to use.")
    config_args.add_argument("--tpu_zone", default=None, help="GCE zone of the TPU.")
    config_args.add_argument(
        "--pod_hosts", default=None,
        help="Comma-separated SSH targets; fan out over plain ssh instead of gcloud.",
    )
    pod_args = parser.add_argument_group("TPU Arguments", "Options run inside the pod.")
    pod_args.add_argument(
        "--use_alpha", action="store_true", help="Use `gcloud alpha` instead of `gcloud`."
    )
    pod_args.add_argument(
        "--command_file", default=None, help="File with commands to run on each worker (one per line)."
    )
    pod_args.add_argument(
        "--command", action="append", nargs="+", help="A command to run; repeatable."
    )
    pod_args.add_argument(
        "--install_accelerate", action="store_true",
        help="Prepend a pip install of this framework on each worker.",
    )
    pod_args.add_argument(
        "--accelerate_version", default="latest",
        help='Version to install ("latest", "dev", or a pin like "==0.1.0").',
    )
    pod_args.add_argument(
        "--debug", action="store_true", help="Print the command instead of running it."
    )
    if subparsers is not None:
        parser.set_defaults(func=tpu_command_launcher)
    return parser


def _flatten_commands(command_arg) -> list[str]:
    """argparse `append`+`nargs='+'` yields [[...], ...]; join each group."""
    commands = []
    for group in command_arg or []:
        commands.append(" ".join(group) if isinstance(group, (list, tuple)) else str(group))
    return commands


def tpu_command_launcher(args):
    defaults = None
    if args.config_file is not None or os.path.isfile(default_config_file):
        defaults = load_config_from_file(args.config_file)
    if defaults is not None:
        extra = defaults.extra or {}
        if not args.command_file and not args.command and extra.get("command_file"):
            args.command_file = extra["command_file"]
        if not args.command and extra.get("commands"):
            args.command = [[c] if isinstance(c, str) else c for c in extra["commands"]]
        if not args.tpu_name:
            args.tpu_name = extra.get("tpu_name")
        if not args.tpu_zone:
            args.tpu_zone = extra.get("tpu_zone")
        if not args.pod_hosts and extra.get("pod_hosts"):
            hosts = extra["pod_hosts"]
            args.pod_hosts = ",".join(hosts) if isinstance(hosts, (list, tuple)) else hosts

    commands = _flatten_commands(args.command)
    if args.command_file:
        with open(args.command_file) as f:
            commands = [line.strip() for line in f if line.strip()] + commands
    if args.install_accelerate:
        if args.accelerate_version == "dev":
            install = "pip install git+https://github.com/accelerate-tpu/accelerate-tpu"
        elif args.accelerate_version == "latest":
            install = "pip install -U accelerate-tpu"
        else:
            version = args.accelerate_version.strip()
            if version and version[0] not in "=<>!~":
                version = f"=={version}"  # bare "0.1.0" → "==0.1.0"
            install = f"pip install accelerate-tpu{version}"
        commands = [install] + commands
    if not commands:
        raise ValueError(
            "No commands given: pass --command, --command_file, or configure "
            "`commands` via `accelerate-tpu config`."
        )
    joined = "; ".join(commands)

    if args.pod_hosts:
        hosts = [h.strip() for h in str(args.pod_hosts).split(",") if h.strip()]
        cmds = [["ssh", host, joined] for host in hosts]
        label = f"{len(hosts)} pod hosts"
    else:
        if not args.tpu_name or not args.tpu_zone:
            raise ValueError(
                "tpu-config needs --tpu_name and --tpu_zone (or --pod_hosts / "
                "config-file defaults)."
            )
        gcloud = ["gcloud", "alpha"] if args.use_alpha else ["gcloud"]
        cmds = [
            gcloud
            + [
                "compute", "tpus", "tpu-vm", "ssh", args.tpu_name,
                "--zone", args.tpu_zone,
                "--command", joined,
                "--worker", "all",
            ]
        ]
        label = f"TPU {args.tpu_name}"

    if args.debug:
        for cmd in cmds:
            print(f"Running {' '.join(cmd)}")
        return
    procs = [subprocess.Popen(cmd) for cmd in cmds]  # all workers in parallel
    failures = [p.wait() for p in procs]
    if any(failures):
        raise RuntimeError(f"tpu-config: {sum(1 for f in failures if f)} host command(s) failed")
    print(f"Successfully ran commands on {label}.")


def main():
    parser = tpu_command_parser()
    args = parser.parse_args()
    tpu_command_launcher(args)


if __name__ == "__main__":
    main()
