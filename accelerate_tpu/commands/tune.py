"""`accelerate-tpu tune` — the profile-guided autotuner CLI.

Lowers a candidate grid over the framework's perf levers (train window × XLA
preset × vocab chunk × remat policy × ZeRO sharding × prefetch), statically
prunes predicted-OOM / invariant-violating candidates via the HBM and program
auditors WITHOUT launching them, short-benches the survivors with trace
capture armed, lets the attribution report steer a successive-halving search,
and emits a ranked evidence report plus a ready-to-use winner ClusterConfig
(docs/tuning.md). Trial wall-clock books as the goodput ledger's ``tune``
badput class; ``bench.py`` replays the winner via ``BENCH_FROM_TUNE``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _csv_ints(raw: str) -> tuple:
    return tuple(int(v.strip()) for v in raw.split(",") if v.strip())


def _csv_strs(raw: str) -> tuple:
    # An explicit empty entry selects the model default (e.g. --remats ",x").
    return tuple(v.strip() for v in raw.split(","))


def tune_command_parser(subparsers=None) -> argparse.ArgumentParser:
    description = (
        "Profile-guided autotuner: statically prune a candidate config grid "
        "(HBM + program audits, no launches), short-bench the survivors with "
        "trace capture armed, steer by the attribution report, and emit a "
        "ranked evidence report + winner ClusterConfig"
    )
    if subparsers is not None:
        parser = subparsers.add_parser("tune", description=description)
    else:
        parser = argparse.ArgumentParser("accelerate-tpu tune", description=description)
    parser.add_argument(
        "--budget", type=int, default=None,
        help="Max short-bench trials (default: ACCELERATE_TUNE_BUDGET, then "
             "16). Static prunes are free — only measured trials spend it.",
    )
    parser.add_argument(
        "--trial_steps", type=int, default=None,
        help="Measured steps per rung-0 trial (default 8); later rungs double "
             "it (successive halving).",
    )
    parser.add_argument(
        "--warmup", type=int, default=2, help="Warmup steps per trial (default 2)",
    )
    parser.add_argument(
        "--rounds", type=int, default=4,
        help="Max search rounds (rungs) before reporting (default 4)",
    )
    parser.add_argument(
        "--batch", type=int, default=8, help="Batch rows for the trial fixture"
    )
    parser.add_argument(
        "--seq", type=int, default=16, help="Sequence length for the trial fixture"
    )
    parser.add_argument(
        "--optimizer", choices=("adamw", "sgd", "adafactor"), default="adamw",
        help="Optimizer whose state the candidates carry (default adamw — the "
             "2-moments-per-param case the ZeRO/memory levers target)",
    )
    parser.add_argument(
        "--budget-gib", type=float, default=None,
        help="Per-device HBM budget for the static prune's OOM verdict (GiB); "
             "default is the chip generation's HBM x the 90%% headroom "
             "contract — memcheck's budget.",
    )
    parser.add_argument(
        "--cpu_virtual_devices", type=int, default=0,
        help="Pin an N-device virtual CPU mesh before building (the memcheck "
             "flag's analog): dp levers — ZeRO, replication verdicts — are "
             "vacuous on a 1-device backend.",
    )
    parser.add_argument(
        "--windows", type=_csv_ints, default=None,
        help="Comma-separated train-window axis (default 1,2,4,8)",
    )
    parser.add_argument(
        "--presets", type=_csv_strs, default=None,
        help="Comma-separated xla-preset axis (default off,latency,"
             "collective_matmul). NOTE: presets are backend-init env flags — "
             "in one tune process they are recorded as recommendations "
             "(preset_applied=false once the backend is live), not A/B-measured.",
    )
    parser.add_argument(
        "--chunks", type=_csv_ints, default=None,
        help="Comma-separated fused-loss vocab-chunk axis, 0 = model default "
             "head (default 0). Order = toward less live-logits memory.",
    )
    parser.add_argument(
        "--remats", type=_csv_strs, default=None,
        help="Comma-separated remat-policy axis; empty entry = model default "
             "(default ''). Order = toward more rematerialization.",
    )
    parser.add_argument(
        "--prefetches", type=_csv_ints, default=None,
        help="Comma-separated device-batch prefetch axis (default 0,2)",
    )
    parser.add_argument(
        "--no-zero", action="store_true",
        help="Exclude ZeRO cross-replica sharding from the space",
    )
    parser.add_argument(
        "--no-capture", action="store_true",
        help="Skip per-trial trace capture (the search then steers by the "
             "memory verdict and step time only — attribution fractions are "
             "absent from the evidence)",
    )
    parser.add_argument(
        "--profile-dir", default=None,
        help="Root for per-trial trace captures (default: "
             "$TMPDIR/accelerate_tune_traces)",
    )
    parser.add_argument(
        "--config_file", default=None,
        help="ClusterConfig yaml to seed the base candidate from (and the "
             "winner config inherits everything else from it)",
    )
    parser.add_argument(
        "--output", default="tune_report.json",
        help="Where to write the ranked evidence report JSON",
    )
    parser.add_argument(
        "--winner-config", default="tune_winner.yaml",
        help="Where to write the winner's ready-to-use ClusterConfig yaml",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="Print the full report JSON on stdout instead of the summary table",
    )
    if subparsers is not None:
        parser.set_defaults(func=tune_command)
    return parser


def _resolve_budget(flag_value) -> int:
    from ..tune.space import DEFAULT_TUNE_BUDGET
    from ..utils.constants import ENV_TUNE_BUDGET

    if flag_value is not None:
        return int(flag_value)
    raw = os.environ.get(ENV_TUNE_BUDGET, "").strip()
    if raw:
        value = int(raw)
        if value > 0:
            return value
    return DEFAULT_TUNE_BUDGET


def tune_command(args) -> None:
    budget = _resolve_budget(args.budget)
    if budget < 1:
        raise SystemExit("--budget must be >= 1")
    if getattr(args, "cpu_virtual_devices", 0):
        if args.cpu_virtual_devices < 1:
            raise SystemExit("--cpu_virtual_devices must be >= 1")
        from ..utils.environment import pin_cpu_platform

        # Must precede the first backend touch (the rig's Accelerator()).
        pin_cpu_platform(args.cpu_virtual_devices)

    from ..tune.prune import static_prune
    from ..tune.report import (
        build_report,
        format_summary,
        write_report,
        write_winner_yaml,
    )
    from ..tune.search import run_search
    from ..tune.space import CandidateSpace
    from ..tune.trials import DEFAULT_MEASURED_STEPS, TrialRig
    from .config_args import load_config_from_file

    base_cfg = None
    if args.config_file is not None:
        base_cfg = load_config_from_file(args.config_file)
    overrides = {}
    if args.windows is not None:
        overrides["windows"] = args.windows
    if args.presets is not None:
        overrides["presets"] = args.presets
    if args.chunks is not None:
        overrides["vocab_chunks"] = args.chunks
    if args.remats is not None:
        overrides["remat_policies"] = args.remats
    if args.prefetches is not None:
        overrides["prefetches"] = args.prefetches
    if args.no_zero:
        overrides["zero_sharding"] = (False,)
    space = CandidateSpace.from_cluster_config(base_cfg, **overrides)

    rig = TrialRig(
        batch_rows=args.batch,
        seq=args.seq,
        optimizer=args.optimizer,
        budget_bytes=(
            int(args.budget_gib * (1 << 30)) if args.budget_gib is not None else None
        ),
        profile_dir=args.profile_dir,
    )

    def prune_fn(candidates):
        return static_prune(candidates, rig.audit_candidate)

    def trial_fn(candidate, evidence, steps):
        try:
            result = rig.run_trial(
                candidate,
                evidence=evidence,
                measured_steps=steps,
                warmup_steps=args.warmup,
                capture=not args.no_capture,
            )
        except Exception as exc:
            print(
                f"tune: trial {candidate.key()} failed "
                f"({type(exc).__name__}: {exc}); skipping",
                file=sys.stderr,
            )
            return None
        return result.to_dict()

    ranked, dropped, trail = run_search(
        space,
        prune_fn=prune_fn,
        trial_fn=trial_fn,
        trial_budget=budget,
        base_steps=args.trial_steps or DEFAULT_MEASURED_STEPS,
        max_rounds=args.rounds,
    )
    trials_run = sum(len(r["trialed"]) + len(r["failed"]) for r in trail)

    backend = device = None
    try:
        import jax

        backend = jax.default_backend()
        # Report metadata only (a capacity/telemetry-style reader, like the
        # baselined ones): which chip generation produced these numbers.
        device = str(jax.devices()[0].device_kind)  # accelerate-lint: disable=raw-device-baseline
    except Exception:
        pass
    report = build_report(
        ranked=ranked,
        dropped=dropped,
        trail=trail,
        space=space,
        trial_budget=budget,
        trials_run=trials_run,
        backend=backend,
        device=device,
    )
    if args.output:
        write_report(args.output, report)
    if report["winner"] is None:
        print(json.dumps(report, indent=1) if args.json else format_summary(report))
        failed = sum(len(r["failed"]) for r in trail)
        if failed:
            diagnosis = (
                f"every short-bench trial failed ({failed} of {trials_run} "
                "spent; see the per-trial stderr above)"
            )
        else:
            diagnosis = (
                f"no candidate survived the static prune ({len(dropped)} "
                "dropped)"
            )
        print(
            f"tune: {diagnosis} — nothing to rank; see "
            f"{args.output or 'the report'}",
            file=sys.stderr,
        )
        raise SystemExit(1)
    if args.winner_config:
        write_winner_yaml(
            args.winner_config, report["winner"]["candidate"], base_cfg=base_cfg
        )
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(format_summary(report))
        if args.output:
            print(f"report: {args.output}")
        if args.winner_config:
            print(
                f"winner config: {args.winner_config} "
                "(launch --config_file it, or replay via BENCH_FROM_TUNE="
                f"{args.output})"
            )


def tune_main() -> None:
    """Console-script entry (`accelerate-tpu-tune`, pyproject [project.scripts])."""
    tune_command(tune_command_parser().parse_args())


if __name__ == "__main__":
    tune_main()
