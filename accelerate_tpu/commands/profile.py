"""`accelerate-tpu profile` / `accelerate-tpu blackbox` — the forensics CLI.

``profile report <dir>`` parses a captured XLA trace (a capture directory
written by the ProfileManager / ``jax.profiler``, the
``plugins/profile/<ts>`` directory itself, or a ``*.trace.json.gz`` file)
into the per-step attribution report: device compute vs collectives (joined
to named mesh axes when an audit inventory is supplied) vs idle vs
host/infeed, the measured compute↔collective overlap fraction, and the top-N
op table. Pure post-processing — no backend, no devices touched.

``blackbox <dump.json>`` renders a flight-recorder dump
(telemetry/flight.py — written on hang / guard trip / restart / crash) as a
causal timeline: the last thing the run was doing, in order, with the
transfer/goodput context it was dumped with.
"""

from __future__ import annotations

import argparse
import json
import sys


# ------------------------------------------------------------------ profile
def profile_command_parser(subparsers=None) -> argparse.ArgumentParser:
    description = "Attribute a captured XLA trace: compute vs collectives vs idle vs host"
    if subparsers is not None:
        parser = subparsers.add_parser("profile", description=description)
    else:
        parser = argparse.ArgumentParser("accelerate-tpu profile", description=description)
    parser.add_argument(
        "action", choices=["report"],
        help="'report' parses a capture into the attribution schema",
    )
    parser.add_argument(
        "trace_dir",
        help="Capture directory (ProfileManager output / jax.profiler log_dir) "
             "or a *.trace.json.gz file",
    )
    parser.add_argument(
        "--audit", default=None,
        help="Program-audit JSON (accelerate-tpu audit output) whose collective "
             "inventory attributes measured collective time to named mesh axes",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="Machine-readable report on stdout (default: human summary + JSON)",
    )
    if subparsers is not None:
        parser.set_defaults(func=profile_command)
    return parser


def profile_command(args) -> None:
    from ..telemetry.traceview import collective_axes_from_audit, report_capture

    axes = None
    if args.audit:
        with open(args.audit) as fh:
            axes = collective_axes_from_audit(json.load(fh))
    report = report_capture(args.trace_dir, collective_axes=axes)
    if args.as_json:
        print(json.dumps(report, indent=1))
        return
    fractions = report["fractions"]
    print(f"trace: {report.get('trace_path', args.trace_dir)}")
    if report.get("n_steps"):
        print(f"steps analyzed: {report['n_steps']} "
              f"(window {report['wall_s'] * 1e3:.1f}ms)")
    else:
        print(f"window: {report['wall_s'] * 1e3:.1f}ms (no step annotations — "
              "whole-capture attribution)")
    print(
        "attribution: "
        f"compute {fractions['compute']:.1%} | "
        f"collective {fractions['collective']:.1%} (exposed) | "
        f"host/infeed {fractions['host']:.1%} | "
        f"idle {fractions['idle']:.1%}"
    )
    overlap = report.get("overlap_fraction")
    if overlap is not None:
        print(f"compute<->collective overlap: {overlap:.1%} of "
              f"{report['collective_s'] * 1e3:.2f}ms raw collective time")
    if report.get("by_axis"):
        per_axis = ", ".join(
            f"{axis}={seconds * 1e3:.2f}ms" for axis, seconds in report["by_axis"].items()
        )
        print(f"collective time by mesh axis: {per_axis}")
    if report.get("top_ops"):
        print("top ops:")
        for op in report["top_ops"]:
            print(
                f"  {op['total_s'] * 1e3:9.3f}ms x{op['count']:<4d} "
                f"[{op['kind']}] {op['name']}"
            )
    print(json.dumps(report, indent=1))


# ----------------------------------------------------------------- blackbox
def blackbox_command_parser(subparsers=None) -> argparse.ArgumentParser:
    description = "Render a flight-recorder dump as a causal timeline"
    if subparsers is not None:
        parser = subparsers.add_parser("blackbox", description=description)
    else:
        parser = argparse.ArgumentParser("accelerate-tpu blackbox", description=description)
    parser.add_argument(
        "dump",
        help="flight_*.json dump written by the flight recorder, or a "
             "directory of them (merged in time order with host labels)",
    )
    parser.add_argument(
        "--last", type=int, default=0,
        help="Only render the last N events (default: all retained)",
    )
    if subparsers is not None:
        parser.set_defaults(func=blackbox_command)
    return parser


def _event_detail(event: dict) -> str:
    skip = ("seq", "t_s", "wall", "kind", "step")
    parts = []
    for key, value in event.items():
        if key in skip or value is None:
            continue
        parts.append(f"{key}={value}")
    return " ".join(parts)


def _blackbox_directory(args) -> None:
    """Merge every flight dump in a directory into one fleet timeline,
    events interleaved by wall time and labelled with the dumping host."""
    import glob
    import os

    paths = sorted(glob.glob(os.path.join(args.dump, "flight_*.json")))
    if not paths:
        raise SystemExit(f"blackbox: no flight_*.json dumps in {args.dump!r}")
    merged = []
    for path in paths:
        try:
            with open(path) as fh:
                dump = json.load(fh)
        except (OSError, ValueError) as exc:
            print(f"blackbox: skipping unreadable dump {path}: {exc}")
            continue
        host = dump.get("process_index", "?")
        print(
            f"dump host {host}: reason={dump.get('reason')!r} "
            f"pid {dump.get('pid')} events {dump.get('events_retained')} "
            f"retained ({path})"
        )
        for event in dump.get("events", []):
            merged.append((host, event))
    if args.last > 0:
        merged.sort(key=lambda pair: pair[1].get("wall", 0))
        merged = merged[-args.last:]
    else:
        merged.sort(key=lambda pair: pair[1].get("wall", 0))
    wall_base = merged[0][1].get("wall", 0) if merged else 0
    print(f"merged timeline ({len(merged)} events; t is seconds since first event):")
    for host, event in merged:
        step = f" step={event['step']}" if "step" in event else ""
        detail = _event_detail(event)
        print(
            f"  t={event.get('wall', 0) - wall_base:>10.3f}  host={host!s:<4}"
            f"{event.get('kind', '?'):<20}{step}{'  ' + detail if detail else ''}"
        )


def blackbox_command(args) -> None:
    import os

    if os.path.isdir(args.dump):
        _blackbox_directory(args)
        return
    with open(args.dump) as fh:
        dump = json.load(fh)
    events = dump.get("events", [])
    if args.last > 0:
        events = events[-args.last:]
    import time as _time

    when = _time.strftime(
        "%Y-%m-%d %H:%M:%S", _time.localtime(dump.get("dumped_at", 0))
    )
    print(
        f"flight recorder dump: reason={dump.get('reason')!r} at {when} "
        f"(pid {dump.get('pid')}, process {dump.get('process_index')})"
    )
    print(
        f"events: {len(events)} shown / {dump.get('events_retained')} retained "
        f"/ {dump.get('events_total')} recorded"
    )
    transfers = dump.get("transfers")
    if transfers:
        print(
            f"transfers at dump: {transfers.get('fetches', 0)} fetches "
            f"({transfers.get('blocking', 0)} blocking), "
            f"{transfers.get('h2d_puts', 0)} uploads "
            f"({transfers.get('h2d_blocking', 0)} waits)"
        )
    goodput = dump.get("goodput")
    if goodput:
        print(
            f"goodput at dump: {goodput.get('goodput_fraction', 0):.1%} of "
            f"{goodput.get('wall_s', 0):.1f}s wall "
            f"({goodput.get('steps', 0)} steps, {goodput.get('restarts', 0)} restarts)"
        )
    # Serving/SLO forensics (telemetry/slo.py + requests.py): breaches and
    # per-request admission decisions land in the ring as first-class events;
    # summarize them up front so the slow-request story doesn't have to be
    # reassembled from the raw timeline below.
    breaches = [e for e in events if e.get("kind") == "slo_breach"]
    admissions = [e for e in events if e.get("kind") == "admission"]
    if breaches or admissions:
        per_target: dict = {}
        for e in breaches:
            per_target[e.get("target", "?")] = per_target.get(e.get("target", "?"), 0) + 1
        decisions: dict = {}
        for e in admissions:
            decisions[e.get("decision", "?")] = decisions.get(e.get("decision", "?"), 0) + 1
        breach_txt = " ".join(f"{k}={v}" for k, v in sorted(per_target.items())) or "none"
        decision_txt = " ".join(f"{k}={v}" for k, v in sorted(decisions.items())) or "none"
        print(f"slo breaches in window: {breach_txt}; admission decisions: {decision_txt}")
    print("timeline (t is seconds since recorder start):")
    for event in events:
        step = f" step={event['step']}" if "step" in event else ""
        detail = _event_detail(event)
        print(
            f"  t={event.get('t_s', 0):>10.3f}  {event.get('kind', '?'):<20}"
            f"{step}{'  ' + detail if detail else ''}"
        )
    spans = dump.get("spans")
    if spans:
        print(f"last spans ({len(spans)}):")
        for span in spans[-16:]:
            print(
                f"  {span['duration_s'] * 1e3:9.3f}ms "
                f"{'  ' * span.get('depth', 0)}{span.get('path', span.get('name'))}"
            )


def main() -> None:  # pragma: no cover - thin shim
    parser = argparse.ArgumentParser("accelerate-tpu-forensics")
    sub = parser.add_subparsers()
    profile_command_parser(subparsers=sub)
    blackbox_command_parser(subparsers=sub)
    args = parser.parse_args()
    if not hasattr(args, "func"):
        parser.print_help()
        sys.exit(1)
    args.func(args)


if __name__ == "__main__":  # pragma: no cover
    main()
