"""`accelerate-tpu env` — environment report (reference ``commands/env.py:131``)."""

from __future__ import annotations

import argparse
import os
import platform

from .. import __version__
from .config_args import default_config_file, load_config_from_file


def env_command_parser(subparsers=None) -> argparse.ArgumentParser:
    description = "Print the accelerate-tpu environment (for bug reports)"
    if subparsers is not None:
        parser = subparsers.add_parser("env", description=description)
    else:
        parser = argparse.ArgumentParser("accelerate-tpu env", description=description)
    parser.add_argument("--config_file", default=None, help="Config file to display")
    if subparsers is not None:
        parser.set_defaults(func=env_command)
    return parser


def env_command(args) -> None:
    import jax
    import jaxlib

    info = {
        "`accelerate_tpu` version": __version__,
        "Platform": platform.platform(),
        "Python version": platform.python_version(),
        "JAX version": jax.__version__,
        "jaxlib version": jaxlib.__version__,
        "JAX backend": jax.default_backend(),
        "Device count": jax.device_count(),
        "Local device count": jax.local_device_count(),
        "Process count": jax.process_count(),
    }
    try:
        import flax

        info["Flax version"] = flax.__version__
    except Exception:
        pass
    try:
        import optax

        info["Optax version"] = optax.__version__
    except Exception:
        pass
    accelerate_env = {k: v for k, v in os.environ.items() if k.startswith("ACCELERATE_")}

    print("\nCopy-and-paste the text below in your GitHub issue\n")
    print("\n".join(f"- {k}: {v}" for k, v in info.items()))
    if accelerate_env:
        print("- ACCELERATE_* environment:")
        print("\n".join(f"\t- {k}: {v}" for k, v in sorted(accelerate_env.items())))
    path = args.config_file or default_config_file
    cfg = load_config_from_file(args.config_file) if (args.config_file or os.path.isfile(path)) else None
    if cfg is not None:
        print(f"- `accelerate-tpu` config ({path}):")
        print("\n".join(f"\t- {k}: {v}" for k, v in cfg.to_dict().items()))
    else:
        print("- `accelerate-tpu` config: not found")


def main() -> None:  # pragma: no cover
    parser = env_command_parser()
    env_command(parser.parse_args())


if __name__ == "__main__":  # pragma: no cover
    main()
