"""Cursor-driven selection menu for the config wizard.

Reference parity: the reference drives its config questionnaire through a
cursor menu package (``src/accelerate/commands/menu/`` — selection menu +
keymap + cursor helpers, ~499 LoC). This is a from-scratch POSIX/ANSI
implementation of the same UX: arrow keys (or vi's j/k, or a digit) move a
highlight over the choices, Enter selects, and the menu redraws in place.
Non-TTY sessions (pipes, CI, the test suite's mocked stdin) never enter the
raw-terminal path — callers keep their plain ``input()`` prompts there, so
scripted configs and the existing wizard contract are untouched.
"""

from __future__ import annotations

import sys

_UP_KEYS = ("\x1b[A", "k")
_DOWN_KEYS = ("\x1b[B", "j")
_ENTER_KEYS = ("\r", "\n")
_INTERRUPT_KEYS = ("\x03",)  # Ctrl-C
_HOME_KEYS = ("\x1b[H",)
_END_KEYS = ("\x1b[F",)


def interactive_tty() -> bool:
    """True when both ends are real terminals AND raw mode is available."""
    try:
        import termios  # noqa: F401  (POSIX only)
        import tty  # noqa: F401
    except ImportError:
        return False
    try:
        return sys.stdin.isatty() and sys.stdout.isatty()
    except (AttributeError, ValueError):
        return False


def _read_key() -> str:
    """One keypress in raw mode; arrow keys return their full CSI sequence."""
    import termios
    import tty

    fd = sys.stdin.fileno()
    old = termios.tcgetattr(fd)
    try:
        tty.setraw(fd)
        ch = sys.stdin.read(1)
        if ch == "\x1b":
            nxt = sys.stdin.read(1)
            if nxt == "[":
                # CSI sequences end at a final byte in @..~ (0x40-0x7e);
                # parameterized forms (Shift+Down = \x1b[1;2B, PgUp =
                # \x1b[5~) carry parameter bytes first — consume the whole
                # sequence so leftovers can't replay as fake keypresses.
                seq = "\x1b["
                while True:
                    b = sys.stdin.read(1)
                    if not b:
                        return seq
                    seq += b
                    if "@" <= b <= "~":
                        return seq
            return ch
        return ch
    finally:
        termios.tcsetattr(fd, termios.TCSADRAIN, old)


def select(prompt: str, choices, default=None, *, read_key=None, out=None):
    """Arrow-key selection over ``choices``; returns the chosen element.

    ``read_key``/``out`` are injection points for tests (and must not be used
    to bypass the TTY check in production callers — use ``interactive_tty()``
    to decide whether to call this at all).
    """
    read_key = read_key or _read_key
    out = out or sys.stdout
    labels = [str(c) for c in choices]
    n = len(labels)
    if n == 0:
        raise ValueError("select() needs at least one choice")
    try:
        idx = list(choices).index(default) if default is not None else 0
    except ValueError:
        idx = 0

    out.write(f"{prompt} (↑/↓ or j/k, Enter to accept)\n")

    def render(first: bool = False):
        if not first:
            out.write(f"\x1b[{n}A")  # cursor up over the menu block
        for i, lab in enumerate(labels):
            cursor = "➤ " if i == idx else "  "
            style = ("\x1b[7m", "\x1b[0m") if i == idx else ("", "")
            out.write("\x1b[2K" + cursor + style[0] + lab + style[1] + "\n")
        out.flush()

    render(first=True)
    while True:
        key = read_key()
        if key in _UP_KEYS:
            idx = (idx - 1) % n
        elif key in _DOWN_KEYS:
            idx = (idx + 1) % n
        elif key in _HOME_KEYS:
            idx = 0
        elif key in _END_KEYS:
            idx = n - 1
        elif key.isdigit() and 1 <= int(key) <= n:
            idx = int(key) - 1
        elif key in _ENTER_KEYS:
            render()
            return list(choices)[idx]
        elif key in _INTERRUPT_KEYS:
            raise KeyboardInterrupt
        render()
