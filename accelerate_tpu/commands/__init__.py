"""Command-line interface — the L7 layer.

Reference parity: ``src/accelerate/commands/accelerate_cli.py:28-50`` registers
subcommands {config, env, launch, test, estimate-memory, merge-weights, tpu}.
Here the same verbs exist but the launcher speaks the JAX multi-host contract
(one process per host, ``jax.distributed.initialize`` rendezvous) instead of
torchrun/NCCL.
"""
