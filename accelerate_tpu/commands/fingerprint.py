"""`accelerate-tpu fingerprint` — the compiled-program drift gate.

Re-lowers the shipped builder matrix (train step / K-step window × ZeRO
sharding × fsdp/tp plans × the ContinuousBatcher decode window) on a pinned
virtual CPU mesh, extracts each program's canonical
:class:`~..analysis.fingerprint.ProgramFingerprint`, and diffs it against the
committed goldens under ``tests/goldens/``:

- ``--check`` (default): exit 1 when any config's drift classifies as a
  **violation** (new dp all-gather, host callback, narrowed/missed donation,
  grown replicated bytes, new low-precision accumulation, vanished ZeRO
  traffic) or a golden is missing. Benign-shape and improvement drifts
  report but pass — an improvement is a prompt to re-bank the golden.
- ``--update``: regenerate the goldens from HEAD — the deliberate-change
  path. Commit the diff; the golden diff IS the review surface for a
  program-contract change.
- ``--json``: one machine-readable verdict document (the audit/memcheck
  ``{verdict, failures, ...}`` shape) for CI and the autotuner.

Determinism contract: the command pins an N-virtual-device CPU mesh
(default 8 — the same rig tier-1 runs on) and scrubs the persistent compile
cache before the first backend touch, so donation is LIVE (the
``safe_donate_argnums`` CPU+cache policy would otherwise waive donor marks
and disarm the dropped-donation detector) and extraction is byte-identical
across processes and rigs. ``--keep-compile-cache`` opts out for in-process
callers that must not disturb a session cache.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# The shipped builder matrix. Tiny shapes keep the whole matrix' lower+compile
# under a minute on a CPU rig; the CONTRACT (collectives, donation, dtype
# flow, replication split) is shape-independent, so tiny pins it as well as
# large would.
_TRAIN_CONFIGS = {
    # name: (window, optimizer, zero_sharding, parallelism kwargs)
    "step": (1, "sgd", False, None),
    "step_zero": (1, "adamw", True, None),
    "window4": (4, "sgd", False, None),
    "window4_zero": (4, "adamw", True, None),
    "step_fsdp8": (1, "sgd", False, {"fsdp_size": 8}),
    "step_tp2_fsdp4": (1, "sgd", False, {"tp_size": 2, "fsdp_size": 4}),
    # Kernel-backed ZeRO step (ops/pallas/fused_update.py engaged via
    # ACCELERATE_KERNELS=interpret — the deterministic CPU-rig resolution of
    # the pallas token): its golden pins the fused-update pallas_call
    # inventory + the unchanged donation contract, so a silently vanished
    # kernel classifies as a violation.
    "step_zero_kernel": (1, "adamw", True, None),
}

# Configs extracted with the Pallas kernel layer pinned to interpret mode
# (byte-stable on the CPU fingerprint rig; the compiled-Mosaic program is a
# TPU-rig artifact the CPU goldens deliberately do not cover).
# `decode_paged_int8` pins the dequant-in-DMA gather inventory: a silently
# vanished dequant kernel classifies as a violation, not silence.
# `spec_verify` pins the speculative verify program — draft scan + one
# multi-token target forward + block-table truncation commit — whose
# donation contract (pool, draft pool, state) is the rejection-surgery seam.
_KERNEL_CONFIGS = ("step_zero_kernel", "decode_paged_kernel",
                   "decode_paged_int8", "spec_verify")

CONFIG_NAMES = tuple(_TRAIN_CONFIGS) + ("decode", "decode_paged",
                                        "decode_paged_kernel", "prefill_paged",
                                        "decode_paged_int8", "spec_verify")


def _reset_singletons():
    from ..state import AcceleratorState, GradientState

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()


def _tiny_config():
    from ..models import LlamaConfig

    return LlamaConfig.tiny(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_attention_heads=2, num_key_value_heads=2, num_hidden_layers=2,
    )


def _train_fingerprint(name: str):
    import numpy as np
    import jax
    import optax

    from ..accelerator import Accelerator
    from ..models import Llama

    window, optimizer, zero, parallelism = _TRAIN_CONFIGS[name]
    _reset_singletons()
    kwargs = {}
    if parallelism:
        from ..parallel.mesh import ParallelismConfig

        kwargs["parallelism_config"] = ParallelismConfig(**parallelism)
    accelerator = Accelerator(**kwargs)
    if zero:
        accelerator.zero_sharding = True
    cfg = _tiny_config()
    model = Llama(cfg)
    model.init_params(jax.random.key(0))
    tx = {
        "sgd": lambda: optax.sgd(0.1),
        "adamw": lambda: optax.adamw(3e-4),
    }[optimizer]()
    pmodel, popt = accelerator.prepare(model, tx)
    ids = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (8, 16)
    ).astype(np.int32)
    batch = {"input_ids": ids, "labels": ids}
    if window > 1:
        built = accelerator.build_train_window(pmodel, popt, window=window)
        batch = {k: np.stack([v] * window) for k, v in batch.items()}
    else:
        built = accelerator.build_train_step(pmodel, popt)
    try:
        return accelerator.fingerprint(built, batch, config=name)
    finally:
        _reset_singletons()


def _decode_fingerprint(name: str = "decode"):
    import jax

    from ..models import Llama, LlamaConfig
    from ..serving import ContinuousBatcher

    _reset_singletons()
    cfg = LlamaConfig.tiny(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_attention_heads=2, num_key_value_heads=2, num_hidden_layers=1,
    )
    model = Llama(cfg)
    model.init_params(jax.random.key(0))
    kwargs = {}
    if name in ("decode_paged", "decode_paged_kernel", "prefill_paged",
                "decode_paged_int8", "spec_verify"):
        # The paged decode window: its committed golden pins the block-table
        # gather inventory and the pool+state donation contract, so the
        # ROADMAP item 3 kernel swap (or any regression in the gather
        # lowering) classifies as deliberate drift, not silence. The
        # `_kernel` variant runs the Pallas chain-walk assembly
        # (op `paged_gather`) and pins its pallas_call inventory instead.
        kwargs = dict(paged=True, block_size=4)
    if name == "decode_paged_int8":
        # int8 KV pool: the golden pins the dequant-in-DMA gather kernel
        # (`paged_gather_dequant_kernel`) plus the per-block scale plumbing.
        kwargs["kv_quant"] = "int8"
    if name == "spec_verify":
        # Draft == target keeps the golden self-contained (no preset drift);
        # the program contract is draft-independent.
        kwargs.update(speculative_k=2, draft_model=model)
    engine = ContinuousBatcher(
        model, batch_slots=2, max_new_tokens=4, max_cache_len=64,
        bucket_sizes=(8,), sync_every=2, **kwargs,
    )
    try:
        if name == "prefill_paged":
            # The prefill-ONLY tier's program (serving_net disaggregation):
            # a prefill host never compiles the decode window, so its
            # contract — chunked prefill writing the paged pool through the
            # block table, first-token sampling — needs its own golden.
            return engine.fingerprint_prefill(config=name)
        if name == "spec_verify":
            return engine.fingerprint_verify(config=name)
        return engine.fingerprint_decode(config=name)
    finally:
        _reset_singletons()


def extract_config(name: str):
    """Build one matrix config and extract its fingerprint. The kernel layer
    is pinned SYMMETRICALLY for every config (restored after): kernel-backed
    configs build under ACCELERATE_KERNELS=interpret (the deterministic
    CPU-rig resolution, so their goldens carry a stable pallas_call
    inventory), and every other config builds with the env SCRUBBED — an
    inherited fleet-wide kernel spec must not leak kernel-backed programs
    into the reference goldens (an `--update` run under such an env would
    otherwise corrupt 8/10 goldens and fail every clean-env `--check`)."""
    from ..utils.constants import ENV_KERNELS

    prev = os.environ.get(ENV_KERNELS)
    if name in _KERNEL_CONFIGS:
        os.environ[ENV_KERNELS] = "interpret"
    else:
        os.environ.pop(ENV_KERNELS, None)
    try:
        if name in ("decode", "decode_paged", "decode_paged_kernel",
                    "prefill_paged", "decode_paged_int8", "spec_verify"):
            return _decode_fingerprint(name)
        if name not in _TRAIN_CONFIGS:
            raise SystemExit(
                f"unknown fingerprint config {name!r}; choose from "
                f"{', '.join(CONFIG_NAMES)}"
            )
        return _train_fingerprint(name)
    finally:
        if prev is None:
            os.environ.pop(ENV_KERNELS, None)
        else:
            os.environ[ENV_KERNELS] = prev


def run_fingerprints(configs, goldens_dir: str, update: bool = False):
    """Extract + compare (or rewrite) each config's golden.

    Returns ``(results, failures)``: ``results`` is ``{config: {hash,
    verdict, drift:[...]}}`` (verdict ``updated`` in update mode, else
    ``match`` / ``benign-shape`` / ``improvement`` / ``violation`` /
    ``missing-golden``); ``failures`` is the exit-1 list for check mode."""
    from ..analysis.fingerprint import (
        classify_drift,
        drift_verdict,
        fingerprint_hash,
        load_golden,
        write_golden,
    )

    results: dict = {}
    failures: list = []
    for name in configs:
        doc = extract_config(name).to_dict()
        digest = fingerprint_hash(doc)
        if update:
            path = write_golden(goldens_dir, doc)
            results[name] = {"hash": digest, "verdict": "updated", "golden": path,
                             "drift": []}
            continue
        golden = load_golden(goldens_dir, name)
        if golden is None:
            results[name] = {"hash": digest, "verdict": "missing-golden",
                             "drift": []}
            failures.append(
                f"{name}: no golden at {goldens_dir} — run "
                f"`accelerate-tpu fingerprint --update --configs {name}` and "
                "commit the file"
            )
            continue
        drifts = classify_drift(golden, doc)
        verdict = drift_verdict(drifts)
        results[name] = {
            "hash": digest,
            "verdict": verdict,
            "drift": [d.to_dict() for d in drifts],
        }
        if verdict == "violation":
            details = "; ".join(
                d.detail for d in drifts if d.kind == "violation"
            )
            failures.append(f"{name}: program-contract violation — {details}")
    return results, failures


# ------------------------------------------------------------------ front end
def fingerprint_command_parser(subparsers=None) -> argparse.ArgumentParser:
    description = (
        "Re-lower the shipped builder matrix, extract canonical program "
        "fingerprints (collectives per mesh axis, donation contract, dtype "
        "flow, replication split), and diff against the committed goldens — "
        "exit 1 on classified violations"
    )
    if subparsers is not None:
        parser = subparsers.add_parser("fingerprint", description=description)
    else:
        parser = argparse.ArgumentParser(
            "accelerate-tpu fingerprint", description=description
        )
    parser.add_argument(
        "--check", action="store_true",
        help="Diff HEAD's fingerprints against the goldens (the default)",
    )
    parser.add_argument(
        "--update", action="store_true",
        help="Regenerate the goldens from HEAD — the deliberate-change path; "
             "commit the diff",
    )
    parser.add_argument(
        "--configs", default=None,
        help=f"Comma-separated subset of the matrix (default: all of "
             f"{','.join(CONFIG_NAMES)})",
    )
    parser.add_argument(
        "--goldens-dir", default=None,
        help="Golden directory (default: tests/goldens next to the package)",
    )
    parser.add_argument(
        "--cpu-virtual-devices", type=int, default=8,
        help="Pin an N-device virtual CPU mesh before building (default 8 — "
             "the tier-1 rig; 0 skips pinning and fingerprints the live "
             "backend, which will NOT match the committed goldens)",
    )
    parser.add_argument(
        "--keep-compile-cache", action="store_true",
        help="Do not scrub ACCELERATE_COMPILE_CACHE_DIR: donation stays "
             "platform-waived on CPU (fingerprints are policy-independent "
             "either way, but the dropped-donor detector is disarmed)",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="Machine-readable verdict document ({verdict, failures, "
             "configs}) instead of the human report; exit codes unchanged",
    )
    parser.add_argument(
        "--list-configs", action="store_true",
        help="Print the config matrix and exit",
    )
    if subparsers is not None:
        parser.set_defaults(func=fingerprint_command)
    return parser


def fingerprint_command(args) -> None:
    from ..analysis.fingerprint import default_goldens_dir

    if args.list_configs:
        for name in CONFIG_NAMES:
            if name == "decode":
                print(f"{name}: ContinuousBatcher sync_every-token decode window")
                continue
            if name == "decode_paged":
                print(f"{name}: paged ContinuousBatcher decode window "
                      "(block-table gather + pool scatter)")
                continue
            if name == "decode_paged_kernel":
                print(f"{name}: paged decode window with the Pallas "
                      "chain-walk kernels engaged (ACCELERATE_KERNELS="
                      "interpret; pins the pallas_call inventory)")
                continue
            if name == "prefill_paged":
                print(f"{name}: chunked-prefill program of a prefill-only "
                      "serving tier (paged pool writes through the block "
                      "table + first-token sampling; no decode window)")
                continue
            if name == "decode_paged_int8":
                print(f"{name}: paged decode window over an int8-quantized "
                      "KV pool with the dequant-in-DMA gather kernel "
                      "engaged (ACCELERATE_KERNELS=interpret)")
                continue
            if name == "spec_verify":
                print(f"{name}: speculative verify program — k-draft scan + "
                      "one multi-token target forward + block-table "
                      "truncation commit (ACCELERATE_KERNELS=interpret)")
                continue
            if name == "step_zero_kernel":
                print(f"{name}: window=1 optimizer=adamw zero=on mesh=dp8 "
                      "with the fused-update Pallas kernel engaged "
                      "(ACCELERATE_KERNELS=interpret)")
                continue
            window, optimizer, zero, parallelism = _TRAIN_CONFIGS[name]
            plan = ",".join(f"{k}={v}" for k, v in (parallelism or {}).items()) or "dp8"
            print(f"{name}: window={window} optimizer={optimizer} "
                  f"zero={'on' if zero else 'off'} mesh={plan}")
        return
    if args.update and args.check:
        raise SystemExit("--check and --update are mutually exclusive")

    if args.cpu_virtual_devices:
        from ..utils.environment import pin_cpu_platform

        # Must precede the first backend touch; the goldens are extracted on
        # exactly this mesh.
        pin_cpu_platform(args.cpu_virtual_devices)
    if not args.keep_compile_cache:
        # Donation must be LIVE for the dropped-donor detector: the CPU +
        # persistent-cache policy (safe_donate_argnums) would waive every
        # donor mark. Scrub before the first Accelerator touches the env.
        os.environ.pop("ACCELERATE_COMPILE_CACHE_DIR", None)

    configs = [c.strip() for c in (args.configs or "").split(",") if c.strip()] \
        or list(CONFIG_NAMES)
    unknown = [c for c in configs if c not in CONFIG_NAMES]
    if unknown:
        raise SystemExit(
            f"unknown config(s) {', '.join(unknown)}; choose from "
            f"{', '.join(CONFIG_NAMES)}"
        )
    goldens_dir = args.goldens_dir or default_goldens_dir()
    results, failures = run_fingerprints(configs, goldens_dir, update=args.update)

    if args.json:
        print(json.dumps({
            "schema_version": 1,
            "command": "fingerprint",
            "verdict": "fail" if failures else "pass",
            "failures": failures,
            "goldens_dir": goldens_dir,
            "configs": results,
        }, indent=1))
    else:
        for name, res in results.items():
            print(f"{name}: {res['verdict']} (hash {res['hash']})")
            for entry in res["drift"]:
                print(f"  [{entry['kind']}] {entry['field']}: {entry['detail']}")
        if args.update:
            print(f"wrote {len(results)} golden(s) to {goldens_dir}")
        else:
            for f in failures:
                print(f"fingerprint: {f}", file=sys.stderr)
            print(
                f"fingerprint: {len(configs)} config(s), "
                f"{len(failures)} violation(s)"
            )
    if failures and not args.update:
        raise SystemExit(1)


def fingerprint_main() -> None:
    """Console-script entry (`accelerate-tpu-fingerprint`)."""
    fingerprint_command(fingerprint_command_parser().parse_args())


if __name__ == "__main__":
    fingerprint_main()
