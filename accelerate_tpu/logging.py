"""Multi-process-aware logging.

Reference parity: ``src/accelerate/logging.py:22-125`` — ``MultiProcessAdapter``
with ``main_process_only`` filtering and ``in_order`` rank-serialized emission,
``get_logger`` factory.
"""

from __future__ import annotations

import functools
import logging
import os
import sys

# Per-callsite emission counters behind ``log_every_n`` — module-global so
# every adapter wrapping the same (or different) loggers shares one count per
# source line, which is what "don't flood the log from this loop" means.
_EVERY_N_COUNTS: dict = {}


class MultiProcessAdapter(logging.LoggerAdapter):
    """Logs only on the main process unless ``main_process_only=False``; with
    ``in_order=True`` each process logs in rank order behind a barrier."""

    @staticmethod
    def _should_log(main_process_only: bool) -> bool:
        from .state import PartialState

        state = PartialState()
        return not main_process_only or state.is_main_process

    def log(self, level, msg, *args, **kwargs):
        if int(os.environ.get("ACCELERATE_DISABLE_RICH", "0")):  # parity env slot
            pass
        main_process_only = kwargs.pop("main_process_only", True)
        in_order = kwargs.pop("in_order", False)
        if in_order:
            # EVERY process must walk the same barrier sequence — ALL filters
            # (rank AND logger level, which can differ per host) decide only
            # who emits inside it. The old form let a process that passed a
            # filter log-and-return without entering the loop while the
            # others sat in num_processes barriers: a latent multi-host hang.
            from .state import PartialState

            state = PartialState()
            for i in range(state.num_processes):
                if (
                    i == state.process_index
                    and self.isEnabledFor(level)
                    and self._should_log(main_process_only)
                ):
                    msg, kwargs = self.process(msg, kwargs)
                    self.logger.log(level, msg, *args, **kwargs)
                state.wait_for_everyone()
        elif self.isEnabledFor(level) and self._should_log(main_process_only):
            msg, kwargs = self.process(msg, kwargs)
            self.logger.log(level, msg, *args, **kwargs)

    def log_every_n(self, n: int, level, msg, *args, **kwargs):
        """Rate-limited ``log``: emits the 1st and then every ``n``-th call
        *per callsite* (keyed on the caller's file:line, shared across adapter
        instances), so per-step telemetry warnings — straggler alerts, skew
        reports — cannot flood a multi-thousand-step run. Suppressed calls
        still count, and the emitted record notes the suppression."""
        if n <= 0:
            raise ValueError(f"log_every_n needs n >= 1, got {n}")
        frame = sys._getframe(1)
        key = (frame.f_code.co_filename, frame.f_lineno)
        count = _EVERY_N_COUNTS.get(key, 0)
        _EVERY_N_COUNTS[key] = count + 1
        if count % n == 0:
            if count and n > 1:
                msg = f"{msg} [1/{n} of {count + 1} occurrences logged]"
            self.log(level, msg, *args, **kwargs)

    @functools.lru_cache(None)
    def warning_once(self, *args, **kwargs):
        self.warning(*args, **kwargs)


def get_logger(name: str, log_level: str | None = None) -> MultiProcessAdapter:
    """Reference ``get_logger`` :85."""
    if log_level is None:
        log_level = os.environ.get("ACCELERATE_LOG_LEVEL", None)
    logger = logging.getLogger(name)
    if log_level is not None:
        logger.setLevel(log_level.upper())
        logger.root.setLevel(log_level.upper())
    return MultiProcessAdapter(logger, {})
