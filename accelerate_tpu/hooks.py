"""Forward-interception hooks — the offload/dispatch runtime.

Reference parity: ``src/accelerate/hooks.py`` — ``ModelHook``/``SequentialHook``
(:43-99), ``add_hook_to_module`` (:130-186, replaces ``module.forward``),
``AlignDevicesHook`` (:225-410: pre_forward moves weights in, post_forward offloads),
``attach_align_device_hook_on_blocks`` (:555-687), ``CpuOffload``/
``UserCpuOffloadHook`` (:689-739), ``LayerwiseCastingHook`` (:741-765).

TPU re-design: the reference intercepts stateful ``nn.Module.forward`` and mutates
``module.weight.data`` in place. Our models are pure functions over param pytrees,
so a hook intercepts ``module.apply`` and transforms **(params, args, kwargs)** on
the way in and **outputs** on the way out. Weight movement becomes ``jax.device_put``
of pytree leaves (host↔HBM DMA), and "remove from device" is dropping the device
reference (XLA frees the buffer) — no ``.data`` mutation exists or is needed.

The per-block streaming runtime for disk/host-offloaded inference lives in
``big_modeling.StreamedScanModel`` which exploits the zoo's stacked-layer layout
(leading ``L`` dim) instead of per-module hook attachment: one compiled block
program + a double-buffered device_put pipeline — the TPU-shaped version of the
reference's AlignDevicesHook hot loop (hooks.py:328-402 there).
"""

from __future__ import annotations

import functools
from typing import Mapping

import numpy as np

import jax
import jax.numpy as jnp


class ModelHook:
    """Hook protocol (reference ``ModelHook`` :43-99). All methods are pure-ish:
    they receive and return the values rather than mutating modules."""

    no_grad = False

    def init_hook(self, module):
        return module

    def pre_forward(self, module, params, args, kwargs):
        return params, args, kwargs

    def post_forward(self, module, output):
        return output

    def detach_hook(self, module):
        return module


class SequentialHook(ModelHook):
    """Compose hooks in order (reference ``SequentialHook`` :84-99)."""

    def __init__(self, *hooks):
        self.hooks = hooks

    def init_hook(self, module):
        for hook in self.hooks:
            module = hook.init_hook(module)
        return module

    def pre_forward(self, module, params, args, kwargs):
        for hook in self.hooks:
            params, args, kwargs = hook.pre_forward(module, params, args, kwargs)
        return params, args, kwargs

    def post_forward(self, module, output):
        for hook in self.hooks:
            output = hook.post_forward(module, output)
        return output

    def detach_hook(self, module):
        for hook in self.hooks:
            module = hook.detach_hook(module)
        return module


def add_hook_to_module(module, hook: ModelHook, append: bool = False):
    """Wrap ``module.apply`` with the hook (reference ``add_hook_to_module``
    :130-186 wraps ``module.forward``). Idempotent-composable via ``append``."""
    if append and getattr(module, "_at_hook", None) is not None:
        old = module._at_hook
        remove_hook_from_module(module)
        hook = SequentialHook(old, hook)

    if getattr(module, "_at_old_apply", None) is None:
        module._at_old_apply = module.apply
    old_apply = module._at_old_apply
    module = hook.init_hook(module)
    module._at_hook = hook

    @functools.wraps(old_apply)
    def new_apply(params, *args, **kwargs):
        params, args, kwargs = hook.pre_forward(module, params, args, kwargs)
        output = old_apply(params, *args, **kwargs)
        return hook.post_forward(module, output)

    module.apply = new_apply
    return module


def remove_hook_from_module(module, recurse: bool = False):
    """Restore the original apply (reference ``remove_hook_from_module`` :189-222)."""
    if getattr(module, "_at_hook", None) is not None:
        module._at_hook.detach_hook(module)
        module._at_hook = None
    if getattr(module, "_at_old_apply", None) is not None:
        module.apply = module._at_old_apply
        module._at_old_apply = None
    return module


class AlignDevicesHook(ModelHook):
    """Move params onto the execution device before forward; optionally release
    them after (reference ``AlignDevicesHook`` :225-410).

    ``weights_map``: optional lazy host/disk mapping (``OffloadedWeightsLoader``)
    consulted by name when a leaf is not already device-resident — the offload
    case. Leaves are placed with ``jax.device_put`` (sharded placement when a
    NamedSharding is given as ``execution_device``). Placement always covers the
    whole param subtree (the reference's ``place_submodules=True``); params are
    passed per call, so per-call device copies are freed after forward, and
    ``offload=True`` additionally pulls any device arrays stored on the module
    itself back to host numpy after each forward.
    """

    def __init__(
        self,
        execution_device=None,
        offload: bool = False,
        io_same_device: bool = False,
        weights_map: Mapping | None = None,
        skip_keys=None,
    ):
        self.execution_device = execution_device
        self.offload = offload
        self.io_same_device = io_same_device
        self.weights_map = weights_map
        self.skip_keys = skip_keys
        self.input_device = None

    def pre_forward(self, module, params, args, kwargs):
        if self.weights_map is not None:
            from .utils.modeling import named_parameters, unflatten_names

            flat = {}
            for name, leaf in named_parameters(params).items():
                if isinstance(leaf, jax.ShapeDtypeStruct) or not isinstance(leaf, jax.Array):
                    if name in self.weights_map:
                        flat[name] = np.asarray(self.weights_map[name])
                        continue
                flat[name] = leaf
            params = unflatten_names(flat, params)
        if self.execution_device is not None:
            if self.io_same_device:
                leaves = [x for x in jax.tree_util.tree_leaves((args, kwargs)) if isinstance(x, jax.Array)]
                self.input_device = leaves[0].sharding if leaves else None
            params = jax.device_put(params, self.execution_device)
            args, kwargs = jax.tree_util.tree_map(
                lambda x: jax.device_put(x, self.execution_device)
                if isinstance(x, (jax.Array, np.ndarray)) else x,
                (args, kwargs),
            )
        return params, args, kwargs

    def post_forward(self, module, output):
        if self.offload and getattr(module, "params", None) is not None:
            # Release device residency of stored params (reference post_forward
            # offload :373-402); per-call copies are freed by scoping already.
            module.params = jax.tree_util.tree_map(
                lambda x: np.asarray(x) if isinstance(x, jax.Array) else x, module.params
            )
        if self.io_same_device and self.input_device is not None:
            output = jax.tree_util.tree_map(
                lambda x: jax.device_put(x, self.input_device) if isinstance(x, jax.Array) else x,
                output,
            )
        return output


class CpuOffload(ModelHook):
    """Keep params on host between calls; move to device for each forward
    (reference ``CpuOffload`` :689-714). ``prev_module_hook`` lets chained models
    (e.g. SD UNet/VAE) evict the previous one when this one runs."""

    def __init__(self, execution_device=None, prev_module_hook=None):
        self.execution_device = execution_device
        self.prev_module_hook = prev_module_hook

    def pre_forward(self, module, params, args, kwargs):
        if self.prev_module_hook is not None:
            self.prev_module_hook.offload()
        device = self.execution_device or jax.local_devices()[0]
        return jax.device_put(params, device), args, kwargs


class UserCpuOffloadHook:
    """User-facing handle pairing a model and its hook (reference
    ``UserCpuOffloadHook`` :717-739)."""

    def __init__(self, model, hook):
        self.model = model
        self.hook = hook

    def offload(self):
        # Drop device buffers by pulling params back to host numpy.
        if getattr(self.model, "params", None) is not None:
            self.model.params = jax.tree_util.tree_map(
                lambda p: np.asarray(jax.device_get(p)) if isinstance(p, jax.Array) else p,
                self.model.params,
            )

    def remove(self):
        remove_hook_from_module(self.model)


class DequantizeHook(ModelHook):
    """Rebuild full-precision weights at forward entry for a quantized param tree
    (the compute side of ``utils/quantization.py``; reference bnb does this inside
    CUDA Linear8bitLt/Linear4bit layers — here the dequant scale-multiply fuses
    into the consuming matmul under jit)."""

    def __init__(self, compute_dtype=jnp.bfloat16):
        self.compute_dtype = compute_dtype

    def pre_forward(self, module, params, args, kwargs):
        from .utils.quantization import dequantize_tree

        return dequantize_tree(params, self.compute_dtype), args, kwargs


class LayerwiseCastingHook(ModelHook):
    """Store in ``storage_dtype``, compute in ``compute_dtype`` (reference
    ``LayerwiseCastingHook`` :741-765). The params stay small in HBM; the upcast
    happens inside the compiled forward and fuses into the first consumer op."""

    def __init__(self, storage_dtype=jnp.float8_e4m3fn, compute_dtype=jnp.bfloat16):
        self.storage_dtype = storage_dtype
        self.compute_dtype = compute_dtype

    def init_hook(self, module):
        if getattr(module, "params", None) is not None:
            module.params = jax.tree_util.tree_map(
                lambda p: p.astype(self.storage_dtype)
                if hasattr(p, "dtype") and jnp.issubdtype(p.dtype, jnp.floating) else p,
                module.params,
            )
        return module

    def pre_forward(self, module, params, args, kwargs):
        params = jax.tree_util.tree_map(
            lambda p: p.astype(self.compute_dtype)
            if hasattr(p, "dtype") and jnp.issubdtype(p.dtype, jnp.floating) else p,
            params,
        )
        return params, args, kwargs
