"""Loss-spike detector — rolling robust statistics as device-side state.

A bad batch (corrupted shard, tokenizer glitch, poisoned document) shows up as
a loss far outside the recent distribution *before* it wrecks the optimizer
state. Plain mean/std statistics are the wrong tool — the spike itself drags
the std up, masking follow-on spikes — so the detector keeps an EMA of the
loss and an EMA of the absolute deviation (a streaming proxy for the MAD,
scaled by the usual 1.4826 normal-consistency constant) and trips on the
robust z-score.

Two properties matter for correctness:

- the statistics live as device arrays and are updated by a pure function the
  guard folds into its single per-step dispatch — no host sync to keep them;
- a tripped (or non-finite) observation does NOT update the statistics: the
  poisoned loss must not drag the baseline toward itself, and a rolled-back
  replay re-observing the same healthy window reproduces the state bit-exactly
  (the property the bit-exact rollback drills pin).
"""

from __future__ import annotations

import jax.numpy as jnp

# Verdict bit (numerics.py owns 1 and 2).
LOSS_SPIKE = 4

_MAD_TO_SIGMA = 1.4826  # E|X-mu| consistency constant for a normal


class SpikeDetector:
    """EMA + MAD-proxy z-score over the scalar loss.

    ``zscore``: robust z threshold that trips the detector. ``warmup_steps``:
    healthy observations required before trips are allowed (the first steps of
    a run legitimately fall fast). ``ema_decay``: smoothing for both the level
    and deviation EMAs.
    """

    def __init__(self, zscore: float = 6.0, warmup_steps: int = 20, ema_decay: float = 0.98):
        if zscore <= 0:
            raise ValueError(f"zscore must be > 0, got {zscore}")
        if not 0.0 < ema_decay < 1.0:
            raise ValueError(f"ema_decay must be in (0, 1), got {ema_decay}")
        self.zscore = float(zscore)
        self.warmup_steps = int(warmup_steps)
        self.ema_decay = float(ema_decay)

    # ---------------------------------------------------------------- state
    def init_state(self):
        """(ema, mad_proxy, healthy_count) — all device-friendly scalars."""
        return (jnp.float32(0.0), jnp.float32(0.0), jnp.int32(0))

    def update(self, state, loss):
        """Traceable: ``(new_state, flags, z)`` for one observation.

        ``flags`` is LOSS_SPIKE or 0; ``z`` the robust z-score (0 while the
        statistics are still warming up). Composed into the guard's jitted
        verdict — callers never dispatch this alone.
        """
        ema, mad, count = state
        loss32 = jnp.asarray(loss, jnp.float32)
        finite = jnp.isfinite(loss32)
        warm = count >= self.warmup_steps
        dev = jnp.abs(loss32 - ema)
        sigma = _MAD_TO_SIGMA * mad
        z = jnp.where(warm & finite, dev / (sigma + 1e-12), 0.0)
        spike = warm & finite & (z > self.zscore)
        # Healthy observations advance the EMAs; spikes and non-finite losses
        # are excluded so the baseline cannot be dragged toward the fault.
        healthy = finite & ~spike
        # Effective decay min(d, n/(n+1)): the first observations form a plain
        # running mean (a 0.98 EMA seeded at the first loss would take ~50
        # steps to forget it, making the whole warmup window a false baseline)
        # and the statistics glide into the EMA once n/(n+1) crosses d.
        cnt = count.astype(jnp.float32)
        d = jnp.minimum(jnp.float32(self.ema_decay), cnt / (cnt + 1.0))
        new_ema = jnp.where(healthy, d * ema + (1 - d) * loss32, ema)
        new_mad = jnp.where(healthy, jnp.where(count == 0, 0.0, d * mad + (1 - d) * dev), mad)
        new_count = jnp.where(healthy, count + 1, count)
        flags = jnp.where(spike, LOSS_SPIKE, 0).astype(jnp.int32)
        return (new_ema, new_mad, new_count), flags, z
