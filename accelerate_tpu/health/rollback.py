"""Last-known-good rollback — recovery without touching disk.

Checkpoint-restart recovers from a poisoned run, but at the cost of a full
restore plus everything since the last (typically infrequent) save. For the
internal faults the health guard catches — one bad batch, one NaN update —
the cheapest recovery is an **in-memory snapshot** taken every K steps:
device-resident copies of params and optimizer state plus the host-side
bookkeeping (step, RNG streams, checkpoint-naming index) needed to make the
replay *bit-exact*. Restore is a buffer copy, not an I/O storm, so K can be
small (tens of steps) where checkpoint cadence is thousands.

Donation safety: the fused train step donates its input buffers, so holding a
reference to the live params is not a snapshot — the next step would
invalidate it. :func:`device_clone` forces a real device-side copy (a jitted
flatten/unflatten that cannot be input-forwarded or aliased), bit-preserving
for every dtype including ``-0.0`` and NaN payloads.
"""

from __future__ import annotations

import copy
import os
import random
import shutil

import numpy as np

import jax
import jax.numpy as jnp

from ..logging import get_logger

logger = get_logger(__name__)

_clone_fns: dict = {}


def _reshape_copy(x):
    key = (x.shape, str(x.dtype))
    fn = _clone_fns.get(key)
    if fn is None:
        # flatten+restore defeats jit's input-output buffer forwarding and,
        # absent donation, XLA must materialize a fresh output buffer — a
        # true copy, bit-exact for every value including -0.0 and NaNs.
        fn = jax.jit(lambda a: jnp.reshape(jnp.reshape(a, (-1,)), a.shape))
        _clone_fns[key] = fn
    return fn(x)


def device_clone(tree):
    """Deep-copy a pytree: jax arrays get fresh device buffers (donation-proof),
    everything else is ``copy.deepcopy``-ed."""
    return jax.tree_util.tree_map(
        lambda x: _reshape_copy(x) if isinstance(x, jax.Array) else copy.deepcopy(x), tree
    )


class LastKnownGood:
    """A short ring of snapshots; ``capture`` clones in, ``restore`` clones
    out (so a snapshot survives being restored more than once).

    Why a ring and not one slot: on async backends a verdict can lag its step
    by a few dispatches, so the newest snapshot may postdate — and contain —
    the fault. ``restore(before_step=trip_step)`` picks the newest snapshot
    *strictly older* than the trip, which lets the guard capture without ever
    force-draining the verdict queue: the healthy path stays wait-free and a
    poisoned snapshot is simply skipped over. ``keep=2`` covers any lag up to
    a full snapshot interval (the guard's pending window is far shorter)."""

    def __init__(self, every_steps: int = 25, keep: int = 2):
        if every_steps < 1:
            raise ValueError(f"every_steps must be >= 1, got {every_steps}")
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.every_steps = int(every_steps)
        self.keep = int(keep)
        self._snapshots: list = []  # [(step, device_state, host_state)] oldest→newest

    @property
    def step(self) -> int | None:
        """Step of the newest snapshot (None before the first capture)."""
        return self._snapshots[-1][0] if self._snapshots else None

    def snapshot_step(self, before_step: int | None = None) -> int | None:
        """Step of the snapshot ``restore`` would pick."""
        for step, _, _ in reversed(self._snapshots):
            if before_step is None or step < before_step:
                return step
        return None

    def due(self, step: int, window: int = 1) -> bool:
        """Whether a capture is due at this step boundary. ``window`` > 1 is
        the K-step fused-window case: ``step`` is the boundary (last in-window
        step) and the capture fires when ANY in-window step crossed the
        cadence — boundaries are the only points the guard sees."""
        from ..utils.cadence import window_cadence_due

        if not self._snapshots:
            return True
        return window_cadence_due(step, window, self.every_steps, include_step0=True)

    def capture(self, step: int, device_state=None, host_state=None):
        device = device_clone(device_state) if device_state is not None else None
        self._snapshots.append((int(step), device, copy.deepcopy(host_state)))
        del self._snapshots[: -self.keep]

    def discard_from(self, step: int):
        """Drop snapshots at/after ``step`` — they were captured on a timeline
        a rollback is about to discard."""
        self._snapshots = [s for s in self._snapshots if s[0] < step]

    def clear(self):
        """Drop every snapshot — after an elastic reshard the held device
        arrays lay on a mesh that no longer exists; restoring one would
        resurrect the dead layout (resilience/elastic.py discards, never
        restores)."""
        self._snapshots = []

    def restore(self, before_step: int | None = None):
        """→ ``(step, device_state, host_state)`` of the newest snapshot older
        than ``before_step`` (newest overall when None) — fresh copies each
        call. Raises when no qualifying snapshot exists."""
        for step, device, host in reversed(self._snapshots):
            if before_step is None or step < before_step:
                return (
                    step,
                    device_clone(device) if device is not None else None,
                    copy.deepcopy(host),
                )
        raise RuntimeError(
            f"no last-known-good snapshot older than step {before_step} is held"
        )


# ---------------------------------------------------- accelerator integration
def snapshot_accelerator(accelerator, lkg: LastKnownGood, step: int, extra_device=None):
    """Capture everything a bit-exact replay needs, into ``lkg``."""
    for opt in accelerator._optimizers:
        resolve = getattr(opt, "_resolve_pending_finite", None)
        if resolve is not None:
            resolve()  # scaler scale / step_count must be final before copying
    device = {
        "params": [m.handle.params for m in accelerator._models],
        "opt_states": [opt.opt_state for opt in accelerator._optimizers],
        # The accumulation buffer rides along: None on the imperative path at
        # a step boundary, a zeros (or partially accumulated) tree on the
        # fused build_train_step path — which reads it on every call and must
        # never see it nulled by a rollback.
        "accum_grads": [opt._accum_grads for opt in accelerator._optimizers],
        "extra": extra_device,
    }
    host = {
        "step": accelerator.step,
        "step_counters": [m.handle.step_counter for m in accelerator._models],
        "opt_meta": [
            {
                "step_count": opt._step_count,
                "scale": opt.scaler.scale if opt.scaler is not None else None,
            }
            for opt in accelerator._optimizers
        ],
        "scheduler_states": [s.state_dict() for s in accelerator._schedulers],
        "python_rng": random.getstate(),
        "numpy_rng": np.random.get_state(),
        "iteration": accelerator.project_configuration.iteration,
    }
    lkg.capture(step, device_state=device, host_state=host)


def restore_accelerator(accelerator, lkg: LastKnownGood, before_step: int | None = None):
    """Roll the accelerator back to the newest snapshot older than
    ``before_step``; returns its step (and the snapshot's extra device
    payload). Auto-named checkpoints saved *after* the snapshot belong to the
    discarded timeline and are deleted so the replay's own saves cannot
    collide."""
    step, device, host = lkg.restore(before_step)
    for model, params in zip(accelerator._models, device["params"]):
        model.handle.params = params
    for opt, opt_state, accum, meta in zip(
        accelerator._optimizers, device["opt_states"], device["accum_grads"], host["opt_meta"]
    ):
        opt.opt_state = opt_state
        opt._accum_grads = accum
        opt._pending_clip_norm = None
        opt._pending_finite = None
        opt._step_was_skipped = False
        opt._step_count = meta["step_count"]
        if opt.scaler is not None and meta["scale"] is not None:
            opt.scaler.scale = meta["scale"]
    for model, counter in zip(accelerator._models, host["step_counters"]):
        model.handle.step_counter = counter
    for sched, state in zip(accelerator._schedulers, host["scheduler_states"]):
        sched.load_state_dict(state)
    random.setstate(host["python_rng"])
    np.random.set_state(host["numpy_rng"])
    accelerator.step = host["step"]
    project = accelerator.project_configuration
    project.iteration = host["iteration"]
    if project.automatic_checkpoint_naming and project.project_dir and accelerator.is_main_process:
        from ..utils.constants import CHECKPOINT_DIR_PREFIX

        base = os.path.join(project.project_dir, "checkpoints")
        if os.path.isdir(base):
            for folder in os.listdir(base):
                if not folder.startswith(f"{CHECKPOINT_DIR_PREFIX}_"):
                    continue
                try:
                    index = int(folder.rsplit("_", 1)[-1])
                except ValueError:
                    continue
                if index >= host["iteration"]:
                    logger.warning(f"Rollback: deleting post-snapshot checkpoint {folder}")
                    shutil.rmtree(os.path.join(base, folder), ignore_errors=True)
    logger.warning(f"Rolled back to last-known-good snapshot at step {step}.")
    return step, device.get("extra")
