"""Numerics sentinel — on-device finite checks, on-trip leaf attribution.

The check itself is a handful of scalar ops folded into the same dispatch as
the spike-detector update (:mod:`.guard` jits the composition once), reading
the loss the step already produced and the grad-norm the optimizer already
computed — so the always-on path costs zero extra host syncs in every
precision mode, not just the fp16 GradScaler path. Only when a check *trips*
does the expensive part run: :func:`nonfinite_leaves` bisects the param (or
grad) tree on device to name the leaves that went non-finite, which is the
difference between "loss was NaN at step 4817" and "the router's gate bias
overflowed" in the post-mortem.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..logging import get_logger
from ..utils.transfer import host_fetch

logger = get_logger(__name__)

# Verdict bitmask (shared with spike.LOSS_SPIKE = 4).
NONFINITE_LOSS = 1
NONFINITE_GRAD = 2


def numerics_flags(loss, gnorm=None):
    """Traceable: int32 bitmask of non-finite findings for one step.

    ``loss`` is the step's scalar loss; ``gnorm`` the pre-clip global grad
    norm when the caller has it (a non-finite gnorm means at least one grad
    leaf is non-finite — the same scalar the optimizer's conditional-skip
    already branches on). Composed into the guard's single jitted dispatch.
    """
    flags = jnp.where(jnp.isfinite(jnp.asarray(loss, jnp.float32)), 0, NONFINITE_LOSS).astype(jnp.int32)
    if gnorm is not None:
        flags = flags | jnp.where(
            jnp.isfinite(jnp.asarray(gnorm, jnp.float32)), 0, NONFINITE_GRAD
        ).astype(jnp.int32)
    return flags


class NumericsSentinel:
    """Thin stateful wrapper: remembers whether grad-norm checking is wanted
    and runs the on-trip attribution. The per-step check itself is the pure
    :func:`numerics_flags`, jitted by the guard alongside the spike update."""

    def __init__(self, check_grads: bool = True):
        self.check_grads = check_grads

    def flags(self, loss, gnorm=None):
        return numerics_flags(loss, gnorm if self.check_grads else None)

    def attribute(self, tree, label: str = "params") -> list[str]:
        """On-trip diagnostic: which leaves of ``tree`` are non-finite."""
        bad = nonfinite_leaves(tree)
        if bad:
            logger.error(
                f"Numerics sentinel: {len(bad)} non-finite {label} leaves: "
                + ", ".join(bad[:16])
                + (" ..." if len(bad) > 16 else "")
            )
        return bad


def finite_scalar(x) -> bool:
    """Host-side convenience: is this (device or host) scalar finite?"""
    return bool(np.isfinite(np.asarray(host_fetch(x), dtype=np.float64)))


def _segment_all_finite(leaves) -> bool:
    """One device reduction + one host fetch over a list of leaves."""
    fn = _segment_check_fn()
    return bool(host_fetch(fn(leaves)))


_segment_check = None


def _segment_check_fn():
    global _segment_check
    if _segment_check is None:
        def check(leaves):
            oks = [jnp.all(jnp.isfinite(l.astype(jnp.float32))) for l in leaves]
            return jnp.all(jnp.stack(oks)) if oks else jnp.bool_(True)

        _segment_check = jax.jit(check)
    return _segment_check


def nonfinite_leaves(tree, max_leaf_checks: int = 256) -> list[str]:
    """Bisect ``tree`` to the leaves containing a NaN/Inf; returns their paths.

    Each bisection level costs one jitted all-finite reduction over a leaf
    subset plus one host fetch, so a single poisoned leaf among L leaves is
    found in ~log2(L) round-trips instead of L. This runs on the trip path
    only — blocking is fine there. ``max_leaf_checks`` caps the number of
    *individually confirmed* bad leaves (a fully poisoned tree would otherwise
    degenerate to per-leaf fetches).
    """
    from ..parallel.sharding import path_str

    items = [
        (path_str(path).replace("/", "."), leaf)
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]
        if hasattr(leaf, "dtype") and jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact)
    ]
    bad: list[str] = []

    def bisect(segment):
        if not segment or len(bad) >= max_leaf_checks:
            return
        if _segment_all_finite([l for _, l in segment]):
            return
        if len(segment) == 1:
            bad.append(segment[0][0])
            return
        mid = len(segment) // 2
        bisect(segment[:mid])
        bisect(segment[mid:])

    bisect(items)
    return bad
