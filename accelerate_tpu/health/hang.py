"""Hang/straggler watchdog — a silent multi-host deadlock costs the whole pod.

One wedged host — a stuck collective, a hung storage mount, a deadlocked data
worker — freezes every other host in its next collective, and an SPMD job
burns its full reservation producing nothing, with no process ever *failing*.
The watchdog converts that silence into action: a daemon thread arms after the
first heartbeat (so multi-minute first-step compiles don't false-positive),
and when no step boundary beats it within ``timeout_s`` it

1. dumps every Python thread's stack plus live-device-array stats to stderr
   (the post-mortem a hung job never leaves behind),
2. books the stalled window as ``hang`` badput in the goodput ledger,
3. fires its action: ``"exit"`` (default) hard-exits with the distinct
   :data:`HANG_EXIT_CODE` so a supervising launcher (``accelerate-tpu launch
   --max_restarts``) restarts the gang, or ``"raise"`` async-raises
   :class:`HangDetected` in the training thread so an in-process
   ``run_resilient(..., hang_timeout_s=...)`` loop can restart-and-resume —
   the ``"raise`` mode can only preempt Python-level stalls; a hang inside a
   C++ collective needs ``"exit"`` and a process-level supervisor.

Heartbeats ride the hooks training loops already call per step
(``Accelerator.guard_step`` / ``checkpoint_on_preemption``), so enabling the
watchdog (``ACCELERATE_HANG_TIMEOUT`` / ``--hang_timeout``) needs no loop
changes.
"""

from __future__ import annotations

import ctypes
import faulthandler
import os
import sys
import threading
import time

from ..logging import get_logger

logger = get_logger(__name__)

# Distinct exit code (outside the shell/signal ranges) so supervisors can tell
# "watchdog killed a hung gang" from ordinary failures.
HANG_EXIT_CODE = 113


class HangDetected(RuntimeError):
    """Raised (asynchronously, in the training thread) by a watchdog in
    ``on_hang="raise"`` mode; ``run_resilient`` treats it like any failure."""

    def __init__(self, idle_s: float = 0.0, step=None):
        # Args must be optional: PyThreadState_SetAsyncExc delivers the CLASS
        # and the interpreter instantiates it with no arguments.
        at = f" after step {step}" if step is not None else ""
        super().__init__(f"hang watchdog: no step completed{f' in {idle_s:.1f}s' if idle_s else ''}{at}")
        self.idle_s = idle_s
        self.step = step


def _dump_diagnostics(idle_s: float, step):
    try:
        sys.stderr.write(
            f"\n=== hang watchdog: no heartbeat for {idle_s:.1f}s "
            f"(last step: {step}) — thread stacks follow ===\n"
        )
        faulthandler.dump_traceback(file=sys.stderr)
        try:
            import jax

            arrays = jax.live_arrays()
            nbytes = sum(getattr(a, "nbytes", 0) for a in arrays)
            sys.stderr.write(
                f"=== live device arrays: {len(arrays)} "
                f"({nbytes / 2**20:.1f} MiB) ===\n"
            )
        except Exception:
            pass
        # Flight-recorder black box (telemetry/flight.py): the stacks say
        # where the run IS; the event ring says what it was doing on the way
        # there — dump it next to the fault for `accelerate-tpu blackbox`.
        try:
            from ..telemetry.flight import get_flight_recorder

            recorder = get_flight_recorder()
            recorder.record("hang", step=step, idle_s=round(idle_s, 3))
            path = recorder.dump("hang")
            if path:
                sys.stderr.write(f"=== flight recorder dumped to {path} ===\n")
        except Exception:
            pass
        sys.stderr.flush()
    except Exception:
        pass  # diagnostics must never mask the hang handling itself


def _async_raise(thread_ident: int, exc_type) -> bool:
    res = ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(thread_ident), ctypes.py_object(exc_type)
    )
    return res == 1


class HangWatchdog:
    """Heartbeat deadline on a daemon thread; see module docstring.

    ``on_hang``: ``"exit"`` | ``"raise"`` | a zero-arg callable. The countdown
    arms on the first :meth:`beat` (compiles and data warmup run un-timed) and
    fires at most once per :meth:`start`.
    """

    def __init__(self, timeout_s: float = 300.0, on_hang="exit", poll_interval_s: float | None = None):
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        if on_hang not in ("exit", "raise") and not callable(on_hang):
            raise ValueError(f"on_hang must be 'exit', 'raise' or a callable, got {on_hang!r}")
        self.timeout_s = float(timeout_s)
        self.on_hang = on_hang
        self.poll_interval_s = poll_interval_s or max(min(timeout_s / 4.0, 5.0), 0.05)
        self._last_beat: float | None = None
        self._last_step = None
        self._fired = False
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._target_ident: int | None = None

    # ------------------------------------------------------------- lifecycle
    def start(self, target_thread: threading.Thread | None = None) -> "HangWatchdog":
        """Idempotent; ``target_thread`` (default: the caller's thread) is
        where ``on_hang='raise'`` delivers :class:`HangDetected`."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._target_ident = (target_thread or threading.current_thread()).ident
        self._stop.clear()
        self._fired = False
        self._last_beat = None
        self._thread = threading.Thread(target=self._run, name="hang-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.poll_interval_s * 4)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # ------------------------------------------------------------ heartbeats
    def beat(self, step=None):
        """A step boundary completed — reset the countdown (arms on first call)."""
        self._last_beat = time.monotonic()
        if step is not None:
            self._last_step = step

    def rearm(self):
        """Reset after a handled trip: the countdown disarms until the next
        beat and the watchdog may fire again (``run_resilient`` re-arms
        between attempts)."""
        self._fired = False
        self._last_beat = None

    @property
    def fired(self) -> bool:
        return self._fired

    # ---------------------------------------------------------------- thread
    def _run(self):
        while not self._stop.wait(self.poll_interval_s):
            if self._last_beat is None or self._fired:
                continue  # not armed yet / already handled
            idle = time.monotonic() - self._last_beat
            if idle <= self.timeout_s:
                continue
            self._fired = True
            logger.error(
                f"Hang watchdog tripped: no step boundary in {idle:.1f}s "
                f"(timeout {self.timeout_s:.1f}s)."
            )
            _dump_diagnostics(idle, self._last_step)
            try:
                from ..resilience.goodput import get_ledger

                get_ledger().add("hang", idle)
            except Exception:
                pass
            if self.on_hang == "exit":
                os._exit(HANG_EXIT_CODE)
            elif self.on_hang == "raise":
                if not _async_raise(self._target_ident, HangDetected):
                    logger.error("Hang watchdog could not interrupt the training thread.")
            else:
                try:
                    self.on_hang()
                except Exception as exc:
                    logger.error(f"Hang watchdog on_hang callback failed: {exc!r}")


# ------------------------------------------------------ process-wide default
_default_watchdog: HangWatchdog | None = None


def get_default_watchdog() -> HangWatchdog | None:
    return _default_watchdog


def set_default_watchdog(watchdog: HangWatchdog | None):
    global _default_watchdog
    _default_watchdog = watchdog


def install_default_watchdog(timeout_s: float, on_hang="exit") -> HangWatchdog:
    """Start (or retune) the process-wide watchdog ``Accelerator`` hooks beat.
    Called by ``PartialState`` when ``ACCELERATE_HANG_TIMEOUT`` is set."""
    global _default_watchdog
    if _default_watchdog is None:
        _default_watchdog = HangWatchdog(timeout_s=timeout_s, on_hang=on_hang)
        _default_watchdog.start(threading.main_thread())
    else:
        _default_watchdog.timeout_s = float(timeout_s)
        _default_watchdog.on_hang = on_hang
        _default_watchdog.start(threading.main_thread())
    return _default_watchdog


def beat_default(step=None):
    """Cheap per-step hook: heartbeat the default watchdog if one is running."""
    if _default_watchdog is not None:
        _default_watchdog.beat(step)


def reset_default_watchdog():
    """Stop and forget the default watchdog (tests)."""
    global _default_watchdog
    if _default_watchdog is not None:
        _default_watchdog.stop()
    _default_watchdog = None
