"""Training-health watchdog — guards against the faults that come from *inside*.

The resilience subsystem (:mod:`..resilience`) recovers from external faults:
preemptions, kills, torn checkpoints. This package covers the internal half —
the failures that silently destroy a run while every process stays alive:

- :mod:`.numerics` — always-on, on-device finite checks of loss and grad-norm
  (piggybacking on the global norm the optimizer already computes — no extra
  host syncs in any precision mode), plus an on-trip bisection pass that
  attributes *which* param-tree leaves went non-finite;
- :mod:`.spike` — a loss-spike detector keeping rolling robust statistics
  (EMA + a streaming MAD proxy) as device-side state updated inside the same
  dispatch as the check;
- :mod:`.rollback` — in-memory last-known-good snapshots taken every K steps,
  restored (with RNG streams and optimizer bookkeeping) when a guard trips;
- :mod:`.hang` — a host-side heartbeat watchdog that converts a silent
  multi-host deadlock into stack dumps + a distinct exit code (or an in-process
  :class:`~.hang.HangDetected` for ``run_resilient`` to restart through);
- :mod:`.guard` — :class:`~.guard.HealthGuard`, the per-step orchestrator
  driven by ``Accelerator.guard_step()``: verdicts are drained without blocking
  the dispatch thread, trips are agreed across hosts with one scalar exchange
  (the :mod:`..resilience.preemption` pattern), and the chosen action —
  rollback or skip+quarantine — is applied identically on every host.

Drills: the fault plan grammar (``ACCELERATE_FAULT_PLAN``) accepts ``nan``,
``loss_spike:<mult>x`` and ``hang:<secs>`` kinds so every recovery path here
runs deterministically in CI. See ``docs/health.md``.
"""

from .guard import HealthGuard, HealthVerdict
from .hang import HANG_EXIT_CODE, HangDetected, HangWatchdog
from .numerics import NONFINITE_GRAD, NONFINITE_LOSS, NumericsSentinel, finite_scalar, nonfinite_leaves
from .rollback import LastKnownGood
from .spike import LOSS_SPIKE, SpikeDetector

__all__ = [
    "HANG_EXIT_CODE",
    "HangDetected",
    "HangWatchdog",
    "HealthGuard",
    "HealthVerdict",
    "LOSS_SPIKE",
    "LastKnownGood",
    "NONFINITE_GRAD",
    "NONFINITE_LOSS",
    "NumericsSentinel",
    "SpikeDetector",
    "finite_scalar",
    "nonfinite_leaves",
]
