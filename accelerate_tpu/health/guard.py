"""HealthGuard — the per-step orchestrator behind ``Accelerator.guard_step()``.

One call per training step, after the optimizer step, mirroring the
``checkpoint_on_preemption`` contract:

    verdict = accelerator.guard_step(loss)        # step defaults to accelerator.step
    if verdict.rolled_back:
        continue                                   # loop re-reads accelerator.step

Per step the guard does four things, none of which stall the dispatch thread:

1. **observe** — one jitted dispatch folds the numerics flags
   (:mod:`.numerics`) and the spike-statistics update (:mod:`.spike`) into a
   single int32 verdict that stays on device;
2. **drain** — pending verdicts whose results have materialized are fetched
   (a copy, not a stall — instrumented via :mod:`...utils.transfer`); unready
   verdicts wait, so detection may lag dispatch by a step or two on async
   backends but never serializes it;
3. **agree** — with >1 process the per-host flags are combined so EVERY host
   trips (or doesn't) at the same step: one scalar device collective (the
   :mod:`...resilience.preemption` idiom), falling back to the JAX
   coordination-service KV store on backends without multiprocess
   computations (the 2-process CPU harness);
4. **act** — healthy steps refresh the last-known-good snapshot every
   ``snapshot_every`` steps (:mod:`.rollback`); a trip either rolls every
   host back to the snapshot and quarantines the poisoned step, or just
   quarantines it (``on_trip="skip"``). Rollback wall-clock lands in the
   goodput ledger's ``rollback`` badput class.

Training loops consult :meth:`HealthGuard.should_skip` before computing a
step so a quarantined batch is never replayed — which is exactly what makes
the post-rollback trajectory bit-exact with a run that never saw the batch.
"""

from __future__ import annotations

import collections
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp

from ..logging import get_logger
from ..utils.transfer import array_is_ready, host_fetch
from .numerics import NONFINITE_GRAD, NONFINITE_LOSS, NumericsSentinel
from .rollback import LastKnownGood, restore_accelerator, snapshot_accelerator
from .spike import LOSS_SPIKE, SpikeDetector

logger = get_logger(__name__)

_FLAG_NAMES = {NONFINITE_LOSS: "non-finite loss", NONFINITE_GRAD: "non-finite grad norm", LOSS_SPIKE: "loss spike"}
_FLAG_BITS = 3


def describe_flags(flags: int) -> str:
    names = [name for bit, name in _FLAG_NAMES.items() if flags & bit]
    return " + ".join(names) if names else "healthy"


@dataclass
class HealthVerdict:
    """What ``guard_step`` decided for (up to) this step."""

    step: int
    flags: int = 0
    tripped: bool = False
    action: str | None = None  # "rollback" | "skip" | None
    resume_step: int | None = None
    quarantined_step: int | None = None
    rolled_back: bool = False
    zscore: float | None = None

    @property
    def description(self) -> str:
        return describe_flags(self.flags)


_GUARD_SEQ = 0


@dataclass
class _Pending:
    step: int
    flags: object  # int32 device scalar (OR over the window when idx is set)
    z: object  # float32 device scalar
    # In-window trip offset (int32 device scalar) for windowed verdicts:
    # ``step`` is then the FIRST in-window step and the tripped step resolves
    # to ``step + idx`` at drain time. None for per-step verdicts.
    idx: object = None


class HealthGuard:
    """See module docstring. ``numerics=False`` disables the finite checks,
    ``spike_zscore=0`` disables the spike detector; ``on_trip`` picks the
    recovery action; ``snapshot_every`` the last-known-good cadence."""

    def __init__(
        self,
        numerics: bool = True,
        check_grads: bool = True,
        spike_zscore: float = 6.0,
        spike_warmup: int = 20,
        ema_decay: float = 0.98,
        snapshot_every: int = 25,
        on_trip: str = "rollback",
        max_pending: int = 8,
        agreement_timeout_s: float = 120.0,
    ):
        if on_trip not in ("rollback", "skip"):
            raise ValueError(f"on_trip must be 'rollback' or 'skip', got {on_trip!r}")
        self.sentinel = NumericsSentinel(check_grads=check_grads) if numerics else None
        self.spike = (
            SpikeDetector(zscore=spike_zscore, warmup_steps=spike_warmup, ema_decay=ema_decay)
            if spike_zscore and spike_zscore > 0
            else None
        )
        self.lkg = LastKnownGood(every_steps=snapshot_every)
        self.on_trip = on_trip
        self.max_pending = int(max_pending)
        self.agreement_timeout_s = float(agreement_timeout_s)
        self.quarantined: set[int] = set()
        self.trips = 0
        self._spike_state = None
        self._pending: collections.deque[_Pending] = collections.deque()
        self._verdict_fns: dict = {}
        self._kv_agreement = False
        self._agree_epoch = 0
        # KV keys/barriers must be unique per (guard, step) and IDENTICAL
        # across ranks: ranks construct guards in the same program order, so a
        # process-wide construction counter lines up.
        global _GUARD_SEQ
        _GUARD_SEQ += 1
        self._guard_id = _GUARD_SEQ

    @property
    def enabled(self) -> bool:
        return self.sentinel is not None or self.spike is not None

    def reset_after_reshard(self, mesh):
        """Elastic world-size transition (resilience/elastic.py): snapshots
        and in-flight verdicts were captured on the old mesh — stale state
        that must be discarded, not restored. The spike statistics (tiny
        scalars) survive the move: the global batch is preserved across the
        transition, so the loss scale they model is unchanged."""
        self.lkg.clear()
        self._pending.clear()
        self._verdict_fns.clear()  # compiled against the old layout
        if self._spike_state is not None:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            self._spike_state = jax.tree_util.tree_map(
                lambda x: jax.device_put(x, NamedSharding(mesh, P()))
                if isinstance(x, jax.Array) else x,
                self._spike_state,
            )

    # ------------------------------------------------------------ quarantine
    def quarantine(self, step: int):
        """Mark ``step``'s batch poisoned: ``should_skip`` will skip it."""
        self.quarantined.add(int(step))

    def should_skip(self, step: int) -> bool:
        return int(step) in self.quarantined

    # --------------------------------------------------------------- observe
    def _get_verdict_fn(self, with_gnorm: bool):
        fn = self._verdict_fns.get(with_gnorm)
        if fn is None:
            sentinel, spike = self.sentinel, self.spike

            def verdict(state, loss, gnorm=None):
                flags = sentinel.flags(loss, gnorm) if sentinel is not None else jnp.int32(0)
                if spike is not None:
                    state, sflags, z = spike.update(state, loss)
                    flags = flags | sflags
                else:
                    z = jnp.float32(0.0)
                return state, flags, z

            fn = jax.jit(verdict) if with_gnorm else jax.jit(lambda s, l: verdict(s, l))
            self._verdict_fns[with_gnorm] = fn
        return fn

    def _get_window_verdict_fn(self):
        """Windowed dispatch: ONE jitted verdict over the K-vector of losses a
        fused train window returns — a ``lax.scan`` of the exact per-step
        update, so the spike statistics evolve bit-identically to K sequential
        scalar verdicts. Returns (state, OR-of-flags, z at the first trip,
        first-tripped in-window index)."""
        fn = self._verdict_fns.get("window")
        if fn is None:
            sentinel, spike = self.sentinel, self.spike

            def verdict(state, losses):
                def one(st, loss):
                    flags = sentinel.flags(loss) if sentinel is not None else jnp.int32(0)
                    if spike is not None:
                        st, sflags, z = spike.update(st, loss)
                        flags = flags | sflags
                    else:
                        z = jnp.float32(0.0)
                    return st, (flags, z)

                state, (flags_vec, z_vec) = jax.lax.scan(
                    one, state, jnp.asarray(losses, jnp.float32)
                )
                idx = jnp.argmax(flags_vec != 0).astype(jnp.int32)
                combined = jax.lax.reduce(
                    flags_vec, jnp.int32(0), jax.lax.bitwise_or, (0,)
                )
                return state, combined, z_vec[idx], idx

            fn = jax.jit(verdict)
            self._verdict_fns["window"] = fn
        return fn

    def observe(self, loss, gnorm=None, step: int = 0, window: int = 1):
        """Dispatch this step's on-device verdict; nothing is fetched here.
        With ``window > 1``, ``loss`` is the K-vector a fused train window
        retained and ``step`` the LAST in-window step; the grad-norm check is
        per-window-boundary state the fused program does not surface, so it
        does not apply there."""
        if not self.enabled:
            return
        if self._spike_state is None:
            self._spike_state = self.spike.init_state() if self.spike is not None else ()
        if window > 1:
            fn = self._get_window_verdict_fn()
            self._spike_state, flags, z, idx = fn(self._spike_state, loss)
            self._pending.append(
                _Pending(step=int(step) - int(window) + 1, flags=flags, z=z, idx=idx)
            )
            return
        fn = self._get_verdict_fn(gnorm is not None)
        args = (self._spike_state, loss) + ((gnorm,) if gnorm is not None else ())
        self._spike_state, flags, z = fn(*args)
        self._pending.append(_Pending(step=int(step), flags=flags, z=z))

    # ----------------------------------------------------------------- drain
    def _drain(self, force: bool = False):
        """Fetch materialized verdicts (all of them when ``force``); returns
        ``(or_of_flags, first_tripped_step, its_zscore)``."""
        flags, trip_step, trip_z = 0, None, None
        while self._pending:
            entry = self._pending[0]
            if not force and not array_is_ready(entry.flags):
                break
            self._pending.popleft()
            f = int(host_fetch(entry.flags))
            if f and trip_step is None:
                trip_step = entry.step
                if entry.idx is not None:  # windowed verdict: resolve in-window
                    trip_step += int(host_fetch(entry.idx))
                trip_z = float(host_fetch(entry.z))
            flags |= f
        return flags, trip_step, trip_z

    # ------------------------------------------------------------- agreement
    def _agree(self, local_flags: int, state) -> int:
        """All-host OR of the verdict bits: any host's trip is every host's
        trip, at the same step — the preemption-sync contract."""
        if state is None or getattr(state, "num_processes", 1) <= 1:
            return local_flags
        if not self._kv_agreement:
            try:
                from ..utils import operations as ops

                vec = np.asarray([(local_flags >> b) & 1 for b in range(_FLAG_BITS)], np.int32)
                total = host_fetch(ops.reduce(vec, reduction="sum"))
                return int(sum(1 << b for b in range(_FLAG_BITS) if total[b] > 0))
            except Exception as exc:
                logger.warning(
                    f"Device-collective health agreement unavailable "
                    f"({type(exc).__name__}: {exc}); using the coordination-service "
                    "KV exchange instead."
                )
                self._kv_agreement = True
        return self._agree_kv(local_flags, state)

    def _agree_kv(self, local_flags: int, state) -> int:
        from ..utils.agreement import kv_or_exchange

        self._agree_epoch += 1
        return kv_or_exchange(
            local_flags,
            state.num_processes,
            state.process_index,
            namespace=f"at_health/{self._guard_id}/{self._agree_epoch}",
            timeout_ms=int(self.agreement_timeout_s * 1000),
        )

    # ----------------------------------------------------------------- check
    def check(self, loss, gnorm=None, step: int = 0, state=None, window: int = 1):
        """Observe + drain + agree, no recovery action: returns
        ``(agreed_flags, trip_step, zscore)``. The building block shared by
        :meth:`guard_step` and loops driving the guard directly (e.g. the
        multi-host agreement drills)."""
        if loss is not None:
            self.observe(loss, gnorm=gnorm, step=step, window=window)
        multi = state is not None and getattr(state, "num_processes", 1) > 1
        # Multi-host: drain fully so every host votes on the same step window.
        flags, trip_step, z = self._drain(force=multi)
        while len(self._pending) > self.max_pending:
            f2, s2, z2 = self._drain(force=True)
            flags |= f2
            if trip_step is None:
                trip_step, z = s2, z2
        agreed = self._agree(flags, state)
        if agreed and trip_step is None:
            trip_step = int(step)  # a remote host tripped; adopt the shared step
        return agreed, trip_step, z

    # ------------------------------------------------------------- guard_step
    def guard_step(self, accelerator, loss, step: int, window: int = 1) -> HealthVerdict:
        """The full per-step protocol against a live :class:`Accelerator`.

        With ``window > 1`` the call runs once per fused train window: ``loss``
        is the retained K-vector, ``step`` the LAST in-window step, the verdict
        is one dispatch over all K losses, a trip's quarantine resolves to the
        exact in-window step, and snapshot capture fires at the window boundary
        whenever any in-window step crossed the snapshot cadence."""
        step = int(step)
        window = max(int(window), 1)
        if window > 1:
            loss = self._maybe_inject_window_faults(loss, step, window)
        else:
            loss = self._maybe_inject_fault(loss, step)
        gnorm = None
        # Under an fp16 GradScaler a non-finite grad norm is ROUTINE — the
        # scale-growth probe overflows by design, the jitted update already
        # skipped conditionally and the scaler backed off. Tripping (and
        # rolling back / quarantining a healthy batch) on it would fight the
        # scaler every growth interval, so the grad check defers to it.
        if (
            window == 1
            and self.sentinel is not None
            and self.sentinel.check_grads
            and getattr(accelerator, "scaler", None) is None
        ):
            for model in accelerator._models:
                if model.handle.last_grad_norm is not None:
                    gnorm = model.handle.last_grad_norm
                    break
        flags, trip_step, z = self.check(
            loss, gnorm=gnorm, step=step, state=accelerator.state, window=window
        )
        if not flags:
            if self.enabled and self.lkg.due(step, window=window):
                # No verdict drain here: the snapshot ring keeps one spare, and
                # rollback picks the newest snapshot OLDER than the trip — so a
                # capture that later turns out poisoned is skipped over rather
                # than guarded against with a blocking fetch.
                snapshot_accelerator(accelerator, self.lkg, step, extra_device=self._spike_state)
            return HealthVerdict(step=step)
        return self._handle_trip(accelerator, flags, trip_step if trip_step is not None else step, z)

    def _maybe_inject_fault(self, loss, step: int):
        if loss is None:
            # A loss-less guard_step (heartbeat/drain only) must not consume
            # the scheduled fault — it would mark the drill fired with nothing
            # injected; the fault waits for a step that reports its loss.
            return loss
        from ..resilience.faults import active_plan

        plan = active_plan()
        fault = plan.take_data_fault(step) if plan is not None else None
        if fault is None:
            return loss
        if fault.action == "nan":
            logger.warning(f"Fault injection: poisoning the step-{step} loss with NaN")
            return jnp.float32(jnp.nan)
        mult = float(str(fault.arg).rstrip("xX")) if fault.arg else 50.0
        logger.warning(f"Fault injection: spiking the step-{step} loss {mult:g}x")
        return jnp.asarray(loss, jnp.float32) * jnp.float32(mult)

    def _maybe_inject_window_faults(self, losses, step: int, window: int):
        """Windowed fault delivery: a ``nan``/``loss_spike`` fault scheduled at
        any in-window step poisons exactly that element of the K-vector, so a
        drill trips at — and quarantines — the right in-window step."""
        if losses is None:
            return losses
        from ..resilience.faults import active_plan

        plan = active_plan()
        if plan is None:
            return losses
        first = step - window + 1
        for i in range(window):
            fault = plan.take_data_fault(first + i)
            if fault is None:
                continue
            losses = jnp.asarray(losses, jnp.float32)
            if fault.action == "nan":
                logger.warning(
                    f"Fault injection: poisoning the step-{first + i} loss "
                    f"(window slot {i}) with NaN"
                )
                losses = losses.at[i].set(jnp.nan)
            else:
                mult = float(str(fault.arg).rstrip("xX")) if fault.arg else 50.0
                logger.warning(
                    f"Fault injection: spiking the step-{first + i} loss "
                    f"(window slot {i}) {mult:g}x"
                )
                losses = losses.at[i].multiply(jnp.float32(mult))
        return losses

    def _handle_trip(self, accelerator, flags: int, trip_step: int, z) -> HealthVerdict:
        self.trips += 1
        logger.error(
            f"Health guard tripped at step {trip_step}: {describe_flags(flags)}"
            + (f" (robust z={z:.2f})" if z else "")
        )
        # Flight recorder: the trip is a black-box moment — record it and dump
        # the event ring so the steps leading up to the poisoned batch are on
        # disk even if the run dies mid-recovery.
        from ..telemetry.flight import get_flight_recorder

        flight = get_flight_recorder()
        flight.record(
            "guard_trip", step=trip_step, verdict=describe_flags(flags),
            zscore=round(float(z), 3) if z else None, action=self.on_trip,
        )
        flight.dump("guard_trip")
        # Telemetry: trips (and rollbacks, below) land in the shared metrics
        # registry so scrapers/trackers see them next to goodput and restarts.
        from ..telemetry.metrics import get_registry

        get_registry().counter(
            "accelerate_health_trips_total",
            "Health-guard trips by verdict kind",
            labelnames=("kind",),
        ).inc(kind=describe_flags(flags))
        if flags & (NONFINITE_LOSS | NONFINITE_GRAD) and self.sentinel is not None:
            for model in accelerator._models:
                self.sentinel.attribute(model.handle.params, label="params")
        action = self.on_trip
        if action == "rollback" and self.lkg.snapshot_step(trip_step) is None:
            logger.error(
                "No last-known-good snapshot predates the trip; degrading to "
                "skip+quarantine."
            )
            action = "skip"
        self.quarantine(trip_step)
        self._pending.clear()  # the poisoned timeline's verdicts are moot
        rolled_back = False
        if action == "rollback":
            from ..resilience.goodput import get_ledger

            with get_ledger().track("rollback"):
                resume_step, spike_state = restore_accelerator(
                    accelerator, self.lkg, before_step=trip_step
                )
            # Anything captured at/after the trip sits on the discarded
            # timeline — a later trip must never restore it.
            self.lkg.discard_from(trip_step)
            if spike_state is not None:
                self._spike_state = spike_state
            rolled_back = True
            flight.record("rollback", step=trip_step, resume_step=resume_step)
            get_registry().counter(
                "accelerate_health_rollbacks_total",
                "Last-known-good rollbacks applied by the health guard",
            ).inc()
        else:
            resume_step = trip_step
        return HealthVerdict(
            step=trip_step,
            flags=flags,
            tripped=True,
            action=action,
            resume_step=resume_step,
            quarantined_step=trip_step,
            rolled_back=rolled_back,
            zscore=z,
        )
