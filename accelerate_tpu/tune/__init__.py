"""Profile-guided autotuner — close the loop from attribution to config.

The framework measures everything (per-step compute/collective/host/idle
attribution, the OOM-before-launch memcheck, the goodput ledger) and exposes
every lever as a flag; this package connects them so a new topology self-tunes
in minutes instead of a human sweeping flags:

1. :mod:`.space` — the declarative candidate grid over train_window ×
   xla_preset × vocab_chunk × remat_policy × zero_sharding × prefetch, seeded
   from ClusterConfig;
2. :mod:`.prune` — every candidate is lowered WITHOUT launching and the
   static HBM + program auditors discard predicted-OOM / invariant-violating
   points, booking why;
3. :mod:`.trials` — survivors run a warmup+N short bench with trace capture
   armed, booked as ``tune`` badput;
4. :mod:`.search` — the traceview attribution steers a successive-halving
   loop (idle → window/latency-preset, collective-bound →
   collective_matmul/ZeRO, memory-bound → remat/vocab-chunk);
5. :mod:`.report` — the ranked evidence report plus a ready-to-use winner
   ClusterConfig (``bench.py`` replays it via ``BENCH_FROM_TUNE``).

Surface: ``accelerate-tpu tune`` (commands/tune.py); docs/tuning.md.
"""

from .prune import (
    REASON_AUDIT_VIOLATION,
    REASON_BUILD_FAILED,
    REASON_PREDICTED_OOM,
    audit_failures,
    static_prune,
)
from .report import (
    TUNE_SCHEMA_VERSION,
    build_report,
    format_summary,
    load_report,
    load_winner,
    winner_cluster_config,
    write_report,
    write_winner_yaml,
)
from .search import (
    classify_bottleneck,
    propose_moves,
    run_search,
)
from .space import (
    DEFAULT_TUNE_BUDGET,
    Candidate,
    CandidateSpace,
)
from .trials import (
    DEFAULT_MEASURED_STEPS,
    DEFAULT_WARMUP_STEPS,
    TrialResult,
    TrialRig,
)

__all__ = [
    "Candidate",
    "CandidateSpace",
    "DEFAULT_MEASURED_STEPS",
    "DEFAULT_TUNE_BUDGET",
    "DEFAULT_WARMUP_STEPS",
    "REASON_AUDIT_VIOLATION",
    "REASON_BUILD_FAILED",
    "REASON_PREDICTED_OOM",
    "TUNE_SCHEMA_VERSION",
    "TrialResult",
    "TrialRig",
    "audit_failures",
    "build_report",
    "classify_bottleneck",
    "format_summary",
    "load_report",
    "load_winner",
    "propose_moves",
    "run_search",
    "static_prune",
    "winner_cluster_config",
    "write_report",
    "write_winner_yaml",
]
