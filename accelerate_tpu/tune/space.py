"""Candidate space — the declarative grid `accelerate-tpu tune` searches.

A :class:`Candidate` is one point in the lever space the framework already
exposes flag-by-flag: the K-step train window (``--train_window``), the XLA
latency-hiding preset (``--xla_preset``), the fused-loss vocab chunk
(``BENCH_VOCAB_CHUNK`` / ``LlamaConfig.fused_loss_chunk``), the remat policy,
ZeRO cross-replica optimizer sharding (``--zero_sharding``), and the device
batch prefetch depth (``BENCH_PREFETCH`` / ``DeviceBatchPrefetcher``).

:class:`CandidateSpace` holds the per-axis value lists (each ordered so the
search's "raise this lever" moves are well-defined), seeds from a
:class:`~..commands.config_args.ClusterConfig`, and enumerates the initial
one-change-at-a-time grid around the base point. Two candidates that differ
only in ``xla_preset`` or ``prefetch`` lower to the SAME program in one
process (presets are backend-init env flags, prefetch is host-side), which is
what lets prune.py audit one lowering per :meth:`Candidate.lowering_key` and
serve every candidate that shares it — the GSPMD one-program-many-configs
idiom (arxiv 2105.04663) applied to the tuner.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field

# Library-default short-bench trial budget (overridden by `tune --budget` /
# ACCELERATE_TUNE_BUDGET / ClusterConfig.tune_budget).
DEFAULT_TUNE_BUDGET = 16


@dataclass(frozen=True)
class Candidate:
    """One point in the lever space. Field defaults are the library defaults
    (per-step dispatch, no preset, model-default loss/remat, ZeRO off)."""

    train_window: int = 1
    xla_preset: str = "off"
    vocab_chunk: int = 0     # 0 = model default head (dense or its own chunk)
    remat_policy: str = ""   # '' = model default policy
    zero_sharding: bool = False
    prefetch: int = 0
    # Pallas kernel-layer spec (ops/registry.py; 'off' = reference lowerings,
    # 'pallas' = kernels where registered — compiled Mosaic on TPU, interpret
    # elsewhere). A compiled-in lever like train_window: different spec,
    # different lowered program.
    kernels: str = "off"

    def key(self) -> str:
        """Stable identity used for dedup, result joins, and the report."""
        return (
            f"w{self.train_window}"
            f".x{self.xla_preset}"
            f".c{self.vocab_chunk}"
            f".r{self.remat_policy or 'default'}"
            f".z{int(self.zero_sharding)}"
            f".p{self.prefetch}"
            f".k{self.kernels or 'off'}"
        )

    def lowering_key(self) -> str:
        """Identity of the LOWERED PROGRAM this candidate runs: excludes
        ``xla_preset`` (process-level env flags, fixed once the backend
        initialized) and ``prefetch`` (host-side feeding) — candidates sharing
        this key share one static audit."""
        return (
            f"w{self.train_window}"
            f".c{self.vocab_chunk}"
            f".r{self.remat_policy or 'default'}"
            f".z{int(self.zero_sharding)}"
            f".k{self.kernels or 'off'}"
        )

    def replace(self, **kw) -> "Candidate":
        return dataclasses.replace(self, **kw)

    def to_dict(self) -> dict:
        return {
            "train_window": self.train_window,
            "xla_preset": self.xla_preset,
            "vocab_chunk": self.vocab_chunk,
            "remat_policy": self.remat_policy,
            "zero_sharding": self.zero_sharding,
            "prefetch": self.prefetch,
            "kernels": self.kernels,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Candidate":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


@dataclass
class CandidateSpace:
    """Axis value lists, each in "raise the lever" order:

    - ``windows`` / ``prefetches``: ascending (more dispatch amortization);
    - ``presets``: more overlap to the right (off → latency →
      collective_matmul);
    - ``vocab_chunks``: toward LESS live-logits memory to the right (0 = model
      default first, then descending chunk sizes);
    - ``remat_policies``: toward MORE rematerialization (less activation
      memory) to the right, starting at '' (model default).
    """

    windows: tuple = (1, 2, 4, 8)
    presets: tuple = ("off", "latency", "collective_matmul")
    vocab_chunks: tuple = (0,)
    remat_policies: tuple = ("",)
    zero_sharding: tuple = (False, True)
    prefetches: tuple = (0, 2)
    # Kernel axis in "raise the lever" order: reference lowerings first, the
    # Pallas kernel layer to the right (the swap the autotuner measures
    # kernel-vs-reference, like any other compiled-in lever).
    kernels: tuple = ("off", "pallas")
    base: Candidate = field(default_factory=Candidate)

    def __post_init__(self):
        from ..utils.xla_flags import normalize_preset_name

        self.windows = tuple(sorted({int(w) for w in self.windows if int(w) >= 1}))
        self.presets = tuple(
            dict.fromkeys(normalize_preset_name(p) for p in self.presets)
        )
        self.vocab_chunks = tuple(dict.fromkeys(int(c) for c in self.vocab_chunks))
        self.remat_policies = tuple(dict.fromkeys(str(r) for r in self.remat_policies))
        self.zero_sharding = tuple(dict.fromkeys(bool(z) for z in self.zero_sharding))
        self.prefetches = tuple(
            sorted({int(p) for p in self.prefetches if int(p) >= 0})
        )
        from ..ops.registry import parse_kernel_spec

        for spec in self.kernels:
            parse_kernel_spec(spec if spec != "off" else "")  # validate
        self.kernels = tuple(dict.fromkeys(str(k) for k in self.kernels))
        # The base point must sit ON the grid — but it is the user's CURRENT
        # config, so the axes absorb it rather than the base being snapped to
        # the axes: a report claiming "winner vs current config" must have
        # trialed the actual current config, not a nearest grid point.
        self._absorb_base()

    def _absorb_base(self):
        base = self.base
        if base.train_window not in self.windows:
            self.windows = tuple(sorted(set(self.windows) | {base.train_window}))
        if base.xla_preset not in self.presets:
            # Keep the canonical overlap ordering (XLA_PRESETS declaration
            # order: off -> latency -> collective_matmul).
            from ..utils.xla_flags import XLA_PRESETS

            rank = {name: i for i, name in enumerate(XLA_PRESETS)}
            self.presets = tuple(sorted(
                set(self.presets) | {base.xla_preset}, key=lambda p: rank[p]
            ))
        if base.vocab_chunk not in self.vocab_chunks:
            # Prepend: the axis is ordered toward LESS live-logits memory, and
            # the current config is the least-aggressive point by definition.
            self.vocab_chunks = (base.vocab_chunk,) + self.vocab_chunks
        if base.remat_policy not in self.remat_policies:
            self.remat_policies = (base.remat_policy,) + self.remat_policies
        if base.zero_sharding not in self.zero_sharding:
            self.zero_sharding = tuple(sorted(
                set(self.zero_sharding) | {base.zero_sharding}
            ))
        if base.prefetch not in self.prefetches:
            self.prefetches = tuple(sorted(set(self.prefetches) | {base.prefetch}))
        if base.kernels not in self.kernels:
            # Prepend: the current config is the least-aggressive point.
            self.kernels = (base.kernels,) + self.kernels

    @classmethod
    def from_cluster_config(cls, cfg=None, **overrides) -> "CandidateSpace":
        """Seed the base point from a ClusterConfig's already-chosen levers
        (``train_window`` / ``xla_preset`` / ``zero_sharding``); axis
        overrides come from the CLI."""
        from ..utils.xla_flags import normalize_preset_name

        base = Candidate(
            train_window=int(getattr(cfg, "train_window", None) or 1),
            xla_preset=normalize_preset_name(getattr(cfg, "xla_preset", "") or "off"),
            zero_sharding=bool(getattr(cfg, "zero_sharding", None) or False),
        )
        return cls(base=base, **overrides)

    # ------------------------------------------------------------------ moves
    def _next(self, axis: tuple, value):
        """The value one step to the right of ``value`` on ``axis`` (None at
        the end or off-axis)."""
        try:
            i = axis.index(value)
        except ValueError:
            return None
        return axis[i + 1] if i + 1 < len(axis) else None

    def raise_window(self, c: Candidate) -> Candidate | None:
        nxt = self._next(self.windows, c.train_window)
        return c.replace(train_window=nxt) if nxt is not None else None

    def raise_prefetch(self, c: Candidate) -> Candidate | None:
        nxt = self._next(self.prefetches, c.prefetch)
        return c.replace(prefetch=nxt) if nxt is not None else None

    def raise_preset(self, c: Candidate, to: str | None = None) -> Candidate | None:
        """Move the preset right — to ``to`` when given (and actually to the
        right of the current), else one step."""
        if to is not None:
            if to not in self.presets:
                return None
            if self.presets.index(to) <= self.presets.index(c.xla_preset):
                return None
            return c.replace(xla_preset=to)
        nxt = self._next(self.presets, c.xla_preset)
        return c.replace(xla_preset=nxt) if nxt is not None else None

    def shrink_chunk(self, c: Candidate) -> Candidate | None:
        nxt = self._next(self.vocab_chunks, c.vocab_chunk)
        return c.replace(vocab_chunk=nxt) if nxt is not None else None

    def strengthen_remat(self, c: Candidate) -> Candidate | None:
        nxt = self._next(self.remat_policies, c.remat_policy)
        return c.replace(remat_policy=nxt) if nxt is not None else None

    def enable_zero(self, c: Candidate) -> Candidate | None:
        if c.zero_sharding or True not in self.zero_sharding:
            return None
        return c.replace(zero_sharding=True)

    def raise_kernels(self, c: Candidate) -> Candidate | None:
        """Move the kernel lever right (off → pallas): the compute-bound
        move — hot ops leave their reference lowerings for the kernel layer."""
        nxt = self._next(self.kernels, c.kernels)
        return c.replace(kernels=nxt) if nxt is not None else None

    # ------------------------------------------------------------------ seeds
    def seeds(self, limit: int | None = None) -> list:
        """The initial rung: the base point first (it is always trialed, so
        the report can state winner-vs-default), then every one-axis mutation
        of it, in deterministic axis order, deduped, optionally truncated."""
        out = [self.base]
        seen = {self.base.key()}
        mutations = []
        for w in self.windows:
            mutations.append(self.base.replace(train_window=w))
        for p in self.presets:
            mutations.append(self.base.replace(xla_preset=p))
        for chunk in self.vocab_chunks:
            mutations.append(self.base.replace(vocab_chunk=chunk))
        for r in self.remat_policies:
            mutations.append(self.base.replace(remat_policy=r))
        for z in self.zero_sharding:
            mutations.append(self.base.replace(zero_sharding=z))
        for pf in self.prefetches:
            mutations.append(self.base.replace(prefetch=pf))
        for k in self.kernels:
            mutations.append(self.base.replace(kernels=k))
        for m in mutations:
            if m.key() not in seen:
                seen.add(m.key())
                out.append(m)
        if limit is not None:
            out = out[: max(int(limit), 1)]
        return out

    def to_dict(self) -> dict:
        return {
            "windows": list(self.windows),
            "presets": list(self.presets),
            "vocab_chunks": list(self.vocab_chunks),
            "remat_policies": list(self.remat_policies),
            "zero_sharding": list(self.zero_sharding),
            "prefetches": list(self.prefetches),
            "kernels": list(self.kernels),
            "base": self.base.to_dict(),
        }
