"""Attribution-driven search — let the traceview report steer, not a sweep.

The measured attribution of a trial (telemetry/traceview.py fractions:
compute / collective / host / idle, disjoint by construction) plus the static
memory audit classify the current best candidate's bottleneck, and each
bottleneck names its moves on the lever space:

- **memory** (predicted peak within ``MEMORY_PRESSURE`` of the budget) →
  stronger remat policy, smaller/enabled vocab chunk, ZeRO sharding on (the
  1/dp opt-state drop);
- **collective** (exposed-collective fraction dominates) → the
  ``collective_matmul`` preset (windowed einsum overlaps the tp/sp gathers),
  ZeRO sharding (reduce-scatter + all-gather instead of a fat all-reduce);
- **idle** (idle + host fraction dominates: the device is starved, the
  dispatch RTT and input feeding are the tax) → raise the train window, the
  ``latency`` preset, deeper prefetch;
- **compute** — the device is busy doing math: the config is at its roofline,
  no move is proposed.

:func:`run_search` wraps the policy in a successive-halving loop: rung 0
short-benches every statically-pruned seed at ``base_steps`` measured steps;
each later rung keeps the top ``keep_fraction`` (re-trialed at doubled steps —
the halving refinement) plus the bottleneck-proposed neighbors of the current
best (statically pruned before they cost a trial). The loop stops when the
trial budget is spent, nothing new is proposed and every keeper is refined, or
``max_rounds`` is hit. Every decision is booked in the returned trail so the
report can show the search's reasoning, not just its ranking.

This module is deliberately engine-free: candidates go in, ``(candidate,
result-dict)`` pairs come out, and the prune/trial callables are injected —
the real adapters live in trials.py / commands/tune.py, deterministic
synthetic fixtures drive the policy tests.
"""

from __future__ import annotations

import math

# Classification outcomes.
BOTTLENECK_MEMORY = "memory"
BOTTLENECK_COLLECTIVE = "collective"
BOTTLENECK_IDLE = "idle"
BOTTLENECK_COMPUTE = "compute"
BOTTLENECK_UNKNOWN = "unknown"

# Predicted peak at/above this fraction of the HBM budget = memory-bound:
# headroom this thin turns into a compile-time OOM on the next shape/batch
# bump, so the search spends moves buying memory back before chasing speed.
MEMORY_PRESSURE = 0.8
# An exposed fraction at/above this is worth spending a move on.
DOMINANCE = 0.25


def classify_bottleneck(
    fractions: dict | None,
    predicted_peak_bytes: int = 0,
    budget_bytes: int = 0,
    memory_pressure: float = MEMORY_PRESSURE,
    dominance: float = DOMINANCE,
) -> str:
    """One bottleneck label for a trial's evidence. ``fractions`` is the
    traceview disjoint attribution (may be None when the trial ran without a
    parseable capture — then only the memory verdict can classify)."""
    if budget_bytes and predicted_peak_bytes >= memory_pressure * budget_bytes:
        return BOTTLENECK_MEMORY
    if not fractions:
        return BOTTLENECK_UNKNOWN
    idle = float(fractions.get("idle", 0.0)) + float(fractions.get("host", 0.0))
    collective = float(fractions.get("collective", 0.0))
    if collective >= dominance and collective >= idle:
        return BOTTLENECK_COLLECTIVE
    if idle >= dominance:
        return BOTTLENECK_IDLE
    return BOTTLENECK_COMPUTE


def propose_moves(candidate, bottleneck: str, space) -> list:
    """The ordered, deduped neighbor candidates the bottleneck names.
    Compute-bound steps have one lever: the Pallas kernel layer — hot ops
    leave their reference lowerings (``raise_kernels``). Unknown stays
    empty: nothing to steer by."""
    moves = []
    if bottleneck == BOTTLENECK_COMPUTE:
        moves = [space.raise_kernels(candidate)]
    elif bottleneck == BOTTLENECK_MEMORY:
        moves = [
            space.strengthen_remat(candidate),
            space.shrink_chunk(candidate),
            space.enable_zero(candidate),
        ]
    elif bottleneck == BOTTLENECK_COLLECTIVE:
        moves = [
            space.raise_preset(candidate, to="collective_matmul"),
            space.enable_zero(candidate),
        ]
    elif bottleneck == BOTTLENECK_IDLE:
        moves = [
            space.raise_window(candidate),
            space.raise_preset(candidate, to="latency"),
            space.raise_prefetch(candidate),
        ]
    out, seen = [], set()
    for m in moves:
        if m is not None and m.key() not in seen:
            seen.add(m.key())
            out.append(m)
    return out


def run_search(
    space,
    *,
    prune_fn,
    trial_fn,
    trial_budget: int,
    seeds=None,
    base_steps: int = 4,
    max_rounds: int = 4,
    keep_fraction: float = 0.5,
):
    """The successive-halving loop (see module docstring).

    ``prune_fn(candidates) -> (kept, dropped)`` is :func:`~.prune
    .static_prune` bound to an audit adapter; ``trial_fn(candidate, evidence,
    steps) -> dict | None`` short-benches one candidate for ``steps`` measured
    steps and returns its result dict (``step_time_s`` required; ``fractions``
    / ``predicted_peak_bytes`` / ``budget_bytes`` steer the policy; None =
    trial failed, candidate is skipped).

    Returns ``(ranked, dropped, trail)``: ``ranked`` is ``[(candidate,
    result), ...]`` best-first by ``step_time_s`` (each candidate's
    longest-rung result), ``dropped`` the booked static prunes, ``trail`` the
    per-round decision log."""
    seeds = space.seeds() if seeds is None else list(seeds)
    rung, dropped = prune_fn(seeds)
    evidence = {cand.key(): ev for cand, ev in rung}
    best = {}     # key -> (candidate, result, steps_ran): the longest rung's
    # Keys that must never be (re-)proposed: trial failures persist across
    # rounds (a deterministically-failing candidate must not re-spend budget
    # every time the same bottleneck re-proposes it), and already-pruned keys
    # must not re-prune into duplicate `dropped` bookings.
    failed_ever = set()
    dropped_keys = {d["key"] for d in dropped}
    trail = []
    budget = int(trial_budget)
    steps = max(int(base_steps), 1)
    for round_idx in range(max(int(max_rounds), 1)):
        if not rung or budget <= 0:
            break
        trialed, failed = [], []
        for cand, ev in rung:
            if budget <= 0:
                break
            prev = best.get(cand.key())
            if prev is not None and prev[2] >= steps:
                continue  # already measured at this rung or a longer one
            result = trial_fn(cand, ev, steps)
            budget -= 1
            if result is None:
                failed.append(cand.key())
                failed_ever.add(cand.key())
                continue
            best[cand.key()] = (cand, result, steps)
            trialed.append(cand.key())
        # Rank the CURRENT rung (the global best is always a member: keepers
        # are the top of the previous rung's ranking).
        rung_ranked = sorted(
            (best[c.key()] for c, _ in rung if c.key() in best),
            key=lambda t: t[1]["step_time_s"],
        )
        if not rung_ranked:
            # Every trial this round failed (or budget ran dry before one
            # succeeded): book the round so the spent budget stays visible in
            # the trail — an empty trail would misread as "never trialed".
            trail.append({
                "round": round_idx,
                "measured_steps": steps,
                "trialed": trialed,
                "failed": failed,
                "best": None,
                "best_step_time_s": None,
                "bottleneck": None,
                "proposed": [],
                "pruned": [],
            })
            break
        top_cand, top_result, _ = rung_ranked[0]
        bottleneck = classify_bottleneck(
            top_result.get("fractions"),
            int(top_result.get("predicted_peak_bytes", 0) or 0),
            int(top_result.get("budget_bytes", 0) or 0),
        )
        proposals = [
            c for c in propose_moves(top_cand, bottleneck, space)
            if c.key() not in best and c.key() not in failed_ever
            and c.key() not in dropped_keys
        ]
        fresh, newly_dropped = prune_fn(proposals) if proposals else ([], [])
        dropped += newly_dropped
        dropped_keys.update(d["key"] for d in newly_dropped)
        evidence.update({cand.key(): ev for cand, ev in fresh})
        # Halving: the rung's top keep_fraction graduate to the next rung and
        # are re-trialed at doubled measured steps (the refinement), alongside
        # the bottleneck-proposed fresh candidates.
        n_keep = max(1, math.ceil(len(rung_ranked) * keep_fraction))
        keepers = [
            (cand, evidence.get(cand.key()))
            for cand, _result, _steps in rung_ranked[:n_keep]
        ]
        trail.append({
            "round": round_idx,
            "measured_steps": steps,
            "trialed": trialed,
            "failed": failed,
            "best": top_cand.key(),
            "best_step_time_s": top_result["step_time_s"],
            "bottleneck": bottleneck,
            "proposed": [c.key() for c in proposals],
            "pruned": [d["key"] for d in newly_dropped],
        })
        steps *= 2
        rung = fresh + keepers
        if not fresh and len(keepers) <= 1:
            # Nothing new to explore and the rung has halved to the winner —
            # further rounds would only re-measure it.
            break
    ranked = sorted(best.values(), key=lambda t: t[1]["step_time_s"])
    return [(cand, result) for cand, result, _steps in ranked], dropped, trail
