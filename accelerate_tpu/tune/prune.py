"""Static prune — discard candidates before they cost a single chip second.

Every candidate is LOWERED, never launched: the existing static analyzers run
on the lowered artifact — the program auditor (analysis/audit.py: collective
inventory per mesh axis, donation aliasing, host callbacks) and the static HBM
auditor (analysis/memory.py: per-device byte attribution and the
OOM-before-launch verdict). A candidate is dropped when

- the memory auditor predicts OOM (``predicted_peak_bytes`` over the budget —
  the per-generation HBM × headroom default, or the tuner's ``--budget-gib``
  override), reason ``predicted_oom``; or
- the program audit is not clean (a dp-axis all-gather, host callback, or
  donation miss — the same zero-tolerance set ``accelerate-tpu audit`` exits 1
  on), reason ``audit_violation``.

Each drop is booked with the failure detail and the audit/memory evidence —
including the candidate's short program-fingerprint hash
(analysis/fingerprint.py), so trial rankings and drop bookings alike name the
EXACT program they judged, not just the flag tuple that requested it — and
the tune report can show WHY a point in the space was never trialed.

The audit callable is injected (``audit_fn(candidate) -> (evidence,
failures)``) — trials.py provides the real lower-and-audit adapter (cached per
:meth:`~.space.Candidate.lowering_key`), whose evidence dict carries
``{"audit", "memory", "fingerprint"}``; tests drive the prune logic with
synthetic verdicts.
"""

from __future__ import annotations

# Machine-readable drop reasons (the report's ``dropped[].reason`` values).
REASON_PREDICTED_OOM = "predicted_oom"
REASON_AUDIT_VIOLATION = "audit_violation"
REASON_BUILD_FAILED = "build_failed"


def audit_failures(audit_summary: dict | None, memory_summary: dict | None,
                   budget_bytes: int | None = None) -> list:
    """The prune verdicts for one lowered candidate, from the analyzers'
    summary dicts (``AuditReport.summary_dict()`` / ``MemoryReport
    .summary_dict()`` — also exactly what ``audit --json`` / ``memcheck
    --json`` put under ``report``). ``budget_bytes`` overrides the memory
    report's own budget for the OOM verdict."""
    failures = []
    if memory_summary is not None:
        peak = int(memory_summary.get("predicted_peak_bytes", 0))
        budget = int(
            budget_bytes if budget_bytes is not None
            else memory_summary.get("budget_bytes", 0)
        )
        if budget and peak > budget:
            failures.append({
                "reason": REASON_PREDICTED_OOM,
                "detail": (
                    f"predicted OOM: peak {peak} B/device exceeds budget "
                    f"{budget} B"
                ),
            })
    if audit_summary is not None and not audit_summary.get("clean", True):
        failures.append({
            "reason": REASON_AUDIT_VIOLATION,
            "detail": (
                "program audit not clean: "
                f"dp_allgathers={audit_summary.get('dp_allgathers')}, "
                f"host_callbacks={audit_summary.get('host_callbacks')}, "
                f"donation_misses={audit_summary.get('donation_misses')}"
            ),
        })
    return failures


def static_prune(candidates, audit_fn):
    """Lower-and-audit each candidate via ``audit_fn`` and split the list into
    survivors and booked drops.

    ``audit_fn(candidate)`` returns ``(evidence, failures)`` where
    ``evidence`` is ``{"audit": summary|None, "memory": summary|None}`` and
    ``failures`` is a possibly-empty list of ``{"reason", "detail"}`` dicts
    (:func:`audit_failures` builds them from real reports). An ``audit_fn``
    that raises books the candidate as ``build_failed`` — a candidate whose
    program cannot even be built must not kill the sweep.

    Returns ``(kept, dropped)``: ``kept`` is ``[(candidate, evidence), ...]``
    in input order; ``dropped`` entries carry the candidate, reasons, details,
    and evidence."""
    kept, dropped = [], []
    for candidate in candidates:
        try:
            evidence, failures = audit_fn(candidate)
        except Exception as exc:
            dropped.append({
                "candidate": candidate.to_dict(),
                "key": candidate.key(),
                "reason": REASON_BUILD_FAILED,
                "failures": [{
                    "reason": REASON_BUILD_FAILED,
                    "detail": f"{type(exc).__name__}: {exc}"[:300],
                }],
                "evidence": None,
            })
            continue
        if failures:
            dropped.append({
                "candidate": candidate.to_dict(),
                "key": candidate.key(),
                # Headline reason = the first failure; the full list rides.
                "reason": failures[0]["reason"],
                "failures": list(failures),
                "evidence": evidence,
            })
        else:
            kept.append((candidate, evidence))
    return kept, dropped
