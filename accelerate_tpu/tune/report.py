"""Evidence report — the ranked outcome of a tune run, plus the winner config.

One schema'd JSON document (``TUNE_SCHEMA_VERSION``) carries everything a
reader — human, CI, or ``bench.py``'s ``BENCH_FROM_TUNE`` replay — needs:

- ``ranked``: every trialed candidate best-first by measured per-step time,
  each with its step time, MFU estimate, traceview attribution fractions,
  predicted peak bytes vs budget, and the program-audit summary;
- ``dropped``: the statically-pruned candidates with their booked reasons
  (``predicted_oom`` / ``audit_violation`` / ``build_failed``) and evidence;
- ``search_trail``: the per-round decision log (bottleneck classification,
  proposed moves, prunes) so the search's reasoning is auditable;
- ``winner`` / ``baseline`` / ``winner_vs_baseline``: the best candidate, the
  base (current-config) candidate's own trial, and the speedup between them;
- ``goodput``: the run's ledger summary — the trials' wall-clock shows up as
  the ``tune`` badput class, never as productive step time.

:func:`winner_cluster_config` turns the winner into a ready-to-use
:class:`~..commands.config_args.ClusterConfig` (``train_window`` /
``xla_preset`` / ``zero_sharding`` are first-class fields; the model-level
levers ride ``extra`` as ``tune_*`` keys so the yaml round-trips losslessly),
and :func:`load_winner` reads a report back for the bench replay path.
"""

from __future__ import annotations

import json
import os

TUNE_SCHEMA_VERSION = 1


def build_report(
    *,
    ranked,
    dropped,
    trail,
    space,
    trial_budget: int,
    trials_run: int,
    backend: str | None = None,
    device: str | None = None,
) -> dict:
    """Assemble the report dict from ``run_search`` outputs (``ranked`` is
    ``[(Candidate, result_dict), ...]`` best-first)."""
    from ..resilience.goodput import get_ledger

    ranked_entries = [
        {"rank": i + 1, **result} for i, (_cand, result) in enumerate(ranked)
    ]
    base_key = space.base.key()
    baseline = next((e for e in ranked_entries if e["key"] == base_key), None)
    winner = ranked_entries[0] if ranked_entries else None
    vs = None
    if winner is not None and baseline is not None and baseline["step_time_s"] > 0:
        vs = {
            "winner_step_time_s": winner["step_time_s"],
            "baseline_step_time_s": baseline["step_time_s"],
            "speedup": round(baseline["step_time_s"] / winner["step_time_s"], 4)
            if winner["step_time_s"] > 0 else None,
        }
    return {
        "schema_version": TUNE_SCHEMA_VERSION,
        "tool": "accelerate-tpu tune",
        "backend": backend,
        "device": device,
        "trial_budget": int(trial_budget),
        "trials_run": int(trials_run),
        "space": space.to_dict(),
        "base": space.base.to_dict(),
        "ranked": ranked_entries,
        "dropped": list(dropped),
        "search_trail": list(trail),
        "winner": winner,
        "baseline": baseline,
        "winner_vs_baseline": vs,
        "goodput": get_ledger().summary(),
    }


def winner_cluster_config(winner_candidate: dict, base_cfg=None):
    """A ClusterConfig carrying the winner's levers: the launcher-native
    fields directly, the model-level levers (vocab chunk, remat policy,
    prefetch) as ``tune_*`` extras — ready for ``launch --config_file``."""
    import copy

    from ..commands.config_args import ClusterConfig

    cfg = copy.deepcopy(base_cfg) if base_cfg is not None else ClusterConfig()
    cfg.train_window = int(winner_candidate.get("train_window", 1))
    cfg.xla_preset = str(winner_candidate.get("xla_preset", "off"))
    cfg.zero_sharding = bool(winner_candidate.get("zero_sharding", False))
    extras = dict(getattr(cfg, "extra", None) or {})
    extras.update({
        "tune_vocab_chunk": int(winner_candidate.get("vocab_chunk", 0)),
        "tune_remat_policy": str(winner_candidate.get("remat_policy", "")),
        "tune_prefetch": int(winner_candidate.get("prefetch", 0)),
        "tuned_by": "accelerate-tpu tune",
    })
    cfg.extra = extras
    return cfg


def write_winner_yaml(path: str, winner_candidate: dict, base_cfg=None) -> str:
    cfg = winner_cluster_config(winner_candidate, base_cfg=base_cfg)
    cfg.to_yaml_file(path)
    return path


def write_report(path: str, report: dict) -> str:
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    return path


def load_report(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        report = json.load(f)
    version = report.get("schema_version")
    if version != TUNE_SCHEMA_VERSION:
        raise ValueError(
            f"{path!r} has tune schema_version {version!r}; this build reads "
            f"{TUNE_SCHEMA_VERSION}"
        )
    return report


def load_winner(path: str) -> dict:
    """The winner's flat candidate dict from a report file — the
    ``BENCH_FROM_TUNE`` consumer. Raises on a report without a winner (a run
    where every candidate was pruned has nothing to replay)."""
    report = load_report(path)
    winner = report.get("winner")
    if not winner or "candidate" not in winner:
        raise ValueError(f"{path!r} records no winner to replay")
    return dict(winner["candidate"])


def format_summary(report: dict, top: int = 5) -> str:
    """The human-facing ranked table `tune` prints (the full evidence lives
    in the JSON)."""
    lines = []
    backend = report.get("backend") or "?"
    lines.append(
        f"tune: {report['trials_run']}/{report['trial_budget']} trials on "
        f"{backend}, {len(report['ranked'])} candidate(s) ranked, "
        f"{len(report['dropped'])} statically pruned"
    )
    for entry in report["ranked"][:top]:
        frac = entry.get("fractions") or {}
        attrib = (
            " compute/coll/host/idle="
            f"{frac.get('compute')}/{frac.get('collective')}"
            f"/{frac.get('host')}/{frac.get('idle')}"
            if frac else ""
        )
        lines.append(
            f"  #{entry['rank']} {entry['key']}: "
            f"{entry['step_time_s'] * 1e3:.2f} ms/step "
            f"(mfu~{entry['mfu_est']:.4f}, peak {entry['predicted_peak_bytes']} B)"
            + attrib
        )
    for drop in report["dropped"]:
        lines.append(f"  pruned {drop['key']}: {drop['reason']}")
    vs = report.get("winner_vs_baseline")
    if vs and vs.get("speedup") is not None:
        lines.append(
            f"winner vs current config: {vs['speedup']:.2f}x "
            f"({vs['baseline_step_time_s'] * 1e3:.2f} -> "
            f"{vs['winner_step_time_s'] * 1e3:.2f} ms/step)"
        )
    return "\n".join(lines)
