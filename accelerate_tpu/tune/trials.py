"""Short-bench trials — measure a surviving candidate for warmup+N steps.

One :class:`TrialRig` owns the model-shape fixture (the tiny train config by
default — the same fixture ``accelerate-tpu audit`` / ``memcheck`` lower) and
builds each candidate's artifact: window program vs per-step program, fused
vocab-chunked loss, remat policy, ZeRO sharding, prefetcher. The built
artifacts are cached per :meth:`~.space.Candidate.lowering_key`, so the static
prune's lowering is the SAME program object the trial then executes, and
candidates differing only in env-level levers (preset) or host-side levers
(prefetch) never recompile.

:func:`run_trial` reuses bench.py's fixed-step discipline — dispatch counts
derived from steps ÷ window, sync only at the measured region's edges — with
the PR-8 capture machinery armed: a per-trial
:class:`~..telemetry.profiler.ProfileManager` manual capture brackets the
measured region, and its parsed traceview attribution (compute / collective /
host / idle fractions) rides the trial result to steer the search.

Accounting: the ENTIRE trial wall-clock (build, compile, warmup, measured
steps) books as the goodput ledger's ``tune`` badput class — trial steps are
never recorded as productive ``step`` time, so a tuned job's MFU/goodput
reflects training only. Capture overhead the ProfileManager already booked as
``profile`` badput is subtracted from the ``tune`` booking so the two classes
never double-count one second.
"""

from __future__ import annotations

import contextlib
import gc
import os
import tempfile
import time
from dataclasses import dataclass

from .space import Candidate

# Default short-bench shape: bench.py's fixed-discipline numbers scaled down —
# enough measured steps to rank, cheap enough to run a dozen trials in minutes.
DEFAULT_WARMUP_STEPS = 2
DEFAULT_MEASURED_STEPS = 8


@dataclass
class BuiltCandidate:
    """One lowered/compiled artifact and everything a trial needs to drive it."""

    candidate: Candidate
    accelerator: object
    model_config: object
    built: object          # build_train_step / build_train_window output
    base_batch: dict       # one per-step host batch
    window: int
    tokens_per_step: int
    flops_per_token: float
    params: int


@dataclass
class TrialResult:
    candidate: Candidate
    measured_steps: int
    warmup_steps: int
    step_time_s: float
    steps_per_sec: float
    tokens_per_sec: float
    mfu_est: float
    final_loss: float
    wall_s: float
    compile_s: float
    fractions: dict | None = None
    overlap_fraction: float | None = None
    trace_dir: str | None = None
    predicted_peak_bytes: int = 0
    budget_bytes: int = 0
    audit: dict | None = None
    memory: dict | None = None
    fingerprint: str | None = None   # short program-identity hash
    xla_preset_flags: tuple = ()
    preset_applied: bool = True

    def to_dict(self) -> dict:
        return {
            "candidate": self.candidate.to_dict(),
            "key": self.candidate.key(),
            "measured_steps": self.measured_steps,
            "warmup_steps": self.warmup_steps,
            "step_time_s": round(self.step_time_s, 6),
            "steps_per_sec": round(self.steps_per_sec, 3),
            "tokens_per_sec": round(self.tokens_per_sec, 1),
            "mfu_est": round(self.mfu_est, 4),
            "final_loss": round(self.final_loss, 4),
            "wall_s": round(self.wall_s, 3),
            "compile_s": round(self.compile_s, 3),
            "fractions": self.fractions,
            "overlap_fraction": self.overlap_fraction,
            "trace_dir": self.trace_dir,
            "predicted_peak_bytes": self.predicted_peak_bytes,
            "budget_bytes": self.budget_bytes,
            "audit": self.audit,
            "memory": self.memory,
            "fingerprint": self.fingerprint,
            "xla_preset_flags": list(self.xla_preset_flags),
            "preset_applied": self.preset_applied,
        }


class TrialRig:
    """Builds, audits, and short-benches candidates on one fixture shape.

    ``batch_rows`` / ``seq`` / ``optimizer`` mirror the ``memcheck`` CLI
    fixture knobs (adamw default: the 2-moments-per-param worst case that
    makes the ZeRO and memory levers visible). ``model_config`` overrides the
    tiny Llama for callers tuning a real shape. ``budget_bytes`` overrides the
    HBM budget the prune verdict gates on (the ``--budget-gib`` path).
    """

    def __init__(
        self,
        batch_rows: int = 8,
        seq: int = 16,
        optimizer: str = "adamw",
        model_config=None,
        budget_bytes: int | None = None,
        profile_dir: str | None = None,
        start_trace=None,
        stop_trace=None,
    ):
        self.batch_rows = int(batch_rows)
        self.seq = int(seq)
        self.optimizer = optimizer
        self.model_config = model_config
        self.budget_bytes = budget_bytes
        self.profile_dir = profile_dir
        self._start_trace = start_trace
        self._stop_trace = stop_trace
        self._built: dict = {}      # lowering_key -> BuiltCandidate
        self._evidence: dict = {}   # lowering_key -> (evidence, failures)

    # ---------------------------------------------------------------- builder
    def _model_config(self, candidate: Candidate):
        from ..models import LlamaConfig

        base = self.model_config if self.model_config is not None else LlamaConfig.tiny()
        kw = {}
        if candidate.vocab_chunk > 0:
            kw["fused_loss"] = True
            kw["fused_loss_chunk"] = min(candidate.vocab_chunk, base.vocab_size)
        if candidate.remat_policy:
            kw["remat"] = True
            kw["remat_policy"] = candidate.remat_policy
        if not kw:
            return base
        cfg = type(base)(**{**_config_dict(base), **kw})
        return cfg

    def build(self, candidate: Candidate) -> BuiltCandidate:
        """The candidate's artifact, cached per lowering_key (preset and
        prefetch do not change the lowered program in-process)."""
        key = candidate.lowering_key()
        cached = self._built.get(key)
        if cached is not None:
            return cached
        import numpy as np
        import jax
        import optax

        from ..accelerator import Accelerator
        from ..models import Llama

        cfg = self._model_config(candidate)
        accelerator = Accelerator()
        accelerator.zero_sharding = candidate.zero_sharding
        # Kernel lever: 'off' = reference lowerings ('' spec), anything else
        # is the registry spec verbatim (resolved per op at build/trace time).
        accelerator.kernels = "" if candidate.kernels == "off" else candidate.kernels
        model = Llama(cfg)
        model.init_params(jax.random.key(0))
        tx = {
            "sgd": lambda: optax.sgd(0.1),
            "adamw": lambda: optax.adamw(3e-4),
            "adafactor": lambda: optax.adafactor(3e-4),
        }[self.optimizer]()
        pmodel, popt = accelerator.prepare(model, tx)
        if candidate.train_window > 1:
            built = accelerator.build_train_window(
                pmodel, popt, window=candidate.train_window
            )
        else:
            built = accelerator.build_train_step(pmodel, popt)
        ids = np.random.default_rng(0).integers(
            0, cfg.vocab_size, (self.batch_rows, self.seq)
        ).astype(np.int32)
        base_batch = {"input_ids": ids, "labels": ids}
        n_params = model.num_params()
        attn_flops = 12 * cfg.num_hidden_layers * cfg.hidden_size * self.seq
        out = BuiltCandidate(
            candidate=candidate,
            accelerator=accelerator,
            model_config=cfg,
            built=built,
            base_batch=base_batch,
            window=candidate.train_window,
            tokens_per_step=self.batch_rows * self.seq,
            flops_per_token=6 * n_params + attn_flops,  # fwd+bwd, bench.py's form
            params=n_params,
        )
        self._built[key] = out
        return out

    # ------------------------------------------------------------ prune hooks
    def audit_candidate(self, candidate: Candidate):
        """The ``audit_fn`` contract of :func:`~.prune.static_prune`: lower
        (without running), audit program + memory, and return ``(evidence,
        failures)`` — cached per lowering_key like the build."""
        import numpy as np

        from .prune import audit_failures

        key = candidate.lowering_key()
        cached = self._evidence.get(key)
        if cached is not None:
            return cached
        built = self.build(candidate)
        if built.window > 1:
            audit_batch = {
                k: np.stack([v] * built.window) for k, v in built.base_batch.items()
            }
        else:
            audit_batch = built.base_batch
        report = built.accelerator.audit(built.built, audit_batch)
        audit_summary = report.summary_dict()
        memory_summary = (
            report.memory.summary_dict() if report.memory is not None else None
        )
        # Program identity: the short fingerprint hash names the exact
        # program this candidate lowers (and, if kept, measures) — rides the
        # evidence into both the pruned-drop bookings and the trial rankings.
        from ..analysis.fingerprint import fingerprint_built, fingerprint_hash

        fp = fingerprint_built(
            built.built, audit_batch,
            config=f"tune_{candidate.key()}", report=report,
        )
        evidence = {
            "audit": audit_summary,
            "memory": memory_summary,
            "fingerprint": fingerprint_hash(fp),
        }
        failures = audit_failures(
            audit_summary, memory_summary, budget_bytes=self.budget_bytes
        )
        self._evidence[key] = (evidence, failures)
        return evidence, failures

    # ----------------------------------------------------------------- trials
    def run_trial(
        self,
        candidate: Candidate,
        evidence: dict | None = None,
        measured_steps: int = DEFAULT_MEASURED_STEPS,
        warmup_steps: int = DEFAULT_WARMUP_STEPS,
        capture: bool = True,
    ) -> TrialResult:
        """Short-bench one candidate; see the module docstring for the
        discipline and accounting. Returns the TrialResult (raises on trial
        failure — commands/tune.py converts that into a skipped candidate)."""
        import numpy as np

        from ..resilience.goodput import get_ledger
        from ..telemetry.profiler import ProfileManager
        from ..telemetry.timeline import device_peak_flops
        from ..utils.xla_flags import (
            active_preset_flags,
            install_xla_preset,
            _backend_already_initialized,
        )

        ledger = get_ledger()
        t_start = time.perf_counter()
        profile_before = ledger.summary()["profile_s"]
        try:
            # The preset is an env-level lever read once at backend init:
            # install records the ask and the resolved flag list for the
            # evidence report, but cannot re-apply to a live backend —
            # preset_applied says which happened (always False mid-tune on a
            # real TPU; inert-but-true before first backend touch).
            preset_applied = not _backend_already_initialized()
            install_xla_preset(candidate.xla_preset)
            preset_flags_resolved = active_preset_flags()

            built = self.build(candidate)
            window = built.window
            warmup_disp = max(int(warmup_steps) // window, 1)
            meas_disp = max(int(measured_steps) // window, 1)
            total_disp = warmup_disp + meas_disp

            if window > 1:
                window_batch = {
                    k: np.stack([v] * window) for k, v in built.base_batch.items()
                }
            else:
                window_batch = built.base_batch
            if candidate.prefetch > 0:
                from ..data_loader import DeviceBatchPrefetcher

                def _stream(n=total_disp * window):
                    for _ in range(n):
                        yield built.base_batch

                batches = iter(DeviceBatchPrefetcher(
                    _stream(), mesh=built.accelerator.mesh,
                    prefetch=candidate.prefetch, window=window,
                ))
                next_batch = lambda: next(batches)  # noqa: E731
            else:
                next_batch = lambda: window_batch  # noqa: E731

            step = built.built

            def _sync(x):
                # Deliberate, counted host sync (utils/transfer.py discipline);
                # under windowed dispatch x is the per-step K-vector — the last
                # element is the newest step's loss.
                from ..utils.transfer import host_fetch

                return float(host_fetch(x).reshape(-1)[-1])

            t_compile = time.perf_counter()
            loss = step(next_batch())
            _sync(loss)
            compile_s = time.perf_counter() - t_compile
            for _ in range(warmup_disp - 1):
                loss = step(next_batch())
            _sync(loss)

            manager = None
            cm = contextlib.nullcontext(None)
            if capture:
                manager = ProfileManager(
                    output_dir=self.profile_dir
                    or os.path.join(tempfile.gettempdir(), "accelerate_tune_traces"),
                    max_captures=1,
                    start_trace=self._start_trace,
                    stop_trace=self._stop_trace,
                )
                trace_dir = os.path.join(
                    manager.output_dir, f"trial_{candidate.key()}"
                )
                cm = manager.manual_capture(trace_dir=trace_dir)
            with cm:
                t0 = time.perf_counter()
                for _ in range(meas_disp):
                    loss = step(next_batch())
                final_loss = _sync(loss)
                dt = time.perf_counter() - t0
            # Capture stop + traceview parse ran at `with` exit — outside the
            # timed region, booked by the manager as `profile` badput.

            steps_ran = meas_disp * window
            steps_per_sec = steps_ran / dt
            tokens_per_sec = steps_per_sec * built.tokens_per_step
            # Peak FLOPs and chip count come from the LIVE mesh the trial ran
            # on, not a raw device-list baseline (elastic reshards change it).
            mesh_devices = built.accelerator.mesh.devices
            mfu = (
                tokens_per_sec * built.flops_per_token
                / (device_peak_flops(mesh_devices.flat[0]) * mesh_devices.size)
            )

            fractions = overlap = trace_path = None
            if manager is not None and manager.captures:
                record = manager.captures[-1]
                trace_path = record.get("trace_dir")
                report = record.get("report")
                if report is not None:
                    fractions = report.get("fractions")
                    overlap = report.get("overlap_fraction")

            ev = evidence or {}
            memory_summary = ev.get("memory") or {}
            return TrialResult(
                candidate=candidate,
                measured_steps=steps_ran,
                warmup_steps=warmup_disp * window,
                step_time_s=dt / steps_ran,
                steps_per_sec=steps_per_sec,
                tokens_per_sec=tokens_per_sec,
                mfu_est=float(mfu),
                final_loss=final_loss,
                wall_s=time.perf_counter() - t_start,
                compile_s=compile_s,
                fractions=fractions,
                overlap_fraction=overlap,
                trace_dir=trace_path,
                predicted_peak_bytes=int(
                    memory_summary.get("predicted_peak_bytes", 0) or 0
                ),
                budget_bytes=int(
                    self.budget_bytes
                    if self.budget_bytes is not None
                    else memory_summary.get("budget_bytes", 0) or 0
                ),
                audit=ev.get("audit"),
                memory=ev.get("memory") or None,
                fingerprint=ev.get("fingerprint"),
                xla_preset_flags=preset_flags_resolved,
                preset_applied=preset_applied,
            )
        finally:
            # The WHOLE trial is `tune` badput, minus whatever the capture
            # machinery already booked as `profile` during it (stop/parse) —
            # the two classes must partition the wall-clock, not double it.
            wall = time.perf_counter() - t_start
            profile_delta = ledger.summary()["profile_s"] - profile_before
            ledger.add("tune", max(wall - profile_delta, 0.0))
            gc.collect()  # drop this candidate's arrays before the next build


def _config_dict(cfg) -> dict:
    """A model config's constructor kwargs (dataclass or attrs-style)."""
    import dataclasses

    if dataclasses.is_dataclass(cfg):
        return {f.name: getattr(cfg, f.name) for f in dataclasses.fields(cfg)}
    return dict(vars(cfg))
