from .losses import cross_entropy_loss, mse_loss
