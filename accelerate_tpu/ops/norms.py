"""Shared normalization primitives for the model zoo.

One fp32-accumulated LayerNorm serves GPT-2, BERT, GPTX, and Whisper (each
previously carried a byte-equivalent copy); RMSNorm lives in ``models/llama.py``
next to its rope siblings. The fp32 round-trip is the mixed-precision contract:
statistics and the affine transform run in fp32, the output returns in the
input dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def layer_norm(x, scale, bias, eps=1e-5):
    dtype = x.dtype
    x = x.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    return ((x - mean) * jax.lax.rsqrt(var + eps) * scale + bias).astype(dtype)
