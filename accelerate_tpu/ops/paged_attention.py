"""Paged (block-table) KV-cache attention — reference lowering + pool helpers.

The serving engine's paged mode (``serving.ContinuousBatcher(paged=True)``)
keeps each layer's KV cache as a **block pool**: a device-resident
``(num_blocks, block_size, kv_heads, head_dim)`` array per layer plus per-slot
**block tables** mapping a request's logical token chain onto pool blocks
(vLLM's layout, shaped for XLA's static-compilation model — every shape here
is fixed at engine construction, so nothing recompiles as traffic changes).
Allocation and free are host-side free-list surgery; cross-request prefix
sharing is refcounted aliasing of full blocks.

This module is the op-level seam:

- :func:`init_kv_pool` / :func:`gather_block_view` / :func:`gather_block_mask`
  are the pool primitives the engine's compiled programs are built from. The
  gather is the **reference lowering** — an XLA gather over the block axis
  that materializes each slot's chain as a contiguous per-slot view, which
  the model's ordinary ``cached_attention`` path then consumes unchanged (so
  every model family — rope, learned wpe, sliding windows, softcap — stays
  bit-exact with zero model changes).
- :func:`export_chain_blocks` / :func:`import_chain_blocks` are the KV-chain
  handoff faces: a finished prefill's block chain leaves one host's pool and
  splices into another's (serving_net/handoff.py) as a bounded per-chain
  transfer — pool blocks are the unit of ownership, so disaggregated
  prefill/decode never copies a whole cache.
- :func:`paged_attention` is the fused op face: one call from query chunk +
  pools + block tables to attention output. The **reference lowering**
  (:func:`paged_attention_reference`) composes the gather with
  :func:`~.attention.cached_attention`; the ROADMAP item 3 Pallas
  ragged-decode kernel (``ops/pallas/paged_decode.py``) sits behind this
  exact signature via the kernel registry (``ops/registry.py``,
  ``ACCELERATE_KERNELS``) — it walks each slot's block chain in-kernel with
  no materialized gather view and skips padded slots, matching the
  reference bit-for-bit on active slots (tests/test_kernels.py pins it; see
  ``benchmarks/kernel_profile.py`` for the op-level attribution harness
  that measures the swap).

Block-size note for that kernel: TPU VMEM tiles are (sublane × 128-lane) with
an 8/16/32-row sublane minimum by dtype, so ``block_size`` should stay a
multiple of 16 (the bf16 sublane) for the eventual kernel to stream blocks
without repacking — the engine's default is 16.

Pool invariants (shared with serving.py):

- Block 0 is the **trash block**: never allocated, never referenced by a
  committed table entry, and its mask rows stay zero — so unassigned table
  entries (0) gather as masked garbage that attention provably ignores.
- ``pool["mask"]`` is per-token validity (1 = real token), the paged analog
  of the contiguous cache's ``kv_mask``: bucket-padding holes and
  inactive-step decode writes are masked out, and sliding windows measure
  VALID-slot distance (``cached_attention``), so holes never stretch a
  window.
- Rope/wpe rotations are baked into K at write time from the *token position
  channel*, not the chain slot — which is what makes a full block's K/V a
  pure function of (params, token prefix) and therefore shareable across any
  requests whose prompts start with the same tokens.
"""

from __future__ import annotations

import jax.numpy as jnp

from .attention import cached_attention


def init_kv_pool(module, num_blocks: int, block_size: int, dtype=jnp.bfloat16,
                 quant: str | None = None):
    """Allocate the per-layer block pool for ``module``'s cache layout.

    Returns ``{"k": (L, N, bs, Hkv, D), "v": same, "mask": (N, bs) int32}``
    with ``N = num_blocks + 1`` — block 0 is the reserved trash block (see
    module docstring). The layer/head/dim axes are probed from the module's
    own ``init_cache`` so every cached decoder family (Llama/GPT-2/GPT-X)
    gets its exact layout without a second cache contract.

    ``quant="int8"`` stores the K/V payloads as int8 and adds per-block scale
    tables ``{"k_scale": (L, N, bs) float32, "v_scale": same}`` — one scale
    per token row per layer (``ops/int8.quantize_kv``), so the pool costs
    ``1 + 8/(2·Hkv·D)`` bytes per bf16 element instead of 2: ~1.9x the
    chains per HBM byte at realistic head counts. Dequantization happens at
    view-assembly time (``gather_view`` / the Pallas DMA kernels), never in
    the pool itself."""
    if quant not in (None, "int8"):
        raise ValueError(f"kv pool quant must be None or 'int8', got {quant!r}")
    probe = module.init_cache(1, block_size, dtype=dtype)
    L, _, _, hkv, hd = probe["k"].shape
    n = num_blocks + 1
    store = jnp.int8 if quant == "int8" else dtype
    pool = {
        "k": jnp.zeros((L, n, block_size, hkv, hd), store),
        "v": jnp.zeros((L, n, block_size, hkv, hd), store),
        "mask": jnp.zeros((n, block_size), jnp.int32),
    }
    if quant == "int8":
        pool["k_scale"] = jnp.zeros((L, n, block_size), jnp.float32)
        pool["v_scale"] = jnp.zeros((L, n, block_size), jnp.float32)
    return pool


def pool_is_quantized(pool) -> bool:
    """Whether a pool carries int8 payloads + per-block scale tables."""
    return "k_scale" in pool


def export_chain_blocks(pool, block_ids):
    """Extract one chain's K/V/mask block contents from the pool: the device
    face of the prefill→decode KV handoff (serving_net/handoff.py).

    ``block_ids``: ``(n,)`` int32 pool block indices in chain order. Returns
    ``{"k": (L, n, bs, Hkv, D), "v": same, "mask": (n, bs)}`` — a bounded
    per-chain payload (n blocks, never the pool), which is the whole point
    of the paged layout: ownership moves block-by-block without copying the
    cache. Pure gather; safe to jit or call eagerly."""
    ids = jnp.asarray(block_ids, jnp.int32)
    chain = {
        "k": jnp.take(pool["k"], ids, axis=1),
        "v": jnp.take(pool["v"], ids, axis=1),
        "mask": jnp.take(pool["mask"], ids, axis=0),
    }
    if pool_is_quantized(pool):
        # Quantized chains ship int8 payloads + their scales: the handoff
        # wire cost drops with the pool, and the importer splices verbatim.
        chain["k_scale"] = jnp.take(pool["k_scale"], ids, axis=1)
        chain["v_scale"] = jnp.take(pool["v_scale"], ids, axis=1)
    return chain


def import_chain_blocks(pool, block_ids, chain):
    """Splice an exported chain's block contents into ``pool`` at freshly
    allocated ``block_ids`` — the decode-host half of the handoff. The
    caller (host free-list surgery in serving_net/handoff.py) guarantees the
    ids are allocated and disjoint from every live chain; the mask is written
    verbatim, so bucket-padding holes stay holes and stale bits of the
    reused blocks are overwritten rather than frontier-masked. Returns the
    updated pool (donation-friendly: one scatter per array)."""
    ids = jnp.asarray(block_ids, jnp.int32)
    out = {
        "k": pool["k"].at[:, ids].set(chain["k"].astype(pool["k"].dtype)),
        "v": pool["v"].at[:, ids].set(chain["v"].astype(pool["v"].dtype)),
        "mask": pool["mask"].at[ids].set(chain["mask"]),
    }
    if pool_is_quantized(pool):
        if "k_scale" not in chain:
            raise ValueError(
                "import_chain_blocks: quantized pool but the chain carries no "
                "scales — exporter and importer must agree on kv_quant"
            )
        out["k_scale"] = pool["k_scale"].at[:, ids].set(chain["k_scale"])
        out["v_scale"] = pool["v_scale"].at[:, ids].set(chain["v_scale"])
    return out


def gather_block_view(pool_kv, block_tables, *, active=None, scales=None,
                      out_dtype=None):
    """Materialize per-slot contiguous KV views from the pool.

    ``pool_kv``: ``(..., N, bs, H, D)`` (a single layer or the L-stacked
    pool); ``block_tables``: ``(B, M)`` int32 block ids. Returns
    ``(..., B, M*bs, H, D)`` — slot ``b``'s chain left-packed in table order.
    This is the reference XLA-gather lowering of paged attention.

    ``scales`` (``(..., N, bs)`` per-block scale tables of a quantized pool)
    arms the dequant seam: the int8 view is gathered together with its
    scales and dequantized per token row (``q.astype(f32) * scale``, then a
    cast to ``out_dtype`` — float32 by default). This exact expression is
    what the Pallas chain-walk kernel replays after its DMA, so reference
    and kernel stay bit-identical on active slots.

    ``active`` (per-slot flags) is accepted for signature parity with the
    chain-walk kernel (``ops/pallas/paged_decode.gather_block_view_kernel``,
    which skips inactive slots); the reference gathers every slot — inactive
    rows are masked garbage either way, and only the kernel bothers to skip
    them. Use :func:`gather_view` for registry-dispatched assembly."""
    del active  # reference computes all slots; masks make the garbage inert
    m = block_tables.shape[-1]
    view = jnp.take(pool_kv, block_tables, axis=-4)  # (..., B, M, bs, H, D)
    view = view.reshape(view.shape[:-4] + (m * view.shape[-3],) + view.shape[-2:])
    if scales is None:
        return view if out_dtype is None else view.astype(out_dtype)
    s = jnp.take(scales, block_tables, axis=-2)  # (..., B, M, bs)
    s = s.reshape(s.shape[:-2] + (m * s.shape[-1],))
    deq = view.astype(jnp.float32) * s[..., None, None].astype(jnp.float32)
    return deq.astype(out_dtype if out_dtype is not None else jnp.float32)


def gather_view(pool_kv, block_tables, *, active=None, scales=None,
                out_dtype=None, backend=None):
    """Registry-dispatched view assembly (op ``paged_gather``): the Pallas
    chain-walk kernel when ``ACCELERATE_KERNELS`` (or ``backend``) selects
    it, the XLA-gather reference otherwise. Bit-identical for active slots
    (pure data movement, or gather+dequant when ``scales`` arms the int8
    path); the kernel skips ``active == 0`` slots."""
    from .registry import dispatch, resolve_backend

    if resolve_backend("paged_gather", backend) == "reference":
        return gather_block_view(pool_kv, block_tables, active=active,
                                 scales=scales, out_dtype=out_dtype)
    return dispatch(
        "paged_gather", pool_kv, block_tables, active=active, scales=scales,
        out_dtype=out_dtype, backend=backend,
    )


def gather_block_mask(pool_mask, block_tables):
    """Per-slot validity view: ``(N, bs)`` pool mask + ``(B, M)`` tables →
    ``(B, M*bs)`` — the paged analog of the contiguous cache's ``kv_mask``."""
    b, m = block_tables.shape
    return jnp.take(pool_mask, block_tables, axis=0).reshape(b, m * pool_mask.shape[1])


def paged_attention_reference(q, k_pool, v_pool, block_tables, *, q_positions,
                              pool_mask=None, window=None, softcap=None,
                              scale=None, active=None, k_scale=None,
                              v_scale=None):
    """The reference lowering: gather each slot's chain to a contiguous view,
    then run the hole-tolerant :func:`~.attention.cached_attention`
    (causality on chain-slot order, validity from the gathered mask, sliding
    windows in valid-slot distance). This is the committed parity seam — the
    Pallas kernel must match it bit-for-bit on active slots on the test
    vectors in tests/test_paged_attention.py and tests/test_kernels.py.
    ``active`` is accepted for kernel-signature parity and ignored (the
    reference computes masked garbage for inactive slots). ``k_scale`` /
    ``v_scale`` (``(N, bs)`` per-block scale tables) arm the int8-pool path:
    views dequantize to float32 before the shared attention math, mirroring
    the kernel's dequant-in-DMA step."""
    del active
    k_view = gather_block_view(k_pool, block_tables, scales=k_scale)
    v_view = gather_block_view(v_pool, block_tables, scales=v_scale)
    kv_mask = (
        gather_block_mask(pool_mask, block_tables) if pool_mask is not None else None
    )
    return cached_attention(
        q, k_view, v_view, q_positions=q_positions, kv_mask=kv_mask,
        window=window, softcap=softcap, scale=scale,
    )


def paged_attention(q, k_pool, v_pool, block_tables, *, q_positions,
                    pool_mask=None, window=None, softcap=None, scale=None,
                    active=None, k_scale=None, v_scale=None, backend=None):
    """Attention of a query chunk against block-table-addressed KV pools.

    q: ``(B, S, H, D)``; k_pool/v_pool: ``(N, bs, Hkv, D)`` (one layer);
    block_tables: ``(B, M)``; q_positions: ``(S,)`` or ``(B, S)`` positions in
    each slot's *chain-slot* index space (chain slot ``j`` of slot ``b`` is
    view column ``j``); pool_mask: ``(N, bs)`` per-token validity;
    ``active``: optional per-slot flags — the Pallas backend skips inactive
    (bucket-padded) slots entirely and returns zeros for them.

    Dispatches through the kernel registry (op ``paged_decode``): the Pallas
    ragged kernel walks each slot's block chain in VMEM with no materialized
    gather view when ``ACCELERATE_KERNELS`` (or ``backend``) selects it; the
    reference gather+``cached_attention`` composition otherwise."""
    from .registry import dispatch, resolve_backend

    if resolve_backend("paged_decode", backend) == "reference":
        return paged_attention_reference(
            q, k_pool, v_pool, block_tables, q_positions=q_positions,
            pool_mask=pool_mask, window=window, softcap=softcap, scale=scale,
            active=active, k_scale=k_scale, v_scale=v_scale,
        )
    return dispatch(
        "paged_decode", q, k_pool, v_pool, block_tables,
        q_positions=q_positions, pool_mask=pool_mask, window=window,
        softcap=softcap, scale=scale, active=active, k_scale=k_scale,
        v_scale=v_scale, backend=backend,
    )
