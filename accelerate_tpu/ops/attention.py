"""Attention dispatch: dense / pallas-flash / ring.

The hot op of every transformer. Several implementations behind one
interface (layout (B, S, H, D), GQA-aware, causal + padding mask):

- ``dense``  — einsum attention, fp32 softmax. Runs anywhere; O(S²) HBM.
- ``flash``  — Pallas TPU flash kernel (block-streamed, O(S) HBM, fwd+bwd in
  VMEM). We use the Mosaic flash kernel shipped *inside JAX*
  (``jax.experimental.pallas.ops.tpu.flash_attention``) — it is part of the
  platform, tuned per TPU generation, with a custom-VJP backward.
- ``splash`` — Pallas block-sparse splash kernel: native local (sliding
  window) masks and tanh logit softcapping — the Mistral/Gemma-2 recipes at
  flash memory/compute (auto-selected for windowed/capped attention at long
  context; measured 1.46x over dense fwd+bwd at S=4096/w=1024 on v5e, with
  the gap growing as the window covers less of S).
- ``ring``   — sequence-parallel ring attention over the mesh ``sp`` axis
  (``parallel/ring.py``): each device holds a sequence chunk, KV chunks rotate
  via ``ppermute`` while flash-style running-softmax statistics merge. The
  reference framework has NO native sequence parallelism (SURVEY.md §2.4) —
  this is the long-context story.

Padding is encoded as segment ids (padding tokens live in their own segment so
real↔pad pairs are masked inside the kernel).
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp

# Dense/flash crossover by device kind: below this sequence length the S²
# einsum rides the MXU faster than the block-streamed kernel. Re-measured with
# benchmarks/attention_crossover.py after tuning the kernel block sizes
# (_flash_block_sizes — the library's 128-everywhere default was the round-2
# bottleneck): on v5 lite flash at S<=1024 lands below the tunnel's host-RTT
# measurement floor (dense doesn't), S=4096 is 1.2ms vs 15.3ms, and at the
# 725M train step flash@1024 measures 57.1% MFU vs 50.1% dense. Override with
# ACCELERATE_FLASH_MIN_SEQ.
_FLASH_CROSSOVER = {"TPU v5 lite": 512, "TPU v5e": 512}
_DEFAULT_FLASH_MIN_SEQ = 1024


@functools.lru_cache(maxsize=1)
def _device_flash_min_seq() -> int:
    try:
        kind = jax.devices()[0].device_kind
    except Exception:
        return _DEFAULT_FLASH_MIN_SEQ
    return _FLASH_CROSSOVER.get(kind, _DEFAULT_FLASH_MIN_SEQ)


def _flash_min_seq() -> int:
    env = os.environ.get("ACCELERATE_FLASH_MIN_SEQ")  # read per call: overridable
    if env:
        return int(env)
    return _device_flash_min_seq()


def repeat_kv(k, v, n_rep: int):
    if n_rep == 1:
        return k, v
    return jnp.repeat(k, n_rep, axis=2), jnp.repeat(v, n_rep, axis=2)


def softcap_scores(scores, cap):
    """Gemma-2 logit softcapping: ``tanh(scores / cap) * cap`` (bounds the
    magnitude smoothly while keeping gradients; applied before masks)."""
    return jnp.tanh(scores / cap) * cap


def dense_attention(q, k, v, *, causal=True, mask=None, positions_q=None, positions_kv=None,
                    window=None, softcap=None, scale=None):
    """q: (B,S,H,D), k/v: (B,Skv,H,D); mask: (B,Skv) 1=real. fp32 softmax.

    ``window``: sliding-window size (Mistral recipe) — a query attends keys
    with ``0 <= q_pos - k_pos < window`` (plus itself); None = full causal.
    ``softcap``: tanh cap on the scores (Gemma-2). ``scale``: query scaling
    override (Gemma-2's query_pre_attn_scalar**-0.5); default 1/sqrt(D)."""
    if window is not None and not causal:
        # Clipping only past keys while future keys stay fully visible matches
        # no known model recipe; reject rather than compute silently-asymmetric
        # semantics (advisor r2).
        raise ValueError("window requires causal=True (bidirectional windows unsupported)")
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if softcap is not None:
        scores = softcap_scores(scores, softcap)
    bias = jnp.zeros_like(scores)
    if causal or window is not None:
        if positions_q is None:
            positions_q = jnp.arange(q.shape[1])
        if positions_kv is None:
            positions_kv = jnp.arange(k.shape[1])
        delta = positions_q[:, None] - positions_kv[None, :]
        keep = delta >= 0 if causal else jnp.ones_like(delta, bool)
        if window is not None:
            keep = keep & (delta < window)
        bias = jnp.where(keep[None, None], bias, -1e30)
    if mask is not None:
        bias = bias + jnp.where(mask[:, None, None, :].astype(bool), 0.0, -1e30)
    probs = jax.nn.softmax(scores + bias, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _flash_available() -> bool:
    if jax.default_backend() != "tpu":
        return False
    try:
        from jax.experimental.pallas.ops.tpu import flash_attention  # noqa

        return True
    except ImportError:
        return False


def _flash_block_sizes(q_len: int, kv_len: int):
    """Tile sizes for the Mosaic flash kernel. The library default is 128
    everywhere (its own TODO admits no heuristic was picked), which at long
    sequence lengths costs >5x on the backward: measured fwd+bwd at
    (B2,H11,S4096,D128) on v5e, 128-blocks take 75.4 ms/iter vs 14.0 ms with
    512-blocks. Use the largest block <= 512 dividing the sequence lengths;
    override with ACCELERATE_FLASH_BLOCK."""
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes

    want = int(os.environ.get("ACCELERATE_FLASH_BLOCK", 512))
    bq = bk = 128
    for b in sorted({want, 512, 256, 128}, reverse=True):
        if b <= want and b % 128 == 0 and q_len % b == 0 and kv_len % b == 0:
            bq = bk = b
            break
    return BlockSizes(
        block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk, block_q_dkv=bq,
        block_k_major_dq=bk, block_k_dq=bk, block_q_dq=bq,
    )


def flash_attention(q, k, v, *, causal=True, mask=None):
    """Pallas TPU flash attention; layout (B,S,H,D) in, internally (B,H,S,D)."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        SegmentIds,
        flash_attention as _flash,
    )

    scale = 1.0 / np.sqrt(q.shape[-1])
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    segment_ids = None
    if mask is not None:
        # real tokens: segment 2, padding: segment 1 — pads only see pads
        seg = jnp.where(mask.astype(bool), 2, 1).astype(jnp.int32)
        segment_ids = SegmentIds(q=seg, kv=seg)
    out = _flash(
        qt, kt, vt, segment_ids=segment_ids, causal=causal, sm_scale=scale,
        block_sizes=_flash_block_sizes(q.shape[1], k.shape[1]),
    )
    return jnp.swapaxes(out, 1, 2)


def _splash_available() -> bool:
    if jax.default_backend() != "tpu":
        return False
    try:
        from jax.experimental.pallas.ops.tpu.splash_attention import (  # noqa
            splash_attention_kernel,
        )

        return True
    except ImportError:
        return False


def splash_attention(q, k, v, *, causal=True, mask=None, window=None, softcap=None,
                     scale=None):
    """Pallas TPU splash-attention kernel — the block-sparse flash variant that
    natively supports **local (sliding-window) masks** and **tanh logit
    softcapping**, i.e. the Mistral and Gemma-2 attention recipes at flash
    memory/compute characteristics (the plain Mosaic flash kernel supports
    neither, which previously forced those models onto the O(S²) dense path
    for long context).

    Layout (B,S,H,D) in; q is pre-scaled (the kernel applies no scale, so the
    Gemma-2 ``query_pre_attn_scalar`` override folds in here); GQA KV heads
    are repeated; padding rides segment ids like the flash wrapper.
    """
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk,
        splash_attention_mask as sm,
    )

    if not causal:
        raise ValueError("splash_attention is causal-only (the mask is built causal)")
    if k.shape[1] != q.shape[1]:
        raise ValueError(
            f"splash_attention needs equal q/kv lengths, got {q.shape[1]} vs "
            f"{k.shape[1]}; use impl='dense' for cross-length attention."
        )
    B, S, H, D = q.shape
    if k.shape[2] != H:  # GQA: repeat KV heads
        rep = H // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    if scale is None:
        scale = 1.0 / np.sqrt(D)
    qt = (jnp.swapaxes(q, 1, 2) * jnp.asarray(scale, q.dtype)).astype(q.dtype)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)

    if window is not None:
        # Our window semantics: attend keys with 0 <= q_pos - k_pos < window.
        base = sm.LocalMask((S, S), window_size=(window - 1, 0), offset=0)
    else:
        base = sm.CausalMask((S, S))
    kernel = sk.make_splash_mha(
        sm.MultiHeadMask([base] * H),
        head_shards=1,
        q_seq_shards=1,
        attn_logits_soft_cap=softcap,
    )
    if mask is not None:
        seg = jnp.where(mask.astype(bool), 2, 1).astype(jnp.int32)  # pads see pads
        seg_ids = sk.SegmentIds(q=seg, kv=seg)
        out = jax.vmap(lambda qq, kk, vv, ss: kernel(qq, kk, vv, segment_ids=ss))(
            qt, kt, vt, seg_ids
        )
    else:
        out = jax.vmap(kernel)(qt, kt, vt)
    return jnp.swapaxes(out, 1, 2)


def cached_attention(q, k_cache, v_cache, *, q_positions, kv_mask=None, window=None,
                     softcap=None, scale=None):
    """Attention of a query chunk against a pre-allocated KV cache (decode path).

    q: (B, S, H, D); k_cache/v_cache: (B, K, Hkv, D) with H = G·Hkv (GQA).
    q_positions: (S,) or (B, S) global positions of the queries.
    kv_mask: (B, K) validity of cache slots (1 = real token). Slots beyond the
    write offset are excluded by the causal comparison alone.

    Sliding windows measure VALID-slot distance when a ``kv_mask`` is given: a
    key is in a query's window iff fewer than ``window`` valid slots separate
    them. On a contiguous cache this equals plain slot distance, so the
    ordinary generate() path is unchanged — but hole-punched caches (the
    serving engine's slot scheme, batched speculative rollback) stay exact:
    holes no longer stretch the window, which is what made windowed models
    unsupported there (VERDICT r4 missing #3). Costs one (B, K) cumsum + an
    (B, S) gather per forward — noise next to the cache GEMV.

    TPU shape notes: queries are grouped (B,S,Hkv,G,D) so the GQA repeat never
    materializes — the einsum contracts each KV head against its G query heads
    directly. For S=1 decode this is a bandwidth-bound GEMV over the cache,
    which is the best any kernel can do; no flash kernel needed.
    """
    B, S, H, D = q.shape
    K, Hkv = k_cache.shape[1], k_cache.shape[2]
    G = H // Hkv
    if scale is None:
        scale = 1.0 / np.sqrt(D)
    qg = q.reshape(B, S, Hkv, G, D)
    scores = jnp.einsum("bshgd,bkhd->bhgsk", qg, k_cache).astype(jnp.float32) * scale
    if softcap is not None:
        scores = softcap_scores(scores, softcap)
    if q_positions.ndim == 1:
        q_positions = jnp.broadcast_to(q_positions[None], (B, S))
    delta = q_positions[:, None, None, :, None] - jnp.arange(K)[None, None, None, None, :]
    keep = delta >= 0
    if window is not None:  # sliding window: the last `window` valid tokens
        if kv_mask is not None:
            rank = jnp.cumsum(kv_mask.astype(jnp.int32), axis=1)  # (B, K)
            q_rank = jnp.take_along_axis(rank, q_positions.astype(jnp.int32), axis=1)
            dvalid = q_rank[:, None, None, :, None] - rank[:, None, None, None, :]
            keep = keep & (dvalid < window)
        else:
            keep = keep & (delta < window)
    bias = jnp.where(keep, 0.0, -1e30)
    if kv_mask is not None:
        bias = bias + jnp.where(kv_mask[:, None, None, None, :].astype(bool), 0.0, -1e30)
    probs = jax.nn.softmax(scores + bias, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhgsk,bkhd->bshgd", probs, v_cache)
    return out.reshape(B, S, H, D)


def resolve_auto_impl(seq_len: int, num_heads: int, head_dim: int,
                      batch: int = 1, *, kv_len: int | None = None,
                      causal: bool = True, window=None, softcap=None,
                      scale=None) -> str:
    """What ``impl='auto'`` resolves to for this shape/recipe — the single
    source of the dispatch predicate, shared by ``attention()`` and
    introspection (bench.py logs it as driver-visible evidence of the kernel
    in use). Windowed/softcapped/scaled recipes resolve to the splash kernel
    (which supports them natively) above the crossover; plain attention to
    the Mosaic flash kernel; everything else to dense."""
    kv_len = seq_len if kv_len is None else kv_len
    shapes_ok = (seq_len >= 128 and seq_len % 128 == 0) and (
        head_dim % 128 == 0 or head_dim in (64, 96, 256)
    )
    if window is not None or softcap is not None or scale is not None:
        if (
            causal
            and kv_len == seq_len
            and _splash_available()
            and shapes_ok
            and seq_len >= _flash_min_seq()
        ):
            return "splash"
        return "dense"
    return (
        "flash"
        if _flash_available() and shapes_ok and seq_len >= _flash_min_seq()
        else "dense"
    )


def attention(q, k, v, *, causal=True, mask=None, impl: str = "auto", mesh=None, window=None,
              softcap=None, scale=None):
    """Unified entry used by the model zoo.
    ``impl``: auto|dense|flash|splash|ring|ulysses. ``window``
    (sliding-window), ``softcap`` and ``scale`` (Gemma-2 score shaping) route
    to the splash kernel on TPU above the crossover, else dense; the plain
    flash kernel and the sequence-parallel paths cannot express them."""
    if window is not None or softcap is not None or scale is not None:
        if impl not in ("auto", "dense", "splash"):
            raise ValueError(
                f"window/softcap/scale attention options need the dense or "
                f"splash path; impl={impl!r} cannot apply them."
            )
        if impl == "splash" and not _splash_available():
            raise ValueError("impl='splash' needs a TPU backend")
        if impl == "auto":
            impl = resolve_auto_impl(
                q.shape[1], q.shape[2], q.shape[3], batch=q.shape[0],
                kv_len=k.shape[1], causal=causal, window=window,
                softcap=softcap, scale=scale,
            )
        if impl == "splash":
            return splash_attention(q, k, v, causal=causal, mask=mask, window=window,
                                    softcap=softcap, scale=scale)
        return dense_attention(q, k, v, causal=causal, mask=mask, window=window,
                               softcap=softcap, scale=scale)
    if impl == "auto":
        impl = resolve_auto_impl(q.shape[1], q.shape[2], q.shape[3], batch=q.shape[0])
    if impl == "flash":
        if not _flash_available():
            impl = "dense"
        else:
            return flash_attention(q, k, v, causal=causal, mask=mask)
    if impl == "ring":
        from ..parallel.ring import ring_attention

        return ring_attention(q, k, v, causal=causal, mask=mask, mesh=mesh)
    if impl == "ulysses":
        from ..parallel.ulysses import ulysses_attention

        return ulysses_attention(q, k, v, causal=causal, mask=mask, mesh=mesh)
    return dense_attention(q, k, v, causal=causal, mask=mask)


def _flash_shapes_ok(q, k) -> bool:
    # Mosaic flash wants seq multiples of the block sizes (min 128) and head_dim
    # aligned to lanes; fall back for tiny/test shapes.
    B, S, H, D = q.shape
    return (S >= 128 and S % 128 == 0) and (D % 128 == 0 or D in (64, 96, 256))


# (kept for callers/tests; resolve_auto_impl is the dispatch source of truth)
